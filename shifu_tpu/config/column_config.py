"""ColumnConfig: per-column state threaded through the whole pipeline.

Wire-compatible with the reference's ColumnConfig.json
(container/obj/ColumnConfig.java:35, ColumnStats.java:33, ColumnBinning.java:38).

Conventions carried over from the reference:
  - ``column_type``: "N" numeric, "C" categorical, "H" hybrid
    (container/obj/ColumnType.java).
  - ``bin_boundary`` for numeric columns starts at -Infinity (serialized as the
    string "-Infinity"), bin i covers [boundary[i], boundary[i+1]).
  - All per-bin count/weight arrays have length ``len(bins) + 1``; the LAST slot
    is the missing-value bin (core/binning/UpdateBinningInfoReducer.java:180-200).
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
from dataclasses import dataclass, field
from typing import Any, List, Optional

from shifu_tpu.config.jsonbase import JsonEnum, decode_dataclass, encode_dataclass


class ColumnType(JsonEnum):
    N = "N"  # numeric
    C = "C"  # categorical
    H = "H"  # hybrid (numeric with some category-like values)


class ColumnFlag(JsonEnum):
    FORCE_SELECT = "ForceSelect"
    FORCE_REMOVE = "ForceRemove"
    META = "Meta"
    TARGET = "Target"
    WEIGHT = "Weight"
    CANDIDATE = "Candidate"


@dataclass
class ColumnStats:
    max: Optional[float] = None
    min: Optional[float] = None
    mean: Optional[float] = None
    median: Optional[float] = None
    total_count: Optional[int] = None
    distinct_count: Optional[int] = None
    missing_count: Optional[int] = None
    std_dev: Optional[float] = None
    missing_percentage: Optional[float] = None
    woe: Optional[float] = None
    ks: Optional[float] = None
    iv: Optional[float] = None
    weighted_ks: Optional[float] = None
    weighted_iv: Optional[float] = None
    weighted_woe: Optional[float] = None
    skewness: Optional[float] = None
    kurtosis: Optional[float] = None
    psi: Optional[float] = None
    unit_stats: Optional[List[str]] = None


@dataclass
class ColumnBinning:
    length: int = 0
    bin_boundary: Optional[List[float]] = None
    bin_category: Optional[List[str]] = None
    bin_count_neg: Optional[List[int]] = None
    bin_count_pos: Optional[List[int]] = None
    bin_pos_rate: Optional[List[float]] = None
    bin_avg_score: Optional[List[float]] = None
    bin_weighted_neg: Optional[List[float]] = None
    bin_weighted_pos: Optional[List[float]] = None
    bin_count_woe: Optional[List[float]] = None
    bin_weighted_woe: Optional[List[float]] = None


@dataclass
class ColumnConfig:
    column_num: int = 0
    column_name: str = ""
    version: str = "0.2.0"
    column_type: Optional[ColumnType] = None
    column_flag: Optional[ColumnFlag] = None
    final_select: bool = False
    column_stats: ColumnStats = field(default_factory=ColumnStats)
    column_binning: ColumnBinning = field(default_factory=ColumnBinning)

    # ---- role predicates (reference ColumnConfig.java isTarget/isMeta/...) ----
    def is_target(self) -> bool:
        return self.column_flag == ColumnFlag.TARGET

    def is_meta(self) -> bool:
        return self.column_flag == ColumnFlag.META

    def is_weight(self) -> bool:
        return self.column_flag == ColumnFlag.WEIGHT

    def is_force_select(self) -> bool:
        return self.column_flag == ColumnFlag.FORCE_SELECT

    def is_force_remove(self) -> bool:
        return self.column_flag == ColumnFlag.FORCE_REMOVE

    def is_candidate(self) -> bool:
        return self.column_flag == ColumnFlag.CANDIDATE

    def is_categorical(self) -> bool:
        return self.column_type == ColumnType.C

    def is_numerical(self) -> bool:
        return self.column_type == ColumnType.N

    def is_hybrid(self) -> bool:
        return self.column_type == ColumnType.H

    # Non-target/meta/weight/force-remove column usable as a model feature.
    def is_feature(self) -> bool:
        return self.column_flag not in (
            ColumnFlag.TARGET,
            ColumnFlag.META,
            ColumnFlag.WEIGHT,
            ColumnFlag.FORCE_REMOVE,
        )

    # ---- convenience accessors mirroring the reference API ----
    @property
    def mean(self) -> Optional[float]:
        return self.column_stats.mean

    @property
    def std_dev(self) -> Optional[float]:
        return self.column_stats.std_dev

    @property
    def ks(self) -> Optional[float]:
        return self.column_stats.ks

    @property
    def iv(self) -> Optional[float]:
        return self.column_stats.iv

    @property
    def missing_percentage(self) -> Optional[float]:
        return self.column_stats.missing_percentage

    @property
    def bin_boundary(self) -> Optional[List[float]]:
        return self.column_binning.bin_boundary

    @property
    def bin_category(self) -> Optional[List[str]]:
        return self.column_binning.bin_category

    @property
    def bin_pos_rate(self) -> Optional[List[float]]:
        return self.column_binning.bin_pos_rate

    @property
    def bin_count_woe(self) -> Optional[List[float]]:
        return self.column_binning.bin_count_woe

    @property
    def bin_weighted_woe(self) -> Optional[List[float]]:
        return self.column_binning.bin_weighted_woe

    def bin_length(self) -> int:
        return self.column_binning.length


def _encode_boundary(values: Optional[List[float]]) -> Optional[List[Any]]:
    """-inf/inf floats are written as "-Infinity"/"Infinity" strings, matching
    Jackson's rendering in the reference fixtures."""
    if values is None:
        return None
    out: List[Any] = []
    for v in values:
        if v == -math.inf:
            out.append("-Infinity")
        elif v == math.inf:
            out.append("Infinity")
        else:
            out.append(v)
    return out


def column_config_to_json(cc: ColumnConfig) -> dict:
    raw = encode_dataclass(cc)
    raw["columnBinning"]["binBoundary"] = _encode_boundary(cc.column_binning.bin_boundary)
    return raw


def column_config_from_json(data: dict) -> ColumnConfig:
    # jsonbase._decode's float path already parses "-Infinity"/"Infinity"
    # boundary strings for List[float] fields.
    return decode_dataclass(ColumnConfig, data)


def save_column_config_list(path: str, columns: List[ColumnConfig]) -> None:
    # tmp + replace: concurrent readers (a peer host process polling for
    # the merge host's post-stats write, serve hot-reload) must see the
    # old or the new complete file, never a torn one
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump([column_config_to_json(c) for c in columns], fh, indent=2)
        fh.write("\n")
    os.replace(tmp, path)


def load_column_config_list(path: str) -> List[ColumnConfig]:
    with open(path) as fh:
        data = json.load(fh)
    return [column_config_from_json(d) for d in data]
