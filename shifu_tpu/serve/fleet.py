"""Replicated serving fleet: per-device scoring replicas behind a
drain-aware router.

The single-device `shifu serve` tops out at one micro-batcher feeding
one fused program. This module takes it to fleet shape — the reference
serves from a fleet of JVM workers behind PayPal's traffic tier; here
the fleet is N replicas of the SAME compiled scoring program, one per
device (TensorFlow's shared train/serve substrate argument), with a
cheap cross-replica reduce for rollout evidence (DrJAX's
MapReduce-as-collectives decomposition, the PR-8 `window_reduce` idiom
via `parallel.mesh.fleet_reduce`).

  ScoringReplica    one device's complete scoring stack: a
                    SwappableRegistry whose fused program, weights and
                    norm/drift constants are pinned to THAT device, its
                    own admission queue, micro-batch worker, health
                    state machine and compiled-program cache. Replica
                    `i` runs on `jax.devices()[i % ndev]` — replicas
                    beyond the device count share devices (useful for
                    tests and oversubscription), never fail.
  DrainAwareRouter  places each request on the replica with the lowest
                    EXPECTED WAIT = queue backlog / observed drain rate
                    (the PR-7 Retry-After estimator computed per
                    replica). Degraded replicas are de-prioritized by a
                    multiplier (`shifu.serve.routerPenalty`), draining
                    replicas are skipped, a full replica spills to the
                    next-best one, and ties rotate round-robin so an
                    idle fleet warms every replica.
  ReplicaFleet      construction + the fleet-level contract: aggregate
                    /healthz (one degraded replica = degraded fleet
                    with the replica named; ALL draining = draining ->
                    503), fleet-wide Retry-After (total backlog over
                    summed drain rates), stage-on-every-replica, the
                    psum-merged shadow evidence, and the ROLLING promote
                    (one replica at a time, each swap atomic under its
                    replica's lock, per-step audit callback).

Replica counts come from `-Dshifu.serve.replicas` (0 = every local
device). `replicas=1` is the degenerate case and preserves the
pre-fleet behavior exactly: same code path, a 1-wide fleet.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Callable, List, Optional, Sequence

import numpy as np

from shifu_tpu.analysis.racetrack import tracked_lock
from shifu_tpu.data.reader import ColumnarData
from shifu_tpu.eval.scorer import DEFAULT_SCORE_SCALE, ScoreResult
from shifu_tpu.serve.batcher import (
    LATENCY_BUCKETS,
    RETRY_AFTER_MAX_S,
    RETRY_AFTER_MIN_S,
    MicroBatcher,
    ScoreRequest,
)
from shifu_tpu.serve.health import (
    BREAKER_CLOSED,
    BREAKER_OPEN,
    DEGRADED,
    DRAINING,
    OK,
    CircuitBreaker,
    HealthMonitor,
    SloTracker,
)
from shifu_tpu.serve.queue import AdmissionQueue, RejectedError
from shifu_tpu.serve.registry import ModelRegistry, records_to_columnar
from shifu_tpu.utils import environment
from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)

DEFAULT_ROUTER_PENALTY = 4.0
DEFAULT_FAILOVER_MAX = 2


def replicas_setting() -> int:
    """shifu.serve.replicas — scoring replicas (0 = all local devices)."""
    return environment.get_int("shifu.serve.replicas", 0)


def failover_max_setting() -> int:
    """shifu.serve.breaker.failoverMax — times one request may be
    replayed on another replica after its batch failed, before it is
    answered with the error."""
    return environment.get_int("shifu.serve.breaker.failoverMax",
                               DEFAULT_FAILOVER_MAX)


def router_penalty_setting() -> float:
    """shifu.serve.routerPenalty — expected-wait multiplier applied to
    DEGRADED replicas (de-prioritize, don't eject)."""
    return environment.get_float("shifu.serve.routerPenalty",
                                 DEFAULT_ROUTER_PENALTY)


class ScoringReplica:
    """One device's complete scoring stack (registry + queue + batcher +
    health), labeled `replica=<i>` on every metric it records."""

    def __init__(self, registry, index: int = 0,
                 admission: Optional[AdmissionQueue] = None,
                 health: Optional[HealthMonitor] = None,
                 max_batch_rows: Optional[int] = None,
                 max_wait_ms: Optional[float] = None,
                 max_restarts: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 batching: Optional[str] = None,
                 queue_depth: Optional[int] = None,
                 observer: Optional[Callable] = None,
                 labels: Optional[dict] = None) -> None:
        self.index = int(index)
        self.name = str(self.index)
        self.registry = registry
        self.device = getattr(registry, "device", None)
        # extra identity labels (the zoo passes {"tenant": "<set>"}) ride
        # UNDER the replica label on every serve.* metric this replica's
        # stack records — one /metrics page stays attributable per
        # (tenant, replica) without a second exporter
        labels = {**dict(labels or {}), "replica": self.name}
        self.labels = labels
        self.admission = (AdmissionQueue(queue_depth, labels=labels)
                          if admission is None else admission)
        self.health = (HealthMonitor(labels=labels)
                       if health is None else health)
        # device-dispatch circuit breaker: repeated batch failures
        # quarantine THIS replica (the router treats it as absent) until
        # half-open probes prove the device back
        self.breaker = CircuitBreaker(labels=labels)
        if observer is None:
            batch_observer = None
        else:
            # the fleet observer wants to know WHICH replica resolved the
            # batch (per-replica scored_sha stamps the fleet-global
            # traffic log); the batcher's hook doesn't — adapt here
            def batch_observer(data, result, _rep=self):
                observer(_rep, data, result)
        self.batcher = MicroBatcher(
            registry.score_raw, self.admission,
            max_batch_rows=max_batch_rows, max_wait_ms=max_wait_ms,
            health=self.health, max_restarts=max_restarts,
            deadline_ms=deadline_ms, observer=batch_observer,
            batching=batching, labels=labels, breaker=self.breaker)

    def snapshot(self) -> dict:
        snap = {
            "replica": self.name,
            **self.registry.snapshot(),
            "health": self.health.snapshot(),
            "breaker": self.breaker.snapshot(),
            "queueDepth": len(self.admission),
            "workerRestarts": self.batcher.restarts,
        }
        if self.device is not None:
            snap["device"] = str(self.device)
        return snap


class DrainAwareRouter:
    """Place each request on the replica that will dispatch it soonest.

    Preference order per submit: skip DRAINING replicas, rank the rest
    by expected wait (backlog / observed drain rate, the per-replica
    Retry-After estimator) with DEGRADED replicas multiplied by
    `penalty`, break ties round-robin. A full replica spills to the
    next candidate (`serve.router.spill`); only when every candidate
    sheds does the caller see the rejection. All replicas draining =
    RejectedError("closed")."""

    def __init__(self, replicas: Sequence[ScoringReplica],
                 penalty: Optional[float] = None) -> None:
        self.replicas = list(replicas)
        self.penalty = (router_penalty_setting() if penalty is None
                        else float(penalty))
        self._lock = tracked_lock("serve.router")
        self._rr = 0

    def order(self, exclude: Optional[ScoringReplica] = None
              ) -> List[ScoringReplica]:
        """Routable replicas, best placement first.

        Circuit-breaker policy: an OPEN breaker inside its backoff makes
        the replica ABSENT (not merely penalized — its device is known
        bad); a breaker due for its half-open probe ranks FIRST, because
        the probe must be an actual request and ranking it last would
        starve recovery behind healthy replicas forever. The probe rides
        the normal failover protection, so a failed probe costs one
        replay, never an unanswered client."""
        now = time.perf_counter()
        mono = time.monotonic()
        with self._lock:
            rr = self._rr
            self._rr += 1
        n = max(1, len(self.replicas))
        ranked = []
        for rep in self.replicas:
            if rep is exclude:
                continue  # failover: never replay onto the failing replica
            state = rep.health.state
            if state == DRAINING:
                continue  # 503 territory: never place new work here
            if not rep.breaker.routable(mono):
                continue  # quarantined: absent from the routing set
            probe = rep.breaker.probe_due(mono)
            wait = rep.batcher.expected_wait(now)
            if state == DEGRADED:
                # de-prioritize, don't eject: the +epsilon keeps an IDLE
                # degraded replica (wait 0.0) behind idle healthy ones
                wait = (wait + 1e-3) * self.penalty
            ranked.append((0 if probe else 1, wait,
                           (rep.index - rr) % n, rep))
        ranked.sort(key=lambda t: (t[0], t[1], t[2]))
        return [t[3] for t in ranked]

    def _place(self, rep: ScoringReplica, put: Callable) -> bool:
        """One placement attempt under the replica's breaker grant.
        `put` raises RejectedError on shed."""
        grant = rep.breaker.admit()
        if grant is None:
            return False  # tripped between order() and here
        try:
            put()
        except RejectedError:
            # give back a consumed probe slot: the probe never dispatched
            rep.breaker.cancel(grant)
            raise
        return True

    def submit(self, data, trace=None) -> ScoreRequest:
        """Admit one request on the best replica, spilling past full
        ones. Raises RejectedError when nothing can take it."""
        from shifu_tpu.obs import registry

        order = self.order()
        if not order:
            raise RejectedError("closed")
        reg = registry()
        last: Optional[RejectedError] = None
        for i, rep in enumerate(order):
            req = ScoreRequest(data,
                               deadline_s=rep.batcher.deadline_s or None,
                               trace=trace)
            try:
                if not self._place(rep, lambda: rep.admission.put(req)):
                    continue
            except RejectedError as e:
                last = e
                if i == 0:
                    # the PLANNED placement shed — everything after is a
                    # drain-around (counted so routing-around-a-backlog
                    # is visible on /metrics)
                    reg.counter("serve.router.spill",
                                **rep.labels).inc()
                continue
            reg.counter("serve.router.routed", **rep.labels).inc()
            if trace is not None:
                trace.annotate(replica=rep.name, spilled=bool(i))
            return req
        raise last if last is not None else RejectedError("closed")

    def resubmit(self, req: ScoreRequest,
                 exclude: Optional[ScoringReplica] = None) -> bool:
        """Failover placement of an ALREADY-admitted request whose batch
        failed: the same ScoreRequest object (same completion event —
        replay can never double-answer) re-enters another replica's
        queue. Returns False when no replica could take it."""
        from shifu_tpu.obs import registry

        for rep in self.order(exclude=exclude):
            try:
                if not self._place(rep, lambda: rep.admission.put(req)):
                    continue
            except RejectedError:
                continue
            registry().counter("serve.failover.rerouted",
                               **rep.labels).inc()
            if req.trace is not None:
                req.trace.annotate(failovers=req.failovers,
                                   replica=rep.name)
            return True
        return False


class ReplicaFleet:
    """N scoring replicas + router + the fleet-level serving contract.

    Also the registry facade the server front end reads: `sha`,
    `model_names`, `fused`, `input_columns`, `score_records` (direct,
    un-routed — parity checks), `warm`, `snapshot`, and the rollout
    surface `stage`/`shadow_snapshot`/`promote`/`observe` — so a
    1-replica fleet is a drop-in for the SwappableRegistry the server
    used to hold."""

    def __init__(self, replicas: Sequence[ScoringReplica],
                 router: Optional[DrainAwareRouter] = None,
                 labels: Optional[dict] = None) -> None:
        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        self.replicas = list(replicas)
        self.router = router or DrainAwareRouter(self.replicas)
        # fleet-identity labels ({"tenant": "<set>"} in a model zoo):
        # ride the fleet-LEVEL metrics (serve.replicas, the fleet
        # Retry-After gauge, SLO counters, stage histograms) the same
        # way each replica's labels ride its own
        self.labels = dict(labels or {})
        # fleet-level health: sticky drift degrades and shutdown live
        # here; per-replica crash/restart state lives on each replica's
        # own monitor and aggregates in health_snapshot()
        self.health = HealthMonitor()
        # control-plane mutual exclusion (stage/unstage/promote): a
        # re-stage landing MID-ROLL would change later replicas' staged
        # shadows after the pre-roll sha validation and strand the
        # fleet half-promoted — so fleet-level rollout operations
        # exclude each other via a flag (never held across device
        # work); a concurrent operation is REFUSED (409 over HTTP),
        # not queued
        self._ctl_lock = tracked_lock("serve.fleet.control")
        self._ctl_busy: Optional[str] = None
        # request-latency SLO accounting (serve/health.py SloTracker):
        # armed by -Dshifu.serve.sloMs, read by /healthz and the
        # shutdown manifest; a no-op object when the knob is unset
        self.slo = SloTracker(labels=self.labels)
        # per-(stage, replica) histogram cache: finish_trace runs once
        # per request, and seven registry get-or-create lookups (label
        # sort + registry lock each) per request are measurable GIL
        # time at fleet concurrency. Plain dict — reads are GIL-atomic,
        # a racing first-miss just does the registry lookup twice and
        # lands on the SAME registry-owned histogram either way. Cleared
        # when the obs registry is swapped (reset) under us.
        self._stage_hists: dict = {}
        self._stage_hists_reg = None
        # request failover: a batch that failed on one replica replays
        # its requests on the others (scoring is pure — replay is safe),
        # bounded per request so a fleet-wide outage still answers
        # everything with the error instead of ping-ponging forever
        self.failover_max = failover_max_setting()
        for rep in self.replicas:
            rep.batcher.failover = (
                lambda req, error, _src=rep:
                self._failover(_src, req, error))
        from shifu_tpu.obs import registry

        registry().gauge("serve.replicas",
                         **self.labels).set(len(self.replicas))

    def _failover(self, src: ScoringReplica, req: ScoreRequest,
                  error: BaseException) -> None:
        """Batcher hook for a failed-batch request: replay it on another
        replica (never the failing one), or answer it with the error
        once the per-request budget is spent — zero unanswered, and the
        one-shot completion event makes double-answering impossible."""
        from shifu_tpu.obs import registry

        reg = registry()
        if req.failovers >= self.failover_max or len(self.replicas) < 2:
            if req.failovers:
                reg.counter("serve.failover.exhausted",
                            **src.labels).inc()
            req.fail(error)
            return
        req.failovers += 1
        reg.counter("serve.failover.requests", **src.labels).inc()
        if req.trace is not None:
            # the hop is a stage on the request's own trace (the
            # X-Shifu-Trace id the caller sent rides through failover):
            # the stitched timeline shows WHERE the retry happened, not
            # just that latency appeared
            with req.trace.stage("failover"):
                req.trace.annotate(failoverFrom=src.name,
                                   failoverError=type(error).__name__)
                rerouted = self.router.resubmit(req, exclude=src)
        else:
            rerouted = self.router.resubmit(req, exclude=src)
        if not rerouted:
            # nothing else could take it (all quarantined/draining/full)
            reg.counter("serve.failover.exhausted",
                        **src.labels).inc()
            req.fail(error)

    @contextmanager
    def _control(self, op: str):
        with self._ctl_lock:
            if self._ctl_busy is not None:
                raise ValueError(
                    f"fleet {self._ctl_busy} in progress — retry when "
                    "it completes")
            self._ctl_busy = op
        try:
            yield
        finally:
            with self._ctl_lock:
                self._ctl_busy = None

    # ---- construction ----
    @classmethod
    def build(cls, models_dir: str, n_replicas: Optional[int] = None,
              scale: float = DEFAULT_SCORE_SCALE,
              column_configs=None, model_config=None, drift=None,
              queue_depth: Optional[int] = None,
              max_batch_rows: Optional[int] = None,
              max_wait_ms: Optional[float] = None,
              max_restarts: Optional[int] = None,
              deadline_ms: Optional[float] = None,
              batching: Optional[str] = None,
              observer: Optional[Callable] = None,
              tenant: Optional[str] = None,
              put_hook=None, cost_hook=None) -> "ReplicaFleet":
        """One replica per device (replica i -> jax.devices()[i % ndev]),
        each loading the model set onto ITS device with its own compiled
        program cache. `n_replicas` falls back to -Dshifu.serve.replicas,
        then to every local device. `tenant` labels every metric the
        fleet's stack records (the zoo's per-set identity); `put_hook`
        streams each replica's weight groups through the zoo's budget
        ledger before they land on device."""
        import jax

        devices = jax.devices()
        n = n_replicas if n_replicas is not None else replicas_setting()
        n = int(n) if n and int(n) > 0 else len(devices)
        extra = {"tenant": tenant} if tenant else {}
        replicas = []
        try:
            for i in range(n):
                dev = devices[i % len(devices)]
                reg = ModelRegistry(
                    models_dir, scale=scale,
                    column_configs=column_configs,
                    model_config=model_config, drift=drift, device=dev,
                    labels={**extra, "replica": str(i)},
                    put_hook=put_hook)
                reg.cost_hook = cost_hook
                from shifu_tpu.loop.hotswap import SwappableRegistry

                sw = SwappableRegistry(reg, labels={**extra,
                                                    "replica": str(i)})
                replicas.append(ScoringReplica(
                    sw, index=i, queue_depth=queue_depth,
                    max_batch_rows=max_batch_rows,
                    max_wait_ms=max_wait_ms,
                    max_restarts=max_restarts, deadline_ms=deadline_ms,
                    batching=batching, observer=observer, labels=extra))
        except BaseException:
            # a later replica's build failing (e.g. the zoo's budget
            # acquire raising mid-stream) must not leak the earlier
            # replicas' worker threads and device weights — the caller
            # releases its ledger charge on this exception, so the
            # bytes have to actually free
            for rep in replicas:
                rep.admission.close()
                rep.batcher.join(1.0)
                rel = getattr(rep.registry, "release", None)
                if rel is not None:
                    rel()
            raise
        log.info("serving fleet%s: %d replica(s) over %d device(s)",
                 f" (tenant {tenant})" if tenant else "", n,
                 min(n, len(devices)))
        return cls(replicas, labels=extra)

    def __len__(self) -> int:
        return len(self.replicas)

    # ---- scoring ----
    def submit(self, data, trace=None) -> ScoreRequest:
        return self.router.submit(data, trace=trace)

    def score_raw(self, data) -> ScoreResult:
        """Routed scoring of one raw batch (blocks for the result)."""
        return self.submit(data).wait()

    # ---- request tracing / SLO ----
    def finish_trace(self, trace) -> bool:
        """Close one request's trace: offer it to the bounded ring
        (obs/reqtrace.buffer — head-sampled or slow-captured), feed the
        per-stage `serve.stage_seconds{stage=,replica=}` histograms
        (retained traces ride along as bucket exemplars, so /metrics
        links straight to the evidence), and count the request against
        the SLO. Returns True when the trace was retained."""
        from shifu_tpu.obs import registry, reqtrace

        total = trace.finish()
        kept = reqtrace.buffer().offer(trace)
        reg = registry()
        if reg is not self._stage_hists_reg:
            # obs scope was reset (new bench scenario/test): old
            # histograms belong to the dead registry
            self._stage_hists = {}
            self._stage_hists_reg = reg
        # a request shed before placement has no replica: label its
        # stage samples "unrouted" rather than fabricating an empty
        # replica="" series next to the real 0..N-1 ones
        replica = str(trace.attrs.get("replica", "unrouted"))
        exemplar = trace.trace_id if kept else None
        for stage, dur in trace.stage_totals().items():
            hist = self._stage_hists.get((stage, replica))
            if hist is None:
                hist = reg.histogram("serve.stage_seconds",
                                     buckets=LATENCY_BUCKETS,
                                     stage=stage, replica=replica,
                                     **self.labels)
                self._stage_hists[(stage, replica)] = hist
            hist.observe(dur, exemplar=exemplar)
        # `status` is set only by the error paths (rejected/timeout/
        # exception): such a request got no score, so it counts BAD
        # whatever its latency — a fleet shedding 90% of traffic in
        # sub-millisecond 429s must burn the SLO budget, not look fast
        self.slo.observe(total,
                         ok=False if "status" in trace.attrs else None)
        return kept

    # ---- registry facade (replica 0 is the canonical read) ----
    @property
    def sha(self) -> str:
        return self.replicas[0].registry.sha

    @property
    def model_names(self) -> List[str]:
        return self.replicas[0].registry.model_names

    @property
    def fused(self) -> bool:
        return self.replicas[0].registry.fused

    @property
    def input_columns(self) -> List[str]:
        return self.replicas[0].registry.input_columns

    def score_records(self, records: Sequence[dict]) -> ScoreResult:
        """Direct (un-routed, un-batched) scoring on replica 0 — the
        parity-check path, NOT the serving path."""
        return self.replicas[0].registry.score_records(records)

    def warm(self, batch_sizes: Sequence[int]) -> List[int]:
        """Pre-compile the buckets on EVERY replica (each owns its own
        compiled-program cache on its own device)."""
        warmed: List[int] = []
        for rep in self.replicas:
            warmed = rep.registry.warm(batch_sizes)
        return warmed

    # ---- health ----
    def health_snapshot(self) -> dict:
        """Aggregate /healthz: per-replica states roll up so a balancer
        gets one verdict and an operator gets the replica named."""
        fleet = self.health.snapshot()
        per = []
        for rep in self.replicas:
            s = rep.health.snapshot()
            s.update({"replica": rep.name,
                      "sha": rep.registry.sha,
                      "breaker": rep.breaker.snapshot(),
                      "queueDepth": len(rep.admission),
                      "workerRestarts": rep.batcher.restarts})
            if s["breaker"]["state"] != BREAKER_CLOSED and s["status"] == OK:
                # a quarantined device is a degraded replica even when
                # its worker is healthy — the breaker names the domain
                s["status"] = DEGRADED
                s["reason"] = (s.get("reason")
                               or f"breaker {s['breaker']['state']}")
            per.append(s)
        bad = [p for p in per if p["status"] != OK]
        if (fleet["status"] == DRAINING
                or all(p["status"] == DRAINING for p in per)):
            status = DRAINING
            reason = fleet["reason"] or "all replicas draining"
        elif fleet["status"] == DEGRADED:
            status, reason = DEGRADED, fleet["reason"]
        elif bad:
            status = DEGRADED
            reason = "; ".join(
                f"replica {p['replica']} {p['status']}"
                + (f": {p['reason']}" if p.get("reason") else "")
                for p in bad)
        else:
            status, reason = OK, ""
        return {
            "status": status,
            "reason": reason,
            "workerCrashes": sum(p["workerCrashes"] for p in per),
            "replicas": per,
        }

    # ---- load hints ----
    def retry_after_seconds(self) -> float:
        """Fleet Retry-After: TOTAL backlog over the SUMMED per-replica
        drain rates — the hint a shed client gets describes the fleet's
        capacity to absorb it, not one replica's. Open-breaker replicas
        are EXCLUDED on both sides: their drain-rate history is stale
        (measured before the device died) and their backlog is being
        failed over — counting either would tell clients to come back
        for capacity that no longer exists. Exported as the unlabeled
        serve.retry_after_seconds gauge (per-replica labeled gauges come
        from each batcher)."""
        from shifu_tpu.obs import registry

        now = time.perf_counter()
        depth_total = 0
        rate_total = 0.0
        rated = False
        for rep in self.replicas:
            if rep.breaker.state == BREAKER_OPEN:
                continue  # quarantined: not surviving capacity
            depth, rate = rep.batcher.drain_stats(now)
            depth_total += depth
            if rate is not None:
                rate_total += rate
                rated = True
        if rated:
            hint = depth_total / max(rate_total, 1e-3)
        else:
            hint = RETRY_AFTER_MIN_S  # no drain history: cheap optimism
        hint = min(max(hint, RETRY_AFTER_MIN_S), RETRY_AFTER_MAX_S)
        registry().gauge("serve.retry_after_seconds",
                         **self.labels).set(hint)
        return hint

    # ---- rollout: stage / shadow evidence / rolling promote ----
    def observe(self, data, result) -> None:
        """Compat shim for callers that treated the registry as the
        observer (single-replica embeddings): replica 0's observer."""
        self.replicas[0].registry.observe(data, result)

    def stage(self, models_dir: str, column_configs=None,
              model_config=None, drift=None,
              put_hook=None) -> Optional[dict]:
        """Stage + warm the candidate as the shadow on EVERY replica
        (each loads it onto its own device and pre-compiles its live
        buckets). Returns the aggregated shadow snapshot. Refused while
        another rollout operation (stage/promote) is in flight.
        `put_hook` makes the stage streamed (zoo budget ledger — see
        SwappableRegistry.stage)."""
        with self._control("stage"):
            staged = [rep.registry.stage(models_dir,
                                         column_configs=column_configs,
                                         model_config=model_config,
                                         drift=drift, put_hook=put_hook)
                      for rep in self.replicas]
            shas = {s["sha"] for s in staged}
            if len(shas) != 1:  # same dir: only a mid-stage redeploy
                raise ValueError(
                    f"staged shadow shas diverge across replicas "
                    f"({shas}) — the candidate dir changed mid-stage; "
                    "re-stage")
            return self.shadow_snapshot()

    def unstage(self) -> None:
        with self._control("unstage"):
            for rep in self.replicas:
                rep.registry.unstage()

    def shadow_snapshot(self) -> Optional[dict]:
        """Fleet shadow evidence: per-replica ShadowStats merged with ONE
        psum/pmax collective over the fleet mesh (additive counts sum,
        maxAbsDelta pmaxes — parallel.mesh.fleet_reduce, the PR-8
        window_reduce substrate), so `shifu promote`'s gates read one
        fleet-wide agreement rate. None until every replica has a staged
        shadow."""
        per = [rep.registry.shadow_snapshot() for rep in self.replicas]
        if any(p is None for p in per):
            return None
        if len(per) == 1:
            agg = dict(per[0], replicas=per)
        else:
            agg = _reduce_shadow_stats(self.replicas, per)
            agg.update({
                "sha": per[0]["sha"],
                "models": per[0]["models"],
                "fused": per[0]["fused"],
                "tolerance": per[0]["tolerance"],
                "replicas": per,
            })
        # the full fleet delta DISTRIBUTION, not just mean/max: the
        # per-replica serve.shadow.score_delta histograms share pinned
        # edges, so Histogram.merge folds them bucket-exact (merged ==
        # recomputed-from-raw) — promote gates and the fleet view read
        # one agreement histogram instead of N
        delta = _merged_delta_histogram(self.replicas)
        if delta.quantile(0.5) is not None:
            agg["deltaHistogram"] = delta.as_dict()
            agg["deltaP50"] = delta.quantile(0.50)
            agg["deltaP99"] = delta.quantile(0.99)
        return agg

    def promote(self, expected_sha: Optional[str] = None,
                step_cb: Optional[Callable] = None) -> dict:
        """ROLLING promote: replicas flip shadow -> active ONE AT A TIME,
        each swap atomic under its replica's lock (the in-flight batch
        finishes on the old version, the next gathered batch scores on
        the new) — requests keep flowing on the not-yet-rolled replicas
        throughout, so the fleet never has a scoring gap.

        The staged sha is validated across ALL replicas (and against
        `expected_sha`, the sha the caller's gate evidence described)
        BEFORE the first swap, and the whole roll excludes concurrent
        stage()/unstage() via the fleet control-plane flag — so a roll
        can neither start on nor be diverted mid-way to a candidate the
        gates never saw, and a refusal always happens with ZERO
        replicas swapped. `step_cb(replica, step)` fires after each
        replica's swap — the server uses it to stamp one sha-bound
        audit manifest per replica step."""
        with self._control("promote"):
            staged = [rep.registry.shadow_snapshot()
                      for rep in self.replicas]
            missing = [rep.name for rep, s in zip(self.replicas, staged)
                       if s is None]
            if missing:
                raise ValueError("no staged candidate on replica(s) "
                                 + ",".join(missing))
            shas = {s["sha"] for s in staged}
            if len(shas) != 1:
                raise ValueError(
                    f"staged shadow shas diverge across replicas "
                    f"({shas}); re-stage before promoting")
            sha = shas.pop()
            if expected_sha and sha != expected_sha:
                raise ValueError(
                    f"staged shadow is {sha}, not the gated candidate "
                    f"{expected_sha} — it was re-staged since the gates "
                    "evaluated; re-run the gate on the current shadow")
            shadow = self.shadow_snapshot()
            steps = []
            from shifu_tpu.obs import registry

            for rep in self.replicas:
                swap = rep.registry.promote(expected_sha)
                step = {"replica": rep.name, **swap}
                steps.append(step)
                registry().counter("serve.swap.steps",
                                   **rep.labels).inc()
                if step_cb is not None:
                    try:
                        step_cb(rep, step)
                    except Exception as e:  # audit trouble must not
                        # stop the roll half-way: a half-promoted fleet
                        # serves two versions indefinitely, which is
                        # worse than a missing manifest
                        log.warning("promote step callback failed on "
                                    "replica %s: %s", rep.name, e)
            return {"from": steps[0]["from"], "to": sha,
                    "replicas": len(steps), "steps": steps,
                    "shadow": shadow}

    @property
    def active_models_dir(self) -> str:
        """Dir of the version currently serving (replica 0 canonical —
        the pre-roll sha validation keeps replicas consistent)."""
        reg = self.replicas[0].registry
        return getattr(reg, "active_models_dir", None) or reg.models_dir

    def memory_analysis(self) -> dict:
        """Fleet resident cost: per-replica registry memory_analysis
        summed — what the zoo's HBM budget ledger trues a tenant's
        charge up to after admission/stage (each replica's weights and
        compiled programs live on its own device, but the budget bounds
        the DEPLOYMENT'S total)."""
        per = []
        total = 0
        for rep in self.replicas:
            ma = getattr(rep.registry, "memory_analysis", None)
            if ma is None:
                continue
            m = ma()
            per.append({"replica": rep.name, **m})
            total += int(m.get("residentBytes", 0))
        return {"replicas": per, "residentBytes": total}

    def release(self) -> int:
        """Eviction seam (zoo): release every replica's registries —
        compiled-program cache entries and device weights drop together.
        Call after close()."""
        n = 0
        for rep in self.replicas:
            rel = getattr(rep.registry, "release", None)
            if rel is not None:
                n += rel()
        return n

    def snapshot(self) -> dict:
        """Manifest/bench view: fleet summary + per-replica registry
        snapshots (warm buckets prove each replica's compile bound)."""
        snap = self.replicas[0].registry.snapshot()
        snap.update({
            "replicas": [rep.snapshot() for rep in self.replicas],
            "replicaCount": len(self.replicas),
        })
        return snap

    # ---- lifecycle ----
    def close(self, timeout: Optional[float] = 30.0) -> None:
        """Stop admitting fleet-wide and drain every replica."""
        self.health.set_draining("shutdown")
        for rep in self.replicas:
            rep.health.set_draining("shutdown")
            rep.admission.close()
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        for rep in self.replicas:
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            rep.batcher.join(remaining)

    def score_batch(self, records: Sequence[dict],
                    timeout: Optional[float] = None,
                    extra_columns: Optional[Sequence[str]] = None,
                    trace=None) -> ScoreResult:
        """Routed in-process scoring of raw records — a list of dicts
        (the JSON path) or an already-columnar batch (a decoded binary
        wire payload, serve/wire.py), which skips record conversion and
        only conforms to the serving schema. A `trace`
        (obs/reqtrace.RequestTrace) rides through record conversion
        (featurize), placement (route) and the batcher stages; the
        CALLER finishes it (finish_trace) so it can stamp its own
        serialize stage first."""
        cols = list(self.input_columns) + [
            c for c in (extra_columns or []) if c not in self.input_columns]

        def featurize():
            if isinstance(records, ColumnarData):
                from shifu_tpu.serve import wire

                return wire.conform_columns(records, cols)
            return records_to_columnar(records, cols)

        if trace is None:
            return self.submit(featurize()).wait(timeout)
        with trace.stage("featurize"):
            data = featurize()
        trace.annotate(rows=data.n_rows)
        t0 = time.perf_counter()
        req = self.submit(data, trace=trace)
        trace.add_stage("route", time.perf_counter() - t0, t0=t0)
        return req.wait(timeout)


def _merged_delta_histogram(replicas: Sequence[ScoringReplica]):
    """Fold every replica's staged-shadow score-delta histogram into one
    fleet histogram via the single exact merge primitive."""
    from shifu_tpu.loop.hotswap import SCORE_DELTA_BUCKETS
    from shifu_tpu.obs import registry
    from shifu_tpu.obs.metrics import Histogram

    reg = registry()
    merged = Histogram(SCORE_DELTA_BUCKETS)
    for rep in replicas:
        merged.merge(reg.histogram("serve.shadow.score_delta",
                                   buckets=SCORE_DELTA_BUCKETS,
                                   **rep.labels))
    return merged


def _reduce_shadow_stats(replicas: Sequence[ScoringReplica],
                         per: List[dict]) -> dict:
    """Merge per-replica shadow stats into the fleet verdict with one
    collective: stats stage per DEVICE (replicas sharing a device sum
    host-side first, exactly like per-shard partials), then one
    psum/pmax closes the fleet totals (parallel.mesh.fleet_reduce)."""
    from shifu_tpu.parallel.mesh import fleet_mesh, fleet_reduce

    # [batches, rows, agreeRows, errors, sumAbsDelta, maxAbsDelta]
    vec = {}
    order: List = []
    for rep, p in zip(replicas, per):
        row = np.asarray(
            [p["batches"], p["rows"], p["agreeRows"], p["errors"],
             p["meanAbsDelta"] * p["rows"], p["maxAbsDelta"]],
            dtype=np.float64)
        key = rep.device
        if key not in vec:
            vec[key] = row.copy()
            order.append(key)
        else:  # same device: host-side partial (max for the extremum)
            vec[key][:5] += row[:5]
            vec[key][5] = max(vec[key][5], row[5])
    parts = np.stack([vec[k] for k in order])
    mesh = fleet_mesh(len(order))
    total = fleet_reduce(mesh, parts, max_cols=1)
    batches, rows, agree, errors, sum_abs, max_abs = total
    rows_div = max(rows, 1.0)
    return {
        "batches": int(batches),
        "rows": int(rows),
        "agreeRows": int(agree),
        "errors": int(errors),
        "agreement": (agree / rows_div if rows else 0.0),
        "meanAbsDelta": (sum_abs / rows_div if rows else 0.0),
        "maxAbsDelta": float(max_abs),
    }
