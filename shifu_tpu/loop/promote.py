"""`shifu promote` — gate a candidate rollout on shadow agreement + drift.

The decision is computed from evidence, not vibes:

  gate "shadow"  the staged candidate's live shadow stats (agreement rate
                 over >= `-Dshifu.loop.promoteMinRows` rows must reach
                 `-Dshifu.loop.promoteAgree`, and shadow scoring must not
                 have errored). Against a RUNNING server the stats come
                 from GET /admin/shadow; offline they come from the last
                 serve manifest's shadow snapshot, so a canary verdict is
                 decidable from the run ledger alone.
  gate "drift"   the candidate must not be promoted while the ACTIVE set
                 shows no drift and the candidate brings nothing — wait,
                 inverted: drift on the active set is the reason TO roll
                 forward. The gate only BLOCKS when the ledger carries no
                 retrain recommendation AND the operator did not pass
                 --no-drift-gate/--force; a recommendation manifest (or a
                 live degraded /healthz with a psi reason) satisfies it.

Every run writes a `promote-<seq>.json` ledger manifest with the gate
evidence and the decision — promoted or held, the audit trail exists.

Execution: with `--serve-url` the promotion is a POST /admin/promote
(zero-downtime hot-swap in the running server); without one it is an
offline atomic dir swap: `models/` -> `models.previous/`, candidate ->
`models/` (os.replace-based, torn-state-proof via a rename sequence that
always leaves a loadable models dir).
"""

from __future__ import annotations

import json
import os
import urllib.request
from typing import Optional

from shifu_tpu.loop import (
    promote_agree_setting,
    promote_min_rows_setting,
)
from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)


def _http_json(url: str, payload: Optional[dict] = None,
               timeout: float = 30.0) -> dict:
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(
        url, data=data,
        headers={"Content-Type": "application/json"} if data else {},
        method="POST" if data is not None else "GET")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def latest_recommendation(root: str) -> Optional[dict]:
    """Newest retrain recommendation manifest, if the drift monitor ever
    stamped one."""
    from shifu_tpu.obs.ledger import list_runs

    runs = list_runs(root, last=1, step="recommend")
    return runs[0] if runs else None


def latest_serve_shadow(root: str) -> Optional[dict]:
    """Shadow snapshot from the newest serve manifest (the offline
    evidence path)."""
    from shifu_tpu.obs.ledger import list_runs

    for m in list_runs(root, step="serve"):
        shadow = (m.get("serve") or {}).get("shadow")
        if shadow:
            return shadow
    return None


def retrain_lineage(root: str, candidate_sha: Optional[str]) -> Optional[dict]:
    """Serve -> train -> promote lineage for the promote manifest: the
    retrain manifest that produced this candidate (matched by candidate
    model-set sha; newest retrain when the sha is unknown) plus the
    traffic-log trace evidence it recorded — so a promoted rollout
    points back at the exact request traces it was trained on."""
    from shifu_tpu.obs.ledger import list_runs

    for m in list_runs(root, step="retrain"):
        rt = m.get("retrain") or {}
        cand = (rt.get("candidate") or {}).get("modelSetSha")
        if candidate_sha is not None and cand != candidate_sha:
            continue
        return {
            "retrainManifest": os.path.basename(m.get("path", "")),
            "parentModelSetSha": (rt.get("parent") or {}).get(
                "modelSetSha"),
            "candidateModelSetSha": cand,
            "source": (rt.get("source") or {}).get("kind"),
            "traffic": rt.get("lineage"),
        }
    return None


def evaluate_gates(shadow: Optional[dict], recommendation: Optional[dict],
                   agree_min: Optional[float] = None,
                   min_rows: Optional[int] = None,
                   require_drift: bool = True,
                   candidate_sha: Optional[str] = None,
                   active_sha: Optional[str] = None) -> dict:
    """Pure gate evaluation — the piece tests pin. Returns
    {promote: bool, gates: {...}} with one entry per gate and a reason
    for every failure.

    `candidate_sha` binds the shadow evidence to the candidate actually
    being promoted — agreement earned by a previously staged set must
    not green-light a different one. `active_sha` binds the drift gate
    to the CURRENT active set: a recommendation stamped against an
    older sha is stale (that drift was already acted on, or the set was
    replaced some other way) and blocks rather than passes. Either
    check is skipped when its sha is unknown (None)."""
    agree_min = (promote_agree_setting() if agree_min is None
                 else float(agree_min))
    min_rows = (promote_min_rows_setting() if min_rows is None
                else int(min_rows))
    gates = {}

    if shadow is None:
        gates["shadow"] = {"ok": False,
                           "reason": "no shadow stats (stage the "
                                     "candidate and let it see traffic)"}
    elif (candidate_sha and shadow.get("sha")
          and shadow["sha"] != candidate_sha):
        gates["shadow"] = {"ok": False,
                           "reason": f"shadow evidence describes "
                                     f"{shadow['sha']}, not the candidate "
                                     f"{candidate_sha} — stage THIS "
                                     "candidate and let it see traffic",
                           "stats": shadow}
    elif shadow.get("errors"):
        gates["shadow"] = {"ok": False,
                           "reason": f"shadow scoring errored "
                                     f"{shadow['errors']} time(s)",
                           "stats": shadow}
    elif shadow.get("rows", 0) < min_rows:
        gates["shadow"] = {"ok": False,
                           "reason": f"only {shadow.get('rows', 0)} shadow "
                                     f"rows (< {min_rows})",
                           "stats": shadow}
    elif shadow.get("agreement", 0.0) < agree_min:
        gates["shadow"] = {"ok": False,
                           "reason": f"agreement "
                                     f"{shadow.get('agreement', 0.0):.4f} "
                                     f"< {agree_min:g}",
                           "stats": shadow}
    else:
        gates["shadow"] = {"ok": True, "stats": shadow}

    if not require_drift:
        gates["drift"] = {"ok": True, "reason": "gate disabled"}
    elif recommendation is None:
        gates["drift"] = {"ok": False,
                          "reason": "no retrain recommendation in the "
                                    "ledger — nothing says the active set "
                                    "needs replacing (--no-drift-gate to "
                                    "override)"}
    else:
        rec = recommendation.get("recommendation", {})
        rec_summary = {
            "driftedColumns": (rec.get("drift") or {}).get(
                "driftedColumns"),
            "maxPsi": (rec.get("drift") or {}).get("maxPsi"),
            "modelSetSha": rec.get("modelSetSha"),
        }
        if (active_sha and rec.get("modelSetSha")
                and rec["modelSetSha"] != active_sha):
            gates["drift"] = {
                "ok": False,
                "reason": f"newest retrain recommendation targets sha "
                          f"{rec['modelSetSha']} but the active set is "
                          f"{active_sha} — that drift was already acted "
                          "on; nothing says the CURRENT set needs "
                          "replacing (--no-drift-gate to override)",
                "recommendation": rec_summary,
            }
        else:
            gates["drift"] = {"ok": True, "recommendation": rec_summary}
    return {"promote": all(g["ok"] for g in gates.values()),
            "gates": gates,
            "agreeMin": agree_min, "minRows": min_rows}


def _models_sha(models_dir: Optional[str]) -> Optional[str]:
    """Content sha of a model dir — the exact identity the registry
    serves under — or None when there is no readable model set there."""
    from shifu_tpu.serve.registry import find_model_paths, model_set_sha

    if not models_dir or not os.path.isdir(models_dir):
        return None
    try:
        paths = find_model_paths(models_dir)
        return model_set_sha(paths) if paths else None
    except OSError:
        return None


def offline_swap(root: str, candidate_dir: str) -> dict:
    """Atomic-enough dir swap for a non-running model set: the current
    `models/` moves aside to `models.previous/`, the candidate renames
    into place. Both moves are single `os.replace`/`os.rename` calls, so
    a kill leaves either the old or the new layout with a loadable
    models dir recoverable by hand — never merged halves."""
    import shutil

    models = os.path.join(os.path.abspath(root), "models")
    previous = models + ".previous"
    candidate_dir = os.path.abspath(candidate_dir)
    if not os.path.isdir(candidate_dir):
        raise FileNotFoundError(f"candidate dir {candidate_dir} not found")
    if os.path.isdir(previous):
        shutil.rmtree(previous)
    if os.path.isdir(models):
        os.rename(models, previous)
    os.rename(candidate_dir, models)
    return {"models": models, "previous": previous}


def run_promote(root: str, candidate_dir: Optional[str],
                serve_url: Optional[str] = None,
                agree_min: Optional[float] = None,
                min_rows: Optional[int] = None,
                require_drift: bool = True,
                force: bool = False,
                stage_first: bool = False) -> int:
    """The `shifu promote` entry point. Returns the process exit code:
    0 promoted, 1 held by a gate, 2 operational error."""
    import sys
    import time

    from shifu_tpu import obs
    from shifu_tpu.obs.ledger import RunLedger

    t0 = time.time()
    shadow = None
    active_sha = None
    mode = "http" if serve_url else "offline"
    try:
        if serve_url:
            serve_url = serve_url.rstrip("/")
            if stage_first and candidate_dir:
                _http_json(f"{serve_url}/admin/stage",
                           {"modelsDir": os.path.abspath(candidate_dir)})
            resp = _http_json(f"{serve_url}/admin/shadow")
            shadow = resp.get("shadow")
            active_sha = resp.get("active")
        else:
            shadow = latest_serve_shadow(root)
            active_sha = _models_sha(os.path.join(os.path.abspath(root),
                                                  "models"))
    except (OSError, ValueError) as e:  # unreachable server / bad JSON
        log.error("promote: cannot reach shadow stats: %s", e)
        return 2
    recommendation = latest_recommendation(root)
    # resolved BEFORE any swap: offline_swap renames the candidate dir
    # into models/, after which the sha (and therefore the lineage
    # match below) would be unrecoverable
    candidate_sha = _models_sha(candidate_dir)
    decision = evaluate_gates(shadow, recommendation,
                              agree_min=agree_min, min_rows=min_rows,
                              require_drift=require_drift,
                              candidate_sha=candidate_sha,
                              active_sha=active_sha)
    if force and not decision["promote"]:
        decision["forced"] = True
        decision["promote"] = True
    swap = None
    error = None
    if decision["promote"]:
        try:
            if serve_url:
                # bind the swap to the sha the gates evaluated: a
                # re-staged shadow between the gate read and this POST
                # is refused server-side (409), never rolled out blind
                swap = _http_json(f"{serve_url}/admin/promote",
                                  {"sha": (shadow or {}).get("sha")})
            else:
                if not candidate_dir:
                    raise ValueError(
                        "offline promote needs a candidate dir "
                        "(default models.candidate is missing)")
                swap = offline_swap(root, candidate_dir)
        except (OSError, ValueError) as e:  # failed swap: held + ledgered
            error = f"{type(e).__name__}: {e}"
            decision["promote"] = False
    # the audit trail: every promote attempt is a ledger manifest,
    # carrying the serve->train lineage of the candidate it gated
    try:
        lineage = retrain_lineage(root, candidate_sha)
    except (OSError, ValueError) as e:
        log.warning("promote: cannot resolve retrain lineage: %s", e)
        lineage = None
    try:
        ledger = RunLedger(root)
        seq = ledger.next_seq("promote")
        path = ledger.write(
            "promote", seq,
            status="ok" if error is None else "failed",
            exit_status=0 if decision["promote"] else 1,
            started_at=t0, elapsed_seconds=time.time() - t0,
            argv=list(sys.argv), registry=obs.registry(),
            error=error,
            extra={"promote": {"mode": mode,
                               "candidateDir": candidate_dir,
                               "decision": decision,
                               "lineage": lineage,
                               "swap": swap}},
        )
        log.info("promote manifest -> %s", path)
    except OSError as e:
        log.warning("cannot write promote manifest: %s", e)
    if error:
        log.error("promote failed: %s", error)
        return 2
    if not decision["promote"]:
        for name, g in decision["gates"].items():
            if not g["ok"]:
                log.error("promote held by %s gate: %s", name, g["reason"])
        return 1
    log.info("promoted: %s", swap)
    return 0
