"""Span tracing: nested wall-clock spans serialized as a Chrome trace.

`with tracer.span("stats.pass2", rows=n):` records start/end/duration and
attributes; the collected events serialize to the Chrome-trace JSON format
(`chrome://tracing` / Perfetto "traceEvents" with ph="X" complete events),
one file per lifecycle step next to the run manifest (obs/ledger.py).

Thread-safe: the streaming pipeline's prefetch worker opens spans on its own
thread; events carry the recording thread id so overlap between the parse
thread and the device thread is visible as parallel tracks.
"""

from __future__ import annotations

import json
import os
import threading
import time

from shifu_tpu.analysis.racetrack import tracked_lock
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, Optional


class Tracer:
    def __init__(self) -> None:
        self._lock = tracked_lock("obs.tracing")
        self._events: List[dict] = []
        self._local = threading.local()
        # one wall-clock anchor so perf_counter offsets render as absolute-ish
        self._t0 = time.perf_counter()

    def _stack(self) -> List[str]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = []
            self._local.stack = st
        return st

    def current_path(self) -> str:
        """Dotted path of the innermost open span on this thread ("" if none)."""
        return "/".join(self._stack())

    @contextmanager
    def span(self, name: str, **attrs: Any) -> Iterator[Dict[str, Any]]:
        """Record a nested span; yields the mutable attrs dict so callers can
        attach results discovered mid-span (row counts, output paths)."""
        stack = self._stack()
        stack.append(name)
        args = dict(attrs)
        t0 = time.perf_counter()
        try:
            yield args
        finally:
            t1 = time.perf_counter()
            stack.pop()
            event = {
                "name": name,
                "ph": "X",
                "ts": (t0 - self._t0) * 1e6,  # Chrome trace wants microseconds
                "dur": (t1 - t0) * 1e6,
                "pid": os.getpid(),
                "tid": threading.get_ident(),
                "args": {k: _jsonable(v) for k, v in args.items()},
            }
            if stack:
                event["args"]["parent"] = "/".join(stack)
            with self._lock:
                self._events.append(event)

    @property
    def events(self) -> List[dict]:
        with self._lock:
            return [dict(e) for e in self._events]

    def span_seconds(self, name: str) -> float:
        """Total recorded duration of all spans with this name (seconds)."""
        with self._lock:
            return sum(e["dur"] for e in self._events
                       if e["name"] == name) / 1e6

    def to_chrome_trace(self) -> dict:
        with self._lock:
            events = sorted(self._events, key=lambda e: e["ts"])
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def save(self, path: str) -> Optional[str]:
        """Write the Chrome-trace JSON; returns the path (None if no spans)."""
        with self._lock:
            if not self._events:
                return None
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as fh:
            json.dump(self.to_chrome_trace(), fh)
        return path


def _jsonable(v: Any) -> Any:
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)
