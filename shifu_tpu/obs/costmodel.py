"""Per-chip peak table + roofline math for the program profiler.

XLA's `cost_analysis()` says what a program *does* (FLOPs, bytes moved);
this module says what the chip *could* do (peak dense-matmul FLOP/s, peak
HBM bandwidth), so the profiler (obs/profile.py) can turn raw counts into
achieved-vs-peak utilization (MFU), arithmetic intensity, and a roofline
verdict: a program whose FLOPs-per-byte sits below the chip's machine
balance is memory-bound — more MXU efficiency cannot speed it up, only
fewer bytes can (the classic Williams/Waterman/Patterson roofline model).

Peaks are public per-chip numbers (bf16 dense matmul TFLOP/s, HBM GB/s),
matched by `device_kind` substring. The CPU entry is a NOMINAL figure so
dev-harness rooflines classify sensibly; treat CPU MFU as relative only.

Override knobs (for unlisted chips or corrected figures):
    -Dshifu.profile.peakTflops=<float>   peak dense TFLOP/s
    -Dshifu.profile.peakGBs=<float>      peak memory bandwidth GB/s
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Optional


class ChipPeaks(NamedTuple):
    """Peak envelope of one accelerator chip."""

    name: str
    kind: str            # raw jax device_kind (or "" when undetected)
    peak_tflops: float   # dense matmul TFLOP/s (bf16 for TPUs)
    peak_hbm_gbs: float  # memory bandwidth GB/s
    source: str          # "table" | "override" | "nominal"

    @property
    def machine_balance(self) -> float:
        """FLOPs per byte at the roofline ridge point."""
        return (self.peak_tflops * 1e12) / (self.peak_hbm_gbs * 1e9)


# device_kind substring -> (display name, peak bf16 TFLOP/s, HBM GB/s).
# Order matters: first substring match wins ("v5 lite" before "v5").
CHIP_TABLE = (
    ("v5 lite", ("TPU v5e", 197.0, 819.0)),
    ("v5e", ("TPU v5e", 197.0, 819.0)),
    ("v5p", ("TPU v5p", 459.0, 2765.0)),
    ("v6", ("TPU v6e", 918.0, 1640.0)),  # Trillium
    ("v4", ("TPU v4", 275.0, 1228.0)),
    ("v3", ("TPU v3", 123.0, 900.0)),
    ("v2", ("TPU v2", 45.0, 700.0)),
)

# Dev-harness nominal: a few AVX cores' worth of f32 matmul and one DDR
# channel-ish of bandwidth. Roofline classification stays meaningful;
# absolute CPU MFU is not a benchmark number.
CPU_NOMINAL = ("CPU (nominal)", 0.25, 25.0)


def lookup(kind: str) -> Optional[ChipPeaks]:
    """Table entry for a device_kind string, or None if unlisted."""
    low = (kind or "").lower()
    for key, (name, tflops, gbs) in CHIP_TABLE:
        if key in low:
            return ChipPeaks(name, kind, tflops, gbs, "table")
    return None


def _overridden(peaks: ChipPeaks) -> ChipPeaks:
    from shifu_tpu.utils import environment

    tflops = environment.get_float("shifu.profile.peakTflops", 0.0)
    gbs = environment.get_float("shifu.profile.peakGBs", 0.0)
    if tflops <= 0.0 and gbs <= 0.0:
        return peaks
    return ChipPeaks(
        peaks.name,
        peaks.kind,
        tflops if tflops > 0.0 else peaks.peak_tflops,
        gbs if gbs > 0.0 else peaks.peak_hbm_gbs,
        "override",
    )


def detect() -> ChipPeaks:
    """Peaks for the current jax backend (override > table > nominal).
    Never raises: an uninitializable jax yields the nominal CPU entry."""
    kind = ""
    try:
        import jax

        devices = jax.devices()
        kind = getattr(devices[0], "device_kind", "") if devices else ""
    except Exception:  # any jax import/init failure -> nominal CPU entry
        kind = ""
    entry = lookup(kind)
    if entry is None:
        name, tflops, gbs = CPU_NOMINAL
        entry = ChipPeaks(name, kind, tflops, gbs, "nominal")
    return _overridden(entry)


def roofline_verdict(flops: float, bytes_accessed: float,
                     peaks: ChipPeaks) -> Optional[str]:
    """Static classification from arithmetic intensity vs machine balance
    (needs no timing, so it holds for async-dispatched programs too)."""
    if not bytes_accessed or flops is None:
        return None
    ai = flops / bytes_accessed
    return "compute-bound" if ai >= peaks.machine_balance else "memory-bound"


def derive(flops: Optional[float], bytes_accessed: Optional[float],
           device_seconds: Optional[float],
           peaks: ChipPeaks) -> Dict[str, Optional[float]]:
    """Achieved-vs-peak numbers for one program (or a totals row).
    Timing-dependent fields are None when `device_seconds` is falsy."""
    out: Dict[str, Optional[float]] = {
        "arithmeticIntensity": None,
        "achievedTflops": None,
        "achievedGBps": None,
        "mfu": None,
        "membw": None,
        "roofline": None,
    }
    if flops is None:
        return out
    if bytes_accessed:
        out["arithmeticIntensity"] = round(flops / bytes_accessed, 4)
        out["roofline"] = roofline_verdict(flops, bytes_accessed, peaks)
    if device_seconds and device_seconds > 0.0:
        tflops = flops / device_seconds / 1e12
        out["achievedTflops"] = round(tflops, 6)
        out["mfu"] = round(tflops / peaks.peak_tflops, 6)
        if bytes_accessed:
            gbps = bytes_accessed / device_seconds / 1e9
            out["achievedGBps"] = round(gbps, 4)
            out["membw"] = round(gbps / peaks.peak_hbm_gbs, 6)
    return out


def peaks_dict(peaks: ChipPeaks) -> dict:
    """JSON form embedded in profile snapshots/manifests."""
    return {
        "name": peaks.name,
        "deviceKind": peaks.kind,
        "peakTflops": peaks.peak_tflops,
        "peakHbmGBs": peaks.peak_hbm_gbs,
        "machineBalance": round(peaks.machine_balance, 4),
        "source": peaks.source,
    }
