"""Dynamic micro-batching: coalesce concurrent requests into one dispatch.

Single-record dispatches waste the accelerator (a 1-row matmul costs the
same launch overhead as a 1024-row one); unbounded batching wastes the
client's latency budget. The batcher sits between the admission queue and
the fused registry program and closes each batch by one of two policies
(`shifu.serve.batching`):

  continuous (default) — in-flight admission: requests coalesce in the
      admission queue WHILE the previous dispatch is on device, and the
      bucket closes on capacity (`shifu.serve.maxBatchRows`) or the
      instant the queue runs dry — never on a wall clock. An idle
      replica dispatches a lone request immediately instead of parking
      it `maxWaitMs` hoping for company, so p99 under load stops paying
      the coalesce deadline: the previous dispatch's device time IS the
      coalescing window.
  barrier — the pre-fleet policy, kept for comparison benches and
      deployments that want a minimum coalesce window:

      * row cap       shifu.serve.maxBatchRows (default 1024)
      * wait deadline shifu.serve.maxWaitMs    (default 2.0 ms after the
                      batch's FIRST request arrives)

Coalesced rows concatenate into one raw batch, score in one fused
dispatch (the registry pads to the power-of-two row bucket, so compile
count stays bounded whatever sizes traffic produces — continuous
buckets close ragged and pad to the same power-of-two shapes), and the
result is sliced back per request — padding rows belong to the
registry, request boundaries to the batcher, and neither leaks into the
other.

Fleet context (serve/fleet.py): one batcher serves one replica. `labels`
(typically {"replica": "0"}) ride every serve.* metric the batcher
records, and `expected_wait`/`drain_stats` expose the observed drain
rate the DrainAwareRouter places micro-batches by.

One worker thread keeps ordering FIFO and the device queue depth at one
batch; requests resolve through a per-request event (`ScoreRequest.wait`).

Self-healing (resilience layer): the worker runs under a supervisor —
an unexpected crash disposes of the in-flight batch's requests
INDIVIDUALLY through the fleet failover hook when one is wired (each
rider replays on a healthy replica, or gets an explicit error once the
budget is spent; standalone batchers answer with the error directly —
either way never a hang), preserves the admission queue, and restarts
the worker up to `shifu.serve.maxWorkerRestarts` times (health flips to
`degraded` until clean batches accumulate). Every batch outcome is also
reported to the replica's circuit breaker (`serve/health.py`): repeated
dispatch failures quarantine the replica out of the routing set
entirely — the failure domain worker restarts cannot heal. Every admitted request also carries a
deadline (`shifu.serve.deadlineMs`): a request that outlives it is shed
with an explicit error before dispatch instead of wasting a wedged
backend's time. The observed drain rate feeds the 429 Retry-After hint
(`retry_after_seconds`, exported as the `serve.retry_after_seconds`
gauge).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from shifu_tpu.analysis.racetrack import tracked_lock
from shifu_tpu.data.reader import ColumnarData
from shifu_tpu.eval.scorer import ScoreResult
from shifu_tpu.serve.health import HealthMonitor
from shifu_tpu.serve.queue import AdmissionQueue
from shifu_tpu.utils import environment
from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)

DEFAULT_MAX_BATCH_ROWS = 1024
DEFAULT_MAX_WAIT_MS = 2.0
DEFAULT_MAX_WORKER_RESTARTS = 5
DEFAULT_DEADLINE_MS = 30_000.0
BATCHING_CONTINUOUS = "continuous"
BATCHING_BARRIER = "barrier"
# Retry-After clamp: never tell a client "come back immediately" while
# shedding, never park it longer than half a minute on a stale estimate
RETRY_AFTER_MIN_S = 1.0
RETRY_AFTER_MAX_S = 30.0
DRAIN_WINDOW_S = 10.0

# Exponential histogram edges, pinned (tests/test_serve.py). The metrics
# registry's DEFAULT_BUCKETS start at 5 ms — useless for a path whose p99
# is single-digit milliseconds: every observation landed in the first two
# buckets and the exported quantiles collapsed. Doubling edges from 100 µs
# give ~equal relative resolution from sub-ms latencies to multi-second
# stalls.
LATENCY_BUCKETS = tuple(0.0001 * 2 ** k for k in range(16)) + (float("inf"),)
# batch sizes are power-of-two-ish by construction (row buckets), so the
# edges are exact powers of two up to the 8192 cap ambit
BATCH_ROWS_BUCKETS = tuple(float(2 ** k) for k in range(14)) + (float("inf"),)


def max_batch_rows_setting() -> int:
    return environment.get_int("shifu.serve.maxBatchRows",
                               DEFAULT_MAX_BATCH_ROWS)


def max_wait_ms_setting() -> float:
    raw = environment.get_property("shifu.serve.maxWaitMs", "")
    try:
        return float(raw) if raw else DEFAULT_MAX_WAIT_MS
    except ValueError:
        return DEFAULT_MAX_WAIT_MS


def max_worker_restarts_setting() -> int:
    return environment.get_int("shifu.serve.maxWorkerRestarts",
                               DEFAULT_MAX_WORKER_RESTARTS)


def batching_setting() -> str:
    """shifu.serve.batching — continuous (close buckets on capacity or
    queue-dry, never a wall clock) | barrier (the maxWaitMs coalesce
    deadline). Unknown values fall back to continuous."""
    raw = environment.get_property("shifu.serve.batching", "").strip()
    return (BATCHING_BARRIER if raw.lower() == BATCHING_BARRIER
            else BATCHING_CONTINUOUS)


def deadline_ms_setting() -> float:
    """shifu.serve.deadlineMs — per-request budget from admission to
    dispatch (0 disables). A request older than this is shed with an
    explicit error instead of being scored for a client that gave up."""
    raw = environment.get_property("shifu.serve.deadlineMs", "")
    try:
        return float(raw) if raw else DEFAULT_DEADLINE_MS
    except ValueError:
        return DEFAULT_DEADLINE_MS


class DeadlineExceededError(TimeoutError):
    """The request outlived shifu.serve.deadlineMs before dispatch."""


class ScoreRequest:
    """One admitted request: a raw columnar slice plus its completion.

    `trace` (obs/reqtrace.RequestTrace, optional) rides along so the
    batcher can stamp the queue-wait / coalesce-wait stages and fan the
    batch-level featurize/device/d2h durations out per request."""

    __slots__ = ("data", "n_rows", "enqueued_at", "popped_at", "deadline",
                 "_done", "result", "error", "trace", "failovers",
                 "wire_format")

    def __init__(self, data: ColumnarData,
                 deadline_s: Optional[float] = None,
                 trace=None) -> None:
        self.data = data
        self.n_rows = data.n_rows
        # which wire format carried this request (serve/wire.py stamps
        # "binary" on decoded batches; everything else is "json") — the
        # format= label on serve.requests / serve.latency_seconds
        self.wire_format = getattr(data, "wire_format", "json")
        self.enqueued_at = time.perf_counter()
        self.popped_at = self.enqueued_at
        self.deadline = (self.enqueued_at + deadline_s
                         if deadline_s else None)
        self._done = threading.Event()
        self.result: Optional[ScoreResult] = None
        self.error: Optional[BaseException] = None
        self.trace = trace
        # times this request was replayed on another replica after its
        # batch failed (fleet failover; bounded by the failover budget).
        # Scoring is pure, so a replay can never double-answer — resolve
        # and fail go through the same one-shot event either way.
        self.failovers = 0

    def expired(self, now: Optional[float] = None) -> bool:
        return (self.deadline is not None
                and (now or time.perf_counter()) > self.deadline)

    def resolve(self, result: ScoreResult) -> None:
        self.result = result
        self._done.set()

    def fail(self, error: BaseException) -> None:
        self.error = error
        self._done.set()

    def wait(self, timeout: Optional[float] = None) -> ScoreResult:
        if not self._done.wait(timeout):
            raise TimeoutError("score request did not complete in time")
        if self.error is not None:
            raise self.error
        return self.result


def _concat_batches(datas: Sequence[ColumnarData]) -> ColumnarData:
    if len(datas) == 1:
        return datas[0]
    names = datas[0].names
    raw = {}
    for name in names:
        typed = [d.typed_column(name) for d in datas]
        if (typed[0] is not None
                and all(t is not None and t.dtype == typed[0].dtype
                        for t in typed)):
            # every rider delivered this column typed (binary wire or
            # typed JSON) with one dtype: the coalesced batch stays
            # typed and the featurizer never parses a string for it.
            # Mixed dtypes (an i64 rider next to an f64 one) fall to
            # strings below — promoting i64 would print "3" as "3.0"
            # and shift its categorical identity.
            raw[name] = np.concatenate(typed)
        else:
            raw[name] = np.concatenate([
                np.asarray(d.column(name), dtype=object) for d in datas])
    return ColumnarData(names=list(names), raw=raw,
                        n_rows=sum(d.n_rows for d in datas),
                        missing_values=datas[0].missing_values)


def _note_popped(req: ScoreRequest) -> None:
    """Stamp the queue-wait stage the moment a request leaves the
    admission queue (enqueue -> worker pop)."""
    now = time.perf_counter()
    req.popped_at = now
    if req.trace is not None:
        req.trace.add_stage("queue", now - req.enqueued_at,
                            t0=req.enqueued_at)


def _slice_result(res: ScoreResult, start: int, stop: int) -> ScoreResult:
    return ScoreResult(
        model_scores=res.model_scores[start:stop],
        mean=res.mean[start:stop],
        max=res.max[start:stop],
        min=res.min[start:stop],
        median=res.median[start:stop],
        model_names=res.model_names,
        model_widths=res.model_widths,
    )


class MicroBatcher:
    """Admission-queue consumer: coalesce -> score -> fan results out,
    supervised — a crashed scoring worker restarts (bounded) with the
    queue preserved and the in-flight batch failed request-by-request."""

    def __init__(self, score_fn: Callable[[ColumnarData], ScoreResult],
                 admission: AdmissionQueue,
                 max_batch_rows: Optional[int] = None,
                 max_wait_ms: Optional[float] = None,
                 health: Optional[HealthMonitor] = None,
                 max_restarts: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 observer: Optional[Callable[[ColumnarData, ScoreResult],
                                             None]] = None,
                 batching: Optional[str] = None,
                 labels: Optional[dict] = None,
                 breaker=None) -> None:
        self.score_fn = score_fn
        self.admission = admission
        # device-dispatch circuit breaker (serve/health.CircuitBreaker),
        # owned by the replica: every batch outcome is reported so
        # repeated dispatch failures quarantine the replica
        self.breaker = breaker
        # fleet failover hook, assigned by ReplicaFleet after
        # construction: called with (request, error) when a batch fails —
        # replays the request on a healthy replica or fails it under the
        # bounded per-request budget. None = fail directly (standalone
        # batchers outside a fleet).
        self.failover: Optional[Callable[[ScoreRequest, BaseException],
                                         None]] = None
        # metric identity: the fleet passes {"replica": "<i>"} so every
        # serve.* sample this batcher records is attributable to its
        # replica on one shared /metrics page
        self.labels = dict(labels or {})
        try:
            self._replica_index: Optional[int] = int(
                self.labels["replica"])
        except (KeyError, ValueError):
            # no replica identity: per-replica fault targeting
            # (`seam@replica=N`) can't match this batcher's events
            self._replica_index = None
        self.batching = batching_setting() if batching is None else (
            BATCHING_BARRIER if str(batching).lower() == BATCHING_BARRIER
            else BATCHING_CONTINUOUS)
        # post-resolution hook: runs AFTER every request in the batch has
        # its answer, so traffic logging / shadow scoring / drift checks
        # (the continuous-loop seams) never add to client latency. An
        # observer crash is contained — it fails no request.
        self.observer = observer
        self.health = health if health is not None else HealthMonitor()
        self.max_batch_rows = (max_batch_rows_setting()
                               if max_batch_rows is None
                               else int(max_batch_rows))
        self.max_wait_s = (max_wait_ms_setting()
                           if max_wait_ms is None
                           else float(max_wait_ms)) / 1000.0
        self.max_restarts = (max_worker_restarts_setting()
                             if max_restarts is None else int(max_restarts))
        self.deadline_s = ((deadline_ms_setting()
                            if deadline_ms is None else float(deadline_ms))
                           / 1000.0)
        self.restarts = 0
        self._inflight: Optional[List[ScoreRequest]] = None
        self._drained = threading.Event()  # set on clean drain OR give-up
        # (t_done, n_requests) per completed batch; the lock covers the
        # worker's append racing retry_after_seconds() on handler threads
        self._drain_log: deque = deque(maxlen=64)
        self._drain_lock = tracked_lock("serve.batcher.drain_log")
        self._worker = self._spawn()

    def _spawn(self) -> threading.Thread:
        worker = threading.Thread(target=self._run,
                                  name="shifu-serve-batcher",
                                  daemon=True)
        worker.start()
        return worker

    def submit(self, data: ColumnarData, trace=None) -> ScoreRequest:
        """Admit one request (raises queue.RejectedError on shed)."""
        req = ScoreRequest(data, deadline_s=self.deadline_s or None,
                           trace=trace)
        self.admission.put(req)
        return req

    def _dispose(self, req: ScoreRequest, error: BaseException) -> None:
        """A request whose batch failed: hand it to the fleet failover
        (replay on a healthy replica, budget-bounded) or answer it with
        the error — never leave it unanswered."""
        fo = self.failover
        if fo is None:
            req.fail(error)
            return
        try:
            fo(req, error)
        except Exception as fe:  # failover trouble must still answer
            log.warning("failover handler failed: %s", fe)
            req.fail(error)

    def join(self, timeout: Optional[float] = None) -> None:
        """Wait for drain: meaningful only after admission.close().
        Event-based, not thread-based — the worker thread may have been
        replaced by the supervisor since this batcher was built."""
        self._drained.wait(timeout)

    @property
    def draining(self) -> bool:
        return self.admission.closed and not self._drained.is_set()

    # ---- supervisor ----
    def _run(self) -> None:
        from shifu_tpu.obs import registry

        try:
            self._loop()
            self._drained.set()  # clean drain (queue closed and empty)
            return
        except BaseException as e:  # supervisor: ANY worker death (incl.
            # injected faults and non-Exception crashes) must be survived
            reg = registry()
            reg.counter("serve.worker.crashes", **self.labels).inc()
            log.warning("serve scoring worker crashed: %s: %s",
                        type(e).__name__, e)
            # the batch being scored when the worker died: every request
            # gets an individual answer — failed over to a healthy
            # replica when a fleet is around it, an error response when
            # not; crashed != hung either way
            inflight, self._inflight = self._inflight, None
            err = RuntimeError(f"scoring worker crashed mid-batch: {e}")
            for r in inflight or []:
                self._dispose(r, err)
            if self.breaker is not None and inflight:
                # a crash WITH a batch in flight is a dispatch failure:
                # the device (or the program around it) ate the batch
                self.breaker.note_failure(
                    f"worker crash: {type(e).__name__}")
            self.health.note_crash(
                f"scoring worker crashed: {type(e).__name__}")
            if self.restarts >= self.max_restarts:
                log.error("serve worker restart budget (%d) exhausted; "
                          "draining", self.max_restarts)
                self.health.set_draining("worker restart budget exhausted")
                self.admission.close()
                # answer everything still queued — zero requests may be
                # left admitted-but-unanswered (in a fleet the backlog
                # fails over to the surviving replicas)
                drain_err = RuntimeError(
                    "scoring worker unavailable (restart budget "
                    "exhausted)")
                while True:
                    req = self.admission.get(timeout=0)
                    if req is None:
                        break
                    self._dispose(req, drain_err)
                self._drained.set()
                return
            self.restarts += 1
            reg.counter("serve.worker.restarts", **self.labels).inc()
            log.info("restarting serve scoring worker (%d/%d)",
                     self.restarts, self.max_restarts)
            self._worker = self._spawn()

    def _gather(self) -> Optional[List[ScoreRequest]]:
        """Block for the next request, then coalesce into the bucket.
        None = queue closed and fully drained.

        Continuous mode: everything already queued joins (up to the row
        cap) and the bucket closes the instant the queue runs dry — the
        coalescing window was the previous dispatch's device time, and
        a lone request on an idle replica dispatches immediately.
        Barrier mode: the bucket additionally holds up to `maxWaitMs`
        after the FIRST request, the pre-fleet policy."""
        first = self.admission.get()
        if first is None:
            return None
        _note_popped(first)
        batch = [first]
        # register with the supervisor IMMEDIATELY (same list object, so
        # later appends are visible): a request popped from the queue is
        # answerable only through _inflight if this worker dies while
        # still coalescing
        self._inflight = batch
        rows = first.n_rows
        if self.batching == BATCHING_CONTINUOUS:
            while rows < self.max_batch_rows:
                nxt = self.admission.get(timeout=0)
                if nxt is None:
                    break  # capacity not hit but nothing is waiting NOW
                _note_popped(nxt)
                batch.append(nxt)
                rows += nxt.n_rows
            return batch
        deadline = time.perf_counter() + self.max_wait_s
        while rows < self.max_batch_rows:
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                break
            nxt = self.admission.get(timeout=remaining)
            if nxt is None:
                break
            _note_popped(nxt)
            batch.append(nxt)
            rows += nxt.n_rows
        return batch

    def _loop(self) -> None:
        from shifu_tpu.obs import registry, reqtrace
        from shifu_tpu.resilience import faults

        while True:
            batch = self._gather()
            if batch is None:
                return
            reg = registry()
            # deadline shed BEFORE dispatch: a request that outlived its
            # budget behind a wedged batch gets an explicit error now,
            # not a result its client stopped waiting for
            now = time.perf_counter()
            live: List[ScoreRequest] = []
            for r in batch:
                if r.expired(now):
                    reg.counter("serve.deadline.shed", **self.labels).inc()
                    r.fail(DeadlineExceededError(
                        "request exceeded shifu.serve.deadlineMs before "
                        "dispatch"))
                else:
                    live.append(r)
            batch = live
            if not batch:
                self._inflight = None
                continue
            # _inflight (registered in _gather) stays set until every
            # request in the batch has an answer: if anything below
            # escapes — e.g. the injected `serve` fault on the next line,
            # or any real crash outside the per-batch guard — the
            # supervisor (_run) reads it and fails each request
            # individually; a finally-clear would hide the batch from the
            # crash path. Re-point it at the post-shed batch (the live
            # set is the honest one; double-failing an already-shed
            # request is harmless).
            self._inflight = batch
            faults.fault_point("serve")
            rows = sum(r.n_rows for r in batch)
            # coalesce-wait closes here: pop -> dispatch is the time a
            # request spent waiting for its bucket to fill/close — the
            # convoy term the continuous-batching policy exists to bound
            dispatch_t = time.perf_counter()
            dispatch_unix = time.time()
            traced = [r for r in batch if r.trace is not None]
            replica = self.labels.get("replica", "0")
            for r in traced:
                r.trace.add_stage("coalesce", dispatch_t - r.popped_at,
                                  t0=r.popped_at)
                r.trace.annotate(replica=replica, batchRequests=len(batch),
                                 batchRows=rows)
            reg.counter("serve.batches", **self.labels).inc()
            reg.histogram(
                "serve.batch.rows", buckets=BATCH_ROWS_BUCKETS,
                **self.labels,
            ).observe(rows)
            try:
                # the registry notes featurize/device/d2h into the
                # thread-local capture; they fan out to every request
                # that rode the bucket (a batch-level stage IS each
                # rider's wait)
                with reqtrace.capture_stages(enabled=bool(traced)) as cap:
                    with reg.timer("serve.batch.score",
                                   **self.labels).time():
                        # the device_dead chaos seam: a persistent
                        # per-replica dispatch failure fires HERE, inside
                        # the per-batch guard — a failed batch, not a
                        # crashed worker (that is the `serve` seam above)
                        faults.fault_point("serve.dispatch",
                                           replica=self._replica_index)
                        concat = _concat_batches([r.data for r in batch])
                        result = self.score_fn(concat)
            except Exception as e:  # fan the failure out per request:
                # failover replays each rider on a healthy replica (or
                # answers it with the error), and the breaker counts the
                # dispatch failure toward quarantining this replica
                log.warning("serve batch of %d requests failed: %s",
                            len(batch), e)
                reg.counter("serve.batch.errors", **self.labels).inc()
                if self.breaker is not None:
                    self.breaker.note_failure(f"{type(e).__name__}: {e}")
                for r in batch:
                    self._dispose(r, e)
                self._inflight = None
                continue
            if cap:
                for stage, dur, t0 in cap.stages:
                    for r in traced:
                        r.trace.add_stage(stage, dur, t0)
                if cap.attrs:
                    # batch-level attributes (the scoring version's sha,
                    # from the SwappableRegistry swap point) annotate
                    # every rider — per-request version lineage that
                    # stays correct across a mid-roll promote
                    for r in traced:
                        r.trace.annotate(**cap.attrs)
            off = 0
            now = time.perf_counter()
            # per-request latency and count carry the wire-format label —
            # a coalesced batch can mix JSON and binary riders, so the
            # split happens here, per rider, not per batch
            lat_by_fmt: dict = {}
            n_by_fmt: dict = {}
            for r in batch:
                r.resolve(_slice_result(result, off, off + r.n_rows))
                off += r.n_rows
                fmt = r.wire_format
                lat = lat_by_fmt.get(fmt)
                if lat is None:
                    lat = reg.histogram("serve.latency_seconds",
                                        buckets=LATENCY_BUCKETS,
                                        format=fmt, **self.labels)
                    lat_by_fmt[fmt] = lat
                lat.observe(now - r.enqueued_at)
                n_by_fmt[fmt] = n_by_fmt.get(fmt, 0) + 1
            for fmt, cnt in n_by_fmt.items():
                reg.counter("serve.requests", format=fmt,
                            **self.labels).inc(cnt)
            reg.counter("serve.records", **self.labels).inc(rows)
            self._inflight = None
            with self._drain_lock:
                self._drain_log.append((now, len(batch)))
            self.health.note_ok()
            if self.breaker is not None:
                self.breaker.note_ok()
            if traced:
                # the convoy witness: which traces shared this bucket
                reqtrace.buffer().note_batch(
                    replica, [r.trace.trace_id for r in traced],
                    requests=len(batch), rows=rows,
                    started_unix=dispatch_unix,
                    dur_s=now - dispatch_t)
            if self.observer is not None:
                # every client already has its answer; the loop seams
                # (traffic log, shadow scoring, drift verdicts) run here
                # so they cost queue headroom, never request latency
                if traced:
                    # per-row trace ids ride the batch into the traffic
                    # log (serve -> retrain lineage); rows of un-traced
                    # requests log the empty token
                    concat.trace_ids = np.concatenate([
                        np.full(r.n_rows,
                                r.trace.trace_id if r.trace else "",
                                dtype=object)
                        for r in batch])
                try:
                    self.observer(concat, result)
                except Exception as oe:  # observers must not kill serving
                    log.warning("serve observer failed: %s", oe)
                    reg.counter("serve.observer.errors",
                                **self.labels).inc()

    # ---- load hints ----
    def drain_stats(self, now: Optional[float] = None
                    ) -> Tuple[int, Optional[float]]:
        """(queued requests, observed drain rate in requests/s over the
        last DRAIN_WINDOW_S, or None with no usable history) — the
        per-replica signal the DrainAwareRouter and the fleet Retry-After
        estimator both read. Rates count REQUESTS, not batches: queue
        depth counts requests, so a batches/s rate would overestimate
        the backlog by the coalescing factor."""
        if now is None:
            now = time.perf_counter()
        with self._drain_lock:
            drained = list(self._drain_log)
        recent = [(t, n) for t, n in drained if now - t <= DRAIN_WINDOW_S]
        # backlog = queued + the bucket currently on device: the router
        # must see a replica whose whole queue just moved into one
        # in-flight bucket as busy, not idle (bare read — _inflight is a
        # single reference the worker swaps, and an off-by-a-batch
        # estimate only shades the ranking)
        inflight = self._inflight
        depth = len(self.admission) + (len(inflight) if inflight else 0)
        if len(recent) >= 2:
            span = max(now - recent[0][0], 1e-3)
            return depth, sum(n for _, n in recent) / span
        return depth, None

    def expected_wait(self, now: Optional[float] = None) -> float:
        """Estimated seconds before a newly admitted request dispatches:
        backlog ÷ observed drain rate. With no drain history yet the raw
        backlog ranks the replica (0.0 for an idle one), which is all
        the router's RELATIVE placement needs."""
        depth, rate = self.drain_stats(now)
        if not depth:
            return 0.0
        if rate is None:
            return float(depth)
        return depth / max(rate, 1e-3)

    def retry_after_seconds(self) -> float:
        """429 Retry-After derived from the OBSERVED drain rate: queue
        depth ÷ recently drained requests/s, clamped — a loaded server
        tells clients how long the backlog actually is instead of a
        fixed hint. Exported as the `serve.retry_after_seconds` gauge.
        (The fleet-wide analog lives on ReplicaFleet: total backlog over
        the SUMMED per-replica drain rates.)"""
        from shifu_tpu.obs import registry

        depth, rate = self.drain_stats()
        if rate is not None:
            hint = depth / max(rate, 1e-3)
        else:
            hint = RETRY_AFTER_MIN_S  # no drain history: cheap optimism
        hint = min(max(hint, RETRY_AFTER_MIN_S), RETRY_AFTER_MAX_S)
        registry().gauge("serve.retry_after_seconds",
                         **self.labels).set(hint)
        return hint
