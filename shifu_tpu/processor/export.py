"""`shifu export` — PMML / columnstats / correlation / woemapping.

Parity: core/processor/ExportModelProcessor.java:70 (PMML :158-172,
columnstats / corr / woe-mapping exports).
"""

from __future__ import annotations

import json
import os

from shifu_tpu.processor.basic import BasicProcessor
from shifu_tpu.utils.errors import ErrorCode, ShifuError
from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)


class ExportProcessor(BasicProcessor):
    step = "export"

    def __init__(self, root: str = ".", kind: str = "pmml", concise: bool = False):
        super().__init__(root)
        self.kind = (kind or "pmml").lower()
        self.concise = concise

    def run_step(self) -> None:
        self.setup()
        self.paths.ensure(self.paths.export_dir())
        if self.kind == "pmml":
            self._export_pmml()
        elif self.kind in ("onebagging", "onebaggingpmml"):
            self._export_onebagging()
        elif self.kind == "columnstats":
            self._export_columnstats()
        elif self.kind in ("corr", "correlation"):
            self._export_correlation()
        elif self.kind in ("woemapping", "woe"):
            self._export_woemapping()
        else:
            raise ShifuError(ErrorCode.INVALID_MODEL_CONFIG,
                             f"unknown export type {self.kind}")

    def _export_pmml(self) -> None:
        from shifu_tpu.eval.scorer import find_model_paths
        from shifu_tpu.export.pmml import nn_to_pmml, tree_to_pmml
        from shifu_tpu.models.nn import NNModelSpec
        from shifu_tpu.models.tree import TreeModelSpec

        paths = [p for p in find_model_paths(self.paths.models_dir())
                 if p.endswith((".nn", ".lr", ".gbt", ".rf"))]
        if not paths:
            raise ShifuError(
                ErrorCode.MODEL_NOT_FOUND,
                "PMML export supports NN/LR/GBT/RF models; none under models/",
            )
        for i, p in enumerate(paths):
            if p.endswith((".gbt", ".rf")):
                spec = TreeModelSpec.load(p)
                xml = tree_to_pmml(spec,
                                   model_name=self.model_config.basic.name)
            else:
                spec = NNModelSpec.load(p)
                xml = nn_to_pmml(spec,
                                 model_name=self.model_config.basic.name)
            out = self.paths.pmml_path(i)
            with open(out, "w") as fh:
                fh.write(xml)
            log.info("PMML -> %s", out)

    def _export_onebagging(self) -> None:
        """One PMML document averaging every bagged model
        (ExportModelProcessor.java:173 one-bagging PMML)."""
        from shifu_tpu.eval.scorer import find_model_paths
        from shifu_tpu.export.pmml import bagged_to_pmml
        from shifu_tpu.models.nn import NNModelSpec
        from shifu_tpu.models.tree import TreeModelSpec

        paths = [p for p in find_model_paths(self.paths.models_dir())
                 if p.endswith((".nn", ".lr", ".gbt", ".rf"))]
        if not paths:
            raise ShifuError(
                ErrorCode.MODEL_NOT_FOUND,
                "one-bagging PMML needs NN/LR/GBT/RF models under models/",
            )
        # native specs only (reference-format files in models/ would sniff
        # into adapters the PMML writer cannot embed)
        specs = [
            TreeModelSpec.load(p) if p.endswith((".gbt", ".rf"))
            else NNModelSpec.load(p)
            for p in paths
        ]
        xml = bagged_to_pmml(specs, model_name=self.model_config.basic.name)
        out = os.path.join(self.paths.export_dir(), "model_onebagging.pmml")
        with open(out, "w") as fh:
            fh.write(xml)
        log.info("one-bagging PMML (%d models) -> %s", len(paths), out)

    def _export_columnstats(self) -> None:
        out = os.path.join(self.paths.export_dir(), "columnstats.csv")
        cols = [
            "columnNum", "columnName", "columnType", "finalSelect", "ks", "iv",
            "mean", "stdDev", "min", "max", "median", "missingPct",
            "distinctCount", "psi",
        ]
        with open(out, "w") as fh:
            fh.write(",".join(cols) + "\n")
            for c in self.column_configs:
                st = c.column_stats
                row = [
                    c.column_num, c.column_name,
                    c.column_type.value if c.column_type else "",
                    c.final_select, st.ks, st.iv, st.mean, st.std_dev,
                    st.min, st.max, st.median, st.missing_percentage,
                    st.distinct_count, st.psi,
                ]
                fh.write(",".join("" if v is None else str(v) for v in row) + "\n")
        log.info("column stats -> %s", out)

    def _export_correlation(self) -> None:
        src = self.paths.correlation_path()
        if not os.path.isfile(src):
            raise ShifuError(ErrorCode.DATA_NOT_FOUND,
                             "run `shifu stats -correlation` first")
        import shutil

        out = os.path.join(self.paths.export_dir(), "correlation.csv")
        shutil.copy(src, out)
        log.info("correlation -> %s", out)

    def _export_woemapping(self) -> None:
        out = os.path.join(self.paths.export_dir(), "woemapping.json")
        mapping = {}
        for c in self.column_configs:
            bn = c.column_binning
            if not bn.bin_count_woe:
                continue
            entry = {"woe": bn.bin_count_woe,
                     "weightedWoe": bn.bin_weighted_woe}
            if c.is_categorical():
                entry["categories"] = bn.bin_category
            else:
                entry["boundaries"] = [
                    str(b) if b in (float("-inf"), float("inf")) else b
                    for b in (bn.bin_boundary or [])
                ]
            mapping[c.column_name] = entry
        with open(out, "w") as fh:
            json.dump(mapping, fh, indent=2)
        log.info("woe mapping (%d columns) -> %s", len(mapping), out)
