"""Stats engine: orchestrates binning + one-pass jit aggregation, then writes
results back into the ColumnConfig list.

Pipeline parity with MapReducerStatsWorker.doStats
(core/processor/stats/MapReducerStatsWorker.java:105): purify -> sample ->
per-column bins -> bin-hit aggregation -> KS/IV/WOE -> ColumnConfig update.
"""

from __future__ import annotations

import math
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from shifu_tpu.config import ColumnConfig, ColumnType
from shifu_tpu.config.model_config import ModelConfig
from shifu_tpu.data.purify import combined_mask
from shifu_tpu.data.reader import ColumnarData, make_tags, make_weights
from shifu_tpu.ops.binagg import bin_aggregate_profiled
from shifu_tpu.stats.binning import (
    categorical_bin_index,
    categorical_bins,
    numeric_bin_index,
    numeric_boundaries,
)
from shifu_tpu.stats.metrics import column_metrics
from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)

# Reference caps categorical cardinality at 10k (shifuconfig:107-108).
MAX_CATEGORY_SIZE = 10_000


def build_codes(
    data: ColumnarData,
    stats_cols: List[ColumnConfig],
) -> Tuple[np.ndarray, np.ndarray, List[int], np.ndarray, List[ColumnConfig]]:
    """Assign each row a bin code for every stats column.

    Returns (codes [n, C] int32, col_offsets [C], slots_per_col, values
    [n, Cn] float32 numeric matrix, numeric_cols). The slot layout comes
    from _column_slot_layout — the one definition the resumable pass-2
    fold shares, so the codes and the offsets they are aggregated under
    cannot diverge."""
    n = data.n_rows
    slots, col_offsets, numeric_cols = _column_slot_layout(stats_cols)
    codes = np.zeros((n, len(stats_cols)), dtype=np.int32)
    numeric_mat: List[np.ndarray] = []
    for j, cc in enumerate(stats_cols):
        if cc.is_categorical():
            cats = cc.column_binning.bin_category or []
            miss = data.missing_mask(cc.column_name)
            codes[:, j] = categorical_bin_index(
                data.column(cc.column_name), cats, miss
            )
        elif cc.is_hybrid():
            # hybrid: numeric bins then category bins then missing
            # (Normalizer.java:622-638); numeric moments come from the
            # parseable values only
            from shifu_tpu.stats.binning import hybrid_bin_index

            bounds = cc.column_binning.bin_boundary or [float("-inf")]
            cats = cc.column_binning.bin_category or []
            miss = data.missing_mask(cc.column_name)
            codes[:, j] = hybrid_bin_index(
                data.column(cc.column_name), bounds, cats, miss
            )
            numeric_mat.append(data.numeric(cc.column_name).astype(np.float32))
        else:
            bounds = cc.column_binning.bin_boundary or [float("-inf")]
            vals = data.numeric(cc.column_name)
            codes[:, j] = numeric_bin_index(vals, bounds)
            numeric_mat.append(vals.astype(np.float32))
    values = (
        np.stack(numeric_mat, axis=1)
        if numeric_mat
        else np.zeros((n, 0), dtype=np.float32)
    )
    return codes, col_offsets, slots, values, numeric_cols


def _prepare_rows(
    mc: ModelConfig, data: ColumnarData, seed, sample_rate: float,
    sample_neg_only: bool, fold_multiclass: bool = False,
) -> Tuple[ColumnarData, np.ndarray, np.ndarray]:
    """purify + invalid-tag drop + sampling (reference samples in the Pig
    job). `seed` may be a sequence (streaming passes [seed, chunk_idx] so
    both passes sample identically).

    `fold_multiclass` (stats callers): fold K class-index tags to
    class0-vs-rest so the binary bin aggregation (binagg counts tags==1 pos /
    ==0 neg) still sees EVERY valid row and binCountPos+binCountNeg ==
    n_valid_rows. Norm callers keep the class indices — they ARE the
    training targets."""
    ds = mc.data_set
    mask = combined_mask(ds.filter_expressions, data.raw, data.n_rows)
    from shifu_tpu.data.reader import make_tags_for

    tags_all = make_tags_for(mc, data.column(ds.target_column_name))
    if fold_multiclass and mc.is_multi_classification():
        tags_all = np.where(tags_all > 0, 1, tags_all).astype(tags_all.dtype)
    mask &= tags_all >= 0
    if sample_rate < 1.0:
        rng = np.random.default_rng(seed)
        keep = rng.random(data.n_rows) < sample_rate
        if sample_neg_only:
            keep |= tags_all >= 1
        mask &= keep
    data = data.select_rows(mask)
    tags = tags_all[mask]
    weights = make_weights(data, ds.weight_column_name)
    return data, tags, weights


def compute_stats(
    mc: ModelConfig,
    columns: List[ColumnConfig],
    data: ColumnarData,
    seed: int = 0,
) -> None:
    """Fill stats + binning for every non-target/meta/weight column, in place."""
    from shifu_tpu.obs import registry, span

    data, tags, weights = _prepare_rows(
        mc, data, seed, mc.stats.sample_rate, mc.stats.sample_neg_only,
        fold_multiclass=True,
    )
    n_pos, n_neg = int((tags == 1).sum()), int((tags == 0).sum())
    log.info("stats over %d rows (%d pos / %d neg)", data.n_rows,
             n_pos, n_neg)

    stats_cols = [
        c for c in columns if not (c.is_target() or c.is_meta() or c.is_weight())
    ]
    reg = registry()
    reg.counter("stats.rows_valid").inc(data.n_rows)
    reg.counter("stats.rows_pos").inc(n_pos)
    reg.counter("stats.rows_neg").inc(n_neg)
    reg.gauge("stats.columns").set(len(stats_cols))
    timers = reg.stage_timers("stats.stage")

    # ---- pass 1: bin construction (host, exact quantiles) ----
    max_bins = mc.stats.max_num_bin
    cate_max = mc.stats.cate_max_num_bin or MAX_CATEGORY_SIZE
    _t_bins = time.perf_counter()
    for cc in stats_cols:
        if cc.is_categorical():
            miss = data.missing_mask(cc.column_name)
            cats = categorical_bins(data.column(cc.column_name), miss, cate_max)
            cc.column_binning.bin_category = cats
            cc.column_binning.bin_boundary = None
            cc.column_binning.length = len(cats)
        elif cc.is_hybrid():
            # hybrid: numeric boundaries from parseable values PLUS
            # categories from non-parseable non-missing tokens
            # (udf/stats/NumericalVarStats hybrid handling)
            vals = data.numeric(cc.column_name)
            miss = data.missing_mask(cc.column_name)
            bounds = numeric_boundaries(
                vals, tags, weights, mc.stats.binning_method, max_bins
            )
            unparseable = np.isnan(vals) & ~miss
            cats = categorical_bins(
                data.column(cc.column_name)[unparseable],
                np.zeros(int(unparseable.sum()), dtype=bool),
                cate_max,
            ) if unparseable.any() else []
            cc.column_binning.bin_boundary = bounds
            cc.column_binning.bin_category = cats
            cc.column_binning.length = len(bounds) + len(cats)
        else:
            vals = data.numeric(cc.column_name)
            bounds = numeric_boundaries(
                vals, tags, weights, mc.stats.binning_method, max_bins
            )
            cc.column_binning.bin_boundary = bounds
            cc.column_binning.bin_category = None
            cc.column_binning.length = len(bounds)

    timers.add("bins", time.perf_counter() - _t_bins)

    # ---- pass 2: one jit aggregation over the code matrix ----
    with span("stats.aggregate", rows=data.n_rows, columns=len(stats_cols)), \
            timers.timer("aggregate"):
        codes, col_offsets, slots, values, numeric_cols = build_codes(
            data, stats_cols)
        total_slots = int(sum(slots))
        import jax.numpy as jnp

        agg = bin_aggregate_profiled(
            jnp.asarray(codes),
            jnp.asarray(col_offsets),
            total_slots,
            jnp.asarray(tags),
            jnp.asarray(weights, dtype=jnp.float32),
            jnp.asarray(values),
        )

    medians = []
    for cc in numeric_cols:
        vals = data.numeric(cc.column_name)
        finite = vals[np.isfinite(vals)]
        medians.append(float(np.median(finite)) if finite.size else None)
    cat_missing = {}
    for cc in stats_cols:
        if cc.is_categorical():
            miss = data.missing_mask(cc.column_name)
            cat_missing[cc.column_name] = (
                int(miss.sum()),
                float(miss.mean()) if data.n_rows else 0.0,
            )

    _write_back(
        stats_cols,
        slots,
        col_offsets,
        np.asarray(agg.pos),
        np.asarray(agg.neg),
        np.asarray(agg.wpos),
        np.asarray(agg.wneg),
        numeric_cols,
        np.asarray(agg.vsum),
        np.asarray(agg.vsumsq),
        np.asarray(agg.vmin),
        np.asarray(agg.vmax),
        np.asarray(agg.vcount),
        np.asarray(agg.vmissing),
        medians,
        cat_missing,
        n_valid_rows=int((tags >= 0).sum()),
    )


def _write_back(
    stats_cols: List[ColumnConfig],
    slots: List[int],
    col_offsets: np.ndarray,
    pos: np.ndarray,
    neg: np.ndarray,
    wpos: np.ndarray,
    wneg: np.ndarray,
    numeric_cols: List[ColumnConfig],
    vsum: np.ndarray,
    vsumsq: np.ndarray,
    vmin: np.ndarray,
    vmax: np.ndarray,
    vcount: np.ndarray,
    vmissing: np.ndarray,
    medians: List[Optional[float]],
    cat_missing: Dict[str, Tuple[int, float]],
    n_valid_rows: int,
) -> None:
    """Fill ColumnStats/ColumnBinning from flat bin aggregates (shared by the
    in-RAM and streaming paths)."""
    # ---- metrics: vectorized KS/IV/WOE over padded [C, max_slots] ----
    max_slots = max(slots) if slots else 1
    C = len(stats_cols)
    pos_pad = np.zeros((C, max_slots), dtype=np.float64)
    neg_pad = np.zeros_like(pos_pad)
    wpos_pad = np.zeros_like(pos_pad)
    wneg_pad = np.zeros_like(pos_pad)
    bin_mask = np.zeros_like(pos_pad)
    for j, cc in enumerate(stats_cols):
        o, s = col_offsets[j], slots[j]
        pos_pad[j, :s] = pos[o : o + s]
        neg_pad[j, :s] = neg[o : o + s]
        wpos_pad[j, :s] = wpos[o : o + s]
        wneg_pad[j, :s] = wneg[o : o + s]
        bin_mask[j, :s] = 1.0
    cm = column_metrics(pos_pad, neg_pad, bin_mask)
    wcm = column_metrics(wpos_pad, wneg_pad, bin_mask)

    ks, iv, woe, bin_woe, cvalid = cm.ks, cm.iv, cm.woe, cm.bin_woe, cm.valid
    wks, wiv, wwoe, wbin_woe = wcm.ks, wcm.iv, wcm.woe, wcm.bin_woe
    num_index = {id(cc): k for k, cc in enumerate(numeric_cols)}

    for j, cc in enumerate(stats_cols):
        s = slots[j]
        st = cc.column_stats
        bn = cc.column_binning
        bn.bin_count_pos = [int(x) for x in pos_pad[j, :s]]
        bn.bin_count_neg = [int(x) for x in neg_pad[j, :s]]
        bn.bin_weighted_pos = [float(x) for x in wpos_pad[j, :s]]
        bn.bin_weighted_neg = [float(x) for x in wneg_pad[j, :s]]
        tot = pos_pad[j, :s] + neg_pad[j, :s]
        with np.errstate(invalid="ignore", divide="ignore"):
            rate = np.where(tot > 0, pos_pad[j, :s] / np.maximum(tot, 1e-12), 0.0)
        bn.bin_pos_rate = [float(x) for x in rate]
        if bool(cvalid[j]):
            bn.bin_count_woe = [float(x) for x in bin_woe[j, :s]]
            bn.bin_weighted_woe = [float(x) for x in wbin_woe[j, :s]]
            st.ks = float(ks[j])
            st.iv = float(iv[j])
            st.woe = float(woe[j])
            st.weighted_ks = float(wks[j])
            st.weighted_iv = float(wiv[j])
            st.weighted_woe = float(wwoe[j])
        st.total_count = n_valid_rows

        k = num_index.get(id(cc))
        if k is not None:
            cnt = float(vcount[k])
            st.missing_count = int(vmissing[k])
            st.missing_percentage = (
                float(vmissing[k]) / max(n_valid_rows, 1) if n_valid_rows else 0.0
            )
            if cnt > 0:
                mean = float(vsum[k]) / cnt
                st.mean = mean
                var = max(float(vsumsq[k]) / cnt - mean * mean, 0.0)
                # sample std like the reference (BasicStatsCalculator)
                st.std_dev = math.sqrt(var * cnt / max(cnt - 1, 1.0))
                st.min = float(vmin[k])
                st.max = float(vmax[k])
                st.median = medians[k]
        else:
            miss_cnt, miss_pct = cat_missing.get(cc.column_name, (0, 0.0))
            st.missing_count = miss_cnt
            st.missing_percentage = miss_pct
            # Categorical stats are over the posrate-encoded variable (the
            # reference's CategoricalVarStats maps value -> binPosRate then
            # runs BasicStats) — closed form from the bin counts, incl. the
            # missing bin. Norm's categorical z-scale depends on these.
            tot_all = float(tot.sum())
            if tot_all > 0:
                mean = float((tot * rate).sum() / tot_all)
                e2 = float((tot * rate * rate).sum() / tot_all)
                var = max(e2 - mean * mean, 0.0)
                st.mean = mean
                st.std_dev = math.sqrt(var * tot_all / max(tot_all - 1.0, 1.0))
                occupied = rate[tot > 0]
                st.min = float(occupied.min()) if occupied.size else None
                st.max = float(occupied.max()) if occupied.size else None
            else:
                st.mean = None


def _column_slot_layout(
    stats_cols: List[ColumnConfig],
) -> Tuple[List[int], np.ndarray, List[ColumnConfig]]:
    """(slots_per_col, col_offsets, numeric_cols) from finalized bins —
    the same layout build_codes derives per chunk, but computable with
    zero chunks in hand (a resumed pass 2 may have none left)."""
    slots: List[int] = []
    numeric_cols: List[ColumnConfig] = []
    for cc in stats_cols:
        if cc.is_categorical():
            slots.append(len(cc.column_binning.bin_category or []) + 1)
        elif cc.is_hybrid():
            slots.append(
                len(cc.column_binning.bin_boundary or [float("-inf")])
                + len(cc.column_binning.bin_category or []) + 1)
            numeric_cols.append(cc)
        else:
            slots.append(
                len(cc.column_binning.bin_boundary or [float("-inf")]) + 1)
            numeric_cols.append(cc)
    col_offsets = np.zeros(len(stats_cols), dtype=np.int32)
    if slots:
        col_offsets[1:] = np.cumsum(slots[:-1])
    return slots, col_offsets, numeric_cols


def _stats_config_sha(mc: ModelConfig, stats_cols: List[ColumnConfig],
                      seed: int, n_shards: int):
    """(sha, per-section shas) of a streaming-stats run for checkpoint
    compatibility: a snapshot folded under one config must never resume
    under another — and a rejection names whether the DATA side (chunk
    geometry, shard plan, sampling, columns) or the STATS side (binning
    method/limits) diverged."""
    from shifu_tpu.data.stream import chunk_rows_setting
    from shifu_tpu.resilience.checkpoint import sectioned_sha

    return sectioned_sha({
        "data": {
            # the recorded chunk index only means anything under the SAME
            # chunk geometry — resuming a 48-row-chunk snapshot under the
            # 65536 default would silently skip/double-fold rows
            "chunkRows": chunk_rows_setting(),
            # ... and under the same shard plan: shard s's cursor means
            # "chunks ci % S == s up to here are folded"
            "shards": n_shards,
            "sampleRate": mc.stats.sample_rate,
            "sampleNegOnly": mc.stats.sample_neg_only,
            "seed": seed,
            "columns": [(c.column_name, str(c.column_type))
                        for c in stats_cols],
        },
        "stats": {
            "method": str(mc.stats.binning_method),
            "maxBins": mc.stats.max_num_bin,
            "cateMax": mc.stats.cate_max_num_bin,
        },
    })


def compute_stats_streaming(
    mc: ModelConfig,
    columns: List[ColumnConfig],
    chunk_factory,
    seed: int = 0,
    checkpoint_root: Optional[str] = None,
    resume: bool = False,
    host_plan=None,
) -> None:
    """Bounded-memory stats: two passes over a re-iterable chunk stream.

    Pass 1 folds every chunk into per-column streaming sketches (SPDT
    histogram for numeric bins — the reference's EqualPopulationBinning
    sketch, core/binning/EqualPopulationBinning.java:34 — plus moments and a
    capped categorical counter). Pass 2 re-streams, bin-codes each chunk and
    accumulates the same flat aggregates the in-RAM path produces in one
    shot (UpdateBinningInfo MR parity, mapper partial sums held on device).
    Peak memory = one chunk x (2 + prefetch depth) + sketches; nothing
    scales with the dataset.

    Both passes are SHARDED map/reduce folds over the lifecycle mesh
    (data/pipeline.py ShardPlan): chunk ci belongs to row shard ci % S
    (S = shifu.lifecycle.shards, default every device), so with S shards
    over K chunks each shard folds at most ceil(K/S) chunks — every pass
    is O(rows/shards). Pass 1 folds each shard's chunks into that
    shard's own sketches, merged once at bin finalization. Pass 2 is the
    device map: one shard_map dispatch per S-chunk super-step aggregates
    every shard's chunk on its own devices into its own f32 window, and
    the windowed flush is ONE psum-tree reduction over the mesh row axes
    followed by ONE device->host sync per ~2^23-total-row window (the
    window flushes into a host float64 fold before the psum'd counts
    could leave f32-exact range, so arbitrarily long streams cannot
    saturate — the PR-1 exactness invariant, shard-count-proof). S=1 is
    the degenerate single-device case
    of the same code path. Parse + purify + bin-coding still ride the
    background prefetch thread, chunks pad to power-of-two row buckets
    (O(log max_chunk_rows) compiled programs), and chunk order is
    deterministic, so results are bit-identical to a serial run
    (shifu.ingest.prefetchChunks=0) and count-exact across shard counts.

    With `checkpoint_root`, the fold is preemption-safe PER SHARD: every
    shifu.ckpt.everyChunks folded chunks each shard's (chunk cursor,
    local sketches / f32 window slice, row counters) lands in its own
    atomic snapshot file plus one shared reduce file (the host f64 fold),
    all epoch-stamped (resilience/checkpoint.ShardedStreamCheckpoint);
    `resume=True` resumes every shard mid-stream from its own cursor.
    Because the snapshots capture the exact per-shard f32 windows (no
    early flush) and per-chunk sampling is keyed by [seed, chunk_index],
    a resumed run is bit-identical to an uninterrupted one — the
    chaos-parity tests pin this under injected preemption, sharded and
    degenerate.

    With a multi-process HostPlan (`host_plan`, or the
    -Dshifu.lifecycle.hosts/-Dshifu.lifecycle.hostIndex knobs), BOTH
    passes fold only this host's chunk-file slice (host_of(ci) = ci % H;
    the per-device ShardPlan round-robins the host's dense local
    ordinals underneath, so all S local shards stay busy). The per-host
    partials meet at two filesystem barriers under the shared model-set
    root (parallel/hostsync.py): after pass 1 every host publishes its S
    sketch sets + row counters and every host merges ALL H*S sets in
    sorted-host order (identical bins everywhere, no back-channel);
    after pass 2 every host publishes its f64 fold and merges the H
    partials the same way. The merge order is fixed, per-chunk work is
    host-independent (sampling keys on the GLOBAL chunk index), and
    counts are integer-exact, so the written artifacts are
    byte-identical to the 1-process run — the CI two-process smoke pins
    this. Checkpoints become per-host families: a preempted host resumes
    its own cursor slice while its peers wait at the next barrier.
    """
    from shifu_tpu.config.model_config import BinningMethod
    from shifu_tpu.data.pipeline import (
        DeviceAccumulator,
        bucket_rows,
        prefetch_iter,
    )
    from shifu_tpu.obs import registry, span
    from shifu_tpu.stats.sketch import CategoricalSketch, NumericSketch

    stats_cols = [
        c for c in columns if not (c.is_target() or c.is_meta() or c.is_weight())
    ]
    method = mc.stats.binning_method
    max_bins = mc.stats.max_num_bin
    cate_max = mc.stats.cate_max_num_bin or MAX_CATEGORY_SIZE
    use_weights = method in (
        BinningMethod.WEIGHT_EQUAL_POSITIVE,
        BinningMethod.WEIGHT_EQUAL_NEGATIVE,
        BinningMethod.WEIGHT_EQUAL_TOTAL,
    )

    def bin_subset(tags: np.ndarray) -> np.ndarray:
        if method in (BinningMethod.EQUAL_POSITIVE,
                      BinningMethod.WEIGHT_EQUAL_POSITIVE):
            return tags == 1
        if method in (BinningMethod.EQUAL_NEGATIVE,
                      BinningMethod.WEIGHT_EQUAL_NEGATIVE):
            return tags == 0
        return tags >= 0

    # ---- the shard plan: every fold below divides chunks over it ----
    from shifu_tpu.data.pipeline import ShardPlan

    plan = ShardPlan(host=host_plan)
    S = plan.n_shards
    hp = plan.host
    if hp.active and checkpoint_root is None:
        raise ValueError(
            "multi-host streaming stats needs the shared model-set root "
            "(checkpoint_root) for the host part exchange")

    def _fresh_sketches() -> Dict[str, object]:
        out: Dict[str, object] = {}
        for cc in stats_cols:
            if cc.is_categorical():
                out[cc.column_name] = CategoricalSketch()
            else:
                out[cc.column_name] = NumericSketch(max_bins=max_bins)
        return out

    # one sketch set PER SHARD — each shard folds only its own chunks,
    # merged once (shard order, deterministic) at bin finalization
    sketches: List[Dict[str, object]] = [_fresh_sketches()
                                         for _ in range(S)]

    # registry-backed: stage timings land in the run manifest, not just a
    # log line (stats.stage{stage=parse1|prepare|sketch|parse2|bincode|
    # device|sync})
    reg = registry()
    timers = reg.stage_timers("stats.stage")

    # ---- preemption safety: per-shard mid-stream checkpoint + resume ----
    import pickle

    from shifu_tpu.resilience import checkpoint as ckpt_mod
    from shifu_tpu.resilience import faults

    # per-shard fold bookkeeping (checkpointed per shard, summed for the
    # global counters)
    shard_valid = np.zeros(S, dtype=np.int64)
    shard_pos = np.zeros(S, dtype=np.int64)
    shard_neg = np.zeros(S, dtype=np.int64)
    shard_chunks = np.zeros(S, dtype=np.int64)
    cursors1 = [-1] * S  # last pass-1 folded chunk per shard
    cursors2 = [-1] * S  # last pass-2 folded chunk per shard

    ck = None
    phase: Optional[str] = None
    resume_acc: Optional[tuple] = None
    sha, sha_sections = _stats_config_sha(mc, stats_cols, seed, S)
    if hp.active and not resume:
        # fresh multi-host run: this host's stale barrier parts (from a
        # crashed or earlier run) must not satisfy a peer's await
        from shifu_tpu.parallel import hostsync

        hostsync.clear_part(checkpoint_root, "stats-pass1", hp)
        hostsync.clear_part(checkpoint_root, "stats-pass2", hp)
    if checkpoint_root is not None and ckpt_mod.ckpt_stream_enabled():
        ck = ckpt_mod.ShardedStreamCheckpoint(
            ckpt_mod.ckpt_base(checkpoint_root, "stats", "stream"),
            sha, S, sections=sha_sections,
            n_hosts=hp.n_hosts, host_index=hp.host_index)
        if resume:
            loaded = ck.load()
            if loaded is not None:
                cursors, per_shard, shared = loaded
                phase = shared[1].get("phase")
                for s, (arrays, meta, blob) in enumerate(per_shard):
                    sketches[s] = pickle.loads(blob)["sketches"]
                    shard_valid[s] = int(meta.get("nValid", 0))
                    shard_pos[s] = int(meta.get("nPos", 0))
                    shard_neg[s] = int(meta.get("nNeg", 0))
                    shard_chunks[s] = int(meta.get("nChunks", 0))
                if phase == "pass1":
                    cursors1 = list(cursors)
                elif phase == "pass2":
                    cursors2 = list(cursors)
                    resume_acc = ([arrays for arrays, _m, _b in per_shard],
                                  shared[0])
                faults.survived("preempt")
                log.info("resuming streaming stats from %s (shard cursors "
                         "%s)", phase, list(cursors))
        else:
            ck.clear()  # fresh run: a stale snapshot must not resurface

    def _shard_states(arrays_per_shard, cursors, extra_meta=None):
        """Per-shard checkpoint payloads: cursor + counters + that
        shard's own sketches (and fold arrays when given)."""
        out = []
        for s in range(S):
            meta = {"nValid": int(shard_valid[s]), "nPos": int(shard_pos[s]),
                    "nNeg": int(shard_neg[s]),
                    "nChunks": int(shard_chunks[s])}
            if extra_meta:
                meta.update(extra_meta)
            out.append((cursors[s],
                        None if arrays_per_shard is None
                        else arrays_per_shard[s],
                        meta,
                        pickle.dumps({"sketches": sketches[s]})))
        return out

    def _prep1(numbered):
        """Background-thread transform: purify + tag + sample one chunk,
        then warm the lazy column caches (to_numeric / missing-mask /
        object materialization) the sketch folds will read — the expensive
        pandas work runs on the prefetch thread, the consumer only merges
        centroids. The chunk index rides along so both passes draw
        identical samples."""
        ci, chunk = numbered
        with timers.timer("prepare"):
            chunk, tags, weights = _prepare_rows(
                mc, chunk, [seed, ci], mc.stats.sample_rate,
                mc.stats.sample_neg_only, fold_multiclass=True,
            )
            if chunk.n_rows:
                for cc in stats_cols:
                    if cc.is_categorical():
                        chunk.column(cc.column_name)
                        chunk.missing_mask(cc.column_name)
                    else:
                        chunk.numeric(cc.column_name)
        return ci, chunk, tags, weights

    # ---- pass 1: the sharded sketch map (each shard folds its own
    # chunks into its own sketches) ----
    if phase in (None, "pass1"):
        with span("stats.pass1", shards=S) as sp1:
            for ci, chunk, tags, weights in prefetch_iter(
                plan.resume_slice(enumerate(chunk_factory()), cursors1),
                transform=_prep1, timers=timers, stage="parse1",
            ):
                # preemption seam: fires BETWEEN chunk folds, so the last
                # snapshot always covers a whole number of chunks
                faults.fault_point("chunk")
                s = plan.shard_of(ci)
                if not chunk.n_rows:
                    cursors1[s] = ci
                    continue
                shard_valid[s] += chunk.n_rows
                shard_pos[s] += int((tags == 1).sum())
                shard_neg[s] += int((tags == 0).sum())
                bm = bin_subset(tags)
                with timers.timer("sketch"):
                    for cc in stats_cols:
                        sk = sketches[s][cc.column_name]
                        if cc.is_categorical():
                            sk.update(chunk.column(cc.column_name),
                                      chunk.missing_mask(cc.column_name))
                        else:
                            sk.update(chunk.numeric(cc.column_name), bm,
                                      weights if use_weights else None)
                cursors1[s] = ci
                plan.record(s, chunk.n_rows, "stats.pass1")
                hp.record(chunk.n_rows, "stats.pass1")
                if ck is not None:
                    ck.maybe_save(lambda: (
                        _shard_states(None, cursors1),
                        (None, {"phase": "pass1"}, None)))
            sp1["rows"] = int(shard_valid.sum())
        if ck is not None:
            # pass-1 complete: pin every shard's full sketch state so a
            # preemption anywhere in pass 2 never re-pays the first pass
            ck.save(_shard_states(None, [-1] * S),
                    (None, {"phase": "pass1-done"}, None))
    if hp.active:
        # ---- pass-1 host barrier: publish this host's S sketch sets +
        # counters, then merge EVERY host's (all-gather: each host
        # derives the identical merged sketches, so the finalized bins
        # below agree everywhere with no bin back-channel) ----
        from shifu_tpu.parallel import hostsync

        hostsync.publish_part(
            checkpoint_root, "stats-pass1", hp, sha,
            arrays={"nValid": shard_valid, "nPos": shard_pos,
                    "nNeg": shard_neg},
            blob=pickle.dumps({"sketches": sketches}))
        parts1 = hostsync.await_parts(checkpoint_root, "stats-pass1",
                                      hp, sha)
        sketch_sets: List[Dict[str, object]] = []
        for arrays, _meta, blob in parts1:
            sketch_sets.extend(pickle.loads(blob)["sketches"])
        n_valid_rows = int(sum(a["nValid"].sum() for a, _m, _b in parts1))
        n_pos = int(sum(a["nPos"].sum() for a, _m, _b in parts1))
        n_neg = int(sum(a["nNeg"].sum() for a, _m, _b in parts1))
    else:
        sketch_sets = sketches
        n_valid_rows = int(shard_valid.sum())
        n_pos = int(shard_pos.sum())
        n_neg = int(shard_neg.sum())
    reg.counter("stats.rows_valid").inc(n_valid_rows)
    reg.counter("stats.rows_pos").inc(n_pos)
    reg.counter("stats.rows_neg").inc(n_neg)
    reg.gauge("stats.columns").set(len(stats_cols))
    log.info("streaming stats pass 1 done: %d rows (%d pos / %d neg) "
             "over %d shards x %d host(s)", n_valid_rows, n_pos, n_neg,
             S, hp.n_hosts)

    # ---- reduce the pass-1 map: merge per-shard sketches in sorted-
    # host, shard-within-host order (the fixed order the byte-parity
    # contract needs). With checkpointing armed, a COPY of the first set
    # receives the merge — the per-shard sketches must stay pristine
    # because pass-2 snapshots keep writing them and a resume re-merges;
    # without a checkpoint nothing ever rereads them, so the first set
    # absorbs the merge in place and the pickle round-trip (multi-MB on
    # wide sketch sets) is skipped. Multi-host sets already came off the
    # barrier as copies. ----
    merged: Dict[str, object] = (
        pickle.loads(pickle.dumps(sketch_sets[0]))
        if ck is not None and not hp.active
        else sketch_sets[0])
    for other in sketch_sets[1:]:
        for name, sk in merged.items():
            sk.merge(other[name])

    # ---- finalize bins from the merged sketches ----
    for cc in stats_cols:
        sk = merged[cc.column_name]
        bn = cc.column_binning
        if cc.is_categorical():
            cats = sk.top_categories(cate_max)
            bn.bin_category = cats
            bn.bin_boundary = None
            bn.length = len(cats)
        else:
            if method == BinningMethod.EQUAL_INTERVAL:
                lo, hi = sk.min, sk.max
                if np.isfinite(lo) and np.isfinite(hi) and hi > lo:
                    step = (hi - lo) / max_bins
                    bounds = [float("-inf")] + [
                        lo + k * step for k in range(1, max_bins)
                    ]
                else:
                    bounds = [float("-inf")]
            else:
                hist = sk.hist if sk.hist.total_weight > 0 else sk.hist_all
                bounds = hist.boundaries(max_bins)
            bn.bin_boundary = bounds
            bn.bin_category = None
            bn.length = len(bounds)

    # ---- pass 2: the sharded device map — S-chunk super-steps through
    # one shard_map fold each, windows closed by a single psum tree ----
    # slot layout is a pure function of the finalized bins — computed
    # up front so a resume that has zero chunks left to fold still has
    # the layout _write_back needs
    slots, col_offsets, numeric_cols = _column_slot_layout(stats_cols)
    total_slots = int(sum(slots))
    n_numeric = len(numeric_cols)
    col_offsets_np = np.asarray(col_offsets, dtype=np.int32)

    def _prep2(numbered):
        """Background-thread stage: purify + bin-code + pad one chunk to
        its power-of-two row bucket (padding rows carry invalid tags /
        zero weight / NaN values, so they change nothing downstream)."""
        ci, chunk = numbered
        with timers.timer("prepare"):
            chunk, tags, weights = _prepare_rows(
                mc, chunk, [seed, ci], mc.stats.sample_rate,
                mc.stats.sample_neg_only, fold_multiclass=True,
            )
        if not chunk.n_rows:
            return None
        n_real = chunk.n_rows
        with timers.timer("bincode"):
            codes, _offs, _sl, values, _ncols = build_codes(
                chunk, stats_cols)
            extra = bucket_rows(codes.shape[0]) - codes.shape[0]
            if extra:
                codes = np.pad(codes, ((0, extra), (0, 0)))
                tags = np.pad(tags, (0, extra), constant_values=-1)
                weights = np.pad(weights, (0, extra))
                values = np.pad(values, ((0, extra), (0, 0)),
                                constant_values=np.nan)
        return ci, n_real, codes, tags, weights, values

    acc_dev = DeviceAccumulator(n_shards=S)
    if phase == "pass2" and resume_acc is not None:
        acc_dev.restore_parts(list(resume_acc[0]), dict(resume_acc[1]))

    # super-step buffer: group g holds chunks [g*S, (g+1)*S), one per
    # shard; a whole group folds in ONE shard_map dispatch. Windows only
    # ever contain whole groups, so a kill mid-group loses nothing — the
    # buffered chunks simply re-parse on resume.
    pending: Dict[int, tuple] = {}
    pending_group: Optional[int] = None

    def _fold_pending():
        nonlocal pending, pending_group
        if not pending:
            pending_group = None
            return
        bucket = max(p[1].shape[0] for p in pending.values())
        codes_g = np.zeros((S, bucket, len(stats_cols)), np.int32)
        tags_g = np.full((S, bucket), -1, np.int32)
        weights_g = np.zeros((S, bucket), np.float32)
        values_g = np.full((S, bucket, n_numeric), np.nan, np.float32)
        rows_g = [0] * S
        for s, (n_real, codes_c, tags_c, weights_c, values_c,
                _ci) in pending.items():
            m = codes_c.shape[0]
            codes_g[s, :m] = codes_c
            tags_g[s, :m] = tags_c
            weights_g[s, :m] = weights_c
            values_g[s, :m] = values_c
            rows_g[s] = n_real
        with timers.timer("device"):
            acc_dev.fold_group(codes_g, col_offsets_np, total_slots,
                               tags_g, weights_g, values_g, rows_g)
        for s, item in pending.items():
            cursors2[s] = item[5]
            shard_chunks[s] += 1
            plan.record(s, item[0], "stats.pass2")
            hp.record(item[0], "stats.pass2")
        pending = {}
        pending_group = None

    def _pass2_state():
        parts, shared_arrays = acc_dev.snapshot_parts()
        return (_shard_states(parts, cursors2),
                (shared_arrays, {"phase": "pass2"}, None))

    with span("stats.pass2", shards=S) as sp2:
        for item in prefetch_iter(
                plan.resume_slice(enumerate(chunk_factory()), cursors2),
                transform=_prep2, timers=timers, stage="parse2"):
            if item is None:
                continue
            faults.fault_point("chunk")
            ci, n_real, codes, tags, weights, values = item
            g = plan.group_of(ci)
            if pending_group is not None and g != pending_group:
                _fold_pending()
            pending_group = g
            pending[plan.shard_of(ci)] = (n_real, codes, tags, weights,
                                          values, ci)
            if ck is not None:
                ck.maybe_save(_pass2_state)
        _fold_pending()
        with timers.timer("sync"):
            acc = acc_dev.fetch()
        sp2["chunks"] = int(shard_chunks.sum())
    n_chunks = int(shard_chunks.sum())
    if hp.active:
        # ---- pass-2 host barrier: publish this host's f64 fold, merge
        # every host's in sorted-host order (sum everywhere, min/max for
        # the extrema) — the same combine the psum tree applies across
        # shards, one level up ----
        from shifu_tpu.parallel import hostsync

        arrays = ({} if acc is None
                  else {f"acc{k}": a for k, a in enumerate(acc)})
        hostsync.publish_part(
            checkpoint_root, "stats-pass2", hp, sha, arrays=arrays,
            meta={"chunks": n_chunks})
        parts2 = hostsync.await_parts(checkpoint_root, "stats-pass2",
                                      hp, sha)
        acc = None
        for h_arrays, h_meta, _blob in parts2:
            if "acc0" not in h_arrays:
                continue  # that host's slice held no surviving rows
            part = [np.asarray(h_arrays[f"acc{k}"], dtype=np.float64)
                    for k in range(10)]
            if acc is None:
                acc = part
            else:
                acc = [
                    np.minimum(a, p) if k == 6 else  # vmin
                    np.maximum(a, p) if k == 7 else  # vmax
                    a + p
                    for k, (a, p) in enumerate(zip(acc, part))
                ]
        n_chunks = int(sum(m.get("chunks", 0) for _a, m, _b in parts2))
    reg.counter("stats.chunks").inc(n_chunks)
    log.info("streaming stats pipeline: %s", timers.summary())
    if ck is not None:
        ck.clear()  # stream complete: nothing left to resume
    if acc is None:
        log.warning("streaming stats: no rows survived filtering")
        return
    pos, neg, wpos, wneg, vsum, vsumsq, vmin, vmax, vcount, vmissing = acc

    medians = [merged[cc.column_name].median for cc in numeric_cols]
    cat_missing = {}
    for cc in stats_cols:
        if cc.is_categorical():
            sk = merged[cc.column_name]
            cat_missing[cc.column_name] = (
                int(sk.missing),
                float(sk.missing) / max(n_valid_rows, 1),
            )
    _write_back(
        stats_cols, slots, col_offsets, pos, neg, wpos, wneg,
        numeric_cols, vsum, vsumsq, vmin, vmax, vcount, vmissing,
        medians, cat_missing, n_valid_rows=n_valid_rows,
    )
