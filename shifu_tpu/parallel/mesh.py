"""Device-mesh helpers — the TPU-native replacement for the Guagua BSP layer.

The reference runs master+workers as Hadoop mappers synchronized through
ZooKeeper (SURVEY §5: guagua-mapreduce, NNParams Bytable exchange). Here the
whole "cluster" is one SPMD program: rows are sharded over the mesh's `data`
axis, weights are replicated, and XLA inserts the gradient all-reduce (the
`psum` that replaces NNMaster.accumulateGradients) when the jitted train step
consumes row-sharded inputs and produces replicated outputs.

Axis names:
    data   — row (batch) parallelism; every trainer uses it
    model  — reserved for tensor-parallel WDL embedding shards
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


def data_mesh(n_devices: Optional[int] = None, model_axis: int = 1):
    """1-or-2-axis mesh over available devices: (data, model)."""
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    if model_axis > 1:
        assert n % model_axis == 0, (n, model_axis)
        dev = np.array(devices).reshape(n // model_axis, model_axis)
        return Mesh(dev, ("data", "model"))
    return Mesh(np.array(devices), ("data",))


def pad_rows(
    arrays: Sequence[np.ndarray], multiple: int
) -> Tuple[list, int]:
    """Pad row dimension to a multiple (sharding needs even splits). Padded
    rows must carry zero significance — callers pad weights with 0."""
    n = arrays[0].shape[0]
    target = ((n + multiple - 1) // multiple) * multiple
    if target == n:
        return list(arrays), n
    out = []
    for a in arrays:
        pad_shape = (target - n,) + a.shape[1:]
        out.append(np.concatenate([a, np.zeros(pad_shape, dtype=a.dtype)], axis=0))
    return out, n


def shard_rows(array, mesh):
    """Place an array on the mesh sharded along its leading (row) axis."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec = P("data", *([None] * (array.ndim - 1)))
    return jax.device_put(array, NamedSharding(mesh, spec))


def replicate(tree, mesh):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda a: jax.device_put(a, sharding), tree)
