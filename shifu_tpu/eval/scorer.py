"""Scorer / ModelRunner: batch scoring of raw records against trained models.

Parity: core/Scorer.java:53 (per-model dispatch, DEFAULT_SCORE_SCALE=1000,
Scorer.java:56), core/ModelRunner.java:54 (header map -> per-model scores,
mean/max/min/median aggregation). TPU-first shape: models are loaded once,
the raw eval dataset is normalized with each model's embedded norm plan into
a dense matrix, and scoring is one batched forward per model.
"""

from __future__ import annotations

import glob
import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from shifu_tpu.data.reader import ColumnarData
from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)

DEFAULT_SCORE_SCALE = 1000.0  # Scorer.java:56

MODEL_SUFFIXES = (".nn", ".lr", ".gbt", ".rf", ".wdl")


def find_model_paths(models_dir: str) -> List[str]:
    """models/model*.{nn,lr,gbt,rf,wdl} sorted by NUMERIC index
    (ModelSpecLoaderUtils.findModels). Numeric, not lexicographic: under
    ONEVSALL the column order is load-bearing (column k = class k), and
    lexicographic order would put model10 before model2.

    Paths are DEDUPED (overlapping globs/symlinked dirs must not score a
    model twice — duplicate columns skew the mean/median aggregates) and
    the order is fully deterministic: numeric index first, then basename —
    unindexed names land after every indexed one in basename order, never
    in whatever order the per-suffix globs happened to run."""
    import re

    out = set()
    for suf in MODEL_SUFFIXES:
        out.update(glob.glob(os.path.join(models_dir, f"model*{suf}")))

    def key(p: str):
        base = os.path.basename(p)
        m = re.search(r"model(\d+)", base)
        # (indexed-first, index, basename): the basename tie-break keeps
        # same-index files of different suffixes and ALL unindexed files
        # in one stable order regardless of glob/filesystem enumeration
        return (0, int(m.group(1)), base) if m else (1, 0, base)

    return sorted(out, key=key)


def load_model(path: str, column_configs=None, model_config=None):
    """Dispatch on extension to the right independent model class.

    Reference-format files (Encog EG text, BinaryNNSerializer gzip,
    BinaryDTSerializer binary, zip spec) are sniffed by magic bytes and
    wrapped in a RefModelAdapter — ModelSpecLoaderUtils.java:389 parity:
    one models/ dir can mix native and reference specs."""
    from shifu_tpu.compat.adapters import load_ref_model

    adapter = load_ref_model(path, column_configs, model_config)
    if adapter is not None:
        return adapter
    suffix = os.path.splitext(path)[1]
    if suffix in (".nn", ".lr"):
        from shifu_tpu.models.nn import NNModelSpec

        return NNModelSpec.load(path)
    if suffix in (".gbt", ".rf"):
        from shifu_tpu.models.tree import TreeModelSpec

        return TreeModelSpec.load(path)
    if suffix == ".wdl":
        from shifu_tpu.models.wdl import WDLModelSpec

        return WDLModelSpec.load(path)
    raise ValueError(f"unknown model type: {path}")


@dataclass
class ScoreResult:
    """Per-record scores: raw per-model + aggregates, 0..scale.

    Multi-class NATIVE models contribute one column PER CLASS, model-major
    ("1,2,3 4,5,6: 1,2,3 is model 0" — ConfusionMatrix.java:760);
    `model_widths[i]` is model i's column count (1 for binary/ONEVSALL)."""

    model_scores: np.ndarray  # [n, sum(model_widths)]
    mean: np.ndarray
    max: np.ndarray
    min: np.ndarray
    median: np.ndarray
    model_names: List[str] = field(default_factory=list)
    model_widths: List[int] = field(default_factory=list)


class ModelRunner:
    def __init__(self, model_paths: List[str], scale: float = DEFAULT_SCORE_SCALE,
                 column_configs=None, model_config=None):
        if not model_paths:
            raise ValueError("no models to score with")
        self.paths = model_paths
        self.specs = [load_model(p, column_configs, model_config)
                      for p in model_paths]
        # independent scorers are created once so their jitted forwards cache
        self.models = [self._independent(spec) for spec in self.specs]
        self.scale = scale
        self._norm_cache: Dict[str, np.ndarray] = {}
        self._codes_cache: Dict[str, np.ndarray] = {}
        self._cached_data_ref = None  # weakref to the cached batch

    def _check_batch(self, data: ColumnarData) -> None:
        """Feature caches are per input batch — a new ColumnarData object
        invalidates them (model signatures alone don't identify the rows).

        Identity is held via WEAKREF, never `id()`: in a streaming loop
        the previous chunk is freed before the next one arrives, and the
        allocator routinely hands the new chunk the old address — an
        id()-keyed check then serves the PREVIOUS chunk's normalized
        features for the new chunk's rows (observed as a whole chunk of
        wrong scores, timing-dependent). A dead or different referent
        always invalidates; the weakref itself keeps no chunk alive, so
        the bounded-memory envelope is untouched."""
        cached = (self._cached_data_ref()
                  if self._cached_data_ref is not None else None)
        if cached is not data:
            self._norm_cache.clear()
            self._codes_cache.clear()
            import weakref

            try:
                self._cached_data_ref = weakref.ref(data)
            except TypeError:  # un-weakrefable batch: never reuse across calls
                self._cached_data_ref = None

    @staticmethod
    def _independent(spec):
        from shifu_tpu.compat.adapters import RefModelAdapter
        from shifu_tpu.models.nn import IndependentNNModel, NNModelSpec

        if isinstance(spec, RefModelAdapter):
            return spec
        if isinstance(spec, NNModelSpec):
            return IndependentNNModel(spec)
        return spec.independent()

    def _normalized_input(self, spec, data: ColumnarData) -> np.ndarray:
        """Normalize raw records with the model's embedded norm plan; plans
        are usually identical across bagged models, so cache by the FULL
        plan signature (type + cutoff + every column table)."""
        from shifu_tpu.norm.normalizer import apply_norm_plan, plan_from_json

        plan_json = {
            "normType": spec.norm_type,
            "cutoff": getattr(spec, "norm_cutoff", 4.0),
            "columns": spec.norm_specs,
        }
        key = json.dumps(plan_json, sort_keys=True)
        if key in self._norm_cache:
            return self._norm_cache[key]
        mat = apply_norm_plan(plan_from_json(plan_json), data)
        self._norm_cache[key] = mat
        return mat

    def _wdl_codes(self, spec, data: ColumnarData) -> np.ndarray:
        """Categorical index matrix for a WDL model, cached per batch like
        tree codes."""
        from shifu_tpu.stats.binning import categorical_bin_index

        key = json.dumps(["wdl", spec.cat_columns, spec.categories],
                         sort_keys=True)
        if key in self._codes_cache:
            return self._codes_cache[key]
        codes = np.zeros((data.n_rows, len(spec.cat_columns)), np.int32)
        for f, name in enumerate(spec.cat_columns):
            miss = data.missing_mask(name)
            codes[:, f] = categorical_bin_index(
                data.column(name), spec.categories[f], miss
            )
        self._codes_cache[key] = codes
        return codes

    def _tree_codes(self, spec, model, data: ColumnarData) -> np.ndarray:
        """Bin codes per tree model, cached by the model's own binning
        signature (different models may embed different columns/bins)."""
        key = json.dumps(
            [spec.input_columns, spec.boundaries, spec.categories],
            sort_keys=True,
        )
        if key in self._codes_cache:
            return self._codes_cache[key]
        codes = model.codes_from_raw(data)
        self._codes_cache[key] = codes
        return codes

    def score_raw(self, data: ColumnarData) -> ScoreResult:
        """Score raw records. NN/LR/WDL models normalize via their embedded
        plan; tree models bin via their embedded boundaries/categories
        (EvalScoreUDF loads models once, then scores row batches)."""
        from shifu_tpu.compat.adapters import RefModelAdapter
        from shifu_tpu.models.tree import TreeModelSpec
        from shifu_tpu.models.wdl import WDLModelSpec

        self._check_batch(data)
        cols = []
        for spec, model in zip(self.specs, self.models):
            if isinstance(spec, RefModelAdapter):
                cols.append(spec.score_raw(data) * self.scale)
            elif isinstance(spec, TreeModelSpec):
                codes = self._tree_codes(spec, model, data)
                cols.append(model.compute(codes) * self.scale)
            elif isinstance(spec, WDLModelSpec):
                dense = self._normalized_input(spec, data)
                wcodes = self._wdl_codes(spec, data)
                cols.append(model.compute_parts(dense, wcodes) * self.scale)
            else:
                x = self._normalized_input(spec, data)
                cols.append(self._nn_scores(spec, model, x))
        return self._aggregate(cols)

    def _nn_scores(self, spec, model, x: np.ndarray) -> np.ndarray:
        """Binary model -> [n]; NATIVE multi-class -> [n, K] per-class."""
        if getattr(spec, "out_dim", 1) > 1:
            return model.compute_all(x) * self.scale
        return model.compute(x) * self.scale

    def score_normalized(self, feats: np.ndarray) -> ScoreResult:
        from shifu_tpu.compat.adapters import RefModelAdapter
        from shifu_tpu.models.nn import NNModelSpec

        cols = []
        for spec, m in zip(self.specs, self.models):
            if isinstance(m, RefModelAdapter):
                cols.append(m.score_normalized(feats) * self.scale)
            elif isinstance(spec, NNModelSpec):
                cols.append(self._nn_scores(spec, m, feats))
            else:
                cols.append(m.compute(feats) * self.scale)
        return self._aggregate(cols)

    def _aggregate(self, cols: List[np.ndarray]) -> ScoreResult:
        mats = [c[:, None] if c.ndim == 1 else c for c in cols]
        m = np.concatenate(mats, axis=1)
        widths = [mat.shape[1] for mat in mats]
        return ScoreResult(
            model_scores=m,
            mean=m.mean(axis=1),
            max=m.max(axis=1),
            min=m.min(axis=1),
            median=np.median(m, axis=1),
            model_names=[os.path.basename(p) for p in self.paths],
            model_widths=widths,
        )
