"""Device-mesh helpers — the TPU-native replacement for the Guagua BSP layer.

The reference runs master+workers as Hadoop mappers synchronized through
ZooKeeper (SURVEY §5: guagua-mapreduce, NNParams Bytable exchange). Here the
whole "cluster" is one SPMD program: rows are sharded over the mesh's row
axes, weights are replicated, and XLA inserts the gradient all-reduce (the
`psum` that replaces NNMaster.accumulateGradients) when the jitted train step
consumes row-sharded inputs and produces replicated outputs.

Axis names:
    dcn    — OUTER axis across slices/hosts connected by data-center
             network (multi-slice pods). Present only when the device set
             spans >1 slice (or when forced via dcn_slices). Row sharding
             spans (dcn, data) so the heavy within-slice reduction rides
             ICI and only the per-slice partial crosses DCN — XLA lowers
             the psum hierarchically from the mesh topology.
    data   — row (batch) parallelism within a slice; every trainer uses it
    model  — reserved for tensor-parallel WDL embedding shards
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


def _slice_count(devices) -> int:
    """Distinct slice indices in the device set (1 on single-slice or when
    the platform doesn't expose slice_index, e.g. CPU)."""
    ids = set()
    for d in devices:
        ids.add(getattr(d, "slice_index", 0) or 0)
    return max(1, len(ids))


def data_mesh(n_devices: Optional[int] = None, model_axis: int = 1,
              dcn_slices: Optional[int] = None):
    """Mesh over the available devices.

    Single slice: (data[, model]). Multi-slice (detected from the devices'
    slice_index, or forced with `dcn_slices` for virtual-device tests):
    (dcn, data[, model]) with `dcn` outermost, so collectives are
    hierarchical — within-slice over ICI first, across slices over DCN
    (SURVEY §5's comm-backend obligation)."""
    import jax
    from jax.sharding import Mesh

    from shifu_tpu.obs import registry

    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    registry().gauge("mesh.devices").set(n)
    n_dcn = dcn_slices if dcn_slices else _slice_count(devices)
    if n_dcn > 1:
        assert n % n_dcn == 0, (n, n_dcn)
        per_slice = n // n_dcn
        if dcn_slices:
            dev = np.array(devices).reshape(n_dcn, per_slice)
        else:  # group real devices by their slice
            by_slice: dict = {}
            for d in devices:
                by_slice.setdefault(getattr(d, "slice_index", 0) or 0,
                                    []).append(d)
            sizes = {k: len(v) for k, v in by_slice.items()}
            if len(set(sizes.values())) != 1:
                raise ValueError(
                    f"device set spans slices unevenly ({sizes}); a mesh "
                    "needs equal devices per slice — pass n_devices as a "
                    "multiple of the slice size")
            dev = np.array([by_slice[k] for k in sorted(by_slice)])
        if model_axis > 1:
            assert per_slice % model_axis == 0, (per_slice, model_axis)
            dev = dev.reshape(n_dcn, per_slice // model_axis, model_axis)
            return Mesh(dev, ("dcn", "data", "model"))
        return Mesh(dev, ("dcn", "data"))
    if model_axis > 1:
        assert n % model_axis == 0, (n, model_axis)
        dev = np.array(devices).reshape(n // model_axis, model_axis)
        return Mesh(dev, ("data", "model"))
    return Mesh(np.array(devices), ("data",))


_LIFECYCLE_MESHES: dict = {}


def lifecycle_shards() -> int:
    """Row-shard count for the lifecycle map/reduce folds (streaming
    stats/norm/eval/autotype): `shifu.lifecycle.shards` when set (>0),
    else every visible device. 1 is the degenerate single-device case —
    the same code path, a 1-wide mesh."""
    from shifu_tpu.utils import environment

    n = environment.get_int("shifu.lifecycle.shards", 0)
    if n > 0:
        return n
    import jax

    return max(1, len(jax.devices()))


def lifecycle_hosts() -> int:
    """Host (process) count of the pod-scale data plane:
    `shifu.lifecycle.hosts` when set (>0), else 1 — the single-controller
    degenerate case every pre-host run is."""
    from shifu_tpu.utils import environment

    return max(1, environment.get_int("shifu.lifecycle.hosts", 1))


def lifecycle_host_index() -> int:
    """This process's host index in [0, lifecycle_hosts()):
    `shifu.lifecycle.hostIndex` when set, else `jax.process_index()` —
    on a real multi-host pod the jax runtime numbers the processes; on a
    CPU fleet of OS processes the launcher pins the index (the PR-14
    lease id names the process, the index orders it)."""
    from shifu_tpu.utils import environment

    idx = environment.get_int("shifu.lifecycle.hostIndex", -1)
    if idx >= 0:
        return idx
    import jax

    return int(jax.process_index())


def reduce_topology() -> str:
    """shifu.reduce.topology — window-reduce lowering override:
    `auto` (default: hierarchical when the mesh has a dcn axis, flat on a
    single-slice mesh), `hierarchical`, or `flat` (forces the one-stage
    joint psum even on a multi-slice mesh — the bit-parity reference)."""
    from shifu_tpu.utils import environment

    v = environment.get_property("shifu.reduce.topology", "auto")
    v = (v or "auto").strip().lower()
    return v if v in ("auto", "hierarchical", "flat") else "auto"


def hierarchical_reduce(mesh) -> bool:
    """Whether window_reduce on `mesh` lowers as the explicit two-stage
    tree (psum over ICI/`data` first, then ONE partial per slice across
    `dcn`). Flat is the 1-slice degenerate case: with no dcn axis there
    is nothing to stage."""
    return "dcn" in row_axes(mesh) and reduce_topology() != "flat"


def lifecycle_mesh(n_shards: Optional[int] = None):
    """The (cached) mesh the lifecycle folds shard rows over: the first
    `n_shards` devices, (dcn, data) when the set spans slices so the
    windowed psum reduce lowers hierarchically — heavy within-slice over
    ICI, one partial per slice over DCN."""
    n = lifecycle_shards() if n_shards is None else max(1, int(n_shards))
    mesh = _LIFECYCLE_MESHES.get(n)
    if mesh is None:
        mesh = data_mesh(n_devices=n)
        _LIFECYCLE_MESHES[n] = mesh
    return mesh


def row_axes(mesh) -> Tuple[str, ...]:
    """Axis names rows shard over: ('dcn', 'data') on a multi-slice mesh,
    ('data',) otherwise. Also the psum axes for gradient/histogram
    all-reduces."""
    return tuple(a for a in mesh.axis_names if a in ("dcn", "data"))


def row_shard_count(mesh) -> int:
    """Number of row shards = product of the row axes' sizes (what row
    counts must pad to)."""
    shape = dict(zip(mesh.axis_names, mesh.devices.shape))
    n = 1
    for a in row_axes(mesh):
        n *= shape.get(a, 1)
    return n


def round_up_rows(n: int, mesh) -> int:
    """Smallest row count >= n that splits evenly over the mesh's row
    shards (padding rows must carry zero significance — see pad_rows)."""
    m = row_shard_count(mesh)
    return -(-n // m) * m


def pad_rows(
    arrays: Sequence[np.ndarray], multiple: int
) -> Tuple[list, int]:
    """Pad row dimension to a multiple (sharding needs even splits). Padded
    rows must carry zero significance — callers pad weights with 0."""
    n = arrays[0].shape[0]
    target = ((n + multiple - 1) // multiple) * multiple
    if target == n:
        return list(arrays), n
    out = []
    for a in arrays:
        pad_shape = (target - n,) + a.shape[1:]
        out.append(np.concatenate([a, np.zeros(pad_shape, dtype=a.dtype)], axis=0))
    return out, n


def shard_rows(array, mesh):
    """Place an array on the mesh sharded along its leading (row) axis —
    over (dcn, data) on a multi-slice mesh."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from shifu_tpu.obs import registry

    axes = row_axes(mesh)
    spec = P(axes if len(axes) > 1 else axes[0],
             *([None] * (array.ndim - 1)))
    # collective-op accounting: every sharded placement seeds a program
    # whose row-sharded consumption XLA closes with a psum over `axes`
    reg = registry()
    reg.counter("mesh.shard_rows", axes="x".join(axes)).inc()
    reg.counter("mesh.h2d_bytes").inc(float(getattr(array, "nbytes", 0)))
    return jax.device_put(array, NamedSharding(mesh, spec))


def replicate(tree, mesh):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from shifu_tpu.obs import registry

    sharding = NamedSharding(mesh, P())
    leaves = jax.tree_util.tree_leaves(tree)
    reg = registry()
    reg.counter("mesh.replicated_arrays").inc(len(leaves))
    reg.counter("mesh.h2d_bytes").inc(
        float(sum(getattr(a, "nbytes", 0) for a in leaves)))
    return jax.tree_util.tree_map(lambda a: jax.device_put(a, sharding), tree)


_FLEET_REDUCE_PROGRAMS: dict = {}


def fleet_mesh(n_devices: int):
    """Mesh over the first `n_devices` devices — the serving fleet's
    replica devices are always a prefix of jax.devices() (serve/fleet.py
    assigns replica i -> device i % ndev), so this is the mesh whose row
    shards line up one-to-one with the fleet's distinct devices. Shares
    the lifecycle mesh cache: the fleet's reduce and the lifecycle folds
    deliberately run on ONE mesh family (the TensorFlow/DrJAX argument —
    train and serve share a compiled-graph substrate)."""
    return lifecycle_mesh(n_shards=max(1, int(n_devices)))


def fleet_reduce(mesh, parts: np.ndarray, max_cols: int = 0) -> np.ndarray:
    """One-collective merge of per-device stat vectors — the serving
    fleet's analog of ops/binagg.window_reduce: `parts` is [D, K] with
    one row per mesh device, the leading K-max_cols columns reduce with
    psum and the trailing `max_cols` columns with pmax (extrema don't
    sum), and every device ends up with the same replicated [K] result —
    the host pulls ONE vector, not D.

    Used for cross-replica shadow-agreement evidence (rolling promote):
    each replica's counts stage onto its own device, one psum tree
    closes the fleet verdict."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    parts = np.asarray(parts, dtype=np.float32)
    axes = row_axes(mesh)
    n_shards = row_shard_count(mesh)
    assert parts.shape[0] == n_shards, (parts.shape, n_shards)
    key = (id(mesh), int(parts.shape[1]), int(max_cols))
    prog = _FLEET_REDUCE_PROGRAMS.get(key)
    if prog is None:
        def local(v):  # v: [1, K] — this device's stat row
            summed = jax.lax.psum(v, axes)
            if max_cols:
                maxed = jax.lax.pmax(v[:, -max_cols:], axes)
                summed = jnp.concatenate(
                    [summed[:, : v.shape[1] - max_cols], maxed], axis=1)
            return summed[0]

        prog = jax.jit(shard_map_compat(
            local, mesh=mesh,
            in_specs=(P(axes if len(axes) > 1 else axes[0], None),),
            out_specs=P()))
        _FLEET_REDUCE_PROGRAMS[key] = prog
    spec = P(axes if len(axes) > 1 else axes[0], None)
    staged = jax.device_put(parts, NamedSharding(mesh, spec))
    from shifu_tpu.obs import registry

    registry().counter("serve.fleet.reduces").inc()
    return np.asarray(jax.device_get(prog(staged)), dtype=np.float64)


def shard_map_compat(fn, *, mesh, in_specs, out_specs, check: bool = False):
    """shard_map across jax versions: newer jax exports `jax.shard_map`
    (replication checking spelled `check_vma`), 0.4.x only has
    `jax.experimental.shard_map.shard_map` (spelled `check_rep`). One
    helper so every call site stays version-agnostic."""
    try:
        from jax import shard_map as _sm

        return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_vma=check)
    except ImportError:
        from jax.experimental.shard_map import shard_map as _sm

        return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=check)
