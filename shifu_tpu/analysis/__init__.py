"""shifu_tpu.analysis — program-level checks for a jit-heavy pipeline.

Two halves, one contract ("the pipeline stays honest without per-PR
hand-audits", ISSUE 4 / DrJAX's no-host-round-trips discipline):

  * static: an AST lint engine (`engine.py`) with JAX-aware rules
    (`rules/jaxrules.py`: host syncs under trace, static-arg hazards,
    jit-in-loop recompiles, f64 drift, side effects under jit) and
    pipeline-hygiene rules (`rules/hygiene.py`). Exposed as
    ``shifu check [--json] [--rules ...] [paths]`` and gated in CI.
  * runtime: an opt-in sanitizer harness (`sanitize.py`),
    ``-Dshifu.sanitize=transfer,nan,recompile`` — transfer guards around
    declared traced stages, debug_nans on trainer steps, a recompile
    watchdog on the obs/jaxprobe compile counters. Verdicts land in the
    run-ledger manifests (obs/ledger.py) and bench scenario JSON.

The static engine imports only the stdlib, so the CI lint job (and
``python -m shifu_tpu check``) runs without jax installed.
"""

from shifu_tpu.analysis.engine import (  # noqa: F401 - public API
    Finding,
    analyze,
    report_human,
    report_json,
    run_check,
)
