"""ModelInspector: per-step validation gate for ModelConfig/ColumnConfig.

Parity with the reference's core/validator/ModelInspector.java:93 — each
lifecycle step `probe`s only the config sections it depends on and fails fast
with an aggregated, human-readable error list before any compute is launched.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import List, Optional

from shifu_tpu.config.model_config import Algorithm, ModelConfig, RunMode


@dataclass
class ValidateResult:
    status: bool = True
    causes: List[str] = field(default_factory=list)

    def fail(self, cause: str) -> None:
        self.status = False
        self.causes.append(cause)

    def merge(self, other: "ValidateResult") -> None:
        if not other.status:
            self.status = False
            self.causes.extend(other.causes)


class ModelStep:
    NEW = "new"
    INIT = "init"
    STATS = "stats"
    NORM = "norm"
    VARSEL = "varsel"
    TRAIN = "train"
    POSTTRAIN = "posttrain"
    EVAL = "eval"
    EXPORT = "export"


_SUPPORTED_ALGS = {
    Algorithm.NN,
    Algorithm.LR,
    Algorithm.SVM,
    Algorithm.GBT,
    Algorithm.RF,
    Algorithm.DT,
    Algorithm.WDL,
    Algorithm.TENSORFLOW,
}


def _check_data_set(mc: ModelConfig, result: ValidateResult, base_dir: str) -> None:
    ds = mc.data_set
    if not ds.data_path:
        result.fail("dataSet.dataPath is empty")
    else:
        from shifu_tpu.fs.source import is_remote

        path = ds.data_path
        if is_remote(path):
            pass  # remote existence is the reader's job (fs/source.py)
        else:
            if not os.path.isabs(path):
                path = os.path.normpath(os.path.join(base_dir, path))
            if not os.path.exists(path):
                result.fail(f"dataSet.dataPath not found: {ds.data_path}")
    if not ds.target_column_name:
        result.fail("dataSet.targetColumnName is empty")
    overlap = set(ds.pos_tags) & set(ds.neg_tags)
    if overlap:
        result.fail(f"posTags and negTags overlap: {sorted(overlap)}")
    if not ds.pos_tags and not ds.neg_tags:
        result.fail("both dataSet.posTags and dataSet.negTags are empty")


def _check_stats(mc: ModelConfig, result: ValidateResult) -> None:
    st = mc.stats
    if st.max_num_bin <= 1:
        result.fail(f"stats.maxNumBin must be > 1, got {st.max_num_bin}")
    if not (0.0 < st.sample_rate <= 1.0):
        result.fail(f"stats.sampleRate must be in (0, 1], got {st.sample_rate}")


def _check_norm(mc: ModelConfig, result: ValidateResult) -> None:
    nm = mc.normalize
    if nm.std_dev_cut_off <= 0:
        result.fail(f"normalize.stdDevCutOff must be > 0, got {nm.std_dev_cut_off}")
    if not (0.0 < nm.sample_rate <= 1.0):
        result.fail(f"normalize.sampleRate must be in (0, 1], got {nm.sample_rate}")


def _check_varsel(mc: ModelConfig, result: ValidateResult) -> None:
    vs = mc.var_select
    if vs.filter_enable and vs.filter_num <= 0 and vs.filter_out_ratio <= 0:
        result.fail("varSelect.filterNum or filterOutRatio must be positive")
    valid_filters = {"KS", "IV", "MIX", "PARETO", "FI", "SE", "ST", "VOTED"}
    if vs.filter_by and vs.filter_by.upper() not in valid_filters:
        result.fail(
            f"varSelect.filterBy '{vs.filter_by}' not in {sorted(valid_filters)}"
        )


def _check_train(mc: ModelConfig, result: ValidateResult) -> None:
    tr = mc.train
    if tr.algorithm not in _SUPPORTED_ALGS:
        result.fail(f"train.algorithm {tr.algorithm} unsupported")
    if tr.bagging_num < 1:
        result.fail(f"train.baggingNum must be >= 1, got {tr.bagging_num}")
    if not (0.0 <= tr.valid_set_rate < 1.0):
        result.fail(f"train.validSetRate must be in [0, 1), got {tr.valid_set_rate}")
    if tr.num_train_epochs < 1:
        result.fail(f"train.numTrainEpochs must be >= 1, got {tr.num_train_epochs}")
    if not (0.0 < tr.bagging_sample_rate <= 1.0):
        result.fail(
            f"train.baggingSampleRate must be in (0, 1], got {tr.bagging_sample_rate}"
        )
    if tr.num_k_fold is not None and tr.num_k_fold > 1 and tr.is_continuous:
        result.fail("train.numKFold and isContinuous cannot both be enabled")
    if tr.algorithm == Algorithm.NN:
        layers = tr.get_param("NumHiddenLayers", 0)
        nodes = tr.get_param("NumHiddenNodes", []) or []
        funcs = tr.get_param("ActivationFunc", []) or []
        if layers and (len(nodes) != layers or len(funcs) != layers):
            result.fail(
                "NN params inconsistent: NumHiddenLayers="
                f"{layers}, NumHiddenNodes={nodes}, ActivationFunc={funcs}"
            )
    if tr.algorithm in (Algorithm.GBT, Algorithm.RF, Algorithm.DT):
        depth = tr.get_param("MaxDepth", 10)
        if not (1 <= int(depth) <= 20):
            result.fail(f"tree MaxDepth must be in [1, 20], got {depth}")
    if tr.algorithm == Algorithm.SVM:
        # the TPU build trains the liblinear path: L2-regularized hinge,
        # Const -> C (core/alg/SVMTrainer.java:38); kernel SVMs are not
        # implemented — fail at validation, not silently mid-train
        kernel = str(tr.get_param("Kernel", "linear") or "linear").lower()
        if kernel != "linear":
            result.fail(
                f"SVM Kernel={kernel!r} unsupported (linear only); "
                "use Kernel=linear or algorithm=NN")


def _check_evals(mc: ModelConfig, result: ValidateResult, base_dir: str) -> None:
    names = set()
    for e in mc.evals or []:
        if not e.name:
            result.fail("eval set with empty name")
        elif e.name in names:
            result.fail(f"duplicate eval set name: {e.name}")
        names.add(e.name)
        if not e.data_set.data_path:
            result.fail(f"eval {e.name}: dataSet.dataPath is empty")


def probe(mc: ModelConfig, step: str, base_dir: str = ".") -> ValidateResult:
    """Validate the sections required by `step` (reference ModelInspector.probe
    ModelInspector.java:113-170). Schema-level constraints run first via the
    bundled config meta (MetaFactory.java:44 parity, config/meta.py)."""
    result = ValidateResult()
    from shifu_tpu.config.meta import validate_model_config

    for cause in validate_model_config(mc):
        result.fail(cause)
    if not mc.basic.name:
        result.fail("basic.name is empty")
    if mc.basic.run_mode is None:
        result.fail("basic.runMode invalid (LOCAL/MAPRED/DIST/TPU)")

    if step in (ModelStep.INIT, ModelStep.STATS, ModelStep.NORM, ModelStep.POSTTRAIN):
        _check_data_set(mc, result, base_dir)
    if step == ModelStep.STATS:
        _check_stats(mc, result)
    if step == ModelStep.NORM:
        _check_norm(mc, result)
    if step == ModelStep.VARSEL:
        _check_varsel(mc, result)
        _check_norm(mc, result)
    if step == ModelStep.TRAIN:
        _check_train(mc, result)
    if step == ModelStep.EVAL:
        _check_evals(mc, result, base_dir)
    return result
