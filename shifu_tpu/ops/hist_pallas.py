"""Pallas TPU kernel for the GBT/RF histogram contraction.

The tree builder's hot op (dt/DTWorker.java:851 featureUpdate, fused by
SURVEY §7.5 into "the histogram kernel") is

    hist[c, l, t] = Σ_i comps[i, c] · (node[i] == l) · (code_t[i] == t)

The XLA lowering in tree_trainer materializes the [blk, T] code one-hot
M in HBM between the compare and the matmul (~2·n·T·4 bytes of traffic
per level). This kernel builds BOTH one-hots in VMEM and feeds the MXU
directly:

    grid (row blocks)  — one VMEM-resident [C·L, W] accumulator per
                         T-chunk, revisited across the grid (init at
                         block 0, += afterwards)
    per block          — oh_node [blk, L] and the chunk's code one-hot
                         [blk, W] are built in-registers/VMEM; a single
                         f32 dot_general contracts over the row axis

Feature one-hots sit at STATIC columns inside each chunk (the flat
per-feature slot layout), so a 10k-category column spans several chunks
instead of padding every feature to its width.

f32 operands keep counts/sums exact (bit-comparable with the scatter
path for integer weights).

MEASURED (v5e, round 5): in-program the XLA T-chunked matmul lowering in
tree_trainer is 10-25% faster than this kernel at both 500k x 30-narrow
and 200k x 200-mixed-wide shapes (Mosaic's unaligned lane stores for the
33/65-wide one-hot segments eat the VMEM-residency win), so the trainer
defaults to XLA and enables this kernel behind SHIFU_PALLAS=1. The
kernel's bandwidth profile (codes-only HBM reads, no [n, T] one-hot
materialization) makes it the right base for regimes the XLA path cannot
reach; it is correctness-tested in interpret mode on CPU."""

from __future__ import annotations

import functools
from typing import List, Optional

# VMEM budget shaping: rows per grid step x max chunk columns. M [BLK, W]
# f32 + A [BLK, C*L] f32 + out [C*L, W] f32 must sit well under ~16 MB.
# Overridable per PROCESS (-Dshifu.pallas.blk / -Dshifu.pallas.wmax) so
# the next kernel-tuning round can sweep shapings without code edits —
# per process because the built kernels are cached (_chunk_call lru,
# tree_trainer's program cache): set the knobs at launch, one process
# per shaping, the way the bench children do. The chosen values land in
# the profiler snapshot (obs.profile annotations, process-global so a
# later obs scope still reports them) so every manifest records which
# shaping produced its numbers.
_BLK = 512
_W_MAX = 1024


def blk_setting() -> int:
    """shifu.pallas.blk — rows per grid step (default 512)."""
    from shifu_tpu.utils import environment

    return max(8, environment.get_int("shifu.pallas.blk", _BLK))


def wmax_setting() -> int:
    """shifu.pallas.wmax — max one-hot columns per VMEM chunk (1024)."""
    from shifu_tpu.utils import environment

    return max(8, environment.get_int("shifu.pallas.wmax", _W_MAX))


def _chunk_runs(lay, target: Optional[int] = None) -> List[list]:
    """Split the flat T axis into chunks of <= target columns, each chunk a
    list of runs: ('vec', f_lo, f_hi, w) for consecutive full features of
    equal width w, or ('piece', f, lo, hi) for a partial piece of a wide
    feature. Chunks always cover whole columns of [0, T) in order and the
    features of one chunk are CONTIGUOUS, so the caller can hand the
    kernel a contiguous column slice of the code matrix."""
    if target is None:
        target = wmax_setting()
    slots = [int(s) for s in lay.slots]
    chunks: List[dict] = []
    cur: List[tuple] = []
    cur_w = 0
    cur_flo = None
    cur_fhi = None

    def flush():
        nonlocal cur, cur_w, cur_flo, cur_fhi
        if cur:
            chunks.append({"runs": cur, "w": cur_w, "f_lo": cur_flo,
                           "f_hi": cur_fhi})
        cur, cur_w, cur_flo, cur_fhi = [], 0, None, None

    for f, s in enumerate(slots):
        lo = 0
        while lo < s:
            take = min(s - lo, target - cur_w)
            if take == 0:
                flush()
                continue
            full = lo == 0 and take == s
            if cur_flo is None:
                cur_flo = f
            cur_fhi = f + 1
            if (full and cur and cur[-1][0] == "vec"
                    and cur[-1][2] == f and cur[-1][3] == s):
                cur[-1] = ("vec", cur[-1][1], f + 1, s)
            elif full:
                cur.append(("vec", f, f + 1, s))
            else:
                cur.append(("piece", f, lo, lo + take))
            cur_w += take
            lo += take
            if cur_w >= target:
                flush()
    flush()
    return chunks


@functools.lru_cache(maxsize=None)
def _chunk_call(L: int, C: int, blk: int, nf: int, w: int, runs: tuple,
                interpret: bool):
    """Build one chunk's pallas_call: (codes_chunk [n, nf], comps [n, C],
    node [n, 1]) -> [C*L, w] accumulated over row blocks. `runs` use
    CHUNK-RELATIVE feature columns: ('vec', a, b, w) spans columns
    [a, b) of the chunk slice; ('piece', a, lo, hi, clip) is one
    column."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from jax.experimental.pallas import tpu as pltpu

    def kernel(codes_ref, comps_ref, node_ref, *out_and_scratch):
        out_refs = out_and_scratch[:C]
        m_ref = out_and_scratch[C]  # [blk, w] VMEM scratch
        i = pl.program_id(0)
        comps = comps_ref[...]  # [blk, C]
        if L == 1:
            oh_node = None
        else:
            node = node_ref[...]  # [blk, 1]
            oh_node = (node == jax.lax.broadcasted_iota(
                jnp.int32, (blk, L), 1)).astype(jnp.float32)
        # build the chunk's code one-hot DIRECTLY into the M scratch at
        # static column offsets (no cols list + concat: half the live
        # VMEM, one copy less per block)
        col = 0
        for run in runs:
            if run[0] == "vec":
                _tag, a, b, cw = run
                for fc in range(a, b):
                    cf = jnp.clip(codes_ref[:, fc:fc + 1], 0, cw - 1)
                    m_ref[:, col:col + cw] = (
                        cf == jax.lax.broadcasted_iota(
                            jnp.int32, (blk, cw), 1)).astype(jnp.float32)
                    col += cw
            else:
                _tag, a, lo, hi, clip = run
                cw = hi - lo
                cf = jnp.clip(codes_ref[:, a:a + 1], 0, clip)
                m_ref[:, col:col + cw] = (
                    (cf - lo) == jax.lax.broadcasted_iota(
                        jnp.int32, (blk, cw), 1)).astype(jnp.float32)
                col += cw
        M = m_ref[...]
        # one dot per component plane (Mosaic-friendly: no [blk, C*L]
        # reshape); each is [L, blk] @ [blk, w] on the MXU
        for c in range(C):
            A_c = (comps[:, c:c + 1] if L == 1
                   else comps[:, c:c + 1] * oh_node)  # [blk, L]
            contrib = jax.lax.dot_general(
                A_c, M, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)  # [L, w]

            @pl.when(i == 0)
            def _init(out_ref=out_refs[c]):
                out_ref[...] = jnp.zeros_like(out_ref)

            out_refs[c][...] += contrib

    def call(codes_chunk, comps, node2d):
        n = codes_chunk.shape[0]
        grid = n // blk
        planes = pl.pallas_call(
            kernel,
            grid=(grid,),
            in_specs=[
                pl.BlockSpec((blk, nf), lambda i: (i, 0)),
                pl.BlockSpec((blk, C), lambda i: (i, 0)),
                pl.BlockSpec((blk, 1), lambda i: (i, 0)),
            ],
            out_specs=[pl.BlockSpec((L, w), lambda i: (0, 0))
                       for _ in range(C)],
            out_shape=[jax.ShapeDtypeStruct((L, w), jnp.float32)
                       for _ in range(C)],
            scratch_shapes=[pltpu.VMEM((blk, w), jnp.float32)],
            interpret=interpret,
        )(codes_chunk, comps, node2d)
        return jnp.stack(planes)  # [C, L, w]

    return call


def make_pallas_hist_fn(L: int, lay, n_classes: int = 0,
                        interpret: bool = False):
    """Traced fn (codes, labels, weights, node_slot, active) -> [C, L, T]
    matching tree_trainer's histogram contract. `interpret=True` runs the
    kernels in pallas interpret mode (CPU tests)."""
    import jax.numpy as jnp

    C = n_classes if n_classes >= 3 else 3
    T = lay.T
    blk_max = blk_setting()
    wmax = wmax_setting()
    chunks = _chunk_runs(lay, target=wmax)
    clips = tuple(int(c) for c in lay.clip_max)
    # the shaping this build chose rides into every profiler snapshot /
    # manifest, so a -Dshifu.pallas.* sweep is self-documenting
    from shifu_tpu.obs import profile as _profile

    _profile.annotate("ops.hist_pallas", blk=blk_max, wMax=wmax,
                      chunks=len(chunks), L=int(L), T=int(T))

    def hist_fn(codes, labels, weights, node_slot, active):
        n, F = codes.shape
        w = jnp.where(active, weights, 0.0)
        nl = jnp.where(active, jnp.clip(node_slot, 0, L - 1), 0)
        if n_classes >= 3:
            cls = jnp.clip(labels.astype(jnp.int32), 0, n_classes - 1)
            comps = jnp.stack(
                [w * (cls == c).astype(jnp.float32)
                 for c in range(n_classes)], 1)
        else:
            comps = jnp.stack([w, w * labels, w * labels * labels], 1)

        blk = min(blk_max, n)
        n_pad = -(-n // blk) * blk
        pad = n_pad - n
        codes_p = jnp.pad(codes, ((0, pad), (0, 0)))
        comps_p = jnp.pad(comps, ((0, pad), (0, 0)))
        node2d = jnp.pad(nl, (0, pad))[:, None]

        parts = []
        for ch in chunks:
            f_lo = ch["f_lo"]
            rel_runs = tuple(
                ("vec", r[1] - f_lo, r[2] - f_lo, r[3]) if r[0] == "vec"
                else ("piece", r[1] - f_lo, r[2], r[3], clips[r[1]])
                for r in ch["runs"])
            call = _chunk_call(L, C, blk, ch["f_hi"] - f_lo,
                               ch["w"], rel_runs, interpret)
            codes_chunk = codes_p[:, f_lo:ch["f_hi"]]
            parts.append(call(codes_chunk, comps_p, node2d))  # [C, L, w]
        return (parts[0] if len(parts) == 1
                else jnp.concatenate(parts, axis=2))  # [C, L, T]

    return hist_fn
