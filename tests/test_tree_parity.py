"""Tree training parity features: flat per-feature slot layout, leaf-wise
growth (maxLeaves), per-tree checkpoint/resume (bit-equal), GBT continuous
training, windowed early stop (DTEarlyStopDecider)."""

import os

import numpy as np
import pytest

from shifu_tpu.models.tree import DenseTree, TreeModelSpec
from shifu_tpu.train.tree_trainer import (
    DTEarlyStopDecider,
    TreeTrainConfig,
    build_tree,
    build_tree_leafwise,
    make_layout,
    train_trees,
)


def _make_data(n=1500, seed=0):
    rng = np.random.default_rng(seed)
    slots = [4, 12, 3, 8]  # deliberately ragged slot counts
    codes = np.stack(
        [rng.integers(0, s, size=n) for s in slots], axis=1
    ).astype(np.int32)
    logits = (codes[:, 1] >= 6) * 2.0 + (codes[:, 0] <= 1) * 1.0 - 1.4
    y = (logits + rng.normal(scale=0.4, size=n) > 0).astype(np.float32)
    w = np.ones(n, dtype=np.float32)
    return codes, y, w, slots


def test_layout_ragged_segments():
    lay = make_layout([3, 5, 2], [False, True, False])
    assert lay.T == 10
    assert lay.off.tolist() == [0, 3, 8]
    assert lay.seg_of_t.tolist() == [0, 0, 0, 1, 1, 1, 1, 1, 2, 2]
    assert lay.pos_in_seg.tolist() == [0, 1, 2, 0, 1, 2, 3, 4, 0, 1]
    assert lay.is_cat_t.tolist() == [False] * 3 + [True] * 5 + [False] * 2
    assert lay.s_max == 5


def test_ragged_slots_split_correctness():
    """The wide feature (12 slots) carries the signal; the flat layout must
    find its cut without inflating the narrow features' segments."""
    import jax.numpy as jnp

    codes, y, w, slots = _make_data()
    cfg = TreeTrainConfig(max_depth=2, min_instances_per_node=1)
    tree, resting = build_tree(
        jnp.asarray(codes), jnp.asarray(y), jnp.asarray(w),
        np.asarray(slots), np.asarray([False] * 4), cfg,
        np.asarray([True] * 4),
    )
    assert tree.feature[0] == 1  # root splits the signal feature
    # mask semantics: bins < 6 go one way, >= 6 the other
    left = set(np.nonzero(tree.left_mask[0][:12])[0].tolist())
    assert left in ({0, 1, 2, 3, 4, 5}, set(range(6, 12)))


def test_leafwise_growth():
    import jax.numpy as jnp

    codes, y, w, slots = _make_data()
    cfg = TreeTrainConfig(max_depth=6, max_leaves=5,
                          min_instances_per_node=1)
    tree, resting = build_tree_leafwise(
        jnp.asarray(codes), jnp.asarray(y), jnp.asarray(w),
        np.asarray(slots), np.asarray([False] * 4), cfg,
        np.asarray([True] * 4),
    )
    assert not tree.is_dense_layout
    n_leaves = int((tree.feature == -1).sum())
    n_splits = int((tree.feature >= 0).sum())
    assert n_leaves <= 5
    assert n_splits == n_leaves - 1  # binary tree invariant
    # children appended after parents (traversal depth relies on it)
    for i in range(tree.n_nodes):
        if tree.left[i] >= 0:
            assert tree.left[i] > i and tree.right[i] > i

    # resting ids give per-row predictions consistent with traversal
    from shifu_tpu.models.tree import traverse_trees

    pred_resting = tree.leaf_value[np.asarray(resting)]
    pred_traverse = np.asarray(
        traverse_trees([tree], jnp.asarray(codes))
    )[:, 0]
    np.testing.assert_allclose(pred_resting, pred_traverse, atol=1e-6)


def test_leafwise_model_roundtrip(tmp_path):
    codes, y, w, slots = _make_data(n=800)
    cfg = TreeTrainConfig(algorithm="GBT", tree_num=5, max_depth=5,
                          max_leaves=6, learning_rate=0.3, seed=1)
    res = train_trees(codes, y, w, slots, [False] * 4,
                      [f"c{i}" for i in range(4)], cfg)
    path = str(tmp_path / "model0.gbt")
    res.spec.save(path)
    loaded = TreeModelSpec.load(path)
    assert all(not t.is_dense_layout for t in loaded.trees)
    s1 = res.spec.independent().compute(codes)
    s2 = loaded.independent().compute(codes)
    np.testing.assert_allclose(s1, s2, atol=1e-6)
    # leaf-wise GBT still learns
    assert ((s1 > 0.5) == (y > 0.5)).mean() > 0.8


@pytest.mark.parametrize("sub", [True, False], ids=["sub-on", "sub-off"])
@pytest.mark.parametrize("alg", ["GBT", "RF"])
def test_resume_is_bit_equal(alg, sub):
    """Kill at tree 5 of 12, resume from the checkpointed forest — the
    resumed run must reproduce the uninterrupted forest BIT-EQUAL
    (per-tree RNG streams keyed by (seed, tree index); the GBT running
    prediction re-derives via the same sequential f32 fold the live run
    used, `_score_existing`). Holds under either histogram-subtraction
    lowering — SAME lowering both sides; a checkpoint written under a
    DIFFERENT lowering may legitimately diverge in float-summation order,
    which the processor's checkpoint fingerprint guards against."""
    codes, y, w, slots = _make_data(n=1000, seed=4)
    cfg = TreeTrainConfig(algorithm=alg, tree_num=12, max_depth=3,
                          learning_rate=0.2, seed=7,
                          feature_subset_strategy="TWOTHIRDS",
                          hist_subtraction=sub)
    cols = [f"c{i}" for i in range(4)]
    full = train_trees(codes, y, w, slots, [False] * 4, cols, cfg)

    cfg5 = TreeTrainConfig(**{**cfg.__dict__, "tree_num": 5})
    part = train_trees(codes, y, w, slots, [False] * 4, cols, cfg5)
    resumed = train_trees(codes, y, w, slots, [False] * 4, cols, cfg,
                          init_trees=part.spec.trees)

    assert len(resumed.spec.trees) == len(full.spec.trees) == 12
    for tf, tr in zip(full.spec.trees, resumed.spec.trees):
        np.testing.assert_array_equal(tf.feature, tr.feature)
        np.testing.assert_array_equal(tf.left_mask, tr.left_mask)
        np.testing.assert_allclose(tf.leaf_value, tr.leaf_value, atol=0)
        assert tf.weight == tr.weight
    # trees are the bit-equal contract; the running-mean error accumulator
    # re-associates floating point on resume (RF), so compare to 1e-7
    assert resumed.valid_error == pytest.approx(full.valid_error, abs=1e-7)


def test_checkpoint_cb_fires():
    codes, y, w, slots = _make_data(n=500)
    cfg = TreeTrainConfig(algorithm="GBT", tree_num=6, max_depth=2, seed=2)
    seen = []
    train_trees(
        codes, y, w, slots, [False] * 4, [f"c{i}" for i in range(4)], cfg,
        checkpoint_cb=lambda k, trees, errs: seen.append(
            (k, len(trees), len(errs))),
    )
    assert seen == [(k, k, k) for k in range(1, 7)]


def test_processor_checkpoint_resume_and_continuous(tmp_path):
    """Processor-level: a leftover checkpoint resumes to the same forest a
    clean run produces; isContinuous then grows the forest to a larger
    TreeNum with the original trees intact."""
    from tests.helpers import make_model_set

    root = str(tmp_path / "ms")
    make_model_set(root, n_rows=400, algorithm="GBT")
    from shifu_tpu.config.model_config import ModelConfig
    from shifu_tpu.processor.init import InitProcessor
    from shifu_tpu.processor.norm import NormProcessor
    from shifu_tpu.processor.stats import StatsProcessor
    from shifu_tpu.processor.train import TrainProcessor

    assert InitProcessor(root).run() == 0
    assert StatsProcessor(root).run() == 0
    assert NormProcessor(root).run() == 0

    def set_train(**kw):
        mc = ModelConfig.load(os.path.join(root, "ModelConfig.json"))
        for k, v in kw.items():
            if k in ("TreeNum", "MaxDepth", "CheckpointInterval"):
                mc.train.params[k] = v
            else:
                setattr(mc.train, k, v)
        mc.save(os.path.join(root, "ModelConfig.json"))

    set_train(TreeNum=8, MaxDepth=3, CheckpointInterval=2)
    assert TrainProcessor(root).run() == 0
    clean = TreeModelSpec.load(os.path.join(root, "models", "model0.gbt"))
    assert len(clean.trees) == 8
    # checkpoint removed after a successful run
    ck = os.path.join(root, "tmp", "checkpoints", "trainer_0", "trees.ckpt")
    assert not os.path.isfile(ck)

    # simulate a crash at tree 4: plant a checkpoint (+ state sidecar with
    # the matching hyperparameter fingerprint), delete the model
    import json

    cfg = TreeTrainConfig.from_model_config(
        ModelConfig.load(os.path.join(root, "ModelConfig.json")), 0)
    TreeModelSpec(
        algorithm="GBT", trees=clean.trees[:4],
        input_columns=clean.input_columns, slots=clean.slots,
        boundaries=clean.boundaries, categories=clean.categories,
        loss=clean.loss, learning_rate=clean.learning_rate,
    ).save(ck)
    import hashlib

    data_sig = hashlib.sha1(json.dumps(
        [list(clean.input_columns), [int(s) for s in clean.slots],
         clean.boundaries, clean.categories],
        sort_keys=True, default=str).encode()).hexdigest()
    with open(ck + ".json", "w") as fh:
        json.dump({
            "fingerprint": {
                "algorithm": cfg.algorithm, "loss": cfg.loss,
                "maxDepth": cfg.max_depth, "maxLeaves": cfg.max_leaves,
                "impurity": cfg.impurity,
                "learningRate": cfg.learning_rate,
                "minInstancesPerNode": cfg.min_instances_per_node,
                "minInfoGain": cfg.min_info_gain,
                "featureSubsetStrategy": cfg.feature_subset_strategy,
                "baggingSampleRate": cfg.bagging_sample_rate,
                "baggingWithReplacement": cfg.bagging_with_replacement,
                "validSetRate": cfg.valid_set_rate, "seed": cfg.seed,
                "dataSignature": data_sig,
            },
            "validErrors": [0.5, 0.4, 0.3, 0.2],
        }, fh)
    os.remove(os.path.join(root, "models", "model0.gbt"))
    assert TrainProcessor(root).run() == 0
    resumed = TreeModelSpec.load(os.path.join(root, "models", "model0.gbt"))
    assert len(resumed.trees) == 8
    for tc, tr in zip(clean.trees, resumed.trees):
        np.testing.assert_array_equal(tc.feature, tr.feature)
        np.testing.assert_allclose(tc.leaf_value, tr.leaf_value, atol=0)

    # continuous: raise TreeNum, original trees stay put
    set_train(TreeNum=12, is_continuous=True)
    assert TrainProcessor(root).run() == 0
    grown = TreeModelSpec.load(os.path.join(root, "models", "model0.gbt"))
    assert len(grown.trees) == 12
    for tc, tg in zip(clean.trees, grown.trees[:8]):
        np.testing.assert_array_equal(tc.feature, tg.feature)

    # already at TreeNum: skip without touching the model
    mtime = os.path.getmtime(os.path.join(root, "models", "model0.gbt"))
    set_train(TreeNum=12, is_continuous=True)
    assert TrainProcessor(root).run() == 0
    assert os.path.getmtime(
        os.path.join(root, "models", "model0.gbt")) == mtime


def test_windowed_early_stop_decider():
    """Flat validation error (no gain) triggers the 3-restart stop; a
    steadily improving series never stops (DTEarlyStopDecider.java:49)."""
    d = DTEarlyStopDecider(3)
    stopped_at = None
    for i in range(400):
        if d.add(0.5):  # perfectly flat: worth no more iterations
            stopped_at = i
            break
    assert stopped_at is not None

    d2 = DTEarlyStopDecider(3)
    for i in range(200):
        assert not d2.add(1.0 / (i + 1.0))  # keeps improving fast


def test_enable_early_stop_via_params():
    codes, y, w, slots = _make_data(n=600)
    cfg = TreeTrainConfig(algorithm="GBT", tree_num=300, max_depth=2,
                          learning_rate=0.5, enable_early_stop=True, seed=3)
    res = train_trees(codes, y, w, slots, [False] * 4,
                      [f"c{i}" for i in range(4)], cfg)
    assert len(res.spec.trees) < 300  # decider fired well before TreeNum


def test_fused_and_per_level_paths_agree(monkeypatch):
    """The single-dispatch fused tree program and the node-batched
    per-level path must grow identical trees (the budget only picks the
    execution strategy, never the result)."""
    codes, y, w, slots = _make_data(n=900, seed=6)
    cols = [f"c{i}" for i in range(4)]
    base = dict(algorithm="GBT", tree_num=4, max_depth=4, learning_rate=0.3,
                seed=11, min_instances_per_node=2)
    fused = train_trees(codes, y, w, slots, [False, True, False, False],
                        cols, TreeTrainConfig(**base))
    # force the per-level node-batched path (cap of 2 nodes per histogram)
    import shifu_tpu.train.tree_trainer as tt

    monkeypatch.setattr(tt, "_node_batch_size", lambda T, mb, k=0: 2)
    batched = train_trees(codes, y, w, slots, [False, True, False, False],
                          cols, TreeTrainConfig(**base))
    assert len(fused.spec.trees) == len(batched.spec.trees)
    for tf, tb in zip(fused.spec.trees, batched.spec.trees):
        np.testing.assert_array_equal(tf.feature, tb.feature)
        np.testing.assert_array_equal(tf.left_mask, tb.left_mask)
        np.testing.assert_allclose(tf.leaf_value, tb.leaf_value, atol=1e-5)


def test_gbt_dart_dropout():
    """DropoutRate > 0: each row independently skips a tree's contribution
    to its running prediction (dt/DTWorker.java:634-640) — the final model
    keeps every tree, but training targets diverge from plain GBT."""
    codes, y, w, slots = _make_data(n=900, seed=8)
    cols = [f"c{i}" for i in range(4)]
    base = dict(algorithm="GBT", tree_num=8, max_depth=3, learning_rate=0.3,
                seed=13, min_instances_per_node=2)
    plain = train_trees(codes, y, w, slots, [False] * 4, cols,
                        TreeTrainConfig(**base))
    dart = train_trees(codes, y, w, slots, [False] * 4, cols,
                       TreeTrainConfig(**base, dropout_rate=0.3))
    assert len(dart.spec.trees) == 8
    # tree 0 identical (dropout starts at tree 1); later trees diverge
    np.testing.assert_array_equal(plain.spec.trees[0].feature,
                                  dart.spec.trees[0].feature)
    diverged = any(
        not np.array_equal(p.feature, d.feature)
        or not np.allclose(p.leaf_value, d.leaf_value)
        for p, d in zip(plain.spec.trees[1:], dart.spec.trees[1:])
    )
    assert diverged
    # still learns
    scores = dart.spec.independent().compute(codes)
    assert ((scores > 0.5) == (y > 0.5)).mean() > 0.8

    # streamed path draws the identical dropout stream
    from shifu_tpu.norm.dataset import write_codes
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        out = os.path.join(td, "CleanedData")
        write_codes(out, codes.astype(np.int16), y.astype(np.int8), w,
                    cols, slots, n_shards=3)
        from shifu_tpu.train.streaming_tree import train_trees_streamed

        streamed = train_trees_streamed(
            out, slots, [False] * 4, cols,
            TreeTrainConfig(**base, dropout_rate=0.3))
        for ts, tm in zip(streamed.spec.trees, dart.spec.trees):
            np.testing.assert_array_equal(ts.feature, tm.feature)


def test_gbt_dart_resume_is_bit_equal():
    """DART runs resume bit-equal too: the per-row keep masks regenerate
    from their (seed, tree, 777) streams."""
    codes, y, w, slots = _make_data(n=800, seed=9)
    cols = [f"c{i}" for i in range(4)]
    cfg = TreeTrainConfig(algorithm="GBT", tree_num=8, max_depth=3,
                          learning_rate=0.3, dropout_rate=0.25, seed=21,
                          min_instances_per_node=2)
    full = train_trees(codes, y, w, slots, [False] * 4, cols, cfg)
    cfg4 = TreeTrainConfig(**{**cfg.__dict__, "tree_num": 4})
    part = train_trees(codes, y, w, slots, [False] * 4, cols, cfg4)
    resumed = train_trees(codes, y, w, slots, [False] * 4, cols, cfg,
                          init_trees=part.spec.trees)
    for tf, tr in zip(full.spec.trees, resumed.spec.trees):
        np.testing.assert_array_equal(tf.feature, tr.feature)
        np.testing.assert_allclose(tf.leaf_value, tr.leaf_value, atol=1e-6)
