"""JAX runtime probes: compile counts/seconds via jax.monitoring listeners.

XLA recompiles are the silent tax of a shape-unstable pipeline (PR 1's
bucketed padding exists to bound them); these probes make every backend
compile a registry counter so run manifests and bench output can say "this
step compiled N programs for M seconds" instead of guessing from wall-clock.

The listener resolves the CURRENT global registry at event time, so the
per-step registry reset in BasicProcessor.run() scopes compile counts to the
step that caused them. Device-transfer counters have no monitoring event in
jax; the explicit placement seams count themselves (parallel/mesh.py h2d,
data/pipeline.py DeviceAccumulator d2h).
"""

from __future__ import annotations

from shifu_tpu.analysis.racetrack import tracked_lock

_installed = False
_lock = tracked_lock("obs.jaxprobe")

# event name -> (counter to inc, timer to accumulate, duration histogram);
# backend_compile is the actual XLA compile, jaxpr_trace fires per
# cache-missing trace. The histogram keeps PER-EVENT durations (not just
# the aggregate the timer holds), so a manifest can show whether a step's
# compile seconds were one monster program or a recompile storm of small
# ones — and the sanitizer's recompile-watchdog breach can quote the
# wall-clock the recompiles actually cost.
_DURATION_EVENTS = {
    "/jax/core/compile/backend_compile_duration":
        ("jax.compiles", "jax.compile", "jax.compile.duration_seconds"),
    "/jax/core/compile/jaxpr_trace_duration":
        ("jax.traces", "jax.trace", "jax.trace.duration_seconds"),
}

# exponential edges, 1 ms .. ~65 s: one XLA compile spans that whole
# range depending on program size, so linear edges resolve nothing
DURATION_BUCKETS = tuple(0.001 * 2 ** k for k in range(17)) + (float("inf"),)


def install() -> bool:
    """Idempotently register the monitoring listeners. Returns True if the
    probes are active (False when jax lacks the monitoring API)."""
    global _installed
    with _lock:
        if _installed:
            return True
        try:
            from jax import monitoring
        except ImportError:  # pragma: no cover - jax always present
            return False
        if not hasattr(monitoring, "register_event_duration_secs_listener"):
            return False  # pragma: no cover - ancient jax

        def _on_duration(name: str, duration: float, **_kw) -> None:
            hit = _DURATION_EVENTS.get(name)
            if hit is None:
                return
            from shifu_tpu.obs import registry

            reg = registry()
            reg.counter(hit[0]).inc()
            reg.timer(hit[1]).add(duration)
            reg.histogram(hit[2], buckets=DURATION_BUCKETS).observe(duration)

        monitoring.register_event_duration_secs_listener(_on_duration)
        _installed = True
        return True
