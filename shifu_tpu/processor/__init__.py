"""Lifecycle step processors — one per CLI subcommand.

Mirrors the reference's core/processor/* layer: every processor loads and
validates the two configs, runs its step, and persists updated state
(BasicModelProcessor.java:57 contract)."""
