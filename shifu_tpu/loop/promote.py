"""`shifu promote` — gate a candidate rollout on shadow agreement + drift.

The decision is computed from evidence, not vibes:

  gate "shadow"  the staged candidate's live shadow stats (agreement rate
                 over >= `-Dshifu.loop.promoteMinRows` rows must reach
                 `-Dshifu.loop.promoteAgree`, and shadow scoring must not
                 have errored). Against a RUNNING server the stats come
                 from GET /admin/shadow; offline they come from the last
                 serve manifest's shadow snapshot, so a canary verdict is
                 decidable from the run ledger alone.
  gate "drift"   the candidate must not be promoted while the ACTIVE set
                 shows no drift and the candidate brings nothing — wait,
                 inverted: drift on the active set is the reason TO roll
                 forward. The gate only BLOCKS when the ledger carries no
                 retrain recommendation AND the operator did not pass
                 --no-drift-gate/--force; a recommendation manifest (or a
                 live degraded /healthz with a psi reason) satisfies it.

Every run writes a `promote-<seq>.json` ledger manifest with the gate
evidence and the decision — promoted or held, the audit trail exists.

Execution: with `--serve-url` the promotion is a POST /admin/promote
(zero-downtime hot-swap in the running server); without one it is an
offline atomic dir swap: `models/` -> `models.previous/`, candidate ->
`models/` (os.replace-based, torn-state-proof via a rename sequence that
always leaves a loadable models dir).

Fleet mode (failure domains, round 14): when live process leases exist
under `.shifu/runs/peers/` (N `shifu serve` processes share this model
set), the offline path becomes a FLEET-ATOMIC two-phase commit
(loop/rounds.py): a prepare record fans out the sha-bound candidate to
every live leaseholder, each stages + validates it on its whole replica
fleet (the in-process pre-roll validation is phase one) and acks, and
the commit record lands only on unanimous acks from the lease-fenced
peer set — re-checked against the live leases immediately before — all
within one lease TTL. Any nack, missing ack, fence break (a peer died
or restarted mid-round) or deadline pass aborts the round and every
staged process rolls back to active: a half-promoted fleet is
impossible. `--serve-url` against a root where MULTIPLE processes hold
leases is refused — promoting one process of a fleet is exactly the
half-promotion the protocol exists to prevent.
"""

from __future__ import annotations

import json
import os
import time
import urllib.request
from typing import Optional

from shifu_tpu.loop import (
    promote_agree_setting,
    promote_min_rows_setting,
)
from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)


def _http_json(url: str, payload: Optional[dict] = None,
               timeout: float = 30.0) -> dict:
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(
        url, data=data,
        headers={"Content-Type": "application/json"} if data else {},
        method="POST" if data is not None else "GET")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def latest_recommendation(root: str) -> Optional[dict]:
    """Newest retrain recommendation manifest, if the drift monitor ever
    stamped one."""
    from shifu_tpu.obs.ledger import list_runs

    runs = list_runs(root, last=1, step="recommend")
    return runs[0] if runs else None


def latest_serve_shadow(root: str) -> Optional[dict]:
    """Shadow snapshot from the newest serve manifest (the offline
    evidence path)."""
    from shifu_tpu.obs.ledger import list_runs

    for m in list_runs(root, step="serve"):
        shadow = (m.get("serve") or {}).get("shadow")
        if shadow:
            return shadow
    return None


def retrain_lineage(root: str, candidate_sha: Optional[str]) -> Optional[dict]:
    """Serve -> train -> promote lineage for the promote manifest: the
    retrain manifest that produced this candidate (matched by candidate
    model-set sha; newest retrain when the sha is unknown) plus the
    traffic-log trace evidence it recorded — so a promoted rollout
    points back at the exact request traces it was trained on."""
    from shifu_tpu.obs.ledger import list_runs

    for m in list_runs(root, step="retrain"):
        rt = m.get("retrain") or {}
        cand = (rt.get("candidate") or {}).get("modelSetSha")
        if candidate_sha is not None and cand != candidate_sha:
            continue
        return {
            "retrainManifest": os.path.basename(m.get("path", "")),
            "parentModelSetSha": (rt.get("parent") or {}).get(
                "modelSetSha"),
            "candidateModelSetSha": cand,
            "source": (rt.get("source") or {}).get("kind"),
            "traffic": rt.get("lineage"),
        }
    return None


def evaluate_gates(shadow: Optional[dict], recommendation: Optional[dict],
                   agree_min: Optional[float] = None,
                   min_rows: Optional[int] = None,
                   require_drift: bool = True,
                   candidate_sha: Optional[str] = None,
                   active_sha: Optional[str] = None) -> dict:
    """Pure gate evaluation — the piece tests pin. Returns
    {promote: bool, gates: {...}} with one entry per gate and a reason
    for every failure.

    `candidate_sha` binds the shadow evidence to the candidate actually
    being promoted — agreement earned by a previously staged set must
    not green-light a different one. `active_sha` binds the drift gate
    to the CURRENT active set: a recommendation stamped against an
    older sha is stale (that drift was already acted on, or the set was
    replaced some other way) and blocks rather than passes. Either
    check is skipped when its sha is unknown (None)."""
    agree_min = (promote_agree_setting() if agree_min is None
                 else float(agree_min))
    min_rows = (promote_min_rows_setting() if min_rows is None
                else int(min_rows))
    gates = {}

    if shadow is None:
        gates["shadow"] = {"ok": False,
                           "reason": "no shadow stats (stage the "
                                     "candidate and let it see traffic)"}
    elif (candidate_sha and shadow.get("sha")
          and shadow["sha"] != candidate_sha):
        gates["shadow"] = {"ok": False,
                           "reason": f"shadow evidence describes "
                                     f"{shadow['sha']}, not the candidate "
                                     f"{candidate_sha} — stage THIS "
                                     "candidate and let it see traffic",
                           "stats": shadow}
    elif shadow.get("errors"):
        gates["shadow"] = {"ok": False,
                           "reason": f"shadow scoring errored "
                                     f"{shadow['errors']} time(s)",
                           "stats": shadow}
    elif shadow.get("rows", 0) < min_rows:
        gates["shadow"] = {"ok": False,
                           "reason": f"only {shadow.get('rows', 0)} shadow "
                                     f"rows (< {min_rows})",
                           "stats": shadow}
    elif shadow.get("agreement", 0.0) < agree_min:
        gates["shadow"] = {"ok": False,
                           "reason": f"agreement "
                                     f"{shadow.get('agreement', 0.0):.4f} "
                                     f"< {agree_min:g}",
                           "stats": shadow}
    else:
        gates["shadow"] = {"ok": True, "stats": shadow}

    if not require_drift:
        gates["drift"] = {"ok": True, "reason": "gate disabled"}
    elif recommendation is None:
        gates["drift"] = {"ok": False,
                          "reason": "no retrain recommendation in the "
                                    "ledger — nothing says the active set "
                                    "needs replacing (--no-drift-gate to "
                                    "override)"}
    else:
        rec = recommendation.get("recommendation", {})
        rec_summary = {
            "driftedColumns": (rec.get("drift") or {}).get(
                "driftedColumns"),
            "maxPsi": (rec.get("drift") or {}).get("maxPsi"),
            "modelSetSha": rec.get("modelSetSha"),
        }
        if (active_sha and rec.get("modelSetSha")
                and rec["modelSetSha"] != active_sha):
            gates["drift"] = {
                "ok": False,
                "reason": f"newest retrain recommendation targets sha "
                          f"{rec['modelSetSha']} but the active set is "
                          f"{active_sha} — that drift was already acted "
                          "on; nothing says the CURRENT set needs "
                          "replacing (--no-drift-gate to override)",
                "recommendation": rec_summary,
            }
        else:
            gates["drift"] = {"ok": True, "recommendation": rec_summary}
    return {"promote": all(g["ok"] for g in gates.values()),
            "gates": gates,
            "agreeMin": agree_min, "minRows": min_rows}


def _models_sha(models_dir: Optional[str]) -> Optional[str]:
    """Content sha of a model dir — the exact identity the registry
    serves under — or None when there is no readable model set there."""
    from shifu_tpu.serve.registry import find_model_paths, model_set_sha

    if not models_dir or not os.path.isdir(models_dir):
        return None
    try:
        paths = find_model_paths(models_dir)
        return model_set_sha(paths) if paths else None
    except OSError:
        return None


def live_peers(root: str) -> list:
    """Live (un-expired) process leases under the root — the set a
    fleet-atomic promotion must fence."""
    from shifu_tpu.resilience import lease

    return [p for p in lease.scan(root) if not p["expired"]]


def round_deadline_ms_setting() -> float:
    """shifu.promote.roundDeadlineMs — promotion-round ack deadline
    (0 = one lease TTL). Raise it for candidates whose fleet-wide
    stage + warm outlasts a TTL: fence SAFETY does not depend on the
    deadline (the fence is re-checked against the live lease files
    immediately before commit, and participants renew right after their
    device-heavy stage) — the TTL default is just the tightest deadline
    that cannot outlive its own liveness evidence."""
    from shifu_tpu.utils import environment

    return environment.get_float("shifu.promote.roundDeadlineMs", 0.0)


def run_promotion_round(root: str, candidate_dir: str,
                        candidate_sha: str, peers: list) -> dict:
    """The two-phase commit coordinator (loop/rounds.py records).

    Prepare fences the CURRENT live incarnations (leaseId/token/epoch);
    every fenced peer must stage + validate the sha-bound candidate and
    ack before the deadline (one lease TTL out, or
    -Dshifu.promote.roundDeadlineMs). The commit record is written only
    after (a) unanimous ok-acks, (b) a fence re-check against the live
    lease files, (c) no abort record exists (a participant that
    self-aborted at deadline+grace writes one — its rollback must win),
    and (d) the deadline has not passed. Everything else aborts.

    The whole round runs under ONE trace id (`round-<rid>`), stamped
    into the prepare record: this coordinator's prepare/acks/fence/
    commit spans and every participant's stage/ack/commit spans share
    it, so `shifu trace --fleet` renders the round as one stitched
    cross-process timeline."""
    from shifu_tpu.loop import rounds
    from shifu_tpu.obs import reqtrace
    from shifu_tpu.resilience import lease

    fence = [{"leaseId": p["leaseId"], "token": p["token"],
              "epoch": p["epoch"]} for p in peers]
    ttl_s = max(float(p.get("ttlMs", 5000.0)) for p in peers) / 1000.0
    deadline_s = round_deadline_ms_setting() / 1000.0 or ttl_s
    rid = rounds.new_round_id()
    deadline = time.time() + deadline_s
    rt = reqtrace.RequestTrace(trace_id=f"round-{rid}", sampled=True)
    rt.annotate(role="coordinator", round=rid, sha=candidate_sha,
                peers=len(fence))
    with rt.stage("prepare"):
        rounds.write_prepare(root, rid, candidate_dir, candidate_sha,
                             fence, deadline, trace=rt.trace_id)
    log.info("promotion round %s: prepared for %d peer(s), deadline in "
             "%.1f s", rid, len(fence), deadline_s)
    want = {f["leaseId"] for f in fence}
    out = {"round": rid, "peers": fence, "acks": {}, "committed": False,
           "deadlineUnix": deadline, "trace": rt.trace_id}

    def _finish(outcome: str) -> None:
        rt.annotate(outcome=outcome)
        reqtrace.buffer().offer(rt)

    def _abort(reason: str) -> dict:
        with rt.stage("abort"):
            rounds.write_abort(root, rid, reason)
        out["reason"] = reason
        _finish("abort")
        log.warning("promotion round %s aborted: %s", rid, reason)
        return out

    t_acks = time.perf_counter()
    while True:
        state = rounds.read_round(root, rid)
        out["acks"] = state["acks"]
        nacks = [a for a in state["acks"].values() if not a.get("ok")]
        if nacks:
            return _abort("peer " + nacks[0]["leaseId"] + " refused: "
                          + str(nacks[0].get("reason")))
        bad_sha = [a for a in state["acks"].values()
                   if a.get("stagedSha") != candidate_sha]
        if bad_sha:
            return _abort(f"peer {bad_sha[0]['leaseId']} staged "
                          f"{bad_sha[0].get('stagedSha')}, not the "
                          f"candidate {candidate_sha}")
        if want <= set(state["acks"]):
            break
        if time.time() >= deadline:
            missing = sorted(want - set(state["acks"]))
            return _abort("no ack from " + ", ".join(missing)
                          + " within the lease TTL")
        time.sleep(rounds.ROUND_POLL_S)
    rt.add_stage("acks", time.perf_counter() - t_acks, t_acks)
    # unanimous — but only the SAME incarnations that acked may commit:
    # a peer that died (lease expired/vanished) or restarted (token or
    # epoch changed) after acking cannot apply the commit, and a fleet
    # minus one is a half-promoted fleet
    with rt.stage("fence"):
        broken = lease.fence_check(root, fence)
    if broken:
        return _abort("; ".join(broken))
    if rounds.read_round(root, rid)["abort"] is not None:
        # a participant self-aborted (it judged the coordinator dead at
        # deadline+grace) — its rollback already happened and MUST win;
        # committing over it would split the fleet
        out["reason"] = "a participant aborted the round first"
        _finish("stale")
        log.warning("promotion round %s: not committing — %s",
                    rid, out["reason"])
        return out
    if time.time() >= deadline:
        # participants may already be rolling back — committing now
        # could split the fleet
        return _abort("unanimous acks arrived after the deadline")
    with rt.stage("commit"):
        rounds.write_commit(root, rid, candidate_sha)
    out["committed"] = True
    _finish("commit")
    log.info("promotion round %s: committed %s on %d peer(s)",
             rid, candidate_sha, len(fence))
    return out


def offline_swap(root: str, candidate_dir: str) -> dict:
    """Atomic-enough dir swap for a non-running model set: the current
    `models/` moves aside to `models.previous/`, the candidate renames
    into place. Both moves are single `os.replace`/`os.rename` calls, so
    a kill leaves either the old or the new layout with a loadable
    models dir recoverable by hand — never merged halves."""
    import shutil

    models = os.path.join(os.path.abspath(root), "models")
    previous = models + ".previous"
    candidate_dir = os.path.abspath(candidate_dir)
    if not os.path.isdir(candidate_dir):
        raise FileNotFoundError(f"candidate dir {candidate_dir} not found")
    if os.path.isdir(previous):
        shutil.rmtree(previous)
    if os.path.isdir(models):
        os.rename(models, previous)
    os.rename(candidate_dir, models)
    return {"models": models, "previous": previous}


def run_promote(root: str, candidate_dir: Optional[str],
                serve_url: Optional[str] = None,
                agree_min: Optional[float] = None,
                min_rows: Optional[int] = None,
                require_drift: bool = True,
                force: bool = False,
                stage_first: bool = False,
                set_name: Optional[str] = None) -> int:
    """The `shifu promote` entry point. Returns the process exit code:
    0 promoted, 1 held by a gate, 2 operational error."""
    import sys
    import time

    from shifu_tpu import obs
    from shifu_tpu.obs.ledger import RunLedger

    t0 = time.time()
    shadow = None
    active_sha = None
    if set_name and not serve_url:
        # a zoo tenant only exists inside a serve process: the offline
        # and fleet-round paths swap the root's models/ dir, which has
        # no per-set meaning
        log.error("promote: --set %s needs --serve-url (model-zoo "
                  "tenants live in a serving process)", set_name)
        return 2
    peers = live_peers(root)
    if serve_url and len(peers) > 1:
        # promoting ONE process of a multi-process fleet through its
        # /admin plane is exactly the half-promotion the lease-fenced
        # round exists to prevent
        log.error("promote: %d live serve processes hold leases under "
                  "%s — drop --serve-url and run the fleet-atomic "
                  "promote instead", len(peers), root)
        return 2
    mode = ("http" if serve_url
            else "fleet" if peers else "offline")
    try:
        if serve_url:
            serve_url = serve_url.rstrip("/")
            if stage_first and candidate_dir:
                stage_doc = {"modelsDir": os.path.abspath(candidate_dir)}
                if set_name:
                    stage_doc["set"] = set_name
                _http_json(f"{serve_url}/admin/stage", stage_doc)
            shadow_url = f"{serve_url}/admin/shadow"
            if set_name:
                shadow_url += f"?set={set_name}"
            resp = _http_json(shadow_url)
            shadow = resp.get("shadow")
            active_sha = resp.get("active")
        else:
            shadow = latest_serve_shadow(root)
            active_sha = _models_sha(os.path.join(os.path.abspath(root),
                                                  "models"))
    except (OSError, ValueError) as e:  # unreachable server / bad JSON
        log.error("promote: cannot reach shadow stats: %s", e)
        return 2
    recommendation = latest_recommendation(root)
    # resolved BEFORE any swap: offline_swap renames the candidate dir
    # into models/, after which the sha (and therefore the lineage
    # match below) would be unrecoverable
    candidate_sha = _models_sha(candidate_dir)
    decision = evaluate_gates(shadow, recommendation,
                              agree_min=agree_min, min_rows=min_rows,
                              require_drift=require_drift,
                              candidate_sha=candidate_sha,
                              active_sha=active_sha)
    swap = None
    error = None
    round_info = None
    if mode == "fleet":
        # the two-phase round IS the shadow-validation gate here: every
        # live leaseholder must stage the sha-bound candidate on its
        # whole replica fleet and ack. Ledger shadow evidence (if an
        # operator staged earlier) stays in the manifest as context.
        # `--force` can override the DRIFT gate, never a failed round —
        # unanimity is a safety property, not an operator preference.
        drift_ok = decision["gates"]["drift"]["ok"]
        if force and not drift_ok:
            decision["forced"] = True
        decision["promote"] = False
        if drift_ok or force:
            try:
                if not candidate_dir or candidate_sha is None:
                    raise ValueError(
                        "fleet promote needs a readable candidate dir "
                        "(default models.candidate is missing)")
                round_info = run_promotion_round(
                    root, os.path.abspath(candidate_dir),
                    candidate_sha, peers)
            except (OSError, ValueError) as e:
                error = f"{type(e).__name__}: {e}"
                round_info = None
            committed = bool(round_info and round_info["committed"])
            decision["gates"]["shadow"] = {
                "ok": committed,
                "reason": (None if committed else
                           (round_info or {}).get("reason", error)),
                "fleetValidated": committed,
                "acks": len((round_info or {}).get("acks", {})),
                "round": (round_info or {}).get("round"),
            }
            decision["promote"] = committed
            if committed:
                try:
                    # the commit record is the atomic decision; the dir
                    # swap makes it durable for future process starts
                    swap = offline_swap(root, candidate_dir)
                    swap.update({"mode": "fleet",
                                 "round": round_info["round"],
                                 "peers": len(round_info["peers"])})
                except (OSError, ValueError) as e:
                    # the fleet IS promoted (every live process swapped);
                    # only the on-disk layout lags — surfaced loudly for
                    # the operator, re-running promote converges it
                    error = (f"committed but dir swap failed: "
                             f"{type(e).__name__}: {e}")
    else:
        if force and not decision["promote"]:
            decision["forced"] = True
            decision["promote"] = True
        if decision["promote"]:
            try:
                if serve_url:
                    # bind the swap to the sha the gates evaluated: a
                    # re-staged shadow between the gate read and this POST
                    # is refused server-side (409), never rolled out blind
                    promote_doc = {"sha": (shadow or {}).get("sha")}
                    if set_name:
                        promote_doc["set"] = set_name
                    swap = _http_json(f"{serve_url}/admin/promote",
                                      promote_doc)
                else:
                    if not candidate_dir:
                        raise ValueError(
                            "offline promote needs a candidate dir "
                            "(default models.candidate is missing)")
                    swap = offline_swap(root, candidate_dir)
            except (OSError, ValueError) as e:  # failed swap: held + ledgered
                error = f"{type(e).__name__}: {e}"
                decision["promote"] = False
    # the audit trail: every promote attempt is a ledger manifest,
    # carrying the serve->train lineage of the candidate it gated
    try:
        lineage = retrain_lineage(root, candidate_sha)
    except (OSError, ValueError) as e:
        log.warning("promote: cannot resolve retrain lineage: %s", e)
        lineage = None
    try:
        ledger = RunLedger(root)
        seq = ledger.next_seq("promote")
        path = ledger.write(
            "promote", seq,
            status="ok" if error is None else "failed",
            exit_status=0 if decision["promote"] else 1,
            started_at=t0, elapsed_seconds=time.time() - t0,
            argv=list(sys.argv), registry=obs.registry(),
            error=error,
            extra={"promote": {"mode": mode,
                               "set": set_name,
                               "candidateDir": candidate_dir,
                               "decision": decision,
                               "lineage": lineage,
                               "round": round_info,
                               "swap": swap}},
        )
        log.info("promote manifest -> %s", path)
        if mode == "fleet" and round_info is not None:
            # the coordinator's round spans, beside the manifest — the
            # half `shifu trace --fleet` stitches with the participants'
            from shifu_tpu.obs import reqtrace

            traces_path = os.path.join(ledger.dir,
                                       f"promote-{seq}.traces.json")
            if reqtrace.buffer().write_traces(traces_path):
                log.info("round trace -> %s", traces_path)
    except OSError as e:
        log.warning("cannot write promote manifest: %s", e)
    if error:
        log.error("promote failed: %s", error)
        return 2
    if not decision["promote"]:
        for name, g in decision["gates"].items():
            if not g["ok"]:
                log.error("promote held by %s gate: %s", name, g["reason"])
        return 1
    log.info("promoted: %s", swap)
    return 0
