"""`shifu train` for WDL — dense numerics from NormalizedData, categorical
codes from CleanedData (parity: prepareWDLParams TrainModelProcessor.java:1474,
wdl/WDLWorker input wiring: numeric z-score + categorical sparse index)."""

from __future__ import annotations

import os

import numpy as np

from shifu_tpu.norm.dataset import load_codes, load_normalized
from shifu_tpu.utils.errors import ErrorCode, ShifuError
from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)


def train_wdl_models(proc) -> None:
    from shifu_tpu.models.wdl import WDLModelSpec
    from shifu_tpu.norm.normalizer import (
        build_norm_plan,
        norm_columns,
        plan_to_json,
        spec_to_json,
    )
    from shifu_tpu.train.wdl_trainer import WDLTrainConfig, train_wdl

    mc = proc.model_config
    norm_dir = proc.paths.normalized_data_dir()
    codes_dir = proc.paths.cleaned_data_dir()
    if not (os.path.isdir(norm_dir) and os.path.isdir(codes_dir)):
        raise ShifuError(ErrorCode.DATA_NOT_FOUND,
                         "run `shifu norm` before WDL training")
    nmeta, feats, tags, weights = load_normalized(norm_dir)
    cmeta, codes, _, _ = load_codes(codes_dir)

    cols = norm_columns(proc.column_configs)
    by_name = {c.column_name: c for c in cols}

    # numeric feature columns come from the normalized matrix; categorical
    # ones from the code matrix (embedding + wide indices)
    num_idx, num_names = [], []
    for j, name in enumerate(nmeta.columns):
        cc = by_name.get(name)
        if cc is not None and not cc.is_categorical():
            num_idx.append(j)
            num_names.append(name)
    cat_idx, cat_names, vocab_sizes, categories = [], [], [], []
    for j, name in enumerate(cmeta.columns):
        cc = by_name.get(name)
        if cc is not None and cc.is_categorical():
            cat_idx.append(j)
            cat_names.append(name)
            vocab_sizes.append(int(cmeta.extra["slots"][j]))
            categories.append(list(cc.column_binning.bin_category or []))

    dense = np.asarray(feats, np.float32)[:, num_idx]
    cat_codes = np.asarray(codes, np.int32)[:, cat_idx]
    tags = np.asarray(tags, np.float32)
    weights = np.asarray(weights, np.float32)
    log.info("WDL inputs: %d dense cols, %d embed fields (vocab %s)",
             len(num_names), len(cat_names), vocab_sizes)

    plan = build_norm_plan(mc, proc.column_configs)
    dense_specs = [
        spec_to_json(s) for s in plan.specs if s.cc.column_name in set(num_names)
    ]

    proc.paths.ensure(proc.paths.models_dir())
    proc.paths.ensure(proc.paths.train_dir())
    bagging = max(1, int(mc.train.bagging_num or 1))
    for i in range(bagging):
        cfg = WDLTrainConfig.from_model_config(mc, trainer_id=i)
        res = train_wdl(dense, cat_codes, tags, weights, vocab_sizes, cfg,
                        mesh=proc._mesh())
        spec = WDLModelSpec(
            hidden=list(cfg.hidden),
            activations=list(cfg.activations),
            embed_dim=cfg.embed_dim,
            dense_columns=num_names,
            cat_columns=cat_names,
            vocab_sizes=vocab_sizes,
            norm_specs=dense_specs,
            norm_cutoff=plan.cutoff,
            categories=categories,
            norm_type=mc.normalize.norm_type.value,
            params=res.params,
            train_error=res.train_error,
            valid_error=res.valid_error,
        )
        path = proc.paths.model_path(i, "wdl")
        spec.save(path)
        with open(proc.paths.val_error_path(i), "w") as fh:
            fh.write(f"{res.valid_error}\n")
        log.info("model %d (WDL) -> %s (valid err %.6f)", i, path,
                 res.valid_error)
