"""Chunked, bounded-memory dataset ingestion.

The reference never holds a dataset in RAM: rows stream through Pig/MR
mappers and training datasets spill to disk past a memory envelope
(core/dtrain/dataset/MemoryDiskFloatMLDataSet.java, shifuconfig:46-50).
This module is the TPU-build analog: data is read in fixed-row chunks
(CSV/gzip/Parquet), every stats/norm stage consumes the chunk stream, and
peak host memory is bounded by the chunk size — never the dataset size.

The operational knobs mirror the reference's shifuconfig memory envelope:
    shifu.ingest.chunkRows        rows per chunk (default 65536)
    shifu.ingest.memoryBudgetMB   datasets whose files exceed this budget
                                  switch to the streaming path (default 512)
    shifu.ingest.prefetchChunks   background prefetch depth for the
                                  overlapped pipeline (data/pipeline.py;
                                  default 2, 0 = serial)
"""

from __future__ import annotations

import os
from typing import Callable, Iterator, List, Optional, Sequence

import numpy as np

from shifu_tpu.data.reader import (
    DEFAULT_MISSING,
    ColumnarData,
    _expand_paths,
    drop_stray_header_rows,
)
from shifu_tpu.utils import environment

DEFAULT_CHUNK_ROWS = 65536
DEFAULT_MEMORY_BUDGET_MB = 512

PARQUET_SUFFIXES = (".parquet", ".parq")


def chunk_rows_setting() -> int:
    return environment.get_int("shifu.ingest.chunkRows", DEFAULT_CHUNK_ROWS)


def memory_budget_bytes() -> int:
    mb = environment.get_int("shifu.ingest.memoryBudgetMB",
                             DEFAULT_MEMORY_BUDGET_MB)
    return int(mb) * 1024 * 1024


def dataset_size_bytes(data_path: str) -> int:
    from shifu_tpu.fs.source import size_of

    return sum(size_of(p) for p in _expand_paths(data_path))


def should_stream(data_path: str) -> bool:
    """Stream when the raw files exceed the configured memory budget (the
    in-RAM object representation costs several times the file size)."""
    if environment.get_property("shifu.ingest.forceStreaming", "") in (
        "true", "1",
    ):
        return True
    return dataset_size_bytes(data_path) > memory_budget_bytes()


def _is_parquet(path: str) -> bool:
    return path.endswith(PARQUET_SUFFIXES)


def _string_dtype():
    """Chunk column dtype: pyarrow-backed strings when available (compact
    contiguous buffers — a 500-byte padding field costs 500 bytes, not a
    ~550-byte Python object per row), plain object strings otherwise. The
    LazyColumns facade (data/reader.py) keeps columns in this storage until
    a stage actually reads them, so the bounded-memory envelope holds."""
    try:
        import pyarrow  # noqa: F401

        return "string[pyarrow]"
    except ImportError:
        return str


def _iter_csv_chunks(
    path: str, names: List[str], delimiter: str, chunk_rows: int,
    usecols: Optional[List[str]] = None,
) -> Iterator["np.ndarray"]:
    import pandas as pd

    compression = "gzip" if path.endswith(".gz") else None
    reader = pd.read_csv(
        path,
        sep=delimiter,
        header=None,
        names=names,
        usecols=usecols,
        dtype=_string_dtype(),
        keep_default_na=False,
        compression=compression,
        engine="c",
        skip_blank_lines=True,
        on_bad_lines="skip",
        chunksize=chunk_rows,
    )
    for df in reader:
        yield df


def _iter_parquet_chunks(
    path: str, names: List[str], chunk_rows: int,
    usecols: Optional[List[str]] = None,
) -> Iterator["np.ndarray"]:
    """Parquet ingestion (reference: ModelNormalizeConf.isParquet,
    udf/NormalizeParquetUDF.java) via pyarrow record batches."""
    import pandas as pd
    import pyarrow.parquet as pq

    want = usecols if usecols is not None else names
    pf = pq.ParquetFile(path)
    cols = [c for c in want if c in pf.schema_arrow.names]
    for batch in pf.iter_batches(batch_size=chunk_rows, columns=cols or None):
        df = batch.to_pandas()
        # align to the expected header: missing columns become empty strings
        for c in want:
            if c not in df.columns:
                df[c] = ""
        # nulls must become the empty-string missing token BEFORE astype —
        # astype(str) would stringify them as "nan"/"None" and they'd dodge
        # the missing-value accounting the CSV path gets from
        # keep_default_na=False
        df = df[want].fillna("").astype(_string_dtype())
        yield df


def iter_columnar_chunks(
    data_path: str,
    names: List[str],
    delimiter: str = "|",
    missing_values: Sequence[str] = DEFAULT_MISSING,
    chunk_rows: Optional[int] = None,
    max_rows: Optional[int] = None,
    columns: Optional[Sequence[str]] = None,
) -> Iterator[ColumnarData]:
    """Yield ColumnarData chunks of at most chunk_rows across all part files.

    Pandas frames are converted chunk-by-chunk; nothing beyond one chunk is
    ever resident. `columns`, when given, restricts parsing to that subset
    of the header (pandas usecols): columns a stage never reads — fat meta/
    padding fields — are discarded at tokenizer level and cost no memory at
    all; the yielded chunks carry only the subset (original header order).
    """
    chunk_rows = chunk_rows or chunk_rows_setting()
    usecols = None
    out_names = list(names)
    if columns is not None:
        keep = set(columns)
        out_names = [n for n in names if n in keep]
        usecols = out_names
    remaining = max_rows
    for path in _expand_paths(data_path):
        if _is_parquet(path):
            frames = _iter_parquet_chunks(path, names, chunk_rows, usecols)
        else:
            frames = _iter_csv_chunks(path, names, delimiter, chunk_rows,
                                      usecols)
        for df in frames:
            # filter stray headers BEFORE the max_rows slice so dropped
            # headers don't consume budget
            df = drop_stray_header_rows(df, out_names)
            if remaining is not None:
                if remaining <= 0:
                    return
                df = df.iloc[:remaining]
                remaining -= len(df)
            if not len(df):
                continue
            # frame-backed: columns stay in pandas' compact (arrow) string
            # storage until a stage actually reads them
            yield ColumnarData.from_frame(
                df.reset_index(drop=True), out_names, missing_values
            )


def chunk_source(
    data_path: str,
    names: List[str],
    delimiter: str = "|",
    missing_values: Sequence[str] = DEFAULT_MISSING,
    chunk_rows: Optional[int] = None,
    max_rows: Optional[int] = None,
    columns: Optional[Sequence[str]] = None,
) -> Callable[[], Iterator[ColumnarData]]:
    """A re-iterable chunk factory — multi-pass algorithms (two-pass stats)
    call it once per pass."""

    def factory() -> Iterator[ColumnarData]:
        return iter_columnar_chunks(
            data_path, names, delimiter, missing_values, chunk_rows,
            max_rows, columns,
        )

    return factory
