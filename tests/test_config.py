"""ModelConfig / ColumnConfig JSON round-trip and validation tests."""

import json
import math
import os

from shifu_tpu.config import (
    Algorithm,
    ColumnConfig,
    ColumnFlag,
    ColumnType,
    ModelConfig,
    NormType,
    RunMode,
    load_column_config_list,
    save_column_config_list,
)
from shifu_tpu.config.inspector import ModelStep, probe
from shifu_tpu.config.model_config import new_model_config

# A reference-format ModelConfig.json (shape per container/obj/ModelConfig.java)
REFERENCE_STYLE_JSON = {
    "basic": {
        "name": "TestWoeZscale",
        "author": "someone",
        "description": "x",
        "version": "0.2.0",
        "runMode": "LOCAL",
        "postTrainOn": False,
        "customPaths": {},
    },
    "dataSet": {
        "source": "LOCAL",
        "dataPath": "./data",
        "dataDelimiter": "|",
        "headerPath": "./data/.pig_header",
        "headerDelimiter": "|",
        "filterExpressions": "",
        "weightColumnName": "",
        "targetColumnName": "diagnosis",
        "posTags": ["M"],
        "negTags": ["B"],
        "missingOrInvalidValues": ["", "*", "#", "?", "null", "~"],
        "metaColumnNameFile": "columns/meta.column.names",
        "categoricalColumnNameFile": "columns/categorical.column.names",
    },
    "stats": {
        "maxNumBin": 10,
        "binningMethod": "EqualPositive",
        "sampleRate": 0.8,
        "sampleNegOnly": False,
        "binningAlgorithm": "SPDTI",
        "psiColumnName": "",
    },
    "varSelect": {
        "forceEnable": True,
        "filterEnable": True,
        "filterNum": 200,
        "filterBy": "KS",
        "wrapperEnabled": False,
        "missingRateThreshold": 0.5,
        "filterBySE": True,
        "params": None,
    },
    "normalize": {
        "stdDevCutOff": 4.0,
        "sampleRate": 1.0,
        "sampleNegOnly": False,
        "normType": "WOE_ZSCORE",
    },
    "train": {
        "baggingNum": 5,
        "baggingWithReplacement": True,
        "baggingSampleRate": 1.0,
        "validSetRate": 0.2,
        "numTrainEpochs": 100,
        "epochsPerIteration": 1,
        "isContinuous": False,
        "workerThreadCount": 4,
        "algorithm": "NN",
        "params": {
            "NumHiddenLayers": 1,
            "ActivationFunc": ["tanh"],
            "NumHiddenNodes": [50],
            "LearningRate": 0.1,
            "Propagation": "Q",
        },
        "customPaths": {},
    },
    "evals": [
        {
            "name": "Eval1",
            "dataSet": {
                "source": "LOCAL",
                "dataPath": "./evaldata",
                "dataDelimiter": "|",
                "headerPath": "",
                "headerDelimiter": "|",
                "filterExpressions": "",
                "weightColumnName": "",
            },
            "performanceBucketNum": 10,
            "performanceScoreSelector": "mean",
            "scoreMetaColumnNameFile": "",
            "customPaths": {},
        }
    ],
}


def test_model_config_reference_format_roundtrip(tmp_path):
    path = tmp_path / "ModelConfig.json"
    path.write_text(json.dumps(REFERENCE_STYLE_JSON))
    mc = ModelConfig.load(str(path))
    assert mc.basic.name == "TestWoeZscale"
    assert mc.basic.run_mode == RunMode.LOCAL
    assert mc.data_set.target_column_name == "diagnosis"
    assert mc.data_set.pos_tags == ["M"]
    assert mc.stats.max_num_bin == 10
    assert mc.normalize.norm_type == NormType.WOE_ZSCORE
    assert mc.train.algorithm == Algorithm.NN
    assert mc.train.get_param("NumHiddenNodes") == [50]
    assert mc.train.get_param("numhiddennodes") == [50]  # case-insensitive
    assert len(mc.evals) == 1 and mc.evals[0].name == "Eval1"

    out = tmp_path / "out.json"
    mc.save(str(out))
    data = json.loads(out.read_text())
    assert data["basic"]["runMode"] == "LOCAL"
    assert data["normalize"]["normType"] == "WOE_ZSCORE"
    assert data["train"]["params"]["NumHiddenNodes"] == [50]
    # reload of our own output is stable
    mc2 = ModelConfig.load(str(out))
    assert mc2.to_json() == mc.to_json()


def test_run_mode_case_insensitive():
    assert RunMode.parse("local") == RunMode.LOCAL
    assert RunMode.parse("DIST") == RunMode.DIST
    assert RunMode.parse("tpu") == RunMode.TPU
    assert NormType.parse("woe_zscale") == NormType.WOE_ZSCALE


def test_column_config_roundtrip(tmp_path):
    cc = ColumnConfig(column_num=2, column_name="col4", column_type=ColumnType.N)
    cc.column_stats.mean = 18.89
    cc.column_stats.std_dev = 4.17
    cc.column_binning.length = 3
    cc.column_binning.bin_boundary = [-math.inf, 17.0, 18.8]
    cc.column_binning.bin_count_pos = [12, 12, 13, 0]
    cc.column_binning.bin_count_neg = [111, 52, 19, 1]
    cc.final_select = True

    path = str(tmp_path / "ColumnConfig.json")
    save_column_config_list(path, [cc])
    raw = json.load(open(path))
    assert raw[0]["columnBinning"]["binBoundary"][0] == "-Infinity"
    assert raw[0]["columnType"] == "N"

    loaded = load_column_config_list(path)
    assert loaded[0].column_binning.bin_boundary[0] == -math.inf
    assert loaded[0].column_binning.bin_count_pos == [12, 12, 13, 0]
    assert loaded[0].final_select is True
    assert loaded[0].is_numerical()


def test_column_flags():
    cc = ColumnConfig(column_name="t", column_flag=ColumnFlag.TARGET)
    assert cc.is_target() and not cc.is_feature()
    cc2 = ColumnConfig(column_name="x")
    assert cc2.is_feature()


def test_inspector_catches_bad_train():
    mc = new_model_config("m", Algorithm.NN)
    mc.train.valid_set_rate = 1.5
    result = probe(mc, ModelStep.TRAIN)
    assert not result.status
    assert any("validSetRate" in c for c in result.causes)


def test_inspector_data_path(tmp_path):
    mc = new_model_config("m", Algorithm.NN)
    mc.data_set.data_path = str(tmp_path / "nope.csv")
    mc.data_set.target_column_name = "y"
    result = probe(mc, ModelStep.INIT, base_dir=str(tmp_path))
    assert not result.status


class TestMetaValidation:
    """Meta-driven schema validation (MetaFactory.java:44 +
    ModelConfigMeta.json parity, config/meta.py)."""

    def _mc(self):
        from shifu_tpu.config.model_config import Algorithm, new_model_config

        mc = new_model_config("MetaTest", Algorithm.NN)
        mc.data_set.data_path = "data.txt"
        mc.data_set.target_column_name = "t"
        return mc

    def test_clean_config_passes(self):
        from shifu_tpu.config.meta import validate_model_config

        assert validate_model_config(self._mc()) == []

    def test_range_violations_reported_with_wire_names(self):
        from shifu_tpu.config.meta import validate_model_config

        mc = self._mc()
        mc.stats.sample_rate = 1.5
        mc.train.bagging_num = 0
        mc.train.valid_set_rate = 0.95
        errors = validate_model_config(mc)
        assert any("stats.sampleRate" in e and "1.5" in e for e in errors)
        assert any("train.baggingNum" in e for e in errors)
        assert any("train.validSetRate" in e for e in errors)

    def test_per_element_eval_validation(self):
        from shifu_tpu.config.meta import validate_model_config

        mc = self._mc()
        mc.evals[0].performance_bucket_num = 0
        errors = validate_model_config(mc)
        assert any("evals[0].performanceBucketNum" in e for e in errors)

    def test_probe_integrates_meta(self, tmp_path):
        import os

        from shifu_tpu.config.inspector import ModelStep, probe

        mc = self._mc()
        data = tmp_path / "data.txt"
        data.write_text("a|b\n")
        mc.data_set.data_path = str(data)
        mc.stats.max_num_bin = 1  # below the schema minimum of 2
        result = probe(mc, ModelStep.STATS, base_dir=str(tmp_path))
        assert not result.status
        assert any("stats.maxNumBin" in c for c in result.causes)
