"""Reference tree-ensemble binary/zip model-spec compatibility.

Byte-compatible reader/writer for the reference's GBT/RF model specs:

* binary ``model*.gbt`` / ``model*.rf`` written by
  core/dtrain/dt/BinaryDTSerializer.java:62 (gzip, version 4; older
  uncompressed v<=3 streams read too) and loaded by
  dt/IndependentTreeModel.loadFromStream (IndependentTreeModel.java:966);
* zip spec (entries ``model.ini`` Jackson JSON + ``trees``) produced by
  util/IndependentTreeModelUtils.java:40 (``shifu convert``).

Scoring mirrors IndependentTreeModel.compute (:352) / predictNode (:516)
vectorized over rows: each node routes its row subset with one boolean
mask instead of per-row pointer chasing.
"""

from __future__ import annotations

import gzip
import io
import json
import zipfile
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from shifu_tpu.compat.javaio import JavaDataInput, JavaDataOutput

TREE_FORMAT_VERSION = 4  # CommonConstants.TREE_FORMAT_VERSION
CONTINUOUS = 1  # FeatureType.CONTINUOUS byte
CATEGORICAL = 2  # FeatureType.CATEGORICAL byte
MAX_CATEGORICAL_VAL_LEN = 10 * 1024  # Constants.MAX_CATEGORICAL_VAL_LEN
GROUP_DELIMITER = "@^"  # Constants.CATEGORICAL_GROUP_VAL_DELIMITER
ROOT_INDEX = 1  # Node.ROOT_INDEX


@dataclass
class RefSplit:
    column_num: int
    feature_type: int  # CONTINUOUS | CATEGORICAL
    threshold: float = 0.0
    is_left: bool = False
    categories: Optional[np.ndarray] = None  # short indices in the bitset


@dataclass
class RefNode:
    id: int
    gain: float = 0.0
    wgt_cnt: float = 0.0
    split: Optional[RefSplit] = None
    predict: Optional[float] = None
    class_value: int = 0
    left: Optional["RefNode"] = None
    right: Optional["RefNode"] = None

    @property
    def is_real_leaf(self) -> bool:
        return self.left is None and self.right is None


@dataclass
class RefTree:
    tree_id: int
    node_num: int
    root: RefNode
    learning_rate: float = 1.0
    root_wgt_cnt: float = 0.0
    features: List[int] = field(default_factory=list)


@dataclass
class RefTreeModel:
    """In-memory image of the reference IndependentTreeModel."""

    algorithm: str  # GBT | RF
    loss: str
    is_classification: bool
    is_one_vs_all: bool
    input_node: int
    numerical_mean: Dict[int, float]
    column_names: Dict[int, str]  # columnNum -> name
    categorical_values: Dict[int, List[str]]  # columnNum -> merged bin categories
    column_mapping: Dict[int, int]  # columnNum -> input array index
    bags: List[List[RefTree]]
    version: int = TREE_FORMAT_VERSION

    # -- derived -------------------------------------------------------------
    def category_index(self, column_num: int) -> Dict[str, int]:
        """Flattened category -> bin index (merged @^ groups share an index,
        parity IndependentTreeModel.loadFromStream:1016)."""
        out: Dict[str, int] = {}
        for j, cat in enumerate(self.categorical_values.get(column_num, [])):
            if GROUP_DELIMITER in cat:
                for piece in cat.split(GROUP_DELIMITER):
                    out[piece] = j
            else:
                out[cat] = j
        return out

    def weights(self) -> List[List[float]]:
        return [[t.learning_rate for t in bag] for bag in self.bags]

    # -- scoring -------------------------------------------------------------
    def data_matrix(self, rows: List[Dict[str, object]]) -> np.ndarray:
        """Raw (columnName -> value) maps -> dense [n, inputs] array,
        parity convertDataMapToDoubleArray (IndependentTreeModel.java:571)."""
        n = len(rows)
        data = np.zeros((n, len(self.column_mapping)), dtype=np.float64)
        cat_idx = {c: self.category_index(c) for c in self.categorical_values}
        for col_num, idx in self.column_mapping.items():
            name = self.column_names.get(col_num)
            if col_num in self.categorical_values:
                size = len(self.categorical_values[col_num])
                table = cat_idx[col_num]
                for i, row in enumerate(rows):
                    obj = row.get(name)
                    j = table.get(str(obj), size) if obj is not None else size
                    data[i, idx] = j if 0 <= j <= size else size
            else:
                mean = self.numerical_mean.get(col_num, 0.0) or 0.0
                for i, row in enumerate(rows):
                    obj = row.get(name)
                    try:
                        v = float(obj)  # type: ignore[arg-type]
                    except (TypeError, ValueError):
                        v = mean
                    data[i, idx] = mean if np.isnan(v) else v
        return data

    def _route(self, node: RefNode, data: np.ndarray, rows: np.ndarray, out: np.ndarray):
        if node.is_real_leaf or node.split is None:
            out[rows] = node.class_value if self.is_classification else (node.predict or 0.0)
            return
        sp = node.split
        vals = data[rows, self.column_mapping[sp.column_num]]
        if sp.feature_type == CONTINUOUS:
            goes_left = vals < sp.threshold
        else:
            size = len(self.categorical_values.get(sp.column_num, []))
            idx = np.where((vals < 0) | (vals >= size), size, vals + 0.1).astype(np.int64)
            cats = set(int(c) for c in (sp.categories if sp.categories is not None else []))
            in_set = np.isin(idx, list(cats)) if cats else np.zeros(len(idx), bool)
            goes_left = in_set if sp.is_left else ~in_set
        if node.left is not None:
            self._route(node.left, data, rows[goes_left], out)
        if node.right is not None:
            self._route(node.right, data, rows[~goes_left], out)

    def predict_tree(self, tree: RefTree, data: np.ndarray) -> np.ndarray:
        out = np.zeros(data.shape[0], dtype=np.float64)
        self._route(tree.root, data, np.arange(data.shape[0]), out)
        return out

    def compute(self, data: np.ndarray, convert: str = "RAW") -> np.ndarray:
        """Regression scores, parity computeRegressionScore
        (IndependentTreeModel.java:387): GBT sums lr-weighted trees, RF does
        the weighted average; bags averaged."""
        data = np.asarray(data, dtype=np.float64)
        total = np.zeros(data.shape[0], dtype=np.float64)
        for bag in self.bags:
            per = np.stack([self.predict_tree(t, data) for t in bag], axis=1)
            wgts = np.array([t.learning_rate for t in bag])
            if self.algorithm.upper() == "GBT":
                raw = per @ wgts
                if convert == "OLD_SIGMOID":
                    raw = 1.0 / (1.0 + np.minimum(1.0e19, np.exp(-raw)))
                elif convert == "SIGMOID":
                    raw = 1.0 / (1.0 + np.minimum(1.0e19, np.exp(-20 * raw)))
                elif convert == "CUTOFF":
                    raw = np.clip(raw, 0.0, 1.0)
                total += raw
            else:
                total += (per @ wgts) / wgts.sum()
        return total / len(self.bags)


# ---------------------------------------------------------------------------
# binary stream format
# ---------------------------------------------------------------------------


def _read_category(di: JavaDataInput) -> str:
    marker = di.read_short()
    if marker < 0:
        return di._read(di.read_int()).decode("utf-8")  # noqa: SLF001
    return di.read_utf_body(marker)


def _write_category(do: JavaDataOutput, cat: str) -> None:
    if len(cat) < MAX_CATEGORICAL_VAL_LEN:
        do.write_utf(cat)
    else:
        do.write_short(-1)  # BinaryDTSerializer.UTF_BYTES_MARKER
        body = cat.encode("utf-8")
        do.write_int(len(body))
        do.write_raw(body)


def _read_split(di: JavaDataInput) -> RefSplit:
    col = di.read_int()
    ftype = di.read_byte()
    if ftype == CATEGORICAL:
        is_left = di.read_boolean()
        cats = None
        if not di.read_boolean():  # not-null marker
            words = np.frombuffer(
                bytes(di._read(di.read_int())), dtype=np.uint8  # noqa: SLF001
            )
            bits = np.unpackbits(words, bitorder="little")
            cats = np.nonzero(bits)[0].astype(np.int64)
        return RefSplit(col, ftype, is_left=is_left, categories=cats)
    return RefSplit(col, ftype, threshold=di.read_double())


def _write_split(do: JavaDataOutput, sp: RefSplit) -> None:
    do.write_int(sp.column_num)
    do.write_byte(sp.feature_type)
    if sp.feature_type == CATEGORICAL:
        do.write_boolean(sp.is_left)
        if sp.categories is None:
            do.write_boolean(True)
        else:
            do.write_boolean(False)
            max_idx = int(max(sp.categories)) if len(sp.categories) else 0
            bits = np.zeros(max_idx + 1, dtype=np.uint8)
            bits[np.asarray(sp.categories, dtype=np.int64)] = 1
            words = np.packbits(bits, bitorder="little")
            do.write_int(len(words))
            do.write_raw(words.tobytes())
    else:
        do.write_double(sp.threshold)


def _read_node(di: JavaDataInput, version: int) -> RefNode:
    node = RefNode(id=di.read_int(), gain=di.read_float())
    node.wgt_cnt = di.read_float() if version <= 2 else di.read_double()
    if di.read_boolean():
        node.split = _read_split(di)
    if di.read_boolean():  # isRealLeaf flag
        if di.read_boolean():  # predict non-null
            node.predict = di.read_double()
            node.class_value = di.read_byte()
    if di.read_boolean():
        node.left = _read_node(di, version)
    if di.read_boolean():
        node.right = _read_node(di, version)
    return node


def _write_node(do: JavaDataOutput, node: RefNode) -> None:
    do.write_int(node.id)
    do.write_float(node.gain)
    do.write_double(node.wgt_cnt)
    if node.split is None:
        do.write_boolean(False)
    else:
        do.write_boolean(True)
        _write_split(do, node.split)
    is_leaf = node.is_real_leaf
    do.write_boolean(is_leaf)
    if is_leaf:
        do.write_boolean(node.predict is not None)
        if node.predict is not None:
            do.write_double(node.predict)
            do.write_byte(node.class_value)
    for child in (node.left, node.right):
        if child is None:
            do.write_boolean(False)
        else:
            do.write_boolean(True)
            _write_node(do, child)


def _read_tree(di: JavaDataInput, version: int, with_features: bool = True) -> RefTree:
    tree_id = di.read_int()
    node_num = di.read_int()
    root = _read_node(di, version)
    lr = di.read_double()
    root_wgt = di.read_double() if root.id == ROOT_INDEX else 0.0
    features: List[int] = []
    if with_features:
        features = [di.read_int() for _ in range(di.read_int())]
    return RefTree(tree_id, node_num, root, lr, root_wgt, features)


def _write_tree(do: JavaDataOutput, tree: RefTree, with_features: bool = True) -> None:
    do.write_int(tree.tree_id)
    do.write_int(tree.node_num)
    _write_node(do, tree.root)
    do.write_double(tree.learning_rate)
    if tree.root.id == ROOT_INDEX:
        do.write_double(tree.root_wgt_cnt)
    if with_features:
        do.write_int_array(tree.features)


def read_tree_model(data: bytes) -> RefTreeModel:
    """Parse binary .gbt/.rf bytes (gzip-sniffing, version-aware)."""
    if data[:2] == b"\x1f\x8b":
        data = gzip.decompress(data)
    di = JavaDataInput(io.BytesIO(data))
    version = di.read_int()
    algorithm = di.read_utf()
    loss = di.read_utf()
    is_classification = di.read_boolean()
    is_one_vs_all = di.read_boolean()
    input_node = di.read_int()
    means = {di.read_int(): di.read_double() for _ in range(di.read_int())}
    names = {di.read_int(): di.read_utf() for _ in range(di.read_int())}
    cats: Dict[int, List[str]] = {}
    for _ in range(di.read_int()):
        col = di.read_int()
        cats[col] = [_read_category(di) for _ in range(di.read_int())]
    mapping = {di.read_int(): di.read_int() for _ in range(di.read_int())}
    n_bags = di.read_int() if version >= 4 else 1
    bags = [
        [_read_tree(di, version) for _ in range(di.read_int())] for _ in range(n_bags)
    ]
    return RefTreeModel(
        algorithm, loss, is_classification, is_one_vs_all, input_node,
        means, names, cats, mapping, bags, version,
    )


def write_tree_model(model: RefTreeModel, compress: bool = True) -> bytes:
    """Serialize to the version-4 stream BinaryDTSerializer.save emits."""
    raw = io.BytesIO()
    do = JavaDataOutput(raw)
    do.write_int(TREE_FORMAT_VERSION)
    do.write_utf(model.algorithm)
    do.write_utf(model.loss)
    do.write_boolean(model.is_classification)
    do.write_boolean(model.is_one_vs_all)
    do.write_int(model.input_node)
    do.write_int(len(model.numerical_mean))
    for col, mean in model.numerical_mean.items():
        do.write_int(col)
        do.write_double(0.0 if mean is None else mean)
    do.write_int(len(model.column_names))
    for col, name in model.column_names.items():
        do.write_int(col)
        do.write_utf(name)
    do.write_int(len(model.categorical_values))
    for col, cats in model.categorical_values.items():
        do.write_int(col)
        do.write_int(len(cats))
        for cat in cats:
            _write_category(do, cat)
    do.write_int(len(model.column_mapping))
    for col, idx in model.column_mapping.items():
        do.write_int(col)
        do.write_int(idx)
    do.write_int(len(model.bags))
    for bag in model.bags:
        do.write_int(len(bag))
        for tree in bag:
            _write_tree(do, tree)
    payload = raw.getvalue()
    return gzip.compress(payload) if compress else payload


# ---------------------------------------------------------------------------
# zip spec format (shifu convert)
# ---------------------------------------------------------------------------


def read_zip_model(data: bytes) -> RefTreeModel:
    """Parse the zip spec (model.ini JSON + trees entry),
    parity IndependentTreeModelUtils.convertZipSpecToBinary (:85)."""
    zf = zipfile.ZipFile(io.BytesIO(data))
    ini = json.loads(zf.read("model.ini").decode("utf-8"))
    di = JavaDataInput(io.BytesIO(zf.read("trees")))
    bags = []
    for _ in range(di.read_int()):
        bags.append(
            [_read_tree(di, TREE_FORMAT_VERSION) for _ in range(di.read_int())]
        )
    # apply the JSON weights (trees entry stores learningRate per tree too,
    # but model.ini is authoritative after Jackson round-trip)
    for bag, wgts in zip(bags, ini.get("weights") or []):
        for tree, w in zip(bag, wgts):
            tree.learning_rate = float(w)
    return RefTreeModel(
        algorithm=ini.get("algorithm", "GBT"),
        loss=ini.get("lossStr", "squared"),
        is_classification=bool(ini.get("classification", False)),
        is_one_vs_all=bool(ini.get("oneVsAll", False)),
        input_node=int(ini.get("inputNode", 0)),
        numerical_mean={int(k): v for k, v in (ini.get("numericalMeanMapping") or {}).items()},
        column_names={int(k): v for k, v in (ini.get("numNameMapping") or {}).items()},
        categorical_values={int(k): v for k, v in (ini.get("categoricalColumnNameNames") or {}).items()},
        column_mapping={int(k): v for k, v in (ini.get("columnNumIndexMapping") or {}).items()},
        bags=bags,
    )


def write_zip_model(model: RefTreeModel) -> bytes:
    """Emit the zip spec the reference's convertBinaryToZipSpec produces."""
    ini = {
        "numNameMapping": {str(k): v for k, v in model.column_names.items()},
        "categoricalColumnNameNames": {str(k): v for k, v in model.categorical_values.items()},
        "columnCategoryIndexMapping": {
            str(k): model.category_index(k) for k in model.categorical_values
        },
        "columnNumIndexMapping": {str(k): v for k, v in model.column_mapping.items()},
        "trees": None,
        "weights": model.weights(),
        "lossStr": model.loss,
        "algorithm": model.algorithm,
        "inputNode": model.input_node,
        "numericalMeanMapping": {str(k): v for k, v in model.numerical_mean.items()},
        "gbtScoreConvertStrategy": "RAW",
        "gbdt": model.algorithm.upper() == "GBT",
        "classification": model.is_classification,
        "convertToProb": False,
        "oneVsAll": model.is_one_vs_all,
    }
    trees_buf = io.BytesIO()
    do = JavaDataOutput(trees_buf)
    do.write_int(len(model.bags))
    for bag in model.bags:
        do.write_int(len(bag))
        for tree in bag:
            _write_tree(do, tree)
    out = io.BytesIO()
    with zipfile.ZipFile(out, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr("model.ini", json.dumps(ini))
        zf.writestr("trees", trees_buf.getvalue())
    return out.getvalue()


# ---------------------------------------------------------------------------
# conversion from our dense TPU tree spec
# ---------------------------------------------------------------------------


def from_dense_spec(spec) -> RefTreeModel:
    """Convert our TreeModelSpec (models/tree.py) into the reference image.

    Our trees split on bin codes; reference trees split on raw values.
    Numeric go-left masks from the trainer are contiguous code prefixes, so
    ``code < k  <=>  raw < boundaries[k]`` maps exactly. Categorical masks
    become the bitset of member category indices. GBT init_pred is folded
    into the first tree's leaves (reference GBT starts from 0).
    """
    is_gbt = spec.algorithm.upper() == "GBT"
    col_names = {j + 1: name for j, name in enumerate(spec.input_columns)}
    mapping = {j + 1: j for j in range(len(spec.input_columns))}
    means: Dict[int, float] = {}
    cats: Dict[int, List[str]] = {}
    for j, name in enumerate(spec.input_columns):
        cat = spec.categories[j] if j < len(spec.categories) else None
        if cat:
            cats[j + 1] = list(cat)
        else:
            bounds = spec.boundaries[j] or []
            finite = [b for b in bounds if np.isfinite(b)]
            means[j + 1] = float(np.mean(finite)) if finite else 0.0

    trees: List[RefTree] = []
    for t_i, dense in enumerate(spec.trees):
        node_counter = [0]

        def build(slot: int) -> Optional[RefNode]:
            if slot >= dense.n_nodes:
                return None
            f = int(dense.feature[slot])
            node_counter[0] += 1
            node = RefNode(id=slot + 1, wgt_cnt=0.0)
            if f < 0:  # leaf
                node.predict = float(dense.leaf_value[slot])
                return node
            mask = dense.left_mask[slot]
            cat = spec.categories[f] if f < len(spec.categories) else None
            if cat:
                members = np.nonzero(mask[: len(cat) + 1])[0]
                node.split = RefSplit(
                    f + 1, CATEGORICAL, is_left=True, categories=members.astype(np.int64)
                )
            else:
                bounds = spec.boundaries[f] or []
                k = int(np.argmin(mask)) if not mask.all() else len(bounds)
                thr = bounds[k] if k < len(bounds) else np.inf
                node.split = RefSplit(f + 1, CONTINUOUS, threshold=float(thr))
            node.left = build(2 * slot + 1)
            node.right = build(2 * slot + 2)
            if node.left is None and node.right is None:
                node.split = None
                node.predict = float(dense.leaf_value[slot])
            return node

        root = build(0)
        assert root is not None
        lr = 1.0 if (is_gbt and t_i == 0) else (dense.weight if not is_gbt else spec.learning_rate)
        trees.append(RefTree(t_i, node_counter[0], root, learning_rate=lr))

    if is_gbt and trees:
        # fold init_pred + per-tree weight differences into leaf values:
        # our score = init + sum(leaf_i * w_i); reference = sum(leaf'_i * lr_i)
        def scale_leaves(node: RefNode, factor: float, offset: float):
            if node.is_real_leaf and node.predict is not None:
                node.predict = node.predict * factor + offset
            for ch in (node.left, node.right):
                if ch is not None:
                    scale_leaves(ch, factor, offset)

        for t_i, (dense, tree) in enumerate(zip(spec.trees, trees)):
            factor = dense.weight / tree.learning_rate
            offset = spec.init_pred / tree.learning_rate if t_i == 0 else 0.0
            scale_leaves(tree.root, factor, offset)

    return RefTreeModel(
        algorithm=spec.algorithm.upper(),
        loss=spec.loss,
        is_classification=False,
        is_one_vs_all=False,
        input_node=len(spec.input_columns),
        numerical_mean=means,
        column_names=col_names,
        categorical_values=cats,
        column_mapping=mapping,
        bags=[trees],
    )
