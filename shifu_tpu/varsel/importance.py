"""Tree feature importance: split-count weighted by node coverage proxy.

Parity: util/CommonUtils.computeTreeModelFeatureImportance (CommonUtils.java
tree FI computation) — importance per feature accumulates over every split
node; normalized to sum 1.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from shifu_tpu.models.tree import TreeModelSpec


def tree_feature_importance(spec: TreeModelSpec) -> Dict[str, float]:
    F = len(spec.input_columns)
    imp = np.zeros(F, dtype=np.float64)
    for tree in spec.trees:
        # depth weighting: splits nearer the root cover more rows; the dense
        # layout encodes depth as floor(log2(node+1))
        for node, f in enumerate(tree.feature):
            if f < 0:
                continue
            depth = int(np.log2(node + 1))
            imp[f] += tree.weight / (2.0**depth)
    total = imp.sum()
    if total > 0:
        imp /= total
    return {name: float(v) for name, v in zip(spec.input_columns, imp)}
