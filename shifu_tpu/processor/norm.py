"""`shifu norm` — produce the dense normalized training matrix.

Parity: core/processor/NormalizeModelProcessor.java:67 (Normalize.pig +
udf/NormalizeUDF) and the optional MR shuffle (core/shuffle/MapReduceShuffle).
TPU-first shape: one pass builds BOTH artifacts every trainer needs —
  NormalizedData/   float32 feature shards (NN/LR/WDL input)
  CleanedData/      int16 bin-code shards (GBT/RF input; replaces the
                    reference's raw-column CleanedData, the tree engine bins
                    at the source instead of per-iteration)
Shuffle is a host-side permutation before sharding (the MR shuffle's only
purpose is balanced random shards — reference NormalizeModelProcessor.java:87).
"""

from __future__ import annotations

import numpy as np

from shifu_tpu.data.purify import combined_mask
from shifu_tpu.data.reader import (
    make_tags_for,
    make_weights,
    read_columnar,
    read_header,
)
from shifu_tpu.norm.dataset import write_codes, write_normalized
from shifu_tpu.norm.normalizer import (
    _slots,
    apply_norm_plan,
    bin_code_matrix,
    build_norm_plan,
    norm_columns,
)
from shifu_tpu.processor.basic import BasicProcessor
from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)


def default_shards() -> int:
    try:
        import jax

        return max(1, len(jax.devices()))
    except Exception:  # pragma: no cover - jax always present in CI
        return 1


class NormProcessor(BasicProcessor):
    step = "norm"

    def __init__(self, root: str = ".", shuffle: bool = False, seed: int = 0,
                 names_override=None, host_plan=None):
        super().__init__(root)
        self.shuffle = shuffle
        self.seed = seed
        # the retrain seam: the traffic log's `_meta.json` names the file
        # layout (input columns + target/weight + score/sha/ts), which is
        # neither the configured header nor ColumnConfig order
        self.names_override = list(names_override) if names_override else None
        # explicit HostPlan override for in-process multi-host drivers
        # (tests/bench); production processes read the lifecycle knobs
        self.host_plan = host_plan

    def run_step(self) -> None:
        self.setup()
        mc = self.model_config
        assert mc is not None
        ds = mc.data_set

        if self.names_override:
            names = list(self.names_override)
        elif ds.header_path:
            names = read_header(self.resolve(ds.header_path), ds.header_delimiter)
        else:
            names = [c.column_name for c in self.column_configs]

        from shifu_tpu.data.stream import should_stream

        if should_stream(self.resolve(ds.data_path)):
            self._run_streaming(names)
            return

        from shifu_tpu.data.pipeline import HostPlan

        hp = self.host_plan if self.host_plan is not None else HostPlan()
        if hp.active:
            raise ValueError(
                "-Dshifu.lifecycle.hosts > 1 requires the streaming norm "
                "path (dataset under the memory budget loads in one "
                "process) — drop the hosts knob or lower "
                "shifu.stream.memoryBudgetMb")

        data = read_columnar(
            self.resolve(ds.data_path),
            names,
            delimiter=ds.data_delimiter,
            missing_values=tuple(ds.missing_or_invalid_values),
        )

        # purify + invalid-tag drop + norm sampling (NormalizeUDF filters rows
        # through DataPurifier and sampler before emitting)
        mask = combined_mask(ds.filter_expressions, data.raw, data.n_rows)
        tags_all = make_tags_for(mc, data.column(ds.target_column_name))
        mask &= tags_all >= 0
        if mc.normalize.sample_rate < 1.0:
            rng = np.random.default_rng(self.seed)
            keep = rng.random(data.n_rows) < mc.normalize.sample_rate
            if mc.normalize.sample_neg_only:
                keep |= tags_all == 1
            mask &= keep
        data = data.select_rows(mask)
        tags = tags_all[mask]
        weights = make_weights(data, ds.weight_column_name)

        if self.shuffle:
            perm = np.random.default_rng(self.seed).permutation(data.n_rows)
            data = data.select_rows(perm)
            tags = tags[perm]
            weights = weights[perm]

        from shifu_tpu.obs import registry, span

        reg = registry()
        timers = reg.stage_timers("norm.stage")
        plan = build_norm_plan(mc, self.column_configs)
        code_cache: dict = {}
        with span("norm.normalize", rows=data.n_rows), \
                timers.timer("normalize"):
            feats = apply_norm_plan(plan, data, code_cache=code_cache)
        reg.counter("norm.rows").inc(int(feats.shape[0]))
        reg.gauge("norm.columns").set(int(feats.shape[1]))
        n_shards = default_shards()
        out_dir = self.paths.normalized_data_dir()
        # persist the output-name -> source-column mapping so later steps
        # (SE/ST varsel under one-hot expansion) don't have to reconstruct
        # the plan against possibly-changed ColumnConfigs
        extra = {"sourceOf": plan.source_of}
        self._add_class_meta(extra, tags)
        with span("norm.write", shards=n_shards), timers.timer("write"):
            write_normalized(
                out_dir,
                feats,
                tags,
                weights,
                plan.out_names,
                norm_type=mc.normalize.norm_type.value,
                n_shards=n_shards,
                extra=extra,
            )
        log.info(
            "normalized %d rows x %d cols (%s) -> %s [%d shards]",
            feats.shape[0], feats.shape[1], mc.normalize.norm_type.value,
            out_dir, n_shards,
        )

        # tree-model bin codes
        tree_cols = norm_columns(self.column_configs)
        with span("norm.bincode"), timers.timer("bincode"):
            codes = bin_code_matrix(tree_cols, data, cache=code_cache)
            write_codes(
                self.paths.cleaned_data_dir(),
                codes,
                tags,
                weights,
                [c.column_name for c in tree_cols],
                [_slots(c) for c in tree_cols],
                n_shards=n_shards,
            )
        log.info("bin codes -> %s", self.paths.cleaned_data_dir())

    def _stream_config_sha(self, plan, slots, n_shards):
        """(sha, per-section shas) for the streaming norm run: the full
        norm plan (type, cutoff, every per-column table) and code layout
        in the `norm` section, chunk geometry / shard plan / sampling in
        the `data` section — a snapshot written under different config
        must not be resumed, and the rejection names which side moved."""
        from shifu_tpu.data.stream import chunk_rows_setting
        from shifu_tpu.norm.normalizer import plan_to_json
        from shifu_tpu.resilience.checkpoint import sectioned_sha

        return sectioned_sha({
            "norm": {
                "plan": plan_to_json(plan),
                "slots": [int(s) for s in slots],
            },
            "data": {
                "seed": self.seed,
                "sampleRate": self.model_config.normalize.sample_rate,
                # chunk geometry governs both the chunk index AND the
                # shard-per-chunk layout — never resume across a change
                "chunkRows": chunk_rows_setting(),
                "shards": int(n_shards),
            },
        })

    def _add_class_meta(self, extra: dict, tags: np.ndarray) -> None:
        """Multi-class: record the tag list + training class priors in
        meta.json — the eval confusion matrix's binRatio source (the
        reference reads binCountPos/Neg per class from the target
        ColumnConfig, ConfusionMatrix.java:645-653)."""
        mc = self.model_config
        if not mc.is_multi_classification():
            return
        from shifu_tpu.eval.multiclass import class_priors

        class_tags = [str(t) for t in mc.tags()]
        extra["classTags"] = class_tags
        extra["classPriors"] = class_priors(
            np.asarray(tags), len(class_tags)
        ).tolist()

    def _run_streaming(self, names) -> None:
        """Bounded-memory norm: one chunked pass writes BOTH artifacts
        (NormalizedData f32 + CleanedData bin codes). Without shuffle, one
        shard per ingest chunk; with shuffle, a two-pass external shuffle
        (ShuffleShardWriter) produces a true uniform global permutation —
        the MR shuffle's contract (core/shuffle/MapReduceShuffle.java:47) —
        with peak memory of one bucket.

        Multi-host (shifu.lifecycle.hosts > 1): each process streams only
        its HostPlan slice of the chunk list, writing chunk-indexed part
        files (HostPartWriter); after a hostsync barrier the merge host
        renames the sorted union into the sequential shard layout, so
        both artifacts are byte-identical to the 1-process run."""
        from shifu_tpu.data.pipeline import HostPlan, prefetch_iter
        from shifu_tpu.data.stream import chunk_source, memory_budget_bytes
        from shifu_tpu.norm.dataset import (
            HostPartWriter,
            ShardWriter,
            ShuffleShardWriter,
        )
        from shifu_tpu.obs import registry, span
        from shifu_tpu.parallel import hostsync
        from shifu_tpu.stats.engine import _prepare_rows

        mc = self.model_config
        ds = mc.data_set
        plan = build_norm_plan(mc, self.column_configs)
        tree_cols = norm_columns(self.column_configs)
        slots = [_slots(c) for c in tree_cols]
        code_dtype = np.int16 if (not slots or max(slots) < 2**15) else np.int32

        hp = self.host_plan if self.host_plan is not None else HostPlan()
        if self.shuffle and hp.active:
            raise ValueError(
                "-shuffle is not multi-host capable: the external-shuffle "
                "writer owns the global permutation and cannot be split "
                "across processes — run the shuffle norm on one process "
                "or drop -Dshifu.lifecycle.hosts")
        if hp.active:
            feat_writer = HostPartWriter(
                self.paths.normalized_data_dir(), "features", np.float32,
                plan.out_names, mc.normalize.norm_type.value,
                extra={"sourceOf": plan.source_of},
            )
            code_writer = HostPartWriter(
                self.paths.cleaned_data_dir(), "codes", code_dtype,
                [c.column_name for c in tree_cols], "CODES",
                extra={"slots": slots},
            )
        elif self.shuffle:
            # bucket count so one bucket fits ~1/4 of the memory budget;
            # gz-compressed text typically expands ~4x when materialized
            from shifu_tpu.data.reader import _expand_paths
            from shifu_tpu.fs.source import size_of

            raw_bytes = sum(
                size_of(p) * (4 if p.endswith(".gz") else 1)
                for p in _expand_paths(self.resolve(ds.data_path)))
            n_buckets = max(
                default_shards(),
                int(np.ceil(raw_bytes / max(memory_budget_bytes() // 4, 1))),
            )
            feat_writer = ShuffleShardWriter(
                self.paths.normalized_data_dir(), "features", np.float32,
                plan.out_names, mc.normalize.norm_type.value,
                n_buckets=n_buckets, seed=self.seed,
                extra={"sourceOf": plan.source_of},
            )
            code_writer = ShuffleShardWriter(
                self.paths.cleaned_data_dir(), "codes", code_dtype,
                [c.column_name for c in tree_cols], "CODES",
                n_buckets=n_buckets, seed=self.seed,
                extra={"slots": slots},
            )
        else:
            feat_writer = ShardWriter(
                self.paths.normalized_data_dir(), "features", np.float32,
                plan.out_names, mc.normalize.norm_type.value,
                extra={"sourceOf": plan.source_of},
            )
            code_writer = ShardWriter(
                self.paths.cleaned_data_dir(), "codes", code_dtype,
                [c.column_name for c in tree_cols], "CODES",
                extra={"slots": slots},
            )
        if ds.filter_expressions:
            needed = None  # expressions may reference any column
        else:
            keep = {s.cc.column_name for s in plan.specs}
            keep.update(c.column_name for c in tree_cols)
            keep.add(ds.target_column_name)
            if ds.weight_column_name:
                keep.add(ds.weight_column_name)
            # parse only the columns this pass reads — meta/padding fields
            # never leave the CSV tokenizer (bounded-memory envelope)
            needed = [n for n in names if n in keep]
        factory = chunk_source(
            self.resolve(ds.data_path), names,
            delimiter=ds.data_delimiter,
            missing_values=tuple(ds.missing_or_invalid_values),
            columns=needed,
        )
        # registry-backed: streaming-stage timings land in the run manifest
        reg = registry()
        timers = reg.stage_timers("norm.stage")

        def _normed(numbered):
            """Prefetch-thread stage: parse + purify + norm + bin-code one
            chunk; the consumer thread only appends to the shard writers."""
            ci, chunk = numbered
            with timers.timer("prepare"):
                chunk, tags, weights = _prepare_rows(
                    mc, chunk, [self.seed, ci], mc.normalize.sample_rate,
                    mc.normalize.sample_neg_only,
                )
            if not chunk.n_rows:
                return None
            with timers.timer("bincode"):
                code_cache: dict = {}
                feats = apply_norm_plan(plan, chunk, code_cache=code_cache)
                codes = bin_code_matrix(tree_cols, chunk, cache=code_cache)
            return ci, feats, codes, tags, weights

        # ---- shard plan + preemption safety: chunks divide round-robin
        # over the lifecycle row shards (ShardPlan — the same plan the
        # stats folds use), each shard keeping its own chunk cursor in
        # its own snapshot file; the artifact writers are the shared
        # reduce state (they append in global chunk order, which is what
        # keeps the output byte-identical across shard counts). The
        # external-shuffle path appends to bucket files and is NOT
        # resumable — it restarts ----
        from shifu_tpu.data.pipeline import ShardPlan
        from shifu_tpu.resilience import checkpoint as ckpt_mod
        from shifu_tpu.resilience import faults

        shard_plan = ShardPlan(host=hp)
        S = shard_plan.n_shards
        cursors = [-1] * S
        shard_rows_f = [0] * S
        ck = None
        n_rows = 0
        all_tag_counts: dict = {}
        sha, sha_sections = self._stream_config_sha(plan, slots, S)
        if not self.shuffle and ckpt_mod.ckpt_stream_enabled():
            # keyed by self.step so a retrain's norm pass (step
            # "retrain-norm") never collides with a real `shifu norm`
            # resume on the same model set
            ck = ckpt_mod.ShardedStreamCheckpoint(
                ckpt_mod.ckpt_base(self.root, self.step, "stream"),
                sha, S, sections=sha_sections,
                n_hosts=hp.n_hosts, host_index=hp.host_index)
            if ckpt_mod.resume_requested():
                loaded = ck.load()
                if loaded is not None:
                    cursors, per_shard, shared = loaded
                    cursors = list(cursors)
                    shard_rows_f = [int(m.get("rows", 0))
                                    for _a, m, _b in per_shard]
                    meta = shared[1]
                    if hp.active:
                        feat_writer.restore(meta["featParts"])
                        code_writer.restore(meta["codeParts"])
                    else:
                        feat_writer.restore(meta["featShardRows"])
                        code_writer.restore(meta["codeShardRows"])
                    n_rows = int(meta["nRows"])
                    all_tag_counts = {int(k): int(v) for k, v in
                                      meta["tagCounts"].items()}
                    faults.survived("preempt")
                    log.info("resuming streaming norm (shard cursors %s)",
                             cursors)
            else:
                ck.clear()
        elif self.shuffle and ckpt_mod.resume_requested():
            log.warning("--resume with -shuffle: the external-shuffle "
                        "writer appends to bucket files and cannot "
                        "resume mid-stream; restarting from row zero")
        if hp.active and not ckpt_mod.resume_requested():
            # fresh fleet run: drop this host's stale barrier part so a
            # dead earlier run can't satisfy the merge barrier early
            hostsync.clear_part(self.root, self.step, hp)

        def _writer_state() -> dict:
            if hp.active:
                return {"featParts": {str(k): v for k, v in
                                      feat_writer.part_rows.items()},
                        "codeParts": {str(k): v for k, v in
                                      code_writer.part_rows.items()}}
            return {"featShardRows": list(feat_writer.shard_rows),
                    "codeShardRows": list(code_writer.shard_rows)}

        def _ckpt_state():
            per_shard = [
                (cursors[s], None, {"rows": shard_rows_f[s]}, None)
                for s in range(S)]
            shared = (None,
                      {**_writer_state(),
                       "nRows": n_rows,
                       "tagCounts": {str(k): v for k, v in
                                     all_tag_counts.items()}},
                      None)
            return per_shard, shared

        with span("norm.stream", shuffle=self.shuffle, shards=S) as sp:
            for item in prefetch_iter(shard_plan.resume_slice(
                                          enumerate(factory()), cursors),
                                      transform=_normed,
                                      timers=timers, stage="parse"):
                if item is None:
                    continue
                faults.fault_point("chunk")
                ci, feats, codes, tags, weights = item
                with timers.timer("write"):
                    if hp.active:
                        feat_writer.add(ci, feats, tags, weights)
                        code_writer.add(ci, codes, tags, weights)
                    else:
                        feat_writer.add(feats, tags, weights)
                        code_writer.add(codes, tags, weights)
                n_rows += len(tags)
                shard = shard_plan.shard_of(ci)
                cursors[shard] = ci
                shard_rows_f[shard] += len(tags)
                shard_plan.record(shard, len(tags), "norm")
                hp.record(len(tags), "norm")
                for t, c in zip(*np.unique(tags, return_counts=True)):
                    all_tag_counts[int(t)] = (
                        all_tag_counts.get(int(t), 0) + int(c))
                if ck is not None:
                    ck.maybe_save(_ckpt_state)
            sp["rows"] = n_rows
        if ck is not None:
            ck.clear()
        reg.counter("norm.rows").inc(n_rows)  # this host's streamed rows
        reg.gauge("norm.columns").set(len(plan.out_names))
        log.info("streaming norm pipeline: %s", timers.summary())

        feat_union: dict = {}
        code_union: dict = {}
        if hp.active:
            # all-gather the per-host part lists; every host learns the
            # fleet union (and merged tag counts) in sorted-host order
            hostsync.publish_part(
                self.root, self.step, hp, sha,
                meta={**_writer_state(),
                      "nRows": n_rows,
                      "tagCounts": {str(k): int(v) for k, v in
                                    all_tag_counts.items()}})
            parts = hostsync.await_parts(self.root, self.step, hp, sha)
            merged_tags: dict = {}
            n_rows = 0
            for _arrays, pmeta, _blob in parts:
                feat_union.update({int(k): int(v) for k, v in
                                   pmeta["featParts"].items()})
                code_union.update({int(k): int(v) for k, v in
                                   pmeta["codeParts"].items()})
                n_rows += int(pmeta["nRows"])
                for k, v in pmeta["tagCounts"].items():
                    merged_tags[int(k)] = merged_tags.get(int(k), 0) + int(v)
            all_tag_counts = merged_tags

        if mc.is_multi_classification() and feat_writer.extra is not None:
            class_tags = [str(t) for t in mc.tags()]
            total = max(sum(all_tag_counts.values()), 1)
            feat_writer.extra["classTags"] = class_tags
            feat_writer.extra["classPriors"] = [
                all_tag_counts.get(k, 0) / total for k in range(len(class_tags))
            ]
        if hp.active:
            if not hp.is_merge_host:
                log.info("streaming norm host %d/%d: %d parts staged; "
                         "merge host writes the artifacts",
                         hp.host_index, hp.n_hosts, len(_writer_state()
                                                        ["featParts"]))
                return
            feat_meta = feat_writer.merge(feat_union)
            code_writer.merge(code_union)
        else:
            feat_meta = feat_writer.close()
            code_writer.close()
        log.info(
            "streaming norm: %d rows x %d cols (%s) -> %s [%d shards] "
            "+ bin codes -> %s",
            n_rows, len(feat_meta.columns), mc.normalize.norm_type.value,
            self.paths.normalized_data_dir(), len(feat_meta.shard_rows),
            self.paths.cleaned_data_dir(),
        )
