"""Remote-source abstraction — the SourceType seam.

The reference keys every path on SourceType {LOCAL, HDFS}
(container/obj/RawSourceData.java, util/HDFSUtils.java:35 cached
FileSystems, fs/ShifuFileUtils scanners). The TPU build's seam is the URI
scheme: plain paths stay on the local filesystem (fast path, zero
indirection), while `scheme://` paths route through fsspec — so
`hdfs://`, `s3://`, `gs://` sources work wherever the matching connector
is installed, and fail with a CLEAR error (naming the missing protocol)
where it is not. `memory://` ships with fsspec and backs the tests.

pandas' readers accept fsspec URLs directly, so the chunked ingest path
needs no special-casing beyond listing.
"""

from __future__ import annotations

from typing import List

from shifu_tpu.utils.errors import ErrorCode, ShifuError


def is_remote(path: str) -> bool:
    """True for scheme-ful URIs (file:// counts — it routes through fsspec
    but reads local bytes)."""
    return "://" in path


def _fs_for(path: str):
    try:
        import fsspec
    except ImportError:  # pragma: no cover - fsspec ships in the image
        raise ShifuError(
            ErrorCode.DATA_NOT_FOUND,
            f"{path}: remote sources need fsspec, which is not installed",
        )
    protocol = path.split("://", 1)[0]
    try:
        return fsspec.filesystem(protocol), protocol
    except (ImportError, ValueError) as e:
        raise ShifuError(
            ErrorCode.DATA_NOT_FOUND,
            f"{path}: no filesystem connector for '{protocol}://' "
            f"({e}); install the matching fsspec backend",
        )


def expand_remote(path: str) -> List[str]:
    """Part-file expansion for a remote data path (dir / glob / file),
    mirroring the local _expand_paths contract: skip dot/underscore marker
    files, error on empty."""
    fs, protocol = _fs_for(path)
    bare = path.split("://", 1)[1]

    def is_data(info) -> bool:
        name = info["name"].rsplit("/", 1)[-1]
        if name.startswith(".") or name.startswith("_"):
            return False
        return info.get("type") == "file" and info.get("size", 1) > 0

    if fs.isdir(bare):
        infos = fs.ls(bare, detail=True)
        parts = sorted(i["name"] for i in infos if is_data(i))
        if not parts:
            raise ShifuError(ErrorCode.DATA_NOT_FOUND,
                             f"empty remote directory {path}")
        return [f"{protocol}://{p}" for p in parts]
    if fs.exists(bare) and fs.isfile(bare):
        return [path]
    hits = sorted(fs.glob(bare))
    files = []
    for h in hits:
        name = h.rsplit("/", 1)[-1]
        if name.startswith(".") or name.startswith("_"):
            continue
        if fs.isfile(h):
            files.append(f"{protocol}://{h}")
    if files:
        return sorted(files)
    raise ShifuError(ErrorCode.DATA_NOT_FOUND, path)


def open_source(path: str, mode: str = "rb"):
    """Open a local path or fsspec URL uniformly."""
    if is_remote(path):
        import fsspec

        return fsspec.open(path, mode).open()
    return open(path, mode)


def size_of(path: str) -> int:
    if is_remote(path):
        fs, _ = _fs_for(path)
        return int(fs.size(path.split("://", 1)[1]) or 0)
    import os

    return os.path.getsize(path)
