"""Tree-ensemble PMML export: structure matches the reference golden's
schema (PMML-4_2, DataDictionary/MiningSchema/Output with RawResult ->
FinalResult x1000 scaling — dttest/model/golf0.pmml) and an INDEPENDENT
mini PMML evaluator (standard Node/SimplePredicate/SimpleSetPredicate/
Segmentation semantics, written against the PMML 4.2 spec, not against our
writer) reproduces the native scores."""

import os
import xml.etree.ElementTree as ET

import numpy as np
import pytest

NS = "{http://www.dmg.org/PMML-4_2}"


# ---------------------------------------------------------------------------
# minimal spec-faithful PMML evaluator (TreeModel + Segmentation)
# ---------------------------------------------------------------------------


def _pred_eval(el, row):
    """True/False/None(unknown) per PMML predicate semantics."""
    tag = el.tag.replace(NS, "")
    if tag == "True":
        return True
    if tag == "False":
        return False
    if tag == "SimplePredicate":
        field, op = el.get("field"), el.get("operator")
        v = row.get(field)
        if op == "isMissing":
            return v is None
        if op == "isNotMissing":
            return v is not None
        if v is None:
            return None  # unknown
        x, t = float(v), float(el.get("value"))
        return {
            "lessThan": x < t, "lessOrEqual": x <= t,
            "greaterThan": x > t, "greaterOrEqual": x >= t,
            "equal": x == t, "notEqual": x != t,
        }[op]
    if tag == "SimpleSetPredicate":
        field = el.get("field")
        v = row.get(field)
        if v is None:
            return None
        arr = el.find(f"{NS}Array")
        members = [s.strip('"') for s in (arr.text or "").split('" "')]
        members = [m.strip('"') for m in members]
        inside = str(v) in members
        return inside if el.get("booleanOperator") == "isIn" else not inside
    raise ValueError(f"unsupported predicate {tag}")


def _node_children(node):
    return node.findall(f"{NS}Node")


def _eval_tree_node(node, row):
    """PMML TreeModel traversal with missingValueStrategy=defaultChild."""
    children = _node_children(node)
    if not children:
        return float(node.get("score"))
    results = []
    for ch in children:
        pred = next(e for e in ch if e.tag != f"{NS}Node")
        results.append(_pred_eval(pred, row))
    for ch, r in zip(children, results):
        if r is True:
            return _eval_tree_node(ch, row)
    if any(r is None for r in results):  # unknown -> defaultChild
        default = node.get("defaultChild")
        for ch in children:
            if ch.get("id") == default:
                return _eval_tree_node(ch, row)
    return float(node.get("score"))  # noTrueChild: fall back to own score


def eval_pmml_mining_model(xml_text, rows):
    root = ET.fromstring(xml_text)
    mm = root.find(f"{NS}MiningModel")
    seg = mm.find(f"{NS}Segmentation")
    method = seg.get("multipleModelMethod")
    out = np.zeros(len(rows))
    n_seg = 0
    for segment in seg.findall(f"{NS}Segment"):
        tm = segment.find(f"{NS}TreeModel")
        top = tm.find(f"{NS}Node")
        n_seg += 1
        for i, row in enumerate(rows):
            out[i] += _eval_tree_node(top, row)
    if method == "average":
        out /= max(n_seg, 1)
    return out


# ---------------------------------------------------------------------------
# fixtures
# ---------------------------------------------------------------------------


def _mixed_spec(seed=0, algorithm="GBT", trees=8, max_leaves=-1):
    from shifu_tpu.train.tree_trainer import TreeTrainConfig, train_trees

    rng = np.random.default_rng(seed)
    n = 1200
    bounds = [-np.inf, -1.0, 0.0, 1.0]  # numeric feature, 5 slots w/ missing
    cats = ["aa", "bb", "cc"]  # categorical, 4 slots w/ missing
    x_num = rng.normal(size=n)
    x_cat = rng.integers(0, 3, size=n)
    codes_num = np.searchsorted(bounds, x_num, side="right") - 1
    y = ((x_num > 0) | (x_cat == 1)).astype(np.float32)
    codes = np.stack([codes_num, x_cat], axis=1).astype(np.int32)
    cfg = TreeTrainConfig(algorithm=algorithm, tree_num=trees, max_depth=4,
                          max_leaves=max_leaves, learning_rate=0.3,
                          valid_set_rate=0.1, seed=3,
                          min_instances_per_node=1)
    res = train_trees(codes, y, np.ones(n, np.float32), [5, 4],
                      [False, True], ["num0", "cat0"], cfg,
                      boundaries=[[float(b) for b in bounds], None],
                      categories=[None, cats])
    rows = [
        {"num0": float(x_num[i]), "cat0": cats[x_cat[i]]} for i in range(n)
    ]
    return res.spec, codes, rows


@pytest.mark.parametrize("algorithm", ["GBT", "RF"])
def test_tree_pmml_scores_match_native(algorithm):
    from shifu_tpu.export.pmml import tree_to_pmml
    from shifu_tpu.models.tree import traverse_trees

    spec, codes, rows = _mixed_spec(algorithm=algorithm)
    xml = tree_to_pmml(spec)
    pmml_scores = eval_pmml_mining_model(xml, rows)

    import jax.numpy as jnp

    per_tree = np.asarray(traverse_trees(spec.trees, jnp.asarray(codes)))
    native = (per_tree.sum(axis=1) if algorithm == "GBT"
              else per_tree.mean(axis=1))
    np.testing.assert_allclose(pmml_scores, native, atol=1e-5)


def test_leafwise_tree_pmml_scores_match_native():
    from shifu_tpu.export.pmml import tree_to_pmml
    from shifu_tpu.models.tree import traverse_trees

    spec, codes, rows = _mixed_spec(algorithm="GBT", trees=5, max_leaves=6)
    xml = tree_to_pmml(spec)
    pmml_scores = eval_pmml_mining_model(xml, rows)
    import jax.numpy as jnp

    native = np.asarray(
        traverse_trees(spec.trees, jnp.asarray(codes))).sum(axis=1)
    np.testing.assert_allclose(pmml_scores, native, atol=1e-5)


def test_tree_pmml_missing_routing():
    """Missing numeric -> defaultChild right; missing category -> the
    missing slot's mask side."""
    from shifu_tpu.export.pmml import tree_to_pmml
    from shifu_tpu.models.tree import traverse_trees

    spec, codes, _rows = _mixed_spec(algorithm="GBT", trees=4)
    xml = tree_to_pmml(spec)
    rows = [{"num0": None, "cat0": None}]  # all missing
    pmml_scores = eval_pmml_mining_model(xml, rows)
    # native: missing codes are the last slot per feature
    import jax.numpy as jnp

    miss_codes = np.array([[4, 3]], np.int32)
    native = np.asarray(
        traverse_trees(spec.trees, jnp.asarray(miss_codes))).sum(axis=1)
    np.testing.assert_allclose(pmml_scores, native, atol=1e-5)


def test_tree_pmml_golden_schema_shape():
    """Same top-level schema as the reference golden (golf0.pmml): PMML-4_2
    namespace, Header/Application, DataDictionary fields, MiningSchema with
    target, Output RawResult + FinalResult scaled 0..1000."""
    from shifu_tpu.export.pmml import tree_to_pmml

    spec, _codes, _rows = _mixed_spec(trees=3)
    root = ET.fromstring(tree_to_pmml(spec))
    assert root.tag == f"{NS}PMML"
    assert root.find(f"{NS}Header/{NS}Application") is not None
    dd = root.find(f"{NS}DataDictionary")
    names = [df.get("name") for df in dd.findall(f"{NS}DataField")]
    assert names == ["num0", "cat0", "TARGET"]
    mm = root.find(f"{NS}MiningModel")
    assert mm.get("functionName") == "regression"
    usage = {mf.get("name"): mf.get("usageType")
             for mf in mm.find(f"{NS}MiningSchema")}
    assert usage["TARGET"] == "target"
    outs = mm.find(f"{NS}Output").findall(f"{NS}OutputField")
    assert [o.get("name") for o in outs] == ["RawResult", "FinalResult"]
    norms = outs[1].find(f"{NS}NormContinuous").findall(f"{NS}LinearNorm")
    assert [(n.get("orig"), n.get("norm")) for n in norms] == [
        ("0.0", "0.0"), ("1.0", "1000.0")
    ]
    seg = mm.find(f"{NS}Segmentation")
    assert len(seg.findall(f"{NS}Segment")) == 3


def test_export_processor_writes_tree_pmml(tmp_path):
    from tests.helpers import make_model_set

    root = str(tmp_path / "ms")
    make_model_set(root, n_rows=300, algorithm="GBT")
    from shifu_tpu.config.model_config import ModelConfig
    from shifu_tpu.processor.export import ExportProcessor
    from shifu_tpu.processor.init import InitProcessor
    from shifu_tpu.processor.norm import NormProcessor
    from shifu_tpu.processor.stats import StatsProcessor
    from shifu_tpu.processor.train import TrainProcessor

    assert InitProcessor(root).run() == 0
    assert StatsProcessor(root).run() == 0
    assert NormProcessor(root).run() == 0
    mc = ModelConfig.load(os.path.join(root, "ModelConfig.json"))
    mc.train.params.update({"TreeNum": 5, "MaxDepth": 3})
    mc.save(os.path.join(root, "ModelConfig.json"))
    assert TrainProcessor(root).run() == 0
    assert ExportProcessor(root, kind="pmml").run() == 0
    import glob

    hits = glob.glob(os.path.join(root, "**", "*.pmml"), recursive=True)
    assert hits
    xml = open(hits[0]).read()
    assert "MiningModel" in xml and "Segmentation" in xml


def test_one_bagging_pmml_trees():
    """One-bagging export: every bag is a Segment of an averaging
    MiningModel (ExportModelProcessor.java:173); the independent evaluator
    must reproduce the bagged MEAN score."""
    from shifu_tpu.export.pmml import bagged_to_pmml
    from shifu_tpu.models.tree import traverse_trees

    spec_a, codes, rows = _mixed_spec(seed=1, trees=4)
    spec_b, _, _ = _mixed_spec(seed=2, trees=4)
    xml = bagged_to_pmml([spec_a, spec_b])
    root = ET.fromstring(xml)
    outer = root.find(f"{NS}MiningModel")
    seg = outer.find(f"{NS}Segmentation")
    assert seg.get("multipleModelMethod") == "average"
    segments = seg.findall(f"{NS}Segment")
    assert len(segments) == 2
    # nested MiningModel per bag
    assert all(s.find(f"{NS}MiningModel") is not None for s in segments)

    # score: average of the two bags' GBT sums
    import jax.numpy as jnp

    def native(spec):
        return np.asarray(
            traverse_trees(spec.trees, jnp.asarray(codes))).sum(axis=1)

    expect = (native(spec_a) + native(spec_b)) / 2.0
    got = np.zeros(len(rows))
    for s in segments:
        inner_mm = s.find(f"{NS}MiningModel")
        inner_seg = inner_mm.find(f"{NS}Segmentation")
        part = np.zeros(len(rows))
        for t in inner_seg.findall(f"{NS}Segment"):
            tm = t.find(f"{NS}TreeModel")
            top = tm.find(f"{NS}Node")
            for i, row in enumerate(rows):
                part[i] += _eval_tree_node(top, row)
        got += part
    got /= len(segments)
    np.testing.assert_allclose(got, expect, atol=1e-5)


def test_one_bagging_pmml_nn_structure():
    from shifu_tpu.export.pmml import bagged_to_pmml
    from shifu_tpu.models.nn import NNModelSpec, init_params

    specs = []
    for seed in (1, 2, 3):
        params = init_params([3, 4, 1], seed=seed)
        specs.append(NNModelSpec(
            layer_sizes=[3, 4, 1], activations=["tanh"],
            input_columns=["a", "b", "c"],
            norm_specs=[{"name": n, "kind": "value", "outNames": [n],
                         "mean": 0.0, "std": 1.0, "fill": 0.0,
                         "zscore": True} for n in ("a", "b", "c")],
            params=params,
        ))
    xml = bagged_to_pmml(specs)
    root = ET.fromstring(xml)
    seg = root.find(f"{NS}MiningModel").find(f"{NS}Segmentation")
    segments = seg.findall(f"{NS}Segment")
    assert len(segments) == 3
    nets = [s.find(f"{NS}NeuralNetwork") for s in segments]
    assert all(n is not None for n in nets)
    # each net carries its own LocalTransformations
    assert all(n.find(f"{NS}LocalTransformations") is not None for n in nets)


def test_nn_pmml_requires_norm_specs():
    """A spec without its normalization plan must fail loudly — the
    alternative is a weight-less NeuralNetwork that evaluators score
    garbage with (round-5 review finding)."""
    from shifu_tpu.export.pmml import nn_to_pmml
    from shifu_tpu.models.nn import NNModelSpec, init_params

    spec = NNModelSpec(layer_sizes=[3, 1], activations=[],
                       input_columns=["a", "b", "c"], norm_specs=[],
                       params=init_params([3, 1], seed=0))
    with pytest.raises(ValueError, match="norm_specs"):
        nn_to_pmml(spec)
