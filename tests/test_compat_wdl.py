"""Reference WDL binary format (BinaryWDLSerializer/IndependentWDLModel)
round-trip + scoring parity against the native WDL model."""

import os

import numpy as np

from tests.helpers import make_model_set


def _trained_wdl_root(tmp_path):
    root = str(tmp_path / "ms")
    make_model_set(root, n_rows=400, algorithm="WDL")
    from shifu_tpu.config.model_config import ModelConfig
    from shifu_tpu.processor.init import InitProcessor
    from shifu_tpu.processor.norm import NormProcessor
    from shifu_tpu.processor.stats import StatsProcessor
    from shifu_tpu.processor.train import TrainProcessor

    assert InitProcessor(root).run() == 0
    assert StatsProcessor(root).run() == 0
    assert NormProcessor(root).run() == 0
    mc = ModelConfig.load(os.path.join(root, "ModelConfig.json"))
    mc.train.num_train_epochs = 30
    mc.train.params.update({"NumHiddenNodes": [16],
                            "ActivationFunc": ["relu"]})
    mc.save(os.path.join(root, "ModelConfig.json"))
    assert TrainProcessor(root).run() == 0
    return root


def _raw_data(root):
    from shifu_tpu.config.model_config import ModelConfig
    from shifu_tpu.data.reader import read_columnar, read_header

    mc = ModelConfig.load(os.path.join(root, "ModelConfig.json"))
    names = read_header(mc.data_set.header_path, mc.data_set.header_delimiter)
    return read_columnar(mc.data_set.data_path, names,
                         delimiter=mc.data_set.data_delimiter)


def test_wdl_ref_roundtrip_and_scoring(tmp_path):
    from shifu_tpu.compat import wdl as cwdl
    from shifu_tpu.config.column_config import load_column_config_list
    from shifu_tpu.models.wdl import IndependentWDLModel, WDLModelSpec

    root = _trained_wdl_root(tmp_path)
    spec = WDLModelSpec.load(os.path.join(root, "models", "model0.wdl"))
    ccs = load_column_config_list(os.path.join(root, "ColumnConfig.json"))
    ref = cwdl.wdl_spec_to_ref(spec, ccs)
    blob = cwdl.write_wdl_model(ref)
    again = cwdl.read_wdl_model(blob)

    # structural round-trip
    assert again.norm_type == ref.norm_type
    assert again.dense_column_ids == ref.dense_column_ids
    assert again.embed_column_ids == ref.embed_column_ids
    assert again.hidden_nodes == ref.hidden_nodes
    assert len(again.column_stats) == len(ref.column_stats)
    for a, b in zip(again.embed_tables, ref.embed_tables):
        assert a[0] == b[0]
        np.testing.assert_allclose(a[1], b[1], rtol=1e-6)
    np.testing.assert_allclose(again.final_layer.weights,
                               ref.final_layer.weights, rtol=1e-6)

    # scoring parity: reference-format model vs native independent model
    data = _raw_data(root)
    native = IndependentWDLModel(spec).compute_raw(data)
    ref_scores = again.compute_raw(data)
    corr = np.corrcoef(native, ref_scores)[0, 1]
    assert corr > 0.99, f"native vs ref-format corr {corr}"
    np.testing.assert_allclose(ref_scores, native, atol=0.05)


def test_wdl_ref_model_via_model_runner(tmp_path):
    """A reference-format .wdl dropped into models/ scores through
    ModelRunner next to (or instead of) native specs."""
    from shifu_tpu.compat import wdl as cwdl
    from shifu_tpu.config.column_config import load_column_config_list
    from shifu_tpu.eval.scorer import ModelRunner
    from shifu_tpu.models.wdl import WDLModelSpec

    root = _trained_wdl_root(tmp_path)
    spec = WDLModelSpec.load(os.path.join(root, "models", "model0.wdl"))
    ccs = load_column_config_list(os.path.join(root, "ColumnConfig.json"))
    blob = cwdl.write_wdl_model(cwdl.wdl_spec_to_ref(spec, ccs))
    ref_path = os.path.join(root, "models", "model1.wdl")
    with open(ref_path, "wb") as fh:
        fh.write(blob)

    runner = ModelRunner([os.path.join(root, "models", "model0.wdl"),
                          ref_path])
    data = _raw_data(root)
    result = runner.score_raw(data)
    assert result.model_scores.shape[1] == 2
    corr = np.corrcoef(result.model_scores[:, 0],
                       result.model_scores[:, 1])[0, 1]
    assert corr > 0.99


def test_ref_to_wdl_params_roundtrip(tmp_path):
    """Imported reference WDL weights map back into our WDLParams and score
    identically on pre-built (dense, codes) inputs."""
    from shifu_tpu.compat import wdl as cwdl
    from shifu_tpu.config.column_config import load_column_config_list
    from shifu_tpu.models.wdl import IndependentWDLModel, WDLModelSpec

    root = _trained_wdl_root(tmp_path)
    spec = WDLModelSpec.load(os.path.join(root, "models", "model0.wdl"))
    ccs = load_column_config_list(os.path.join(root, "ColumnConfig.json"))
    ref = cwdl.read_wdl_model(
        cwdl.write_wdl_model(cwdl.wdl_spec_to_ref(spec, ccs)))
    params = cwdl.ref_to_wdl_params(ref)

    data = _raw_data(root)
    ind = IndependentWDLModel(spec)
    dense, codes = ind.inputs_from_raw(data)
    native = ind.compute_parts(dense, codes)
    spec2 = WDLModelSpec(
        hidden=spec.hidden, activations=spec.activations,
        embed_dim=spec.embed_dim, dense_columns=spec.dense_columns,
        cat_columns=spec.cat_columns, vocab_sizes=spec.vocab_sizes,
        params=params,
    )
    imported = IndependentWDLModel(spec2).compute_parts(dense, codes)
    np.testing.assert_allclose(imported, native, atol=1e-5)
