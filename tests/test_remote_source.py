"""Remote-source abstraction (fs/source.py) — the SourceType {LOCAL, HDFS}
seam (RawSourceData.java, util/HDFSUtils.java) exercised end-to-end through
fsspec's built-in memory:// filesystem."""

import os

import numpy as np
import pytest

from tests.helpers import make_binary_dataset


def _put_memory_dataset(n_rows=300):
    import fsspec

    fs = fsspec.filesystem("memory")
    names, rows, y = make_binary_dataset(n_rows=n_rows)
    data = "\n".join("|".join(r) for r in rows) + "\n"
    header = "|".join(names) + "\n"
    with fs.open("/ds/data/part-000.txt", "w") as fh:
        fh.write(data)
    with fs.open("/ds/header.txt", "w") as fh:
        fh.write(header)
    # marker files must be skipped like the local path does
    with fs.open("/ds/data/_SUCCESS", "w") as fh:
        fh.write("")
    return names, y


def test_expand_and_read_remote_directory():
    from shifu_tpu.data.reader import read_columnar, read_header

    names, y = _put_memory_dataset()
    got = read_header("memory://ds/header.txt", "|")
    assert got == names
    data = read_columnar("memory://ds/data", names, delimiter="|")
    assert data.n_rows == len(y)
    assert set(data.names) == set(names)


def test_remote_pipeline_end_to_end(tmp_path):
    """A model set whose dataPath/headerPath live on memory:// runs
    init -> stats -> norm -> train."""
    from shifu_tpu.config.model_config import Algorithm, new_model_config
    from shifu_tpu.processor.init import InitProcessor
    from shifu_tpu.processor.norm import NormProcessor
    from shifu_tpu.processor.stats import StatsProcessor
    from shifu_tpu.processor.train import TrainProcessor

    _put_memory_dataset()
    root = str(tmp_path / "ms")
    os.makedirs(root, exist_ok=True)
    mc = new_model_config("RemoteTest", Algorithm.NN)
    mc.data_set.data_path = "memory://ds/data"
    mc.data_set.header_path = "memory://ds/header.txt"
    mc.data_set.data_delimiter = "|"
    mc.data_set.header_delimiter = "|"
    mc.data_set.target_column_name = "diagnosis"
    mc.data_set.pos_tags = ["M"]
    mc.data_set.neg_tags = ["B"]
    mc.data_set.source = "HDFS"  # declared remote source
    mc.train.num_train_epochs = 15
    mc.save(os.path.join(root, "ModelConfig.json"))

    assert InitProcessor(root).run() == 0
    assert StatsProcessor(root).run() == 0
    assert NormProcessor(root).run() == 0
    assert TrainProcessor(root).run() == 0
    assert os.path.isfile(os.path.join(root, "models", "model0.nn"))


def test_missing_connector_is_a_clear_error():
    from shifu_tpu.data.reader import read_columnar
    from shifu_tpu.utils.errors import ShifuError

    with pytest.raises(ShifuError) as ei:
        read_columnar("nosuchproto://bucket/data", ["a"], delimiter="|")
    assert "nosuchproto" in str(ei.value)


def test_file_protocol_real_path_semantics(tmp_path):
    """file:// routes through fsspec's LocalFileSystem — REAL directory
    listing, glob, marker-file and absolute-path semantics (memory:// is
    flat and forgiving; hdfs:///s3:// behave like this one). Catches the
    listing bugs a first real connector user would hit."""
    from shifu_tpu.data.reader import read_columnar, read_header
    from shifu_tpu.fs.source import expand_remote

    ds = tmp_path / "ds"
    (ds / "data").mkdir(parents=True)
    (ds / "header.txt").write_text("a|b|target")
    rng = __import__("numpy").random.default_rng(0)
    for i in range(3):
        rows = "\n".join(
            f"{rng.normal():.4f}|{rng.normal():.4f}|{int(rng.random() < 0.5)}"
            for _ in range(40))
        (ds / "data" / f"part-{i:02d}").write_text(rows + "\n")
    # marker files real pipelines leave behind must be skipped
    (ds / "data" / "_SUCCESS").write_text("")
    (ds / "data" / ".pig_header").write_text("a|b|target")

    base = f"file://{ds}"
    header = read_header(f"{base}/header.txt", "|")
    assert header == ["a", "b", "target"]
    parts = expand_remote(f"{base}/data")
    assert len(parts) == 3 and all("part-" in p for p in parts)
    data = read_columnar(f"{base}/data", header, delimiter="|")
    assert data.n_rows == 120

    # a directory with only marker files errors clearly, not silently
    empty = tmp_path / "empty"
    (empty).mkdir()
    (empty / "_SUCCESS").write_text("")
    from shifu_tpu.utils.errors import ShifuError

    with pytest.raises(ShifuError):
        expand_remote(f"file://{empty}")


def test_file_protocol_pipeline_end_to_end(tmp_path):
    """Full init->stats over file:// URLs (same flow as the memory://
    e2e, on the real local filesystem connector)."""
    import numpy as np

    from tests.helpers import make_model_set

    root = str(tmp_path / "ms")
    make_model_set(root, n_rows=250)
    from shifu_tpu.config.model_config import ModelConfig
    from shifu_tpu.processor.init import InitProcessor
    from shifu_tpu.processor.stats import StatsProcessor

    mc = ModelConfig.load(os.path.join(root, "ModelConfig.json"))
    mc.data_set.data_path = f"file://{root}/data/data.txt"
    mc.data_set.header_path = f"file://{root}/data/header.txt"
    mc.save(os.path.join(root, "ModelConfig.json"))
    assert InitProcessor(root).run() == 0
    assert StatsProcessor(root).run() == 0
    import json

    cc = json.load(open(os.path.join(root, "ColumnConfig.json")))
    assert any(c.get("columnStats", {}).get("ks") for c in cc)
