"""Bridges between reference model specs and the TPU framework's scorer.

``load_ref_model`` sniffs any reference-format model file (Encog EG text
``.nn``, BinaryNNSerializer gzip ``.nn``, BinaryDTSerializer ``.gbt``/``.rf``,
zip spec) and wraps it so ModelRunner can score it next to native models —
the reference's prod scorers and ours become interchangeable
(ModelSpecLoaderUtils.java:389 loadModel dispatch parity).

Export helpers emit our trained models in the reference's own formats so the
reference's IndependentNNModel / IndependentTreeModel / Encog loaders can
score them.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from shifu_tpu.compat import egb, encog, sniff_model_format, treespec
from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)


class RefModelAdapter:
    """Duck-typed stand-in for a native model spec inside ModelRunner."""

    def __init__(self, kind: str, model, path: str = "",
                 norm_plan=None):
        self.kind = kind  # 'eg-nn' | 'egb-nn' | 'ref-tree' | 'ref-wdl'
        self.model = model
        self.path = path
        self.norm_plan = norm_plan  # NormPlan for eg-nn (external stats)
        self.algorithm = (
            model.algorithm if kind in ("ref-tree", "ref-wdl") else "NN"
        )

    # -- scoring -------------------------------------------------------------
    def _tree_matrix(self, data) -> np.ndarray:
        """Columnar, vectorized convertDataMapToDoubleArray
        (IndependentTreeModel.java:571)."""
        m: treespec.RefTreeModel = self.model
        out = np.zeros((data.n_rows, len(m.column_mapping)), dtype=np.float64)
        for col_num, idx in m.column_mapping.items():
            name = m.column_names.get(col_num)
            if name is None or name not in data.names:
                if col_num in m.categorical_values:
                    out[:, idx] = len(m.categorical_values[col_num])
                else:
                    out[:, idx] = m.numerical_mean.get(col_num, 0.0) or 0.0
                continue
            if col_num in m.categorical_values:
                table = m.category_index(col_num)
                size = len(m.categorical_values[col_num])
                vals = data.column(name)
                idxs = np.array(
                    [table.get(str(v), size) for v in vals], dtype=np.float64
                )
                miss = data.missing_mask(name)
                idxs[miss] = size
                out[:, idx] = idxs
            else:
                mean = m.numerical_mean.get(col_num, 0.0) or 0.0
                vals = data.numeric(name).astype(np.float64)
                vals = np.where(np.isnan(vals), mean, vals)
                out[:, idx] = vals
        return out

    def score_raw(self, data) -> np.ndarray:
        """ColumnarData of raw records -> scores in [0, 1]."""
        if self.kind == "ref-wdl":
            return np.clip(self.model.compute_raw(data), 0.0, 1.0)
        if self.kind == "ref-tree":
            m: treespec.RefTreeModel = self.model
            raw = m.compute(self._tree_matrix(data))
            if m.algorithm.upper() == "GBT" and m.loss == "log":
                return 1.0 / (1.0 + np.exp(-raw))
            return np.clip(raw, 0.0, 1.0)
        if self.kind == "egb-nn":
            rows = _columnar_to_rows(data)
            return np.clip(self.model.compute_raw(rows), 0.0, 1.0)
        # eg-nn: normalize via external plan (project ColumnConfig stats)
        if self.norm_plan is None:
            raise ValueError(
                f"{self.path}: Encog EG model needs ColumnConfig stats to "
                "normalize raw input — score via `shifu eval` in a model dir"
            )
        from shifu_tpu.norm.normalizer import apply_norm_plan

        feats = apply_norm_plan(self.norm_plan, data)
        return np.clip(np.ravel(self.model.compute(feats)), 0.0, 1.0)

    def score_normalized(self, feats: np.ndarray) -> np.ndarray:
        if self.kind in ("ref-tree", "ref-wdl"):
            raise ValueError(
                "reference tree/WDL models score raw records (they need "
                "bin codes / categorical values, not a normalized matrix)"
            )
        return np.clip(np.ravel(self.model.compute(feats)), 0.0, 1.0)


def _columnar_to_rows(data) -> List[dict]:
    names = list(data.names)
    cols = {n: data.column(n) for n in names}
    miss = {n: data.missing_mask(n) for n in names}
    return [
        {n: (None if miss[n][i] else cols[n][i]) for n in names}
        for i in range(data.n_rows)
    ]


def load_ref_model(path: str, column_configs=None, model_config=None
                   ) -> Optional[RefModelAdapter]:
    """Load a reference-format model file; None if it is a native spec."""
    with open(path, "rb") as fh:
        blob = fh.read()
    fmt = sniff_model_format(blob)
    if fmt == "native":
        return None
    if fmt == "eg-text":
        net = encog.read_eg(blob)
        plan = None
        if column_configs is not None and model_config is not None:
            from shifu_tpu.norm.normalizer import build_norm_plan

            plan = build_norm_plan(model_config, column_configs)
        return RefModelAdapter("eg-nn", net, path, norm_plan=plan)
    if fmt == "zip":
        return RefModelAdapter("ref-tree", treespec.read_zip_model(blob), path)
    # gzip java stream: tree vs nn vs wdl container — extension first
    suffix = path.rsplit(".", 1)[-1].lower()
    if suffix in ("gbt", "rf"):
        return RefModelAdapter("ref-tree", treespec.read_tree_model(blob), path)
    if suffix == "wdl":
        from shifu_tpu.compat import wdl as wdl_compat

        return RefModelAdapter("ref-wdl", wdl_compat.read_wdl_model(blob),
                               path)
    try:
        return RefModelAdapter("egb-nn", egb.read_nn_model(blob), path)
    except Exception:  # not an NN container after all
        return RefModelAdapter("ref-tree", treespec.read_tree_model(blob), path)


# ---------------------------------------------------------------------------
# export: our specs -> reference formats
# ---------------------------------------------------------------------------


def nn_spec_to_eg_bytes(spec) -> bytes:
    """Our NNModelSpec -> Encog EG text loadable by
    EncogDirectoryPersistence (ModelSpecLoaderUtils.java:409)."""
    weights = [np.asarray(p["W"], np.float64) for p in spec.params]
    biases = [np.asarray(p["b"], np.float64) for p in spec.params]
    hidden_acts = list(spec.activations)
    net = encog.from_layers(weights, biases, hidden_acts, spec.out_activation)
    return encog.write_eg(net)


def _stats_from_column_configs(column_configs, cutoff: float
                               ) -> List[egb.RefNNColumnStats]:
    from shifu_tpu.norm.normalizer import woe_mean_std

    out = []
    for cc in column_configs:
        if not cc.final_select:
            continue
        stats = cc.column_stats
        binning = cc.column_binning
        woes = cc.bin_count_woe or []
        try:
            wm, ws = woe_mean_std(cc, weighted=False)
            wwm, wws = woe_mean_std(cc, weighted=True)
        except Exception:  # stats absent/degenerate: export zero WOE moments
            wm = ws = wwm = wws = 0.0
        out.append(
            egb.RefNNColumnStats(
                column_num=cc.column_num,
                column_name=cc.column_name,
                column_type=cc.column_type.value if cc.column_type else "N",
                cutoff=cutoff,
                mean=stats.mean or 0.0,
                stddev=stats.std_dev or 1.0,
                woe_mean=wm, woe_stddev=ws,
                woe_wgt_mean=wwm, woe_wgt_stddev=wws,
                bin_boundaries=[float(b) for b in (cc.bin_boundary or [])],
                bin_categories=list(cc.bin_category or []),
                bin_pos_rates=[float(v) for v in (cc.bin_pos_rate or [])],
                bin_count_woes=[float(v) for v in woes],
                bin_weight_woes=[float(v) for v in (cc.bin_weighted_woe or [])],
            )
        )
    return out


def nn_spec_to_egb_bytes(spec, column_configs, cutoff: float = 4.0) -> bytes:
    """Our NNModelSpec + project ColumnConfig -> BinaryNNSerializer .nn
    container readable by IndependentNNModel.loadFromStream."""
    weights = [np.asarray(p["W"], np.float64) for p in spec.params]
    biases = [np.asarray(p["b"], np.float64) for p in spec.params]
    net = encog.from_layers(weights, biases, list(spec.activations),
                            spec.out_activation)
    stats = _stats_from_column_configs(column_configs, cutoff)
    mapping = {cs.column_num: j for j, cs in enumerate(stats)}
    model = egb.RefNNModel(spec.norm_type, stats, mapping, [net])
    return egb.write_nn_model(model)


def tree_spec_to_ref_bytes(spec) -> bytes:
    """Our TreeModelSpec -> reference binary .gbt/.rf."""
    return treespec.write_tree_model(treespec.from_dense_spec(spec))


def tree_spec_to_zip_bytes(spec) -> bytes:
    """Our TreeModelSpec -> reference zip spec (shifu convert format)."""
    return treespec.write_zip_model(treespec.from_dense_spec(spec))
