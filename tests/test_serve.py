"""Online scoring subsystem (shifu_tpu/serve/): registry fusion parity,
shape-bucket compile bounds, micro-batching, admission backpressure, the
HTTP front end, the shutdown run-ledger manifest, and PMML export parity
against the fused scorer.

The model set is trained once per module with HYBRID normalization so the
fused program exercises BOTH device norm paths (numeric z-score-with-
clamp value kernel + categorical woe table gather) and the PMML parity
test pins both embedded LocalTransformations semantics (NormContinuous
clamp, woe MapValues) against the same registry.
"""

import json
import math
import os
import threading
import time
import urllib.error
import urllib.request
import xml.etree.ElementTree as ET

import numpy as np
import pytest

from tests.helpers import make_model_set

NS = "{http://www.dmg.org/PMML-4_2}"


@pytest.fixture(scope="module")
def model_set(tmp_path_factory):
    from shifu_tpu.processor.init import InitProcessor
    from shifu_tpu.processor.norm import NormProcessor
    from shifu_tpu.processor.stats import StatsProcessor
    from shifu_tpu.processor.train import TrainProcessor

    root = str(tmp_path_factory.mktemp("serve_ms"))
    make_model_set(root, n_rows=400)
    mcp = os.path.join(root, "ModelConfig.json")
    mc = json.load(open(mcp))
    mc["normalize"]["normType"] = "HYBRID"  # numeric z-score + cat woe
    mc["train"]["numTrainEpochs"] = 40
    json.dump(mc, open(mcp, "w"), indent=2)
    assert InitProcessor(root).run() == 0
    assert StatsProcessor(root).run() == 0
    assert NormProcessor(root).run() == 0
    assert TrainProcessor(root).run() == 0
    return root


@pytest.fixture(scope="module")
def raw_data(model_set):
    from shifu_tpu.data.reader import read_columnar, read_header

    names = read_header(os.path.join(model_set, "data", "header.txt"))
    return read_columnar(os.path.join(model_set, "data", "data.txt"),
                         names)


def _registry(model_set):
    from shifu_tpu.serve.registry import ModelRegistry

    return ModelRegistry(os.path.join(model_set, "models"))


# ---------------------------------------------------------------------------
# find_model_paths (satellite): dedupe + deterministic ordering
# ---------------------------------------------------------------------------


class TestFindModelPaths:
    def test_mixed_numeric_and_unindexed_order(self, tmp_path):
        from shifu_tpu.eval.scorer import find_model_paths

        d = str(tmp_path)
        for name in ("model10.nn", "model2.nn", "model.nn",
                     "model_extra.gbt", "model1.rf"):
            open(os.path.join(d, name), "w").close()
        got = [os.path.basename(p) for p in find_model_paths(d)]
        # numeric index order first (1 < 2 < 10, NOT lexicographic), then
        # unindexed names in basename order — same answer whatever order
        # the per-suffix globs enumerate
        assert got == ["model1.rf", "model2.nn", "model10.nn",
                       "model.nn", "model_extra.gbt"]
        assert len(got) == len(set(got))  # deduped

    def test_repeated_calls_identical(self, tmp_path):
        from shifu_tpu.eval.scorer import find_model_paths

        d = str(tmp_path)
        for name in ("model.nn", "model_b.wdl", "model_a.lr"):
            open(os.path.join(d, name), "w").close()
        assert find_model_paths(d) == find_model_paths(d)
        got = [os.path.basename(p) for p in find_model_paths(d)]
        assert got == sorted(got)  # unindexed fallback: basename order


# ---------------------------------------------------------------------------
# registry: fused program parity + shape buckets
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_fused_scores_match_model_runner(self, model_set, raw_data):
        from shifu_tpu.eval.scorer import ModelRunner, find_model_paths

        reg = _registry(model_set)
        assert reg.fused
        runner = ModelRunner(
            find_model_paths(os.path.join(model_set, "models")))
        res_f = reg.score_raw(raw_data)
        res_r = runner.score_raw(raw_data)
        np.testing.assert_allclose(res_f.model_scores,
                                   res_r.model_scores, atol=2e-3)
        np.testing.assert_allclose(res_f.mean, res_r.mean, atol=2e-3)
        np.testing.assert_allclose(res_f.median, res_r.median, atol=2e-3)
        np.testing.assert_allclose(res_f.min, res_r.min, atol=2e-3)
        np.testing.assert_allclose(res_f.max, res_r.max, atol=2e-3)
        assert res_f.model_widths == res_r.model_widths
        assert res_f.model_names == res_r.model_names

    def test_records_missing_fields_score_like_missing_tokens(
            self, model_set, raw_data):
        reg = _registry(model_set)
        # a record missing a numeric and a categorical field must score
        # exactly like the same record with explicit missing tokens
        base = {c: str(raw_data.column(c)[0]) for c in reg.input_columns}
        with_tokens = dict(base, num_0="?", cat_0="")
        without = {k: v for k, v in with_tokens.items()
                   if k not in ("num_0", "cat_0")}
        r1 = reg.score_records([with_tokens])
        r2 = reg.score_records([without])
        np.testing.assert_allclose(r1.model_scores, r2.model_scores,
                                   atol=1e-6)

    def test_shape_bucket_compile_bound(self, model_set, raw_data):
        from shifu_tpu import obs

        obs.reset()
        reg = _registry(model_set)
        # 25 distinct batch sizes; buckets must collapse to O(log n)
        for n in list(range(1, 21)) + [33, 57, 100, 128, 250]:
            reg.score_raw(raw_data.select_rows(np.arange(n)))
        snap = reg.snapshot()
        assert set(snap["warmBuckets"]) <= {8, 16, 32, 64, 128, 256}
        compiles = obs.registry().snapshot()["counters"].get(
            "serve.program_compiles", 0)
        assert compiles == len(snap["warmBuckets"])

    def test_warm_precompiles_buckets(self, model_set):
        reg = _registry(model_set)
        warmed = reg.warm([1, 3, 16])
        assert warmed == [8, 16]
        assert reg.snapshot()["warmBuckets"] == [8, 16]

    def test_model_runner_fallback_serves_tree_sets(self, tmp_path):
        """A non-NN model set is still served (batched ModelRunner path):
        input_columns, warm(), score_records, snapshot and the batcher
        all work with fused=False."""
        from shifu_tpu.eval.scorer import ModelRunner
        from shifu_tpu.serve.registry import ModelRegistry
        from shifu_tpu.serve.registry import records_to_columnar
        from shifu_tpu.serve.server import Scorer
        from shifu_tpu.train.tree_trainer import (
            TreeTrainConfig,
            train_trees,
        )

        rng = np.random.default_rng(0)
        n = 400
        bounds = [-np.inf, -1.0, 0.0, 1.0]
        cats = ["aa", "bb", "cc"]
        x_num = rng.normal(size=n)
        x_cat = rng.integers(0, 3, size=n)
        codes = np.stack(
            [np.searchsorted(bounds, x_num, side="right") - 1, x_cat],
            axis=1).astype(np.int32)
        y = ((x_num > 0) | (x_cat == 1)).astype(np.float32)
        cfg = TreeTrainConfig(algorithm="GBT", tree_num=3, max_depth=3,
                              learning_rate=0.3, valid_set_rate=0.1,
                              seed=3, min_instances_per_node=1)
        res = train_trees(codes, y, np.ones(n, np.float32), [5, 4],
                          [False, True], ["num0", "cat0"], cfg,
                          boundaries=[[float(b) for b in bounds], None],
                          categories=[None, cats])
        models_dir = str(tmp_path / "models")
        os.makedirs(models_dir)
        res.spec.save(os.path.join(models_dir, "model0.gbt"))

        reg = ModelRegistry(models_dir)
        assert not reg.fused
        assert reg.input_columns == ["num0", "cat0"]
        assert reg.warm([1]) == [8]
        snap = reg.snapshot()
        assert snap["fused"] is False and snap["models"] == ["model0.gbt"]

        recs = [{"num0": f"{x_num[i]:.5f}", "cat0": cats[x_cat[i]]}
                for i in range(10)]
        got = reg.score_records(recs)
        expect = ModelRunner(
            [os.path.join(models_dir, "model0.gbt")]).score_raw(
            records_to_columnar(recs, reg.input_columns))
        np.testing.assert_allclose(got.mean, expect.mean, atol=1e-6)

        scorer = Scorer(reg, max_wait_ms=1)
        res_b = scorer.score_batch(recs[:2])
        np.testing.assert_allclose(res_b.mean, expect.mean[:2], atol=1e-6)
        scorer.close(10)

    def test_sha_tracks_model_content(self, model_set, tmp_path):
        import shutil

        from shifu_tpu.serve.registry import model_set_sha

        src = os.path.join(model_set, "models")
        d1 = str(tmp_path / "a")
        shutil.copytree(src, d1)
        paths = sorted(
            os.path.join(d1, f) for f in os.listdir(d1))
        sha1 = model_set_sha(paths)
        with open(paths[0], "ab") as fh:
            fh.write(b"\0")
        assert model_set_sha(paths) != sha1


# ---------------------------------------------------------------------------
# micro-batcher + admission queue
# ---------------------------------------------------------------------------


def _fake_result(values):
    from shifu_tpu.eval.scorer import ScoreResult

    m = np.asarray(values, np.float64)[:, None]
    return ScoreResult(model_scores=m, mean=m[:, 0], max=m[:, 0],
                       min=m[:, 0], median=m[:, 0],
                       model_names=["fake"], model_widths=[1])


def _one_row(v):
    from shifu_tpu.data.reader import ColumnarData

    return ColumnarData(names=["v"],
                        raw={"v": np.asarray([str(v)], object)}, n_rows=1)


class TestBatcherQueue:
    def test_coalescing_and_padding_aware_unpacking(self):
        from shifu_tpu.serve.batcher import MicroBatcher
        from shifu_tpu.serve.queue import AdmissionQueue

        batch_sizes = []
        gate = threading.Event()

        def score(data):
            gate.wait(10)
            vals = [float(x) for x in data.column("v")]
            batch_sizes.append(len(vals))
            return _fake_result(vals)

        batcher = MicroBatcher(score, AdmissionQueue(64),
                               max_batch_rows=64, max_wait_ms=50)
        reqs = [batcher.submit(_one_row(i)) for i in range(20)]
        gate.set()
        results = [r.wait(10) for r in reqs]
        # every request got ITS OWN row back, whatever batch it rode in
        for i, res in enumerate(results):
            assert res.mean[0] == pytest.approx(float(i))
        # the 20 requests coalesced into fewer dispatches
        assert 1 <= len(batch_sizes) < 20
        batcher.admission.close()
        batcher.join(5)

    def test_row_cap_bounds_batch_size(self):
        from shifu_tpu.serve.batcher import MicroBatcher
        from shifu_tpu.serve.queue import AdmissionQueue

        batch_sizes = []
        gate = threading.Event()

        def score(data):
            gate.wait(10)
            vals = [float(x) for x in data.column("v")]
            batch_sizes.append(len(vals))
            return _fake_result(vals)

        batcher = MicroBatcher(score, AdmissionQueue(64),
                               max_batch_rows=4, max_wait_ms=200)
        reqs = [batcher.submit(_one_row(i)) for i in range(12)]
        gate.set()
        for r in reqs:
            r.wait(10)
        assert max(batch_sizes) <= 4
        batcher.admission.close()
        batcher.join(5)

    def test_scoring_error_fans_out_not_kills_worker(self):
        from shifu_tpu.serve.batcher import MicroBatcher
        from shifu_tpu.serve.queue import AdmissionQueue

        calls = []

        def score(data):
            calls.append(data.n_rows)
            if len(calls) == 1:
                raise ValueError("boom")
            return _fake_result([float(x) for x in data.column("v")])

        batcher = MicroBatcher(score, AdmissionQueue(8),
                               max_batch_rows=8, max_wait_ms=1)
        bad = batcher.submit(_one_row(1))
        with pytest.raises(ValueError, match="boom"):
            bad.wait(10)
        good = batcher.submit(_one_row(2))
        assert good.wait(10).mean[0] == pytest.approx(2.0)
        batcher.admission.close()
        batcher.join(5)

    def test_backpressure_sheds_fast_and_drains_clean(self):
        """Acceptance: saturation -> explicit rejection (not a timeout);
        close() -> every ADMITTED request still completes."""
        from shifu_tpu.serve.batcher import MicroBatcher
        from shifu_tpu.serve.queue import AdmissionQueue, RejectedError

        gate = threading.Event()
        entered = threading.Event()

        def score(data):
            entered.set()
            gate.wait(10)
            return _fake_result([float(x) for x in data.column("v")])

        admission = AdmissionQueue(3)
        batcher = MicroBatcher(score, admission,
                               max_batch_rows=1, max_wait_ms=1)
        # worker picks up the first request and blocks in score(); wait
        # for it to actually arrive there, then saturate the queue
        first = batcher.submit(_one_row(0))
        assert entered.wait(10)
        admitted = [batcher.submit(_one_row(i)) for i in range(1, 4)]
        t0 = time.perf_counter()
        with pytest.raises(RejectedError) as exc:
            batcher.submit(_one_row(99))
        shed_latency = time.perf_counter() - t0
        assert exc.value.reason == "full"
        assert shed_latency < 0.5  # an explicit shed, not a timeout
        # drain: close admission, release the scorer, everything admitted
        # completes with its own result
        admission.close()
        with pytest.raises(RejectedError) as exc2:
            batcher.submit(_one_row(100))
        assert exc2.value.reason == "closed"
        gate.set()
        assert first.wait(10).mean[0] == pytest.approx(0.0)
        for i, req in enumerate(admitted):
            assert req.wait(10).mean[0] == pytest.approx(float(i + 1))
        batcher.join(5)
        assert not batcher.draining


# ---------------------------------------------------------------------------
# HTTP front end + shutdown manifest
# ---------------------------------------------------------------------------


def _post(url, body, ctype="application/json"):
    req = urllib.request.Request(
        url, data=body if isinstance(body, bytes) else body.encode(),
        headers={"Content-Type": ctype})
    with urllib.request.urlopen(req, timeout=30) as r:
        return r.status, json.loads(r.read())


class TestScoringServer:
    def test_endpoints_scoring_and_shutdown_manifest(self, model_set,
                                                     raw_data):
        from shifu_tpu import obs
        from shifu_tpu.serve.server import ScoringServer

        obs.reset()
        # replicas=1 pins the single-replica semantics this test is
        # about (the suite forces 8 virtual devices, and the default
        # fleet would spread these requests); multi-replica behavior is
        # tests/test_fleet.py's job
        srv = ScoringServer(root=model_set, max_wait_ms=1,
                            replicas=1).start()
        base = f"http://127.0.0.1:{srv.port}"
        cols = srv.registry.input_columns
        recs = [{c: str(raw_data.column(c)[i]) for c in cols}
                for i in range(3)]

        # JSON document form
        status, out = _post(f"{base}/score", json.dumps({"records": recs}))
        assert status == 200
        assert len(out["scores"]) == 3
        expect = srv.registry.score_records(recs)
        got = [s["mean"] for s in out["scores"]]
        np.testing.assert_allclose(got, expect.mean, atol=1e-2)

        # JSONL form scores identically
        jsonl = "\n".join(json.dumps(r) for r in recs)
        status, out2 = _post(f"{base}/score", jsonl, "application/jsonl")
        assert status == 200
        assert [s["mean"] for s in out2["scores"]] == got

        with urllib.request.urlopen(f"{base}/healthz", timeout=10) as r:
            health = json.loads(r.read())
        assert health["status"] == "ok"
        assert health["sha"] == srv.registry.sha
        with urllib.request.urlopen(f"{base}/metrics", timeout=10) as r:
            metrics = r.read().decode()
        assert "serve_requests_total" in metrics
        assert "serve_latency_seconds_bucket" in metrics
        assert "serve_queue_depth" in metrics

        with pytest.raises(urllib.error.HTTPError) as he:
            _post(f"{base}/score", "not json [")
        assert he.value.code == 400
        # valid JSON whose records are not objects is a 400 too, never a
        # dropped connection
        with pytest.raises(urllib.error.HTTPError) as he:
            _post(f"{base}/score", "[1, 2, 3]")
        assert he.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as he:
            _post(f"{base}/nope", "{}")
        assert he.value.code == 404

        manifest_path = srv.shutdown()
        assert manifest_path and os.path.isfile(manifest_path)
        m = json.load(open(manifest_path))
        assert m["schema"] == "shifu.run/1"
        assert m["step"] == "serve"
        assert m["serve"]["sha"] == srv.registry.sha
        # fleet PR: serve.* metrics carry a replica label (replica "0"
        # is the whole fleet at the default single-replica test config);
        # wire PR: requests/latency additionally split by format=
        assert m["metrics"]["counters"][
            'serve.requests{format="json",replica="0"}'] >= 2
        assert m["metrics"]["counters"]['serve.records{replica="0"}'] >= 6
        # post-shutdown: in-process scoring is an explicit rejection
        from shifu_tpu.serve.queue import RejectedError

        with pytest.raises(RejectedError):
            srv.scorer.score_batch(recs[:1])

    def test_fleet_endpoints_answer_on_every_process(self, model_set,
                                                     raw_data):
        """PR 17: /admin/metrics.json serves the lossless snapshot the
        fleet collector scrapes, and /fleet/metrics + /fleet/healthz
        answer the MERGED view even on a fleet of one."""
        from shifu_tpu import obs
        from shifu_tpu.serve.server import ScoringServer

        obs.reset()
        srv = ScoringServer(root=model_set, max_wait_ms=1,
                            replicas=1).start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            cols = srv.registry.input_columns
            recs = [{c: str(raw_data.column(c)[i]) for c in cols}
                    for i in range(2)]
            status, _out = _post(f"{base}/score",
                                 json.dumps({"records": recs}))
            assert status == 200

            with urllib.request.urlopen(f"{base}/admin/metrics.json",
                                        timeout=10) as r:
                doc = json.loads(r.read())
            assert doc["schema"] == "shifu.obs.metrics/1"
            assert doc["leaseId"] == srv.lease_id
            local = doc["metrics"]["counters"][
                'serve.requests{format="json",replica="0"}']
            assert local >= 1

            with urllib.request.urlopen(f"{base}/fleet/metrics",
                                        timeout=10) as r:
                text = r.read().decode()
            from shifu_tpu.obs.metrics import parse_prometheus

            flat = parse_prometheus(text)
            # fleet of one: merged counter == the local counter, and the
            # membership gauges name this process
            assert flat[
                'serve_requests_total{format="json",replica="0"}'] \
                >= local
            assert flat["fleet_processes_live"] == 1.0

            with urllib.request.urlopen(f"{base}/fleet/healthz",
                                        timeout=10) as r:
                hz = json.loads(r.read())
            assert hz["answeredBy"] == srv.lease_id
            assert hz["liveProcesses"] == 1
            assert "fleet" in hz["slo"]
            assert any(p["leaseId"] == srv.lease_id
                       for p in hz["processes"])
        finally:
            srv.shutdown()

    def test_http_429_under_saturation_then_clean_drain(self, model_set,
                                                        raw_data):
        """Acceptance over HTTP: saturated queue -> 429 with Retry-After,
        in-flight requests drain on shutdown, manifest written."""
        from shifu_tpu.serve.registry import records_to_columnar
        from shifu_tpu.serve.server import ScoringServer

        # replicas=1: with a fleet, saturating ONE replica no longer
        # sheds — the router drains around it (pinned in test_fleet.py);
        # this test pins the single-replica 429 contract
        srv = ScoringServer(root=model_set, queue_depth=2,
                            max_batch_rows=1, max_wait_ms=1,
                            replicas=1).start()
        base = f"http://127.0.0.1:{srv.port}"
        cols = srv.registry.input_columns
        rec = {c: str(raw_data.column(c)[0]) for c in cols}

        gate = threading.Event()
        entered = threading.Event()
        orig = srv.scorer.batcher.score_fn

        def gated(data):
            entered.set()
            gate.wait(10)
            return orig(data)

        srv.scorer.batcher.score_fn = gated
        # one request in the worker (wait until it actually picks it up —
        # otherwise the queue-fillers below race it for the depth budget)
        # + two in the queue = saturated
        first = srv.scorer.batcher.submit(records_to_columnar([rec], cols))
        assert entered.wait(10)
        inflight = [first] + [
            srv.scorer.batcher.submit(records_to_columnar([rec], cols))
            for _ in range(2)
        ]
        with pytest.raises(urllib.error.HTTPError) as he:
            _post(f"{base}/score", json.dumps(rec))
        assert he.value.code == 429
        assert he.value.headers.get("Retry-After")
        body = json.loads(he.value.read())
        assert body["reason"] == "full"

        done = {}

        def finish():
            gate.set()
            done["manifest"] = srv.shutdown()

        t = threading.Thread(target=finish)
        t.start()
        # every admitted request completes despite the shutdown
        for req in inflight:
            assert req.wait(15).mean.shape == (1,)
        t.join(15)
        assert done["manifest"] and os.path.isfile(done["manifest"])


# ---------------------------------------------------------------------------
# PMML parity (satellite): exported LocalTransformations vs fused scorer
# ---------------------------------------------------------------------------


def _act(name, z):
    if name == "tanh":
        return math.tanh(z)
    if name == "logistic":
        return 1.0 / (1.0 + math.exp(-z))
    if name == "rectifier":
        return max(0.0, z)
    return z  # identity


def _eval_derived(df_el, value):
    """PMML 4.2 DerivedField semantics, written against the spec (not our
    writer): NormContinuous with outliers=asExtremeValues clamps to the
    anchor norms; MapValues falls back to defaultValue/mapMissingTo."""
    nc = df_el.find(f"{NS}NormContinuous")
    if nc is not None:
        if value is None:
            return float(nc.get("mapMissingTo"))
        x = float(value)
        a1, a2 = nc.findall(f"{NS}LinearNorm")
        o1, n1 = float(a1.get("orig")), float(a1.get("norm"))
        o2, n2 = float(a2.get("orig")), float(a2.get("norm"))
        if x <= o1:
            return n1
        if x >= o2:
            return n2
        return n1 + (x - o1) * (n2 - n1) / (o2 - o1)
    mv = df_el.find(f"{NS}MapValues")
    if mv is not None:
        if value is None:
            return float(mv.get("mapMissingTo"))
        for row in mv.find(f"{NS}InlineTable").findall(f"{NS}row"):
            if row.find(f"{NS}in").text == str(value):
                return float(row.find(f"{NS}out").text)
        return float(mv.get("defaultValue"))
    raise AssertionError("unsupported DerivedField")


def eval_pmml_nn(xml_text, rows):
    """Independent mini NN evaluator: LocalTransformations -> NeuralInputs
    -> NeuralLayers -> NeuralOutputs, per the PMML 4.2 spec."""
    root = ET.fromstring(xml_text)
    nn = root.find(f"{NS}NeuralNetwork")
    default_act = nn.get("activationFunction")
    lt = nn.find(f"{NS}LocalTransformations")
    derived = {df.get("name"): df
               for df in lt.findall(f"{NS}DerivedField")}
    in_ids, in_fields = [], []
    for ni in nn.find(f"{NS}NeuralInputs").findall(f"{NS}NeuralInput"):
        in_ids.append(ni.get("id"))
        ref = ni.find(f"{NS}DerivedField").find(f"{NS}FieldRef")
        in_fields.append(ref.get("field"))
    out_neuron = nn.find(f"{NS}NeuralOutputs").find(
        f"{NS}NeuralOutput").get("outputNeuron")
    outs = []
    for row in rows:
        acts = {}
        for iid, field in zip(in_ids, in_fields):
            col = field[len("norm_"):]
            acts[iid] = _eval_derived(derived[field], row.get(col))
        for layer in nn.findall(f"{NS}NeuralLayer"):
            lact = layer.get("activationFunction") or default_act
            fresh = {}
            for neuron in layer.findall(f"{NS}Neuron"):
                z = float(neuron.get("bias"))
                for con in neuron.findall(f"{NS}Con"):
                    z += acts[con.get("from")] * float(con.get("weight"))
                fresh[neuron.get("id")] = _act(lact, z)
            acts.update(fresh)
        outs.append(acts[out_neuron])
    return np.asarray(outs)


class TestPmmlServeParity:
    def test_exported_pmml_matches_fused_registry(self, model_set,
                                                  raw_data):
        import glob

        from shifu_tpu.eval.scorer import DEFAULT_SCORE_SCALE
        from shifu_tpu.processor.export import ExportProcessor

        assert ExportProcessor(model_set, kind="pmml").run() == 0
        hits = glob.glob(os.path.join(model_set, "**", "*.pmml"),
                         recursive=True)
        assert hits
        xml = open(hits[0]).read()

        reg = _registry(model_set)
        n = 60
        sub = raw_data.select_rows(np.arange(n))
        rows = []
        for i in range(n):
            row = {}
            for c in reg.input_columns:
                row[c] = (None if sub.missing_mask(c)[i]
                          else str(sub.column(c)[i]))
            rows.append(row)
        # synthetic edge rows: z-score CLAMP (huge magnitude numerics) and
        # woe MapValues default routing (unseen category) must also agree
        rows.append(dict(rows[0], num_0="1e9", num_1="-1e9"))
        rows.append(dict(rows[1], cat_0="never-seen-category"))
        rows.append({c: None for c in reg.input_columns})  # all missing

        pmml_scores = eval_pmml_nn(xml, rows) * DEFAULT_SCORE_SCALE
        recs = [{c: (v if v is not None else "") for c, v in r.items()}
                for r in rows]
        native = reg.score_records(recs)
        np.testing.assert_allclose(pmml_scores,
                                   native.model_scores[:, 0], atol=0.5)

    def test_local_transformations_shapes(self, model_set):
        """HYBRID export embeds BOTH transformation kinds: NormContinuous
        (numeric z-score clamp) and MapValues over an InlineTable (woe)."""
        import glob

        from shifu_tpu.processor.export import ExportProcessor

        assert ExportProcessor(model_set, kind="pmml").run() == 0
        xml = open(glob.glob(os.path.join(model_set, "**", "*.pmml"),
                             recursive=True)[0]).read()
        root = ET.fromstring(xml)
        lt = root.find(f"{NS}NeuralNetwork").find(
            f"{NS}LocalTransformations")
        kinds = {("nc" if df.find(f"{NS}NormContinuous") is not None
                  else "mv" if df.find(f"{NS}MapValues") is not None
                  else "other")
                 for df in lt.findall(f"{NS}DerivedField")}
        assert kinds == {"nc", "mv"}
        # clamp anchors present on a numeric derived field
        nc = lt.find(f"{NS}DerivedField/{NS}NormContinuous")
        assert nc.get("outliers") == "asExtremeValues"
        assert len(nc.findall(f"{NS}LinearNorm")) == 2


class TestFlatNumericFastPath:
    """Fleet-PR satellite: flat_numeric_matrix grew a C-speed cast fast
    path for fully numeric batches (the serve hot path competes with
    every replica worker for the GIL). The fast and slow paths MUST
    stay value-identical — python-float grammar extras (underscore
    separators, non-ASCII digits) are routed to the slow parser by the
    codepoint guard, and numeric-looking missing tokens disable the
    fast path entirely."""

    def _data(self, cols, missing=("", "?")):
        from shifu_tpu.data.reader import ColumnarData

        n = len(next(iter(cols.values())))
        return ColumnarData(
            names=list(cols),
            raw={k: np.asarray(v, dtype=object) for k, v in cols.items()},
            n_rows=n, missing_values=set(missing))

    def test_fast_and_slow_paths_identical(self):
        from shifu_tpu.data.reader import flat_numeric_matrix

        fast = self._data({"a": ["1.5", "  2e3 ", "+4", ".5"],
                           "b": ["-1", "inf", "3", "0"]})
        slow = self._data({"a": ["1.5", "  2e3 ", "+4", ".5"],
                           "b": ["-1", "inf", "3", "?"]})
        got_fast = flat_numeric_matrix(fast, ["a", "b"])
        got_slow = flat_numeric_matrix(slow, ["a", "b"])
        np.testing.assert_array_equal(got_fast[:, 0], got_slow[:, 0])
        np.testing.assert_array_equal(
            got_fast[:3, 1], got_slow[:3, 1])
        assert np.isnan(got_slow[3, 1])       # token -> missing
        assert np.isnan(got_fast[1, 1])       # inf -> non-finite -> NaN

    def test_python_float_grammar_extras_route_to_slow_parser(self):
        """'1_234' and full-width digits parse under python float but
        coerce to NaN under pandas — the guard must keep the documented
        to_numeric semantics, not widen them."""
        from shifu_tpu.data.reader import flat_numeric_matrix

        got = flat_numeric_matrix(
            self._data({"a": ["1_234", "2.0"]}), ["a"])
        assert np.isnan(got[0, 0]) and got[1, 0] == 2.0
        got = flat_numeric_matrix(
            self._data({"a": ["１２３", "2.0"]}), ["a"])
        assert np.isnan(got[0, 0]) and got[1, 0] == 2.0

    def test_numeric_missing_token_still_masks(self):
        """A missing token that itself parses as a number ('999') must
        still mask — the fast path is disabled for such token sets."""
        from shifu_tpu.data.reader import flat_numeric_matrix

        got = flat_numeric_matrix(
            self._data({"a": ["999", "1.0"]}, missing=("", "999")),
            ["a"])
        assert np.isnan(got[0, 0]) and got[1, 0] == 1.0


class TestLatencyHistogramBuckets:
    """ISSUE-6 satellite: the serve latency/batch-rows histograms use
    PINNED exponential buckets. The registry's DEFAULT_BUCKETS start at
    5 ms, so a fused path whose p99 is single-digit milliseconds exported
    every observation into its first two buckets — the Prometheus
    quantiles collapsed. Doubling edges from 100 µs resolve the whole
    sub-ms..seconds range at constant relative error."""

    def test_bucket_edges_pinned(self):
        from shifu_tpu.serve.batcher import (
            BATCH_ROWS_BUCKETS,
            LATENCY_BUCKETS,
        )

        assert LATENCY_BUCKETS[0] == pytest.approx(1e-4)
        assert LATENCY_BUCKETS[-1] == float("inf")
        finite = LATENCY_BUCKETS[:-1]
        assert len(finite) == 16
        for lo, hi in zip(finite[:-1], finite[1:]):
            assert hi == pytest.approx(2 * lo)  # exponential, base 2
        # ms-scale latencies land in distinct buckets (the old default
        # linearish edges put 1ms and 4ms in the same first bucket)
        import bisect

        assert (bisect.bisect_left(finite, 0.001)
                != bisect.bisect_left(finite, 0.004))
        assert BATCH_ROWS_BUCKETS[0] == 1.0
        assert BATCH_ROWS_BUCKETS[-1] == float("inf")
        assert list(BATCH_ROWS_BUCKETS[:-1]) == [
            float(2 ** k) for k in range(14)]

    def test_batcher_observes_into_pinned_buckets(self, model_set):
        from shifu_tpu import obs
        from shifu_tpu.serve.batcher import LATENCY_BUCKETS, MicroBatcher
        from shifu_tpu.serve.queue import AdmissionQueue
        from shifu_tpu.serve.registry import ModelRegistry

        obs.reset()
        registry = ModelRegistry(os.path.join(model_set, "models"))
        admission = AdmissionQueue(16)
        batcher = MicroBatcher(registry.score_raw, admission,
                               max_batch_rows=8, max_wait_ms=1)
        rec = {c: "0.1" for c in registry.input_columns}
        from shifu_tpu.serve.registry import records_to_columnar

        req = batcher.submit(records_to_columnar([rec],
                                                 registry.input_columns))
        req.wait(30)
        admission.close()
        batcher.join(10)
        snap = obs.registry().snapshot()["histograms"]
        lat = snap['serve.latency_seconds{format="json"}']
        want = ["inf" if b == float("inf") else b for b in LATENCY_BUCKETS]
        assert lat["buckets"] == want
        assert lat["count"] == 1
        rows = snap["serve.batch.rows"]
        assert rows["buckets"][:3] == [1.0, 2.0, 4.0]
