"""`shifu train` for WDL — dense numerics from NormalizedData, categorical
codes from CleanedData (parity: prepareWDLParams TrainModelProcessor.java:1474,
wdl/WDLWorker input wiring: numeric z-score + categorical sparse index).

WDL is a FIRST-CLASS trainer: vmapped bagging, grid search, k-fold,
continuous training and checkpoints — identical treatment to NN
(TrainModelProcessor.java:768-945 fans WDL out exactly like NN jobs)."""

from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from shifu_tpu.norm.dataset import load_codes, load_normalized
from shifu_tpu.utils.errors import ErrorCode, ShifuError
from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)


def _wdl_signature(cfg) -> tuple:
    """Static program signature: trials sharing it differ only in traced
    operands (LearningRate, seed) and batch on the member axis."""
    return (
        tuple(cfg.hidden), tuple(cfg.activations), cfg.embed_dim,
        cfg.optimizer, cfg.l2_reg, cfg.num_epochs, cfg.valid_set_rate,
        cfg.bagging_sample_rate, cfg.bagging_with_replacement,
        cfg.early_stop_window,
    )


def _wdl_column_mapping(proc, nmeta, cmeta):
    """(num_idx, num_names, cat_idx, cat_names, vocab_sizes, categories):
    numeric feature columns come from the normalized matrix; categorical
    ones from the code matrix (embedding + wide indices)."""
    from shifu_tpu.norm.normalizer import norm_columns

    cols = norm_columns(proc.column_configs)
    by_name = {c.column_name: c for c in cols}
    num_idx, num_names = [], []
    for j, name in enumerate(nmeta.columns):
        cc = by_name.get(name)
        if cc is not None and not cc.is_categorical():
            num_idx.append(j)
            num_names.append(name)
    cat_idx, cat_names, vocab_sizes, categories = [], [], [], []
    for j, name in enumerate(cmeta.columns):
        cc = by_name.get(name)
        if cc is not None and cc.is_categorical():
            cat_idx.append(j)
            cat_names.append(name)
            vocab_sizes.append(int(cmeta.extra["slots"][j]))
            categories.append(list(cc.column_binning.bin_category or []))
    return num_idx, num_names, cat_idx, cat_names, vocab_sizes, categories


def train_wdl_models(proc) -> None:
    from shifu_tpu.models.wdl import WDLModelSpec, flatten_wdl
    from shifu_tpu.norm.normalizer import (
        build_norm_plan,
        spec_to_json,
    )
    from shifu_tpu.train.grid_search import flatten_params
    from shifu_tpu.train.wdl_trainer import (
        WDLTrainConfig,
        train_wdl,
        train_wdl_bagged,
    )

    mc = proc.model_config
    norm_dir = proc.paths.normalized_data_dir()
    codes_dir = proc.paths.cleaned_data_dir()
    if not (os.path.isdir(norm_dir) and os.path.isdir(codes_dir)):
        raise ShifuError(ErrorCode.DATA_NOT_FOUND,
                         "run `shifu norm` before WDL training")

    from shifu_tpu.train.streaming import should_stream_training

    # co-resident runs always stream: the stage pipeline feeds from the
    # paired (norm, codes) shard feed whatever the matrix size
    if (getattr(proc, "coresident_cfg", None) is not None
            or should_stream_training(
                norm_dir, force_attr=bool(mc.train.train_on_disk))
            or should_stream_training(codes_dir)):
        _train_wdl_streamed(proc)
        return

    nmeta, feats, tags, weights = load_normalized(norm_dir)
    cmeta, codes, _, _ = load_codes(codes_dir)
    (num_idx, num_names, cat_idx, cat_names, vocab_sizes,
     categories) = _wdl_column_mapping(proc, nmeta, cmeta)

    dense = np.asarray(feats, np.float32)[:, num_idx]
    cat_codes = np.asarray(codes, np.int32)[:, cat_idx]
    tags = np.asarray(tags, np.float32)
    weights = np.asarray(weights, np.float32)
    log.info("WDL inputs: %d dense cols, %d embed fields (vocab %s)",
             len(num_names), len(cat_names), vocab_sizes)

    plan = build_norm_plan(mc, proc.column_configs)
    dense_specs = [
        spec_to_json(s) for s in plan.specs if s.cc.column_name in set(num_names)
    ]

    proc.paths.ensure(proc.paths.models_dir())
    proc.paths.ensure(proc.paths.train_dir())

    def save_member(i, cfg, res):
        _save_wdl_member(proc, i, cfg, res, num_names, cat_names,
                         vocab_sizes, dense_specs, plan.cutoff, categories)

    def continuous_init(i) -> Optional[np.ndarray]:
        """Resume from the existing model's weights when isContinuous
        (checkContinuousTraining:1149 parity; shape mismatch = scratch)."""
        if not mc.train.is_continuous:
            return None
        path = proc.paths.model_path(i, "wdl")
        if not os.path.isfile(path):
            return None
        try:
            spec = WDLModelSpec.load(path)
            flat = flatten_wdl(spec.params)
            log.info("continuous training: resuming WDL model %d from %s",
                     i, path)
            return flat
        except Exception as e:  # corrupt/mismatched spec: fresh start, logged
            log.warning("cannot resume from %s (%s); fresh start", path, e)
            return None


    mesh = proc._mesh()
    composites = flatten_params(
        mc.train.params or {},
        proc.resolve(mc.train.grid_config_file)
        if mc.train.grid_config_file else None,
    )
    num_kfold = mc.train.num_k_fold or -1
    bagging = max(1, int(mc.train.bagging_num or 1))
    ck_every = proc._checkpoint_every()

    # ---- grid search: trials batched on the member axis per signature ----
    if len(composites) > 1:
        orig = mc.train.params
        cfgs = []
        for gi, params in enumerate(composites):
            mc.train.params = params
            try:
                cfgs.append(WDLTrainConfig.from_model_config(mc, trainer_id=gi))
            finally:
                mc.train.params = orig
        groups: dict = {}
        for gi, cfg in enumerate(cfgs):
            groups.setdefault(_wdl_signature(cfg), []).append(gi)
        scored = []
        for idxs in groups.values():
            trial_results = train_wdl_bagged(
                dense, cat_codes, tags, weights, vocab_sizes, cfgs[idxs[0]],
                len(idxs), mesh=mesh,
                member_lrs=[cfgs[i].learning_rate for i in idxs],
            )
            for gi, res in zip(idxs, trial_results):
                scored.append((res.valid_error, gi, composites[gi]))
                log.info("wdl grid trial %d/%d valid err %.6f params=%s",
                         gi + 1, len(composites), res.valid_error,
                         composites[gi])
        scored.sort(key=lambda r: r[0])
        best = scored[0][2]
        log.info("wdl grid search best params: %s", best)
        mc.train.params = best
        composites = [best]

    # ---- k-fold: folds on the member axis, unbiased holdout ----
    if num_kfold > 0:
        n = dense.shape[0]
        fold = np.arange(n) % num_kfold
        base = WDLTrainConfig.from_model_config(mc, trainer_id=0)
        base.valid_set_rate = 0.0
        base.early_stop_window = 0
        sig_t = np.stack([
            np.where(fold == i, 0.0, weights) for i in range(num_kfold)
        ]).astype(np.float32)
        sig_v = np.stack([
            np.where(fold == i, weights, 0.0) for i in range(num_kfold)
        ]).astype(np.float32)
        results = train_wdl_bagged(
            dense, cat_codes, tags, weights, vocab_sizes, base, num_kfold,
            mesh=mesh, member_sigs=(sig_t, sig_v),
        )
        errors = []
        for i, res in enumerate(results):
            cfg_i = WDLTrainConfig.from_model_config(mc, trainer_id=i)
            save_member(i, cfg_i, res)
            errors.append(res.valid_error)
            log.info("wdl fold %d/%d holdout err %.6f", i + 1, num_kfold,
                     res.valid_error)
        log.info("wdl k-fold avg validation error: %.6f",
                 float(np.mean(errors)))
        return

    # ---- bagging (vmapped) / single model ----
    base_cfg = WDLTrainConfig.from_model_config(mc, trainer_id=0)
    base_cfg.checkpoint_every = ck_every
    if bagging > 1:
        init_flats = [continuous_init(i) for i in range(bagging)]
        checkpoint_paths = [
            os.path.join(proc.paths.ensure(proc.paths.checkpoint_dir(i)),
                         "weights.npy")
            for i in range(bagging)
        ]
        from shifu_tpu.processor.train_common import member_progress_writer

        base_cfg.progress_cb = member_progress_writer(
            [proc.paths.progress_path(i) for i in range(bagging)]
        )
        results = train_wdl_bagged(
            dense, cat_codes, tags, weights, vocab_sizes, base_cfg, bagging,
            mesh=mesh, init_flats=init_flats,
            checkpoint_paths=checkpoint_paths,
        )
        for i, res in enumerate(results):
            cfg_i = WDLTrainConfig.from_model_config(mc, trainer_id=i)
            save_member(i, cfg_i, res)
        return

    cfg = base_cfg
    cfg.checkpoint_path = os.path.join(
        proc.paths.ensure(proc.paths.checkpoint_dir(0)), "weights.npy"
    )
    from shifu_tpu.processor.train_common import progress_writer

    cfg.progress_cb = progress_writer(proc.paths.progress_path(0))
    res = train_wdl(dense, cat_codes, tags, weights, vocab_sizes, cfg,
                    mesh=mesh, init_flat=continuous_init(0))
    save_member(0, cfg, res)


def _save_wdl_member(proc, i, cfg, res, num_names, cat_names, vocab_sizes,
                     dense_specs, cutoff, categories) -> None:
    """ONE spec construction + artifact write for both the in-memory and
    streamed WDL paths — the schema must never diverge between them."""
    from shifu_tpu.models.wdl import WDLModelSpec

    mc = proc.model_config
    spec = WDLModelSpec(
        hidden=list(cfg.hidden),
        activations=list(cfg.activations),
        embed_dim=cfg.embed_dim,
        dense_columns=num_names,
        cat_columns=cat_names,
        vocab_sizes=vocab_sizes,
        norm_specs=dense_specs,
        norm_cutoff=cutoff,
        categories=categories,
        norm_type=mc.normalize.norm_type.value,
        params=res.params,
        train_error=res.train_error,
        valid_error=res.valid_error,
    )
    path = proc.paths.model_path(i, "wdl")
    spec.save(path)
    with open(proc.paths.val_error_path(i), "w") as fh:
        fh.write(f"{res.valid_error}\n")
    log.info("model %d (WDL) -> %s (valid err %.6f)", i, path,
             res.valid_error)


def _train_wdl_streamed(proc) -> None:
    """Larger-than-memory WDL: per-shard gradient accumulation over the
    row-aligned (NormalizedData, CleanedData) shard pairs
    (train/streaming_wdl.py). Members run serially; grid/k-fold need the
    in-memory trainer."""
    from shifu_tpu.models.wdl import WDLModelSpec, flatten_wdl
    from shifu_tpu.norm.dataset import read_meta
    from shifu_tpu.norm.normalizer import build_norm_plan, spec_to_json
    from shifu_tpu.train.grid_search import flatten_params
    from shifu_tpu.train.streaming_wdl import train_wdl_streamed
    from shifu_tpu.train.wdl_trainer import WDLTrainConfig

    mc = proc.model_config
    norm_dir = proc.paths.normalized_data_dir()
    codes_dir = proc.paths.cleaned_data_dir()
    composites = flatten_params(
        mc.train.params or {},
        proc.resolve(mc.train.grid_config_file)
        if mc.train.grid_config_file else None,
    )
    if len(composites) > 1 or (mc.train.num_k_fold or -1) > 0:
        raise ShifuError(
            ErrorCode.INVALID_MODEL_CONFIG,
            "WDL grid search / k-fold need the in-memory trainer; raise "
            "-Dshifu.train.memoryBudgetMB or disable train.trainOnDisk",
        )
    nmeta = read_meta(norm_dir)
    cmeta = read_meta(codes_dir)
    (num_idx, num_names, cat_idx, cat_names, vocab_sizes,
     categories) = _wdl_column_mapping(proc, nmeta, cmeta)
    plan = build_norm_plan(mc, proc.column_configs)
    dense_specs = [
        spec_to_json(s) for s in plan.specs
        if s.cc.column_name in set(num_names)
    ]
    proc.paths.ensure(proc.paths.models_dir())
    proc.paths.ensure(proc.paths.train_dir())
    bagging = max(1, int(mc.train.bagging_num or 1))
    import jax

    from shifu_tpu.parallel.mesh import data_mesh

    mesh = data_mesh() if len(jax.devices()) > 1 else None
    log.info("WDL training STREAMED from %s + %s (%d member(s)); shards "
             "stream row-sharded over the data mesh (tensor-parallel "
             "embedding sharding needs the in-memory trainer)",
             norm_dir, codes_dir, bagging)

    for i in range(bagging):
        cfg = WDLTrainConfig.from_model_config(mc, trainer_id=i)
        cfg.checkpoint_every = proc._checkpoint_every()
        cfg.checkpoint_path = os.path.join(
            proc.paths.ensure(proc.paths.checkpoint_dir(i)), "weights.npy"
        )
        from shifu_tpu.processor.train_common import progress_writer

        cfg.progress_cb = progress_writer(proc.paths.progress_path(i), i)
        init_flat = None
        if mc.train.is_continuous:
            path = proc.paths.model_path(i, "wdl")
            if os.path.isfile(path):
                try:
                    init_flat = flatten_wdl(WDLModelSpec.load(path).params)
                    log.info("continuous: resuming WDL model %d", i)
                except Exception as e:  # corrupt model: fresh start, logged
                    log.warning("cannot resume from %s (%s)", path, e)
        from shifu_tpu.resilience.checkpoint import resume_requested

        cc_base = getattr(proc, "coresident_cfg", None)
        if cc_base is not None:
            from dataclasses import replace as dc_replace

            from shifu_tpu.coresident import train_wdl_coresident

            ccfg_i = dc_replace(
                cc_base, tenant=(cc_base.tenant if i == 0
                                 else f"{cc_base.tenant}-m{i}"))
            res = train_wdl_coresident(
                norm_dir, codes_dir, num_idx, cat_idx, vocab_sizes, cfg,
                ccfg=ccfg_i, init_flat=init_flat,
                resume=resume_requested())
        else:
            res = train_wdl_streamed(norm_dir, codes_dir, num_idx,
                                     cat_idx, vocab_sizes, cfg,
                                     init_flat=init_flat, mesh=mesh,
                                     resume=resume_requested())
        _save_wdl_member(proc, i, cfg, res, num_names, cat_names,
                         vocab_sizes, dense_specs, plan.cutoff, categories)
