"""Resilience layer (shifu_tpu/resilience/): fault-spec grammar, seeded
determinism, bounded retry with backoff+jitter, atomic writes, stream
checkpoints, the SH104 hygiene rule, and the self-healing serve worker
(supervised restart with zero lost-but-unanswered requests)."""

import os
import threading
import time

import numpy as np
import pytest

from shifu_tpu.resilience import checkpoint as ckpt_mod
from shifu_tpu.resilience import faults, retry
from shifu_tpu.resilience.faults import (
    FaultPlan,
    FaultSpecError,
    InjectedFaultError,
    PreemptionError,
)


class TestFaultSpec:
    def test_grammar_round_trip(self):
        plan = FaultPlan.parse(
            "io:p=0.01:seed=7,device,preempt@chunk=40,slow:ms=250")
        seams = [c.seam for c in plan.clauses]
        assert seams == ["io", "device", "preempt", "slow"]
        io = plan.clauses[0]
        assert io.p == 0.01 and io.seed == 7 and io.counter == "io"
        pre = plan.clauses[2]
        assert pre.at == 40 and pre.counter == "chunk" and pre.max == 1
        slow = plan.clauses[3]
        assert slow.ms == 250 and slow.counter == "io" and slow.p == 1.0

    @pytest.mark.parametrize("bad", [
        "bogus", "io:p=2", "preempt@chunk", "io:frobnicate=1",
        "io:p=abc", "preempt@chunk=x",
    ])
    def test_bad_specs_raise_at_parse(self, bad):
        with pytest.raises(FaultSpecError):
            FaultPlan.parse(bad)

    def test_scheduled_preempt_fires_at_exact_ordinal(self):
        plan = FaultPlan.parse("preempt@chunk=3")
        plan.fire("chunk")
        plan.fire("chunk")
        with pytest.raises(PreemptionError):
            plan.fire("chunk")
        plan.fire("chunk")  # max=1: fired once, never again

    def test_probabilistic_is_seed_deterministic(self):
        def fired_at(seed):
            plan = FaultPlan.parse(f"io:p=0.3:seed={seed}")
            hits = []
            for k in range(50):
                try:
                    plan.fire("io")
                except InjectedFaultError:
                    hits.append(k)
            return hits

        assert fired_at(7) == fired_at(7)  # same seed, same schedule
        assert fired_at(7) != fired_at(8)
        assert fired_at(7)  # p=0.3 over 50 events: some fire

    def test_preempt_not_consumed_by_transient_on_shared_counter(self):
        # a transient clause due on the same event must not burn the
        # preempt clause's budget: preemption outranks and fires
        plan = FaultPlan.parse("io:p=1:max=0,preempt@io=3")
        for _ in range(2):
            with pytest.raises(InjectedFaultError):
                plan.fire("io")
        with pytest.raises(PreemptionError):
            plan.fire("io")

    def test_absolute_index_pins_the_event(self):
        plan = FaultPlan.parse("preempt@chunk=5")
        plan.fire("chunk", index=10)  # ordinal 11 != 5
        with pytest.raises(PreemptionError):
            plan.fire("chunk", index=4)  # ordinal 5

    def test_fault_point_noop_without_plan(self):
        faults.fault_point("io")  # no plan armed: must not raise

    def test_injected_faults_counted(self):
        from shifu_tpu.obs import registry

        before = registry().counter("fault.injected", seam="io").value
        with faults.activate(FaultPlan.parse("io:p=1.0")):
            with pytest.raises(InjectedFaultError):
                faults.fault_point("io")
        after = registry().counter("fault.injected", seam="io").value
        assert after == before + 1


class TestRetry:
    def test_recovers_and_counts(self):
        from shifu_tpu.obs import registry

        calls = []
        sleeps = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise InjectedFaultError("io", len(calls))
            return "ok"

        before = registry().counter("retry.recovered", seam="io").value
        surv = registry().counter("fault.survived", seam="io").value
        out = retry.retry_call(flaky, seam="io", sleeper=sleeps.append)
        assert out == "ok" and len(calls) == 3
        assert len(sleeps) == 2
        assert registry().counter(
            "retry.recovered", seam="io").value == before + 1
        # both injected failures were survived — the proof pair
        assert registry().counter(
            "fault.survived", seam="io").value == surv + 2

    def test_budget_exhaustion_reraises_original(self):
        def always():
            raise OSError("flaky disk")

        with pytest.raises(OSError, match="flaky disk"):
            retry.retry_call(always, seam="io", sleeper=lambda s: None)

    def test_preemption_never_retried(self):
        calls = []

        def pre():
            calls.append(1)
            raise PreemptionError("now")

        with pytest.raises(PreemptionError):
            retry.retry_call(pre, seam="io", sleeper=lambda s: None)
        assert len(calls) == 1

    def test_backoff_windows_grow_and_jitter(self):
        import random

        rng = random.Random(3)
        d1 = [retry.backoff_delay("io", 1, rng=rng) for _ in range(50)]
        d2 = [retry.backoff_delay("io", 2, rng=rng) for _ in range(50)]
        base, cap = retry.backoff_ms("io")
        assert all(0 <= d <= base / 1000.0 for d in d1)
        assert all(0 <= d <= 2 * base / 1000.0 for d in d2)
        assert max(d2) > max(d1)  # window doubles
        assert len({round(d, 9) for d in d1}) > 10  # full jitter, not fixed

    def test_per_seam_budget_override(self):
        from shifu_tpu.utils import environment

        environment.set_property("shifu.retry.io.max", "5")
        try:
            assert retry.max_attempts("io") == 5
            assert retry.max_attempts("device") == 3
        finally:
            environment.set_property("shifu.retry.io.max", "")


class TestAtomicWrite:
    def test_kill_during_write_preserves_previous(self, tmp_path):
        path = str(tmp_path / "weights.npy")
        ckpt_mod.atomic_save_npy(path, np.arange(4.0))
        # injected ckpt fault fires after the temp bytes land but before
        # the rename — the failure window a direct np.save loses to
        with faults.activate(FaultPlan.parse("ckpt@ckpt=1")):
            with pytest.raises(InjectedFaultError):
                ckpt_mod.atomic_write(path, b"torn")
        np.testing.assert_array_equal(np.load(path), np.arange(4.0))
        # no temp debris left behind
        assert os.listdir(str(tmp_path)) == ["weights.npy"]

    def test_stream_checkpoint_save_retries_injected_ckpt_fault(
            self, tmp_path):
        ck = ckpt_mod.StreamCheckpoint(str(tmp_path / "s.ckpt.npz"), "sha")
        with faults.activate(FaultPlan.parse("ckpt@ckpt=1")):
            ck.save(3, arrays={"a": np.ones(2)}, meta={"k": 1})
        ci, arrays, meta, blob = ck.load()
        assert ci == 3 and meta == {"k": 1} and blob is None
        np.testing.assert_array_equal(arrays["a"], np.ones(2))

    def test_atomic_write_json_and_replace(self, tmp_path):
        path = str(tmp_path / "state.json")
        ckpt_mod.atomic_write_json(path, {"a": 1})
        ckpt_mod.atomic_write_json(path, {"a": 2})
        import json

        assert json.load(open(path)) == {"a": 2}


class TestFsListing:
    """The shared SH301 helpers every artifact-reading glob now routes
    through (shifu_tpu/fs/listing.py): listings must come back in one
    deterministic order on every host, no matter what readdir says."""

    def test_sorted_glob_is_sorted(self, tmp_path):
        from shifu_tpu.fs.listing import sorted_glob

        for name in ("part-h002.npz", "part-h000.npz", "part-h001.npz"):
            (tmp_path / name).write_bytes(b"x")
        hits = sorted_glob(str(tmp_path / "part-*.npz"))
        assert [os.path.basename(h) for h in hits] == [
            "part-h000.npz", "part-h001.npz", "part-h002.npz"]
        assert hits == sorted(hits)

    def test_sorted_glob_recursive(self, tmp_path):
        from shifu_tpu.fs.listing import sorted_glob

        (tmp_path / "b" / "deep").mkdir(parents=True)
        (tmp_path / "a").mkdir()
        (tmp_path / "b" / "deep" / "z.ckpt").write_bytes(b"x")
        (tmp_path / "a" / "a.ckpt").write_bytes(b"x")
        hits = sorted_glob(str(tmp_path / "**" / "*.ckpt"),
                           recursive=True)
        assert [os.path.basename(h) for h in hits] == ["a.ckpt", "z.ckpt"]

    def test_sorted_listdir(self, tmp_path):
        from shifu_tpu.fs.listing import sorted_listdir

        for name in ("c", "a", "b"):
            (tmp_path / name).write_bytes(b"x")
        assert sorted_listdir(str(tmp_path)) == ["a", "b", "c"]

    def test_clear_and_list_resumable_ride_the_helper(self, tmp_path):
        """Regression for the ShardedStreamCheckpoint.clear()/
        list_resumable raw-glob sites: both must enumerate the family
        deterministically (and clear must still remove every file)."""
        root = str(tmp_path)
        ck = ckpt_mod.ShardedStreamCheckpoint(
            ckpt_mod.ckpt_path(root, "stats", "stream"), "sha", 2, every=1)
        ck.save([(0, None, {"ci": 0}, None), (1, None, {"ci": 1}, None)],
                (None, {"phase": "p"}, None))
        names = [e["name"] for e in ckpt_mod.list_resumable(root)]
        assert names == sorted(names) and names
        ck.clear()
        assert ckpt_mod.list_resumable(root) == []
        leftovers = [p for p in os.listdir(
            os.path.dirname(ckpt_mod.ckpt_path(root, "stats", "stream")))
            if p.endswith(ckpt_mod.CKPT_SUFFIX)]
        assert leftovers == []


class TestStreamCheckpoint:
    def test_config_sha_mismatch_rejects(self, tmp_path):
        path = str(tmp_path / "s.ckpt.npz")
        ckpt_mod.StreamCheckpoint(path, "sha-A").save(7, meta={"x": 1})
        assert ckpt_mod.StreamCheckpoint(path, "sha-B").load() is None
        assert ckpt_mod.StreamCheckpoint(path, "sha-A").load() is not None

    def test_corrupt_file_rejected_not_crashed(self, tmp_path):
        path = str(tmp_path / "s.ckpt.npz")
        with open(path, "wb") as fh:
            fh.write(b"not an npz")
        assert ckpt_mod.StreamCheckpoint(path, "sha").load() is None

    def test_cadence_and_clear(self, tmp_path):
        path = str(tmp_path / "s.ckpt.npz")
        ck = ckpt_mod.StreamCheckpoint(path, "sha", every=3)
        writes = []
        for ci in range(7):
            wrote = ck.maybe_save(ci, lambda: (None, {"ci": ci}, None))
            if wrote:
                writes.append(ci)
        assert writes == [2, 5]  # every 3rd folded chunk
        assert ck.load()[0] == 5
        ck.clear()
        assert ck.load() is None
        ck.clear()  # idempotent

    def test_blob_round_trip(self, tmp_path):
        import pickle

        ck = ckpt_mod.StreamCheckpoint(str(tmp_path / "b.ckpt.npz"), "s")
        ck.save(1, blob=pickle.dumps({"sk": [1, 2, 3]}))
        _ci, _arrays, _meta, blob = ck.load()
        assert pickle.loads(blob) == {"sk": [1, 2, 3]}

    def test_list_resumable(self, tmp_path):
        root = str(tmp_path)
        ck = ckpt_mod.StreamCheckpoint(
            ckpt_mod.ckpt_path(root, "stats", "stream"), "sha")
        ck.save(12, meta={"phase": "pass2"})
        entries = ckpt_mod.list_resumable(root)
        assert len(entries) == 1
        assert entries[0]["name"] == "stats-stream"
        assert entries[0]["chunkIndex"] == 12
        assert entries[0]["configSha"] == "sha"


class TestDeviceAccumulatorSnapshot:
    def test_snapshot_restore_bit_identical(self):
        import jax.numpy as jnp

        from shifu_tpu.data.pipeline import DeviceAccumulator
        from shifu_tpu.ops.binagg import BinAggregates

        def agg(seed):
            rng = np.random.default_rng(seed)
            return BinAggregates(*[
                jnp.asarray(rng.normal(size=5).astype(np.float32))
                for _ in range(10)])

        a = DeviceAccumulator(flush_rows=100)
        b = DeviceAccumulator(flush_rows=100)
        for s in range(4):
            a.add(agg(s), rows=30)  # forces one mid-stream window flush
            b.add(agg(s), rows=30)
        # snapshot b mid-fold, restore into a FRESH accumulator
        c = DeviceAccumulator(flush_rows=100)
        c.restore(b.snapshot())
        for s in range(4, 7):
            a.add(agg(s), rows=30)
            c.add(agg(s), rows=30)
        fa, fc = a.fetch(), c.fetch()
        for xa, xc in zip(fa, fc):
            np.testing.assert_array_equal(xa, xc)


class TestSH104:
    def _findings(self, src):
        from shifu_tpu.analysis.engine import Module, PackageContext
        from shifu_tpu.analysis.rules.hygiene import NonAtomicCheckpointWrite

        m = Module("x.py", src)
        ctx = PackageContext([m])
        return list(NonAtomicCheckpointWrite().check(m, ctx))

    def test_flags_np_save_to_checkpoint_path(self):
        src = ("import numpy as np\n"
               "def f(cfg, w):\n"
               "    np.save(cfg.checkpoint_path, w)\n")
        found = self._findings(src)
        assert len(found) == 1 and found[0].severity == "error"
        assert "atomic_save_npy" in found[0].message

    def test_flags_open_w_to_manifest_path(self):
        src = ("def f(manifest_path, doc):\n"
               "    with open(manifest_path, 'w') as fh:\n"
               "        fh.write(doc)\n")
        assert len(self._findings(src)) == 1

    def test_clean_for_atomic_helper_and_plain_paths(self):
        src = ("import numpy as np\n"
               "from shifu_tpu.resilience.checkpoint import atomic_save_npy\n"
               "def f(cfg, w, out):\n"
               "    atomic_save_npy(cfg.checkpoint_path, w)\n"
               "    np.save(out, w)\n"
               "    open(out, 'w').close()\n")
        assert self._findings(src) == []

    def test_flags_constant_sleep_retry_loop(self):
        src = ("import time\n"
               "def f(fetch):\n"
               "    while True:\n"
               "        try:\n"
               "            return fetch()\n"
               "        except OSError:\n"
               "            time.sleep(1.0)\n")
        found = self._findings(src)
        assert len(found) == 1 and found[0].severity == "warning"

    def test_computed_backoff_sleep_is_clean(self):
        src = ("import time\n"
               "def f(fetch, delay):\n"
               "    while True:\n"
               "        try:\n"
               "            return fetch()\n"
               "        except OSError:\n"
               "            time.sleep(delay * 2)\n")
        assert self._findings(src) == []

    def test_repo_sweep_clean(self):
        from shifu_tpu.analysis.engine import analyze

        pkg = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "shifu_tpu")
        findings = [f for f in analyze([pkg], ["SH104"])
                    if not f.suppressed]
        assert findings == []


# ---------------------------------------------------------------------------
# self-healing serve
# ---------------------------------------------------------------------------


def _fake_result(values):
    from shifu_tpu.eval.scorer import ScoreResult

    m = np.asarray(values, np.float64)[:, None]
    return ScoreResult(model_scores=m, mean=m[:, 0], max=m[:, 0],
                       min=m[:, 0], median=m[:, 0],
                       model_names=["fake"], model_widths=[1])


def _one_row(v):
    from shifu_tpu.data.reader import ColumnarData

    return ColumnarData(names=["v"],
                        raw={"v": np.asarray([str(v)], object)}, n_rows=1)


class TestServeSelfHealing:
    def test_worker_crash_survived_zero_unanswered(self):
        """Acceptance: a serve worker crash is survived — the in-flight
        batch fails request-by-request, the queue is preserved, the
        restarted worker answers everything else, and health walks
        degraded -> ok."""
        from shifu_tpu.serve.batcher import MicroBatcher
        from shifu_tpu.serve.health import DEGRADED, OK, HealthMonitor
        from shifu_tpu.serve.queue import AdmissionQueue

        health = HealthMonitor(ok_after=1)
        batcher = MicroBatcher(
            lambda data: _fake_result(
                [float(x) for x in data.column("v")]),
            AdmissionQueue(64), max_batch_rows=1, max_wait_ms=1,
            health=health, max_restarts=3)
        # one injected `serve` fault: kills the worker WITH a gathered
        # batch in flight
        with faults.activate(FaultPlan.parse("serve@serve=1")):
            reqs = [batcher.submit(_one_row(i)) for i in range(12)]
            outcomes = []
            for r in reqs:
                try:
                    outcomes.append(("ok", r.wait(10).mean[0]))
                except RuntimeError as e:
                    outcomes.append(("err", str(e)))
        # EVERY admitted request got a response or an explicit error
        assert len(outcomes) == 12
        crashed = [o for o in outcomes if o[0] == "err"]
        served = [o for o in outcomes if o[0] == "ok"]
        assert len(crashed) >= 1  # the in-flight batch failed explicitly
        assert "crashed" in crashed[0][1]
        assert len(served) == 12 - len(crashed)  # queue preserved
        assert batcher.restarts == 1
        assert health.state in (OK, DEGRADED)
        # clean batches after the crash walked health back to ok
        batcher.submit(_one_row(99)).wait(10)
        assert health.state == OK
        batcher.admission.close()
        batcher.join(5)

    def test_restart_budget_exhaustion_drains_with_answers(self):
        from shifu_tpu.serve.batcher import MicroBatcher
        from shifu_tpu.serve.health import DRAINING, HealthMonitor
        from shifu_tpu.serve.queue import AdmissionQueue, RejectedError

        health = HealthMonitor()
        # every batch crashes the worker; budget of 1 restart
        with faults.activate(FaultPlan.parse("serve:p=1:max=0")):
            batcher = MicroBatcher(
                lambda data: _fake_result([0.0] * data.n_rows),
                AdmissionQueue(64), max_batch_rows=1, max_wait_ms=1,
                health=health, max_restarts=1)
            reqs = []
            errors = 0
            for i in range(6):
                try:
                    reqs.append(batcher.submit(_one_row(i)))
                except RejectedError:
                    errors += 1  # queue already closed by the give-up path
            for r in reqs:
                with pytest.raises(RuntimeError):
                    r.wait(10)
            batcher.join(5)
        assert health.state == DRAINING
        assert "exhausted" in health.reason
        assert batcher.restarts == 1

    def test_deadline_sheds_instead_of_hanging(self):
        from shifu_tpu.serve.batcher import (
            DeadlineExceededError,
            MicroBatcher,
        )
        from shifu_tpu.serve.queue import AdmissionQueue

        gate = threading.Event()

        def slow_score(data):
            gate.wait(10)
            return _fake_result([float(x) for x in data.column("v")])

        batcher = MicroBatcher(slow_score, AdmissionQueue(8),
                               max_batch_rows=1, max_wait_ms=1,
                               deadline_ms=50.0)
        first = batcher.submit(_one_row(1))   # occupies the worker
        stale = batcher.submit(_one_row(2))   # will outlive its deadline
        time.sleep(0.2)
        gate.set()
        assert first.wait(10).mean[0] == pytest.approx(1.0)
        with pytest.raises(DeadlineExceededError):
            stale.wait(10)
        batcher.admission.close()
        batcher.join(5)

    def test_retry_after_tracks_drain_rate(self):
        from shifu_tpu.obs import registry
        from shifu_tpu.serve.batcher import (
            RETRY_AFTER_MAX_S,
            RETRY_AFTER_MIN_S,
            MicroBatcher,
        )
        from shifu_tpu.serve.queue import AdmissionQueue

        batcher = MicroBatcher(
            lambda data: _fake_result(
                [float(x) for x in data.column("v")]),
            AdmissionQueue(256), max_batch_rows=4, max_wait_ms=1)
        for i in range(32):
            batcher.submit(_one_row(i)).wait(10)
        hint = batcher.retry_after_seconds()
        assert RETRY_AFTER_MIN_S <= hint <= RETRY_AFTER_MAX_S
        # empty queue + healthy drain history -> the floor
        assert hint == pytest.approx(RETRY_AFTER_MIN_S)
        assert registry().gauge(
            "serve.retry_after_seconds").value == pytest.approx(hint)
        batcher.admission.close()
        batcher.join(5)

    def test_health_monotone_draining(self):
        from shifu_tpu.serve.health import (
            DEGRADED,
            DRAINING,
            OK,
            HealthMonitor,
        )

        h = HealthMonitor(ok_after=2)
        assert h.state == OK
        h.note_crash("boom")
        assert h.state == DEGRADED and h.reason == "boom"
        h.note_ok()
        assert h.state == DEGRADED  # hysteresis: one ok is not enough
        h.note_ok()
        assert h.state == OK and h.reason == ""
        h.set_draining("shutdown")
        h.note_ok()
        h.note_crash("x")
        assert h.state == DRAINING  # monotone: drained stays drained
