"""Bin aggregation: the TPU-native replacement for the UpdateBinningInfo MR
job (core/binning/UpdateBinningInfoMapper.java:71 / Reducer.java:57).

One scatter-add over a flat (column, bin) index space produces every
per-column per-bin count in a single fused XLA program; the multi-chip path
wraps the same function in shard_map over the row axis and psums the
aggregates — the analog of the reference's mapper-side partial sums merged in
one reducer.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


class BinAggregates(NamedTuple):
    """Flat (column-offset + bin) histograms + per-numeric-column moments."""

    pos: jax.Array  # [total_slots] positive counts
    neg: jax.Array  # [total_slots] negative counts
    wpos: jax.Array  # [total_slots] weighted positive
    wneg: jax.Array  # [total_slots] weighted negative
    vsum: jax.Array  # [n_numeric] sum of non-missing values
    vsumsq: jax.Array  # [n_numeric] sum of squares
    vmin: jax.Array  # [n_numeric]
    vmax: jax.Array  # [n_numeric]
    vcount: jax.Array  # [n_numeric] non-missing count
    vmissing: jax.Array  # [n_numeric] missing count (valid-tag rows)


def bin_aggregate(
    codes: jax.Array,  # [n, C] int32, per-column bin index (missing = last slot)
    col_offsets: jax.Array,  # [C] int32 prefix offsets into the flat slot space
    total_slots: int,
    tags: jax.Array,  # [n] int32 {1 pos, 0 neg, -1 invalid}
    weights: jax.Array,  # [n] float32
    values: jax.Array,  # [n, Cn] float32 numeric matrix, NaN = missing
) -> BinAggregates:
    valid = tags >= 0
    posm = (tags == 1) & valid
    negm = (tags == 0) & valid

    flat = (codes + col_offsets[None, :]).reshape(-1)  # [n*C]
    n, c = codes.shape

    def scatter(row_mask, row_weight):
        contrib = jnp.where(row_mask, row_weight, 0.0).astype(jnp.float32)
        tiled = jnp.repeat(contrib, c)  # row value for every column slot
        return jnp.zeros(total_slots, jnp.float32).at[flat].add(tiled)

    ones = jnp.ones_like(weights)
    pos = scatter(posm, ones)
    neg = scatter(negm, ones)
    wpos = scatter(posm, weights)
    wneg = scatter(negm, weights)

    missing = jnp.isnan(values)
    vvalid = (~missing) & valid[:, None]
    v0 = jnp.where(vvalid, values, 0.0)
    vsum = v0.sum(axis=0)
    vsumsq = (v0 * v0).sum(axis=0)
    vmin = jnp.where(vvalid, values, jnp.inf).min(axis=0)
    vmax = jnp.where(vvalid, values, -jnp.inf).max(axis=0)
    vcount = vvalid.sum(axis=0).astype(jnp.float32)
    vmissing = (missing & valid[:, None]).sum(axis=0).astype(jnp.float32)
    return BinAggregates(pos, neg, wpos, wneg, vsum, vsumsq, vmin, vmax, vcount, vmissing)


bin_aggregate_jit = jax.jit(bin_aggregate, static_argnames=("total_slots",))

# profiled seam for the stats engine (in-RAM pass 2 + streamed chunks):
# same program, with per-dispatch FLOPs/bytes accounting in the obs scope.
# Async — streamed chunks fold into the DeviceAccumulator without a
# per-chunk wait. `bin_aggregate_jit` itself stays raw for direct/test use
# (tests probe its _cache_size underneath this wrapper).
from shifu_tpu.obs.profile import wrap as _profile_wrap  # noqa: E402

bin_aggregate_profiled = _profile_wrap(
    "stats.bin_aggregate", bin_aggregate_jit, sync=False,
    static_argnums=(2,), static_argnames=("total_slots",))


def bin_aggregate_sharded(
    mesh: Mesh,
    codes: jax.Array,
    col_offsets: jax.Array,
    total_slots: int,
    tags: jax.Array,
    weights: jax.Array,
    values: jax.Array,
    axis: str = "data",
) -> BinAggregates:
    """Row-sharded SPMD variant: each device aggregates its row shard, then a
    single psum merges — gradients-of-histograms over ICI instead of
    ZooKeeper-merged Bytables."""

    def local(codes, tags, weights, values):
        agg = bin_aggregate(codes, col_offsets, total_slots, tags, weights, values)
        psum = lambda x: jax.lax.psum(x, axis)  # noqa: E731
        return BinAggregates(
            pos=psum(agg.pos),
            neg=psum(agg.neg),
            wpos=psum(agg.wpos),
            wneg=psum(agg.wneg),
            vsum=psum(agg.vsum),
            vsumsq=psum(agg.vsumsq),
            vmin=jax.lax.pmin(agg.vmin, axis),
            vmax=jax.lax.pmax(agg.vmax, axis),
            vcount=psum(agg.vcount),
            vmissing=psum(agg.vmissing),
        )

    from shifu_tpu.parallel.mesh import shard_map_compat

    fn = shard_map_compat(
        local,
        mesh=mesh,
        in_specs=(P(axis, None), P(axis), P(axis), P(axis, None)),
        out_specs=BinAggregates(*([P()] * 10)),
        check=True,  # keep the replication check this call always had
    )
    return fn(codes, tags, weights, values)
