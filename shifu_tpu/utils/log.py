"""Logging setup shared by the CLI and library."""

from __future__ import annotations

import logging
import sys


def get_logger(name: str) -> logging.Logger:
    return logging.getLogger(name)


def configure(verbose: bool = False) -> None:
    level = logging.DEBUG if verbose else logging.INFO
    logging.basicConfig(
        level=level,
        stream=sys.stderr,
        format="%(asctime)s %(levelname)-5s %(name)s - %(message)s",
        datefmt="%Y-%m-%d %H:%M:%S",
    )
    # JAX compilation chatter stays at WARNING unless verbose.
    if not verbose:
        logging.getLogger("jax").setLevel(logging.WARNING)
