"""ModelConfig: the single user-facing pipeline configuration.

Wire-compatible with the reference's ModelConfig.json — six sections
(container/obj/ModelConfig.java:65-95): basic, dataSet, stats, varSelect,
normalize, train, plus a list of evals (container/obj/EvalConfig.java:41).
"""

from __future__ import annotations

import datetime
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from shifu_tpu.config.jsonbase import (
    JsonEnum,
    decode_dataclass,
    dump_json,
    encode_dataclass,
)


class RunMode(JsonEnum):
    """Execution mode. The reference has LOCAL/MAPRED/DIST
    (container/obj/ModelBasicConf.java:30); here MAPRED/DIST both mean "SPMD
    over the full device mesh" and LOCAL means single-device."""

    LOCAL = "LOCAL"
    MAPRED = "MAPRED"
    DIST = "DIST"
    TPU = "TPU"


class Algorithm(JsonEnum):
    """container/obj/ModelTrainConf.java:43-45."""

    NN = "NN"
    LR = "LR"
    SVM = "SVM"
    DT = "DT"
    RF = "RF"
    GBT = "GBT"
    TENSORFLOW = "TENSORFLOW"
    WDL = "WDL"


class BinningMethod(JsonEnum):
    """stats.binningMethod (container/obj/ModelStatsConf.java)."""

    EQUAL_POSITIVE = "EqualPositive"
    EQUAL_TOTAL = "EqualTotal"
    EQUAL_INTERVAL = "EqualInterval"
    EQUAL_NEGATIVE = "EqualNegative"
    WEIGHT_EQUAL_POSITIVE = "WeightEqualPositive"
    WEIGHT_EQUAL_NEGATIVE = "WeightEqualNegative"
    WEIGHT_EQUAL_TOTAL = "WeightEqualTotal"


class BinningAlgorithm(JsonEnum):
    """stats.binningAlgorithm — which engine builds numeric bins. All map to
    the same streaming-mergeable histogram here (SPDT-style)."""

    NATIVE = "Native"
    SPDT = "SPDT"
    SPDTI = "SPDTI"
    MUNRO_PAT = "MunroPat"
    MUNRO_PATI = "MunroPatI"
    DYNAMIC_BINNING = "DynamicBinning"


class NormType(JsonEnum):
    """normalize.normType (container/obj/ModelNormalizeConf.java:33-46)."""

    ZSCALE = "ZSCALE"
    ZSCORE = "ZSCORE"
    OLD_ZSCALE = "OLD_ZSCALE"
    OLD_ZSCORE = "OLD_ZSCORE"
    WOE = "WOE"
    WEIGHT_WOE = "WEIGHT_WOE"
    HYBRID = "HYBRID"
    WEIGHT_HYBRID = "WEIGHT_HYBRID"
    WOE_ZSCORE = "WOE_ZSCORE"
    WOE_ZSCALE = "WOE_ZSCALE"
    WEIGHT_WOE_ZSCORE = "WEIGHT_WOE_ZSCORE"
    WEIGHT_WOE_ZSCALE = "WEIGHT_WOE_ZSCALE"
    ONEHOT = "ONEHOT"
    ZSCALE_ONEHOT = "ZSCALE_ONEHOT"
    DISCRETE_ZSCORE = "DISCRETE_ZSCORE"
    DISCRETE_ZSCALE = "DISCRETE_ZSCALE"
    ASIS_WOE = "ASIS_WOE"
    ASIS_PR = "ASIS_PR"
    ZSCORE_INDEX = "ZSCORE_INDEX"
    ZSCALE_INDEX = "ZSCALE_INDEX"
    WOE_INDEX = "WOE_INDEX"
    WOE_ZSCALE_INDEX = "WOE_ZSCALE_INDEX"

    def is_woe(self) -> bool:
        return "WOE" in self.name and "ZS" not in self.name and "INDEX" not in self.name

    def is_weighted(self) -> bool:
        return self.name.startswith("WEIGHT_")


class MultipleClassification(JsonEnum):
    """train.multiClassifyMethod (container/obj/ModelTrainConf.java:54)."""

    NATIVE = "NATIVE"
    ONEVSALL = "ONEVSALL"
    ONEVSREST = "ONEVSREST"  # alias of ONEVSALL in the reference
    ONEVSONE = "ONEVSONE"  # not implemented upstream either


class MissingValueFillType(JsonEnum):
    MEAN = "MEAN"
    POSRATE = "POSRATE"
    ZERO = "ZERO"


DEFAULT_MISSING_VALUES = ["", "*", "#", "?", "null", "~"]


@dataclass
class CustomPathsMixin:
    pass


@dataclass
class ModelBasicConf:
    name: str = ""
    author: str = ""
    description: Optional[str] = None
    version: str = "0.1.0"
    run_mode: RunMode = RunMode.LOCAL
    post_train_on: bool = False
    custom_paths: Optional[Dict[str, str]] = field(default_factory=dict)


@dataclass
class RawSourceData:
    """dataSet section shared by the training set and each eval set
    (container/obj/RawSourceData.java:32)."""

    source: str = "LOCAL"
    data_path: str = ""
    data_delimiter: str = "|"
    header_path: Optional[str] = None
    header_delimiter: str = "|"
    filter_expressions: Optional[str] = ""
    weight_column_name: Optional[str] = ""


@dataclass
class ModelSourceDataConf(RawSourceData):
    target_column_name: str = ""
    pos_tags: List[str] = field(default_factory=list)
    neg_tags: List[str] = field(default_factory=list)
    missing_or_invalid_values: List[str] = field(
        default_factory=lambda: list(DEFAULT_MISSING_VALUES)
    )
    meta_column_name_file: Optional[str] = None
    categorical_column_name_file: Optional[str] = None
    autoType: bool = field(default=True, metadata={"json": "autoType"})
    auto_type_threshold: int = 10


@dataclass
class ModelStatsConf:
    max_num_bin: int = 10
    cate_max_num_bin: int = 0
    binning_method: BinningMethod = BinningMethod.EQUAL_POSITIVE
    sample_rate: float = 1.0
    sample_neg_only: bool = False
    binning_algorithm: BinningAlgorithm = BinningAlgorithm.SPDTI
    psi_column_name: Optional[str] = ""


@dataclass
class ModelVarSelectConf:
    force_enable: bool = True
    force_select_column_name_file: Optional[str] = None
    force_remove_column_name_file: Optional[str] = None
    filter_enable: bool = True
    filter_num: int = 200
    filter_out_ratio: float = 0.05
    filter_by: str = "KS"  # KS | IV | MIX | PARETO | FI | SE | ST
    wrapper_enabled: bool = False
    wrapper_num: int = 50
    wrapper_ratio: float = 0.05
    wrapper_by: str = "S"
    missing_rate_threshold: float = 0.98
    correlation_threshold: float = 1.0
    min_iv_threshold: float = 0.0
    min_ks_threshold: float = 0.0
    filter_by_se: bool = field(default=True, metadata={"json": "filterBySE"})
    params: Optional[Dict[str, Any]] = None


@dataclass
class ModelNormalizeConf:
    std_dev_cut_off: float = 4.0
    sample_rate: float = 1.0
    sample_neg_only: bool = False
    norm_type: NormType = NormType.ZSCALE
    is_parquet: bool = False
    category_missing_norm_type: MissingValueFillType = MissingValueFillType.POSRATE


@dataclass
class ModelTrainConf:
    bagging_num: int = 1
    bagging_with_replacement: bool = False
    bagging_sample_rate: float = 1.0
    valid_set_rate: float = 0.2
    num_train_epochs: int = 100
    epochs_per_iteration: int = 1
    train_on_disk: bool = False
    fix_initial_input: bool = False
    is_continuous: bool = False
    is_cross_over: bool = False
    worker_thread_count: int = 4
    up_sample_weight: float = 1.0
    num_k_fold: int = -1
    convergence_threshold: float = 0.0
    convergence_judger: str = "error"
    algorithm: Algorithm = Algorithm.NN
    multi_classify_method: MultipleClassification = MultipleClassification.NATIVE
    # legacy configs carry an explicit boolean; honored alongside the enum
    legacy_one_vs_all: bool = field(
        default=False, metadata={"json": "isOneVsAll"}
    )
    params: Dict[str, Any] = field(default_factory=dict)
    grid_config_file: Optional[str] = None
    custom_paths: Optional[Dict[str, str]] = field(default_factory=dict)

    def is_one_vs_all(self) -> bool:
        """ModelTrainConf.isOneVsAll: ONEVSALL and ONEVSREST both mean
        per-class binary models (ModelTrainConf.java:54); a legacy
        "isOneVsAll": true JSON field is honored too."""
        return self.legacy_one_vs_all or self.multi_classify_method in (
            MultipleClassification.ONEVSALL,
            MultipleClassification.ONEVSREST,
        )

    def get_param(self, key: str, default: Any = None) -> Any:
        """Params map is case-sensitive in the reference, but user configs vary;
        fall back to case-insensitive lookup."""
        if self.params is None:
            return default
        if key in self.params:
            return self.params[key]
        low = key.lower()
        for k, v in self.params.items():
            if k.lower() == low:
                return v
        return default


@dataclass
class EvalConfig:
    name: str = ""
    data_set: RawSourceData = field(default_factory=RawSourceData)
    performance_bucket_num: int = 10
    performance_score_selector: str = "mean"
    score_meta_column_name_file: Optional[str] = ""
    match_column_name: Optional[str] = ""
    pos_tags: Optional[List[str]] = None
    neg_tags: Optional[List[str]] = None
    custom_paths: Optional[Dict[str, str]] = field(default_factory=dict)
    gbt_convert_to_prob: bool = field(default=True, metadata={"json": "gbtConvertToProb"})
    gbt_score_convert_strategy: str = field(
        default="OLD_SIGMOID", metadata={"json": "gbtScoreConvertStrategy"}
    )


@dataclass
class ModelConfig:
    basic: ModelBasicConf = field(default_factory=ModelBasicConf)
    data_set: ModelSourceDataConf = field(default_factory=ModelSourceDataConf)
    stats: ModelStatsConf = field(default_factory=ModelStatsConf)
    var_select: ModelVarSelectConf = field(default_factory=ModelVarSelectConf)
    normalize: ModelNormalizeConf = field(default_factory=ModelNormalizeConf)
    train: ModelTrainConf = field(default_factory=ModelTrainConf)
    evals: List[EvalConfig] = field(default_factory=list)

    # ---- accessors mirroring the reference convenience API ----
    @property
    def model_set_name(self) -> str:
        return self.basic.name

    @property
    def algorithm(self) -> Algorithm:
        return self.train.algorithm

    def is_regression(self) -> bool:
        """Binary model with both tag sets (reference ModelConfig.java:376-384
        calls binary-with-pos+neg "regression" — score is a continuous
        probability-like output)."""
        return bool(self.data_set.pos_tags) and bool(self.data_set.neg_tags)

    def is_classification(self) -> bool:
        """Multi-class: exactly one of posTags/negTags set (reference XOR
        semantics) — each tag is its own class."""
        return bool(self.data_set.pos_tags) != bool(self.data_set.neg_tags)

    def is_multi_classification(self) -> bool:
        return self.is_classification() and len(self.tags()) > 2

    def tags(self) -> List[str]:
        return list(self.data_set.pos_tags) + list(self.data_set.neg_tags)

    def get_eval(self, name: str) -> Optional[EvalConfig]:
        for e in self.evals:
            if e.name == name:
                return e
        return None

    def is_local_mode(self) -> bool:
        return self.basic.run_mode == RunMode.LOCAL

    # ---- IO ----
    @classmethod
    def load(cls, path: str) -> "ModelConfig":
        import json

        from shifu_tpu.utils.errors import ErrorCode, ShifuError

        with open(path) as fh:
            data = json.load(fh)
        try:
            return decode_dataclass(cls, data)
        except ValueError as e:
            raise ShifuError(ErrorCode.INVALID_MODEL_CONFIG, f"{path}: {e}")

    def save(self, path: str) -> None:
        dump_json(self, path)

    def to_json(self) -> dict:
        return encode_dataclass(self)


# ---------------------------------------------------------------------------
# Defaults for `shifu new` per algorithm
# (reference: ModelTrainConf.createParamsByAlg, container/obj/ModelTrainConf.java:531)
# ---------------------------------------------------------------------------

def default_train_params(alg: Algorithm) -> Dict[str, Any]:
    if alg in (Algorithm.NN, Algorithm.TENSORFLOW):
        return {
            "NumHiddenLayers": 1,
            "ActivationFunc": ["tanh"],
            "NumHiddenNodes": [50],
            "RegularizedConstant": 0.0,
            "LearningRate": 0.1,
            "Propagation": "R",
        }
    if alg == Algorithm.LR:
        return {"LearningRate": 0.1, "RegularizedConstant": 0.0, "L1orL2": "NONE"}
    if alg in (Algorithm.GBT, Algorithm.RF, Algorithm.DT):
        return {
            "TreeNum": 100 if alg == Algorithm.GBT else 10,
            "FeatureSubsetStrategy": "ALL" if alg == Algorithm.GBT else "TWOTHIRDS",
            "MaxDepth": 6 if alg == Algorithm.GBT else 10,
            "MaxStatsMemoryMB": 256,
            "Impurity": "variance",
            "LearningRate": 0.05,
            "MinInstancesPerNode": 5,
            "MinInfoGain": 0.0,
            "Loss": "squared",
        }
    if alg == Algorithm.WDL:
        return {
            "NumHiddenLayers": 2,
            "ActivationFunc": ["relu", "relu"],
            "NumHiddenNodes": [100, 50],
            "NumEmbedColumnIds": [],
            "EmbedOutputs": 8,
            "LearningRate": 0.005,
            "Optimizer": "ADAM",
            "L2Reg": 0.0,
        }
    if alg == Algorithm.SVM:
        return {"Kernel": "linear", "Const": 1.0, "Gamma": 1.0}
    return {}


def new_model_config(name: str, alg: Algorithm = Algorithm.NN) -> ModelConfig:
    mc = ModelConfig()
    mc.basic.name = name
    mc.basic.author = os.environ.get("USER", "shifu-tpu")
    mc.basic.description = "Created at %s" % datetime.datetime.now().strftime(
        "%Y-%m-%d %H:%M:%S"
    )
    mc.basic.run_mode = RunMode.LOCAL
    mc.data_set.data_path = "."
    mc.train.algorithm = alg
    mc.train.params = default_train_params(alg)
    eval_conf = EvalConfig(name="Eval1")
    eval_conf.data_set = RawSourceData()
    mc.evals = [eval_conf]
    return mc
