"""Multi-tenant model zoo (shifu_tpu/serve/zoo.py): budget ledger,
LRU eviction, streamed shadow staging, cold-start 429s.

The acceptance pins live here: a tenant larger than the whole budget is
rejected at registration with ILLEGAL_ARGUMENT; evicting a tenant
mid-promote (or with a staged shadow) is refused; the LRU tie-break is
deterministic (registration order, then name); a tenant re-admitted
after eviction scores BIT-identically to never-evicted; a streamed
shadow stage + promote on a near-full budget keeps the ledger's peak
inside the budget at every instant; cold tenants answer 429 with an
observed-warm-up Retry-After instead of hanging; and all serve.*
metrics carry tenant= labels on one valid exporter page.

Runs under the conftest-forced 8-virtual-device CPU mesh; zoo fleets
pin replicas=1 or 2 to stay fast.
"""

import json
import os
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from shifu_tpu.utils import environment
from shifu_tpu.utils.errors import ErrorCode, ShifuError


def _make_set(d, cols_n=4, hidden=3, bags=1, seed=0):
    from shifu_tpu.models.nn import NNModelSpec, init_params

    cols = [f"c{i}" for i in range(cols_n)]
    sizes = [cols_n, hidden, 1]
    models = os.path.join(d, "models")
    os.makedirs(models, exist_ok=True)
    for b in range(bags):
        specs = [{"name": c, "kind": "value", "outNames": [c],
                  "mean": 0.0, "std": 1.0, "fill": 0.0, "zscore": True}
                 for c in cols]
        NNModelSpec(layer_sizes=sizes, activations=["tanh"],
                    input_columns=cols, norm_specs=specs,
                    params=init_params(sizes, seed=seed + b),
                    ).save(os.path.join(models, f"model{b}.nn"))
    return cols


def _records(cols, n=2, seed=0):
    rng = np.random.default_rng(seed)
    return [{c: f"{v:.5f}" for c, v in zip(cols, row)}
            for row in rng.normal(size=(n, len(cols)))]


def _set_cost(models_dir, buckets=(1, 8)):
    """Measured resident cost of one set at replicas=1 (weights +
    compiled-program peak), via the same memory_analysis the ledger
    prices with."""
    from shifu_tpu.serve.registry import ModelRegistry

    reg = ModelRegistry(models_dir)
    reg.warm(buckets)
    cost = reg.memory_analysis()["residentBytes"]
    reg.release()
    return cost


@pytest.fixture()
def three_sets(tmp_path):
    root = str(tmp_path)
    cols = _make_set(os.path.join(root, "a"), seed=0)
    _make_set(os.path.join(root, "b"), seed=7)
    _make_set(os.path.join(root, "c"), seed=13)
    return root, cols


def _zoo(root, budget_mb, **kw):
    from shifu_tpu import obs
    from shifu_tpu.serve.zoo import ModelZoo

    obs.reset()
    zoo = ModelZoo(root, n_replicas=kw.pop("n_replicas", 1),
                   budget_mb=budget_mb, **kw)
    for name in ("a", "b", "c"):
        zoo.register(name, os.path.join(root, name))
    return zoo


class TestRegistration:
    def test_oversized_tenant_rejected_at_registration(self, tmp_path):
        """A tenant whose weights alone exceed the whole budget can
        never be resident — ILLEGAL_ARGUMENT at register, not a hang on
        the first request."""
        from shifu_tpu.serve.zoo import ModelZoo

        root = str(tmp_path)
        _make_set(os.path.join(root, "big"), cols_n=16, hidden=64,
                  bags=2)
        zoo = ModelZoo(root, n_replicas=1, budget_mb=0.001)  # ~1 KB
        with pytest.raises(ShifuError) as ei:
            zoo.register("big", os.path.join(root, "big"))
        assert ei.value.code is ErrorCode.ILLEGAL_ARGUMENT
        assert "big" not in zoo.tenants()

    def test_bad_names_rejected(self, tmp_path):
        from shifu_tpu.serve.zoo import ModelZoo

        root = str(tmp_path)
        _make_set(os.path.join(root, "a"))
        zoo = ModelZoo(root, n_replicas=1, budget_mb=0)
        for bad in ("", "a/b", "a b", ".hidden", "x" * 70):
            with pytest.raises(ShifuError) as ei:
                zoo.register(bad, os.path.join(root, "a"))
            assert ei.value.code is ErrorCode.ILLEGAL_ARGUMENT

    def test_duplicate_name_rejected(self, three_sets):
        root, _cols = three_sets
        zoo = _zoo(root, budget_mb=0)
        with pytest.raises(ShifuError) as ei:
            zoo.register("a", os.path.join(root, "a"))
        assert ei.value.code is ErrorCode.ILLEGAL_ARGUMENT


class TestLruEviction:
    def test_admission_past_budget_evicts_lru_and_ledgers_it(
            self, three_sets):
        from shifu_tpu import obs

        root, cols = three_sets
        cost = _set_cost(os.path.join(root, "a", "models"))
        zoo = _zoo(root, budget_mb=2.5 * cost / (1024 * 1024))
        zoo.ensure_resident("a")
        zoo.ensure_resident("b")
        # touch a so b is the LRU
        zoo.score_batch("a", _records(cols))
        zoo.ensure_resident("c")  # must evict b
        states = {n: zoo._get(n).state for n in zoo.tenants()}
        assert states == {"a": "resident", "b": "cold", "c": "resident"}
        counters = obs.registry().snapshot()["counters"]
        assert counters.get(
            'serve.zoo.evictions{reason="pressure",tenant="b"}') == 1
        # budget invariant: the ledger's high-water mark never crossed
        assert zoo.ledger.peak <= zoo.ledger.budget_bytes
        zoo.close()

    def test_lru_tie_break_is_deterministic(self, three_sets):
        """Never-scored tenants tie at last_used=0.0 and break by
        registration order — the FIRST-registered of the never-used
        goes, reproducibly."""
        root, _cols = three_sets
        cost = _set_cost(os.path.join(root, "a", "models"))
        zoo = _zoo(root, budget_mb=2.5 * cost / (1024 * 1024))
        zoo.ensure_resident("a")
        zoo.ensure_resident("b")
        # neither a nor b ever scored: tie — registration order says a
        zoo.ensure_resident("c")
        assert zoo._get("a").state == "cold"
        assert zoo._get("b").state == "resident"
        zoo.close()

    def test_readmission_scores_bit_identically(self, three_sets):
        root, cols = three_sets
        cost = _set_cost(os.path.join(root, "a", "models"))
        zoo = _zoo(root, budget_mb=2.5 * cost / (1024 * 1024))
        recs = _records(cols, n=4, seed=3)
        zoo.ensure_resident("a")
        zoo.ensure_resident("b")
        before = zoo.score_batch("a", recs)
        zoo.score_batch("b", recs)          # b now most-recent
        zoo.ensure_resident("c")            # evicts a (LRU)
        assert zoo._get("a").state == "cold"
        after = zoo.score_batch("b", recs)  # b untouched by the churn
        zoo.ensure_resident("a")            # re-admits a, evicting LRU
        again = zoo.score_batch("a", recs)
        assert zoo._get("a").evictions == 1
        # BIT-identical: same files, same configs, same fused program
        np.testing.assert_array_equal(before.model_scores,
                                      again.model_scores)
        np.testing.assert_array_equal(before.mean, again.mean)
        del after
        zoo.close()

    def test_readmission_rewarns_remembered_buckets(self, three_sets):
        root, cols = three_sets
        cost = _set_cost(os.path.join(root, "a", "models"))
        zoo = _zoo(root, budget_mb=2.2 * cost / (1024 * 1024))
        zoo.ensure_resident("a")
        zoo.score_batch("a", _records(cols, n=1))
        zoo.evict("a", reason="test")
        assert 8 in zoo._get("a").warm_buckets  # SERVE_MIN_ROW_BUCKET
        zoo.ensure_resident("a")
        snap = zoo._get("a").fleet.snapshot()
        assert 8 in snap["warmBuckets"]  # re-warmed, not re-discovered
        zoo.close()

    def test_evicting_mid_promote_tenant_is_refused(self, three_sets):
        root, _cols = three_sets
        cost = _set_cost(os.path.join(root, "a", "models"))
        zoo = _zoo(root, budget_mb=0)  # unbounded: isolate the refusal
        zoo.ensure_resident("a")
        tenant = zoo._get("a")
        zoo._busy_guard(tenant, "promote")  # a promote is in flight
        try:
            with pytest.raises(ValueError, match="mid-promote"):
                zoo.evict("a")
            # nor may the LRU scan pick it
            assert zoo._claim_victim() is None
        finally:
            zoo._busy_clear(tenant)
        zoo.close()
        del cost

    def test_evicting_shadow_staged_tenant_is_refused(self, three_sets):
        root, _cols = three_sets
        zoo = _zoo(root, budget_mb=0)
        zoo.ensure_resident("a")
        zoo.stage("a", os.path.join(root, "b", "models"))
        with pytest.raises(ValueError, match="staged shadow"):
            zoo.evict("a")
        assert zoo._claim_victim() is None  # LRU scan skips it too
        zoo.unstage("a")
        zoo.evict("a")  # now legal
        assert zoo._get("a").state == "cold"
        zoo.close()

    def test_cold_tenant_is_not_evictable(self, three_sets):
        root, _cols = three_sets
        zoo = _zoo(root, budget_mb=0)
        with pytest.raises(ValueError, match="not resident"):
            zoo.evict("a")


class TestBudgetLedger:
    def test_ledger_never_exceeds_budget_through_stage_and_promote(
            self, three_sets):
        """The tentpole invariant: through admit -> streamed stage ->
        promote on a near-full budget, the ledger's peak stays <=
        budget at EVERY instant (acquire-before-put makes it so by
        construction; this pins it end to end)."""
        root, cols = three_sets
        cost = _set_cost(os.path.join(root, "a", "models"))
        # room for ~1.8 sets: a resident + a streamed shadow does NOT
        # fit as two full registries plus another resident set
        budget = int(2.6 * cost)
        zoo = _zoo(root, budget_mb=budget / (1024 * 1024))
        zoo.ensure_resident("a")
        zoo.ensure_resident("b")
        zoo.score_batch("a", _records(cols))
        # streamed stage of a candidate for a: must evict b (cold LRU)
        # group by group rather than overshoot
        zoo.stage("a", os.path.join(root, "c", "models"))
        assert zoo.ledger.peak <= budget
        assert zoo._get("b").state == "cold"  # made room for the stage
        shadow = zoo.shadow_snapshot("a")
        assert shadow is not None
        swap = zoo.promote("a", expected_sha=shadow["sha"])
        assert swap["to"] == shadow["sha"]
        assert zoo.ledger.peak <= budget
        # post-promote: one version's charge per tenant again
        assert zoo.ledger.charge_of("a", "shadow") == 0
        assert zoo.ledger.charge_of("a", "active") > 0
        # the promoted dir is what re-admission must rebuild
        assert zoo._get("a").active_dir == os.path.join(
            root, "c", "models")
        zoo.close()

    def test_stage_is_streamed_in_groups(self, three_sets):
        """The stage acquires the candidate layer-group by layer-group:
        multiple ledger acquires, each bounded — not one monolithic
        second-registry charge."""
        root, _cols = three_sets
        zoo = _zoo(root, budget_mb=0)
        zoo.ensure_resident("a")
        groups = []
        orig = zoo.ledger.acquire

        def spy(tenant, kind, nbytes):
            if kind == "shadow":
                groups.append(int(nbytes))
            return orig(tenant, kind, nbytes)

        zoo.ledger.acquire = spy
        try:
            zoo.stage("a", os.path.join(root, "b", "models"))
        finally:
            zoo.ledger.acquire = orig
        # norm consts + per-layer W/b for a 2-layer net = several
        # separate acquires, all BEFORE the true-up
        assert len(groups) >= 4, groups
        zoo.unstage("a")
        assert zoo.ledger.charge_of("a", "shadow") == 0
        zoo.close()

    def test_failed_admission_releases_charge(self, three_sets, tmp_path):
        root, _cols = three_sets
        zoo = _zoo(root, budget_mb=0)
        # register a valid set, then break its active dir before the
        # admission (the on-disk set vanished between registrations)
        tenant = zoo.register("broken", os.path.join(root, "b"))
        tenant.active_dir = str(tmp_path / "vanished-models")
        with pytest.raises(Exception):
            zoo.ensure_resident("broken")
        assert zoo.ledger.charge_of("broken") == 0
        assert zoo._get("broken").state == "cold"
        zoo.close()

    def test_register_fails_fast_on_empty_dir(self, tmp_path):
        from shifu_tpu.serve.zoo import ModelZoo

        zoo = ModelZoo(str(tmp_path), n_replicas=1, budget_mb=0)
        with pytest.raises(ValueError, match="no models"):
            zoo.register("empty", str(tmp_path))


class TestColdStart:
    def test_cold_request_answers_coldstart_not_hang(self, three_sets):
        from shifu_tpu.serve.zoo import ColdStartError

        root, cols = three_sets
        zoo = _zoo(root, budget_mb=0)
        t0 = time.perf_counter()
        with pytest.raises(ColdStartError) as ei:
            zoo.score_batch("a", _records(cols))
        # answered IMMEDIATELY (the admission runs in the background)
        assert time.perf_counter() - t0 < 1.0
        assert ei.value.reason == "cold_start"
        assert ei.value.retry_after_s >= 1.0
        # the background admission completes and the tenant serves
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            try:
                res = zoo.score_batch("a", _records(cols))
                break
            except ColdStartError:
                time.sleep(0.05)
        else:
            pytest.fail("background admission never completed")
        assert res.mean.shape == (2,)
        zoo.close()

    def test_retry_after_uses_observed_warmup(self, three_sets):
        root, cols = three_sets
        zoo = _zoo(root, budget_mb=0)
        zoo.ensure_resident("a")
        observed = zoo._get("a").warm_seconds
        assert observed is not None and observed > 0
        zoo.evict("a", reason="test")
        # a fresh cold hint derives from the observed warm-up (clamped
        # to the 1s floor for these tiny sets), not the 5s default
        hint = zoo._cold_retry_after(zoo._get("a"))
        assert hint == pytest.approx(max(observed, 1.0), abs=0.5)
        zoo.close()


class TestTenantMetricsAndHealth:
    def test_tenant_labels_on_single_exporter_page(self, three_sets):
        from shifu_tpu import obs

        root, cols = three_sets
        zoo = _zoo(root, budget_mb=0, n_replicas=2)
        zoo.ensure_resident("a")
        zoo.ensure_resident("b")
        zoo.score_batch("a", _records(cols))
        zoo.score_batch("b", _records(cols))
        page = obs.registry().to_prometheus()
        assert ('serve_requests_total'
                '{format="json",replica="0",tenant="a"}') in page
        assert ('serve_requests_total'
                '{format="json",replica="0",tenant="b"}') in page
        assert 'serve_queue_depth{replica="0",tenant="a"}' in page
        assert 'serve_zoo_hbm_used_bytes' in page
        assert 'serve_zoo_resident_tenants 2' in page
        # one VALID exporter page: every TYPE declared exactly once
        types = [ln.split()[2] for ln in page.splitlines()
                 if ln.startswith("# TYPE")]
        assert len(types) == len(set(types))
        # round-trip through the repo's own parser (the PR-12 pin)
        from shifu_tpu.obs.metrics import parse_prometheus

        parsed = parse_prometheus(page)
        assert any("tenant=\"a\"" in k for k in parsed)
        zoo.close()

    def test_health_snapshot_fields(self, three_sets):
        root, cols = three_sets
        cost = _set_cost(os.path.join(root, "a", "models"))
        zoo = _zoo(root, budget_mb=2.5 * cost / (1024 * 1024))
        zoo.ensure_resident("a")
        h = zoo.health_snapshot()
        assert h["residentTenants"] == 1
        assert h["hbmBudgetUsedMB"] > 0
        assert h["hbmBudgetUsedMB"] <= h["hbmBudgetMB"]
        assert h["tenants"]["a"]["state"] == "resident"
        assert h["tenants"]["b"]["state"] == "cold"
        zoo.close()


class TestZooServer:
    """HTTP surface: /score/<set> routes, cold 429 + Retry-After,
    /healthz zoo section, per-tenant admin plane."""

    @pytest.fixture()
    def server(self, three_sets):
        from shifu_tpu import obs

        root, cols = three_sets
        obs.reset()
        cost = _set_cost(os.path.join(root, "a", "models"))
        environment.set_property(
            "shifu.serve.hbmBudgetMB",
            str(2.6 * cost / (1024 * 1024)))
        environment.set_property("shifu.lease.ttlMs", "0")
        from shifu_tpu.serve.server import ScoringServer

        srv = ScoringServer(
            root=root, port=0, replicas=1,
            zoo={"a": os.path.join(root, "a"),
                 "b": os.path.join(root, "b"),
                 "c": os.path.join(root, "c")})
        srv.start()
        try:
            yield srv, cols
        finally:
            srv.shutdown()
            environment.set_property("shifu.serve.hbmBudgetMB", "")
            environment.set_property("shifu.lease.ttlMs", "")

    @staticmethod
    def _post(srv, path, doc):
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}{path}",
            json.dumps(doc).encode(),
            {"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req) as r:
                return r.status, json.load(r), dict(r.headers)
        except urllib.error.HTTPError as e:
            return e.code, json.load(e), dict(e.headers)

    @staticmethod
    def _get(srv, path):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}{path}") as r:
            return r.status, json.load(r)

    def test_per_set_routes_and_cold_429(self, server):
        srv, cols = server
        body = {"records": _records(cols)}
        code, doc, _h = self._post(srv, "/score/a", body)
        assert code == 200 and doc["scores"]
        code, _doc, _h = self._post(srv, "/score", body)  # default = a
        assert code == 200
        # budget fits 2: c stayed cold at startup -> immediate 429 with
        # a Retry-After header, then the background admission lands it
        code, doc, hdrs = self._post(srv, "/score/c", body)
        assert code == 429
        assert doc["reason"] == "cold_start"
        assert int(hdrs["Retry-After"]) >= 1
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            code, doc, _h = self._post(srv, "/score/c", body)
            if code == 200:
                break
            time.sleep(0.1)
        assert code == 200 and doc["scores"]
        # an unknown set is a 404, not a hang
        code, doc, _h = self._post(srv, "/score/nope", body)
        assert code == 404 and "nope" in doc["error"]

    def test_healthz_zoo_section(self, server):
        srv, cols = server
        code, h = self._get(srv, "/healthz")
        assert code == 200
        z = h["zoo"]
        assert z["residentTenants"] >= 1
        assert z["hbmBudgetUsedMB"] <= z["hbmBudgetMB"]
        assert set(z["tenants"]) == {"a", "b", "c"}

    def test_admin_evict_and_stage_by_set(self, server, three_sets):
        root, _cols = three_sets
        srv, cols = server
        # evict b (resident, never scored): ledgered + state flips
        code, doc, _h = self._post(srv, "/admin/evict", {"set": "b"})
        assert code == 200, doc
        assert doc["zoo"]["tenants"]["b"]["state"] == "cold"
        # stage a candidate for a, read its shadow, promote it — all
        # per-set through the admin plane
        code, doc, _h = self._post(
            srv, "/admin/stage",
            {"set": "a", "modelsDir": os.path.join(root, "b", "models")})
        assert code == 200, doc
        sha = doc["staged"]["sha"]
        code, doc = self._get(srv, "/admin/shadow?set=a")
        assert code == 200 and doc["shadow"]["sha"] == sha
        code, doc, _h = self._post(srv, "/admin/promote",
                                   {"set": "a", "sha": sha})
        assert code == 200 and doc["to"] == sha
        # scoring the promoted set still answers
        code, doc, _h = self._post(srv, "/score/a",
                                   {"records": _records(cols)})
        assert code == 200

    def test_shutdown_manifest_carries_zoo_ledger(self, three_sets):
        from shifu_tpu import obs

        root, cols = three_sets
        obs.reset()
        environment.set_property("shifu.lease.ttlMs", "0")
        from shifu_tpu.serve.server import ScoringServer

        try:
            srv = ScoringServer(root=root, port=0, replicas=1,
                                zoo={"a": os.path.join(root, "a")})
            srv.start()
            self._post(srv, "/score/a", {"records": _records(cols)})
            path = srv.shutdown()
        finally:
            environment.set_property("shifu.lease.ttlMs", "")
        man = json.load(open(path))
        assert "ledger" in man["zoo"]
        assert man["zoo"]["tenants"]["a"]["requests"] == 1
        assert "memory" in man["zoo"]["tenants"]["a"]


class TestBackgroundTenants:
    """Co-resident trainer tenancy (PR 20): background charges share
    the serving budget but sit on the far side of a strict priority
    line — background acquires are fit-or-fail (never evict serving),
    serving pressure evicts background STRICTLY first, and an evicted
    trainer's record survives for /healthz until it completes."""

    def test_background_acquire_is_fit_or_fail(self, three_sets):
        root, _cols = three_sets
        from shifu_tpu.serve.zoo import LedgerFullError

        cost = _set_cost(os.path.join(root, "a", "models"))
        zoo = _zoo(root, budget_mb=2.5 * cost / (1024 * 1024))
        zoo.ensure_resident("a")
        zoo.ensure_resident("b")
        grant = zoo.admit_background("retrain", meta={"algo": "nn"})
        assert grant["freeBytes"] is not None
        ask = grant["freeBytes"] + 1  # one byte past the free budget
        with pytest.raises(LedgerFullError) as ei:
            zoo.background_acquire("retrain", ask)
        assert ei.value.deficit >= 1
        # fit-or-fail: no serving tenant was evicted to make room
        assert zoo._get("a").state == "resident"
        assert zoo._get("b").state == "resident"
        zoo.background_acquire("retrain", grant["freeBytes"])  # fits
        zoo.close()

    def test_serving_pressure_evicts_background_first(self, three_sets):
        from shifu_tpu import obs

        root, _cols = three_sets
        cost = _set_cost(os.path.join(root, "a", "models"))
        zoo = _zoo(root, budget_mb=2.5 * cost / (1024 * 1024))
        zoo.ensure_resident("a")
        zoo.ensure_resident("b")
        grant = zoo.admit_background("retrain", meta={"stages": 2})
        zoo.background_acquire("retrain", grant["freeBytes"])
        assert zoo.background_heartbeat("retrain", 3) is False
        zoo.ensure_resident("c")  # needs the trainer's bytes AND a's
        counters = obs.registry().snapshot()["counters"]
        assert counters.get('serve.zoo.evictions{'
                            'reason="pressure_background",'
                            'tenant="retrain"}') == 1
        # the trainer went FIRST; only then did LRU touch serving
        assert counters.get(
            'serve.zoo.evictions{reason="pressure",tenant="a"}') == 1
        assert zoo._get("b").state == "resident"
        # the flag reaches the trainer at its next heartbeat
        assert zoo.background_heartbeat("retrain", 4) is True
        zoo.close()

    def test_evicted_record_survives_until_final_release(
            self, three_sets):
        root, _cols = three_sets
        zoo = _zoo(root, budget_mb=0)
        zoo.admit_background("retrain", meta={"algo": "nn",
                                              "stages": 2})
        zoo.background_acquire("retrain", 4096)
        zoo.background_heartbeat("retrain", 5)
        zoo.evict("retrain")  # the /admin/evict path, background branch
        snap = zoo.health_snapshot()["background"]["retrain"]
        assert snap["evictRequested"] and snap["evictions"] == 1
        assert snap["epoch"] == 5 and snap["stages"] == 2
        # the eviction release keeps the record (checkpointed epoch
        # stays visible); re-admission clears the flag
        zoo.background_release("retrain", final=False)
        assert "retrain" in zoo.health_snapshot()["background"]
        zoo.admit_background("retrain")
        assert zoo.background_heartbeat("retrain", 6) is False
        # completion forgets the tenant
        zoo.background_release("retrain", final=True)
        assert "retrain" not in (zoo.health_snapshot().get("background")
                                 or {})
        zoo.close()

    def test_name_collisions_rejected_both_ways(self, three_sets):
        root, _cols = three_sets
        zoo = _zoo(root, budget_mb=0)
        with pytest.raises(ValueError, match="serving tenant"):
            zoo.admit_background("a")  # "a" is a registered serving set
        zoo.admit_background("retrain")
        with pytest.raises(ShifuError) as ei:
            zoo.register("retrain", os.path.join(root, "a"))
        assert ei.value.code is ErrorCode.ILLEGAL_ARGUMENT
        zoo.close()

    def test_flagged_tenant_cannot_reacquire(self, three_sets):
        """Between the eviction flag and the trainer's checkpoint there
        is a one-epoch grace window; the ledger refuses NEW charges in
        it so a slow trainer cannot grow while flagged."""
        from shifu_tpu.serve.zoo import LedgerFullError

        root, _cols = three_sets
        zoo = _zoo(root, budget_mb=0)
        zoo.admit_background("retrain")
        zoo.background_acquire("retrain", 1024)
        zoo.evict("retrain")
        with pytest.raises(LedgerFullError, match="flagged"):
            zoo.background_acquire("retrain", 1024)
        zoo.close()
