"""Multi-tenant model zoo on a bounded HBM budget.

One deployment, many model sets: the reference serves one model set per
JVM fleet; PR 12-14 kept that assumption — one `ScoringServer`, one
resident `ReplicaFleet`. Production wants the TensorFlow-paper shape
instead (shared devices partitioned between heterogeneous programs): N
tenants behind one server on a FIXED device-memory budget, where a
tenant is a complete model set with its own `/score/<set>` route, its
own per-replica `SwappableRegistry` stack (drift windows, version
counters, traffic-log stream, shadow gates) — all riding the existing
replica fleet — and residency is a managed, accounted resource rather
than an accident of construction order.

Three pieces:

  HbmLedger   the budget ledger. Every byte a tenant puts on device is
              acquired BEFORE the device_put that moves it (the
              registry's `put_hook` seam) and priced afterwards from
              the PR-6 `memory_analysis()` numbers (weights + compiled-
              program args/temps/out per warm bucket), so
              `used <= budget` holds at every instant BY CONSTRUCTION
              and the ledger's high-water mark (`peak`) is the proof.
              `-Dshifu.serve.hbmBudgetMB` (0 = unbounded).
  ZooTenant   one registered model set: registration survives eviction
              (models dir — the PROMOTED dir, not the original —, warm
              buckets to re-warm, last measured cost, the traffic-log
              stream and drift monitor), residency does not.
  ModelZoo    admission, LRU eviction, streamed shadow staging, and the
              per-tenant continuous-loop seams.

Admission & eviction: a tenant whose weights alone exceed the whole
budget is rejected at REGISTRATION (`ErrorCode.ILLEGAL_ARGUMENT` — it
could never serve). Admission past the budget evicts cold tenants in
strict LRU order (least-recently-scored first; ties break by
registration order then name, deterministically) — an evicted tenant's
compiled-program cache entries and device weights are dropped TOGETHER
(`ModelRegistry.release` purges the profiler cost cache that would
otherwise pin them), the eviction is ledgered
(`serve.zoo.evictions{tenant=,reason=}`), and re-admission rebuilds the
identical registry from the identical files, so re-admitted scores are
bit-identical to never-evicted ones (pinned in tests/test_zoo.py). A
tenant mid-stage/mid-promote, or with a staged shadow, is never chosen
and an explicit evict of it is REFUSED — evicting the swap target would
strand the rollout.

Cold starts never hang the admission queue: a request for a non-
resident tenant kicks a background admission and is answered 429 with a
Retry-After derived from OBSERVED warm-up time (this tenant's last
admission, else the zoo-wide average, else
`-Dshifu.serve.zoo.warmupMs`), minus the time the in-flight admission
has already spent. `/healthz` carries `zoo.residentTenants` /
`zoo.hbmBudgetUsedMB` and a non-sticky `cold_start` degrade reason
while any admission is in flight.

Streamed shadow staging: `stage()` threads the ledger's acquire through
the registry's per-layer-group `put_hook`, so a candidate's weights
land group by group, each group admitted (evicting cold tenants if
needed) BEFORE its device_put — a promote on a near-full budget never
materializes a full second registry and never OOMs; the ledger's peak
proves residency stayed inside the budget through the whole
stage -> shadow-score -> promote sequence. On promote the OLD active
version's charge is released and the shadow's charge becomes the
active one.
"""

from __future__ import annotations

import os
import re
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from shifu_tpu.analysis.racetrack import tracked_lock
from shifu_tpu.serve.fleet import ReplicaFleet, replicas_setting
from shifu_tpu.serve.registry import estimate_weights_bytes
from shifu_tpu.utils import environment
from shifu_tpu.utils.errors import ErrorCode, ShifuError
from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)

MB = 1024.0 * 1024.0

# tenant states
COLD = "cold"            # registered, nothing on device
ADMITTING = "admitting"  # background build+warm in flight
RESIDENT = "resident"    # serving
EVICTING = "evicting"    # draining out of the budget

# URL-safe tenant names: they become /score/<set> path segments and
# tenant= metric label values
_NAME_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9_.-]{0,63}$")

# cold-start histogram edges: admissions are 100ms..minutes, not the
# sub-ms LATENCY_BUCKETS scale
COLD_START_BUCKETS = tuple(0.05 * 2 ** k for k in range(16)) + (
    float("inf"),)

# Retry-After clamp for cold starts (wider than the queue clamp: a
# compile-heavy admission legitimately takes tens of seconds)
COLD_RETRY_MIN_S = 1.0
COLD_RETRY_MAX_S = 120.0

DEFAULT_WARMUP_MS = 5000.0
EVICT_DRAIN_TIMEOUT_S = 30.0


def hbm_budget_mb_setting() -> float:
    """shifu.serve.hbmBudgetMB — total device-memory budget the zoo's
    ledger admits tenants against (0 = unbounded)."""
    return environment.get_float("shifu.serve.hbmBudgetMB", 0.0)


def zoo_warmup_ms_setting() -> float:
    """shifu.serve.zoo.warmupMs — cold-start Retry-After fallback before
    any admission has been observed."""
    return environment.get_float("shifu.serve.zoo.warmupMs",
                                 DEFAULT_WARMUP_MS)


class LedgerFullError(RuntimeError):
    """The budget cannot fit the requested bytes and nothing is
    evictable (every other tenant is cold, busy, or shadow-staged)."""

    def __init__(self, msg: str, deficit: int = 0) -> None:
        super().__init__(msg)
        self.deficit = int(deficit)


class ColdStartError(RuntimeError):
    """The tenant is not resident; admission is in flight. HTTP answers
    429 + Retry-After (never a hung connection while a compile runs)."""

    def __init__(self, tenant: str, retry_after_s: float,
                 detail: str = "") -> None:
        super().__init__(
            f"tenant {tenant} is warming up"
            + (f" ({detail})" if detail else "")
            + f" — retry in {retry_after_s:.0f}s")
        self.tenant = tenant
        self.reason = "cold_start"
        self.retry_after_s = float(retry_after_s)


class HbmLedger:
    """Budget-accounted residency: (tenant, kind) -> charged bytes.

    `kind` is "active" (the serving version) or "shadow" (a staged
    candidate); `transfer()` renames shadow -> active at promote.
    Acquire NEVER records past the budget — the caller (ModelZoo) evicts
    between attempts — so `peak <= budget` is an invariant, not a hope;
    the gauges serve.zoo.hbm_used_bytes / hbm_peak_bytes publish it."""

    def __init__(self, budget_mb: float = 0.0) -> None:
        self.budget_bytes = int(max(0.0, float(budget_mb)) * MB)
        self._lock = tracked_lock("serve.zoo.ledger")
        self._charges: Dict[tuple, int] = {}
        self._used = 0
        self._peak = 0
        from shifu_tpu.obs import registry

        registry().gauge("serve.zoo.hbm_budget_bytes").set(
            self.budget_bytes)

    def acquire(self, tenant: str, kind: str, nbytes: int) -> None:
        """Charge `nbytes` to (tenant, kind) or raise LedgerFullError
        with the deficit — never over-commits."""
        nbytes = int(nbytes)
        if nbytes <= 0:
            return
        with self._lock:
            if (self.budget_bytes
                    and self._used + nbytes > self.budget_bytes):
                deficit = self._used + nbytes - self.budget_bytes
                raise LedgerFullError(
                    f"HBM budget full: {tenant}/{kind} needs {nbytes} "
                    f"bytes, {deficit} over the "
                    f"{self.budget_bytes} budget", deficit)
            self._charges[(tenant, kind)] = (
                self._charges.get((tenant, kind), 0) + nbytes)
            self._used += nbytes
            self._peak = max(self._peak, self._used)
            used = self._used
            peak = self._peak
            tb = self._tenant_bytes(tenant)
        self._publish(used, peak, tenant, tb)

    def reduce(self, tenant: str, kind: str, nbytes: int) -> None:
        """Shrink a charge (measured cost came in under the streamed
        estimate)."""
        with self._lock:
            have = self._charges.get((tenant, kind), 0)
            cut = min(have, max(0, int(nbytes)))
            if cut:
                self._charges[(tenant, kind)] = have - cut
                self._used -= cut
            used, peak = self._used, self._peak
            tb = self._tenant_bytes(tenant)
        self._publish(used, peak, tenant, tb)

    def release(self, tenant: str, kind: str) -> int:
        """Drop the whole (tenant, kind) charge; returns it."""
        with self._lock:
            freed = self._charges.pop((tenant, kind), 0)
            self._used -= freed
            used, peak = self._used, self._peak
            tb = self._tenant_bytes(tenant)
        self._publish(used, peak, tenant, tb)
        return freed

    def transfer(self, tenant: str, src: str, dst: str) -> None:
        """Rename a charge (shadow -> active at promote): no byte moves,
        so no budget check and no instant of double counting."""
        with self._lock:
            amt = self._charges.pop((tenant, src), 0)
            if amt:
                self._charges[(tenant, dst)] = (
                    self._charges.get((tenant, dst), 0) + amt)
            used, peak = self._used, self._peak
            tb = self._tenant_bytes(tenant)
        self._publish(used, peak, tenant, tb)

    def charge_of(self, tenant: str, kind: Optional[str] = None) -> int:
        with self._lock:
            if kind is not None:
                return self._charges.get((tenant, kind), 0)
            return sum(v for (t, _k), v in self._charges.items()
                       if t == tenant)

    @property
    def used(self) -> int:
        with self._lock:
            return self._used

    @property
    def peak(self) -> int:
        with self._lock:
            return self._peak

    def _tenant_bytes(self, tenant: str) -> int:
        # caller holds self._lock
        return sum(v for (t, _k), v in self._charges.items()
                   if t == tenant)

    def _publish(self, used: int, peak: int,
                 tenant: Optional[str] = None,
                 tenant_bytes: int = 0) -> None:
        # gauges set OUTSIDE the ledger lock (the racetrack discipline:
        # tracked metric locks never nest under subsystem locks)
        from shifu_tpu.obs import registry

        reg = registry()
        reg.gauge("serve.zoo.hbm_used_bytes").set(used)
        reg.gauge("serve.zoo.hbm_peak_bytes").set(peak)
        if tenant is not None:
            # per-tenant residency: the mutated tenant's new total
            # (evicted = 0, so the series reads true, not stale) — the
            # fleet view / `shifu top` attribute HBM occupancy per
            # tenant per process from this one series
            reg.gauge("serve.zoo.tenant_hbm_bytes",
                      tenant=tenant).set(tenant_bytes)

    def snapshot(self) -> dict:
        with self._lock:
            charges = dict(self._charges)
            used, peak = self._used, self._peak
        per: Dict[str, float] = {}
        for (tenant, _kind), v in charges.items():
            per[tenant] = per.get(tenant, 0) + v
        return {
            "budgetMB": round(self.budget_bytes / MB, 3),
            "usedMB": round(used / MB, 3),
            "peakMB": round(peak / MB, 3),
            "tenantsMB": {t: round(v / MB, 3)
                          for t, v in sorted(per.items())},
        }


class BackgroundTenant:
    """A `priority=background` ledger tenant — the co-resident trainer.

    Background tenants hold ledger bytes but never serve scores; under
    serving pressure they are evicted STRICTLY FIRST (the LRU never
    picks a serving tenant while any background charge remains), and
    their own acquires are fit-or-fail (a trainer never evicts a
    serving tenant to stay resident). Eviction here is a FLAG plus an
    immediate charge drop: the trainer observes the flag at its next
    epoch-boundary heartbeat, checkpoints, and frees its device buffers
    — a grace window bounded by one epoch (see docs/SERVING.md)."""

    def __init__(self, name: str, reg_seq: int,
                 meta: Optional[dict] = None) -> None:
        self.name = name
        self.reg_seq = int(reg_seq)
        self.meta = dict(meta or {})
        self.epoch = -1                 # last heartbeat epoch
        self.evict_requested = False
        self.evictions = 0
        self.admitted_at = time.time()

    def snapshot(self) -> dict:
        return {
            "priority": "background",
            "epoch": self.epoch,
            "stages": self.meta.get("stages"),
            "algo": self.meta.get("algo"),
            "evictRequested": self.evict_requested,
            "evictions": self.evictions,
        }


def load_set_configs(root: str):
    """Best-effort (column_configs, model_config) from a model-set root
    — same degrade-never-fail contract as the single-tenant server."""
    ccs = mc = None
    try:
        cc_path = os.path.join(root, "ColumnConfig.json")
        if os.path.isfile(cc_path):
            from shifu_tpu.config import load_column_config_list

            ccs = load_column_config_list(cc_path)
    except Exception as e:  # malformed config degrades, never kills
        log.warning("zoo: cannot load ColumnConfig.json under %s (%s); "
                    "drift monitoring off for this tenant", root, e)
    try:
        mc_path = os.path.join(root, "ModelConfig.json")
        if os.path.isfile(mc_path):
            from shifu_tpu.config import ModelConfig

            mc = ModelConfig.load(mc_path)
    except Exception as e:  # malformed config degrades, never kills
        log.warning("zoo: cannot load ModelConfig.json under %s (%s)",
                    root, e)
    return ccs, mc


class ZooTenant:
    """One registered model set. Registration state survives eviction;
    everything device-resident lives behind `fleet` and drops with it."""

    def __init__(self, name: str, root: str, models_dir: str,
                 column_configs=None, model_config=None,
                 reg_seq: int = 0) -> None:
        self.name = name
        self.root = root              # the set's own config root
        self.models_dir = models_dir  # as registered
        self.active_dir = models_dir  # tracks promotes across evictions
        self.column_configs = column_configs
        self.model_config = model_config
        self.reg_seq = int(reg_seq)
        self.state = COLD
        self.fleet: Optional[ReplicaFleet] = None
        self.scorer = None
        self.drift = None
        self.traffic = None
        self.label_cols: List[str] = []
        self.busy: Optional[str] = None   # "stage" | "promote" in flight
        self.shadow_staged = False
        self.last_used = 0.0              # monotonic; 0 = never scored
        self.requests = 0
        self.evictions = 0
        self.warm_buckets: List[int] = []
        self.warm_seconds: Optional[float] = None  # observed admission
        self.admit_started = 0.0
        self.admit_event: Optional[threading.Event] = None
        self.admit_error: Optional[str] = None
        self.admit_evict = True  # may this admission evict others?
        self._obs_lock = tracked_lock("serve.zoo.tenant_observe")
        self.observed_batches = 0
        self.last_drift_verdict: Optional[dict] = None

    def lru_key(self) -> tuple:
        """Strict, deterministic eviction order: least-recently-scored
        first; never-scored tenants tie at 0.0 and break by registration
        order, then name — so an eviction decision is reproducible from
        the ledger alone."""
        return (self.last_used, self.reg_seq, self.name)

    def snapshot(self) -> dict:
        snap = {
            "state": self.state,
            "modelsDir": self.active_dir,
            "requests": self.requests,
            "evictions": self.evictions,
            "warmBuckets": list(self.warm_buckets),
        }
        if self.warm_seconds is not None:
            snap["warmSeconds"] = round(self.warm_seconds, 3)
        if self.busy:
            snap["busy"] = self.busy
        if self.shadow_staged:
            snap["shadowStaged"] = True
        if self.admit_error:
            snap["admitError"] = self.admit_error
        fleet = self.fleet
        if fleet is not None and self.state == RESIDENT:
            snap["sha"] = fleet.sha
            if self.last_drift_verdict is not None:
                v = self.last_drift_verdict
                snap["drift"] = {"status": v["status"],
                                 "maxPsi": round(v["maxPsi"], 6)}
        return snap


class ModelZoo:
    """N model sets behind one server on one HBM budget."""

    def __init__(self, root: str = ".",
                 n_replicas: Optional[int] = None,
                 budget_mb: Optional[float] = None,
                 queue_depth: Optional[int] = None,
                 max_batch_rows: Optional[int] = None,
                 max_wait_ms: Optional[float] = None,
                 batching: Optional[str] = None,
                 scale: Optional[float] = None) -> None:
        self.root = os.path.abspath(root)
        self.n_replicas = n_replicas
        self.queue_depth = queue_depth
        self.max_batch_rows = max_batch_rows
        self.max_wait_ms = max_wait_ms
        self.batching = batching
        self.scale = scale
        self.ledger = HbmLedger(hbm_budget_mb_setting()
                                if budget_mb is None else budget_mb)
        self._lock = tracked_lock("serve.zoo")
        # fleet traffic-log writer id (the server's lease id); set by the
        # owning server once the lease exists, adopted by every tenant
        # stream wired after that — see ServeServer._finish_init
        self.writer = ""
        self._tenants: Dict[str, ZooTenant] = {}
        self._background: Dict[str, BackgroundTenant] = {}
        self._reg_seq = 0
        self._default_name: Optional[str] = None  # first registered
        self._closed = False
        self._warm_ema: Optional[float] = None  # zoo-wide observed
        from shifu_tpu.loop import drift_check_batches_setting

        self._drift_check_every = max(1, drift_check_batches_setting())

    # ---- registration ----
    def _replica_count(self) -> int:
        import jax

        n = (self.n_replicas if self.n_replicas is not None
             else replicas_setting())
        return int(n) if n and int(n) > 0 else len(jax.devices())

    def register(self, name: str, path: str,
                 column_configs=None, model_config=None,
                 admit: bool = False) -> ZooTenant:
        """Register one model set as tenant `name`. `path` is a model-
        set root (ColumnConfig.json/ModelConfig.json beside a models/
        dir) or a bare models dir. Rejects names that cannot be URL/
        label segments and — when a budget is set — tenants whose
        weights ALONE exceed the whole budget (they could never be
        resident; failing at registration beats failing on the first
        request)."""
        if not _NAME_RE.match(name or ""):
            raise ShifuError(
                ErrorCode.ILLEGAL_ARGUMENT,
                f"tenant name {name!r} must match {_NAME_RE.pattern} "
                "(it becomes the /score/<set> route and the tenant= "
                "metric label)")
        path = os.path.abspath(path)
        sub = os.path.join(path, "models")
        models_dir = sub if os.path.isdir(sub) else path
        if column_configs is None and model_config is None:
            column_configs, model_config = load_set_configs(path)
        with self._lock:
            if name in self._tenants:
                raise ShifuError(
                    ErrorCode.ILLEGAL_ARGUMENT,
                    f"tenant {name} is already registered")
            if name in self._background:
                raise ShifuError(
                    ErrorCode.ILLEGAL_ARGUMENT,
                    f"tenant name {name!r} is held by a background "
                    "(co-resident trainer) tenant")
        n_rep = self._replica_count()
        weights = estimate_weights_bytes(models_dir, column_configs,
                                         model_config) * n_rep
        if self.ledger.budget_bytes and weights > self.ledger.budget_bytes:
            raise ShifuError(
                ErrorCode.ILLEGAL_ARGUMENT,
                f"tenant {name} needs {weights} weight bytes across "
                f"{n_rep} replica(s) — more than the whole "
                f"{self.ledger.budget_bytes}-byte HBM budget; it could "
                "never be resident")
        with self._lock:
            if name in self._tenants:  # raced registration
                raise ShifuError(
                    ErrorCode.ILLEGAL_ARGUMENT,
                    f"tenant {name} is already registered")
            if name in self._background:  # raced background admit
                raise ShifuError(
                    ErrorCode.ILLEGAL_ARGUMENT,
                    f"tenant name {name!r} is held by a background "
                    "(co-resident trainer) tenant")
            tenant = ZooTenant(name, path, models_dir,
                               column_configs=column_configs,
                               model_config=model_config,
                               reg_seq=self._reg_seq)
            self._reg_seq += 1
            self._tenants[name] = tenant
            if self._default_name is None:
                self._default_name = name
            count = len(self._tenants)
        from shifu_tpu.obs import registry

        registry().gauge("serve.zoo.tenants").set(count)
        log.info("zoo: registered tenant %s (%s, ~%d weight bytes x %d "
                 "replicas)", name, models_dir, weights // max(1, n_rep),
                 n_rep)
        if admit:
            self.ensure_resident(name)
        return tenant

    def tenants(self) -> List[str]:
        with self._lock:
            return sorted(self._tenants)

    def _get(self, name: str) -> ZooTenant:
        with self._lock:
            if name not in self._tenants:
                raise KeyError(f"unknown tenant {name!r} "
                               f"(registered: {sorted(self._tenants)})")
            return self._tenants[name]

    @property
    def default_tenant(self) -> Optional[str]:
        """First-registered tenant: what a bare /score routes to.
        Cached at registration — registration order never changes, and
        this is read on every request/health probe (no lock, no
        scan)."""
        return self._default_name

    # ---- residency ----
    def ensure_resident(self, name: str, wait: bool = True,
                        evict: bool = True) -> Optional[ReplicaFleet]:
        """Make `name` resident. `wait=True` blocks through the build +
        warm (tests, eager startup); `wait=False` kicks a background
        admission and raises ColdStartError (the request path).
        `evict=False` (eager startup warm-up) admits only into FREE
        budget — pre-warming tenant N must not evict tenant N-1 that
        was just admitted; only demand (a scored request) earns an
        eviction."""
        tenant = self._get(name)
        while True:
            with self._lock:
                if self._closed:
                    raise RuntimeError(
                        "zoo is closed — no admissions after shutdown")
                state = tenant.state
                if state == RESIDENT:
                    return tenant.fleet
                if state == COLD:
                    tenant.state = ADMITTING
                    tenant.admit_event = threading.Event()
                    tenant.admit_started = time.monotonic()
                    tenant.admit_error = None
                    tenant.admit_evict = evict
                    claimed = True
                else:
                    claimed = False
                event = tenant.admit_event
            if claimed:
                if wait:
                    self._admit(tenant)  # raises on failure
                    return tenant.fleet
                threading.Thread(target=self._admit_bg, args=(tenant,),
                                 name=f"shifu-zoo-admit-{name}",
                                 daemon=True).start()
                raise ColdStartError(name, self._cold_retry_after(tenant))
            if not wait:
                raise ColdStartError(
                    name, self._cold_retry_after(tenant),
                    detail=state)
            if event is not None:
                event.wait(timeout=600.0)
            else:
                time.sleep(0.05)  # EVICTING: poll until it lands cold
            with self._lock:
                if (tenant.state == COLD
                        and tenant.admit_error is not None):
                    raise RuntimeError(
                        f"tenant {name} admission failed: "
                        f"{tenant.admit_error}")

    def _admit_bg(self, tenant: ZooTenant) -> None:
        # failures are fully recorded (log + admit_error + counter) by
        # _admit BEFORE it signals waiters, so this wrapper must emit
        # nothing afterwards — a late log line from this daemon thread
        # would land outside any captured test/CI scope
        import contextlib

        with contextlib.suppress(Exception):
            self._admit(tenant)

    def _admit(self, tenant: ZooTenant) -> None:
        """Build + warm the tenant's fleet inside the budget. Caller has
        already flipped the tenant to ADMITTING."""
        from shifu_tpu.obs import registry as obs_registry

        reg = obs_registry()
        kind = "readmit" if tenant.evictions else "initial"
        reg.counter("serve.zoo.admissions", tenant=tenant.name,
                    kind=kind).inc()
        t0 = time.perf_counter()
        fleet = None
        try:
            drift = tenant.drift
            if drift is None and tenant.column_configs:
                from shifu_tpu.loop.drift import DriftMonitor

                drift = DriftMonitor(tenant.column_configs)
                if not drift.enabled:
                    drift = None
                tenant.drift = drift
            fleet = ReplicaFleet.build(
                tenant.active_dir,
                n_replicas=self.n_replicas,
                column_configs=tenant.column_configs,
                model_config=tenant.model_config,
                drift=drift,
                queue_depth=self.queue_depth,
                max_batch_rows=self.max_batch_rows,
                max_wait_ms=self.max_wait_ms,
                batching=self.batching,
                observer=self._observer(tenant),
                tenant=tenant.name,
                put_hook=lambda n: self._acquire(
                    tenant, "active", n, evict=tenant.admit_evict),
                cost_hook=lambda: self._reprice(tenant),
                **({"scale": self.scale}
                   if self.scale is not None else {}))
            buckets = tenant.warm_buckets or [1]
            fleet.warm(buckets)
            # true-up: streamed weight acquires covered the puts; the
            # compiled programs' args/temps/out (memory_analysis) join
            # the charge now the executables exist
            measured = fleet.memory_analysis()["residentBytes"]
            charged = self.ledger.charge_of(tenant.name, "active")
            if measured > charged:
                self._acquire(tenant, "active", measured - charged,
                              evict=tenant.admit_evict)
            elif measured < charged:
                self.ledger.reduce(tenant.name, "active",
                                   charged - measured)
            self._wire_loop(tenant, fleet)
            from shifu_tpu.serve.server import Scorer

            scorer = Scorer(fleet=fleet,
                            extra_columns=tenant.label_cols)
            warm_s = time.perf_counter() - t0
            # every side effect (histogram, log) lands BEFORE the state
            # flips to RESIDENT: the moment a poller can see the tenant
            # serving, this background thread must have nothing left to
            # emit (a post-teardown log line from an admission thread
            # corrupts captured test/CI output)
            reg.histogram("serve.zoo.cold_start_seconds",
                          buckets=COLD_START_BUCKETS,
                          tenant=tenant.name).observe(warm_s)
            log.info("zoo: tenant %s resident in %.2fs (%d bytes "
                     "ledgered)", tenant.name, warm_s,
                     self.ledger.charge_of(tenant.name))
            with self._lock:
                closed = self._closed
                if not closed:
                    tenant.fleet = fleet
                    tenant.scorer = scorer
                    tenant.state = RESIDENT
                    tenant.warm_seconds = warm_s
                    if self._warm_ema is None:
                        self._warm_ema = warm_s
                    else:
                        self._warm_ema = (0.7 * self._warm_ema
                                          + 0.3 * warm_s)
                else:
                    # the zoo closed while this admission compiled
                    # (close() waits a bounded time, not forever): the
                    # fleet must not outlive the shutdown — tear it
                    # down and leave the tenant cold
                    tenant.state = COLD
                event = tenant.admit_event
                tenant.admit_event = None
            if closed:
                fleet.close(timeout=1.0)
                fleet.release()
                self.ledger.release(tenant.name, "active")
                if event is not None:
                    event.set()
                return
            self._publish_resident()
            if event is not None:
                event.set()
        except BaseException as e:
            if fleet is not None:
                try:  # tear a partial build down so its programs free
                    fleet.close(timeout=1.0)
                    fleet.release()
                except Exception as te:  # best-effort: the charge
                    # release below is the accounting that matters
                    log.warning("zoo: partial-build teardown of %s: %s",
                                tenant.name, te)
            self.ledger.release(tenant.name, "active")
            with self._lock:
                tenant.state = COLD
                tenant.fleet = None
                tenant.scorer = None
                tenant.admit_error = f"{type(e).__name__}: {e}"
                event = tenant.admit_event
                tenant.admit_event = None
            reg.counter("serve.zoo.admission_errors",
                        tenant=tenant.name).inc()
            log.warning("zoo: admission of %s failed: %s: %s",
                        tenant.name, type(e).__name__, e)
            if event is not None:
                event.set()
            raise

    def _reprice(self, tenant: ZooTenant) -> None:
        """Registry cost-hook: a NEW row bucket was compiled by live (or
        shadow) traffic after admission — re-read memory_analysis and
        true the tenant's total charge UP so the ledger keeps describing
        actual residency. Downward corrections happen at admission and
        promote; this only ever adds, so used <= budget keeps holding.

        Runs on the replica's scoring worker, so it must NEVER block on
        another tenant's eviction drain (up to 30 s — a p99 cliff for
        every rider queued behind the batch): when the extra bytes
        don't fit the free budget, the evict-and-acquire pass is
        deferred to a background thread and the ledger catches up
        within one drain — the same off-request-path discipline as
        cold-start admission."""
        with self._lock:
            if tenant.state != RESIDENT or tenant.fleet is None:
                return
            fleet = tenant.fleet
        measured = fleet.memory_analysis()["residentBytes"]
        charged = self.ledger.charge_of(tenant.name)
        if measured <= charged:
            return
        try:
            self.ledger.acquire(tenant.name, "active", measured - charged)
        except LedgerFullError:
            threading.Thread(target=self._reprice_evicting,
                             args=(tenant,),
                             name=f"shifu-zoo-reprice-{tenant.name}",
                             daemon=True).start()

    def _reprice_evicting(self, tenant: ZooTenant) -> None:
        """Background half of _reprice: recompute the deficit fresh
        (racing reprices must not double-charge) and acquire with LRU
        eviction allowed."""
        import contextlib

        with contextlib.suppress(Exception):  # accounting must never
            # kill the thread loudly; the next new bucket re-trues
            with self._lock:
                if tenant.state != RESIDENT or tenant.fleet is None:
                    return
                fleet = tenant.fleet
            measured = fleet.memory_analysis()["residentBytes"]
            charged = self.ledger.charge_of(tenant.name)
            if measured > charged:
                self._acquire(tenant, "active", measured - charged)

    def _acquire(self, tenant: ZooTenant, kind: str,
                 nbytes: int, evict: bool = True) -> None:
        """Ledger acquire with LRU eviction between attempts: evict the
        least-recently-scored evictable tenant until the bytes fit or
        nothing is left to evict (`evict=False`: fit-or-fail)."""
        while True:
            try:
                self.ledger.acquire(tenant.name, kind, nbytes)
                return
            except LedgerFullError as e:
                if evict:
                    # background tenants (co-resident trainers) go
                    # STRICTLY FIRST: the LRU never evicts a serving
                    # tenant while any background charge remains
                    bg = self._claim_background_victim()
                    if bg is not None:
                        self._evict_background(
                            bg, reason="pressure_background")
                        continue
                victim = (self._claim_victim(exclude=tenant)
                          if evict else None)
                if victim is None:
                    raise LedgerFullError(
                        f"cannot fit {nbytes} bytes for {tenant.name}/"
                        f"{kind}: {e.deficit} bytes over budget and no "
                        "evictable tenant (others are cold, mid-"
                        "rollout, or shadow-staged)", e.deficit)
                self._evict(victim, reason="pressure")

    def _claim_victim(self, exclude: Optional[ZooTenant] = None
                      ) -> Optional[ZooTenant]:
        with self._lock:
            candidates = [
                t for t in self._tenants.values()
                if (t.state == RESIDENT and t is not exclude
                    and t.busy is None and not t.shadow_staged)
            ]
            if not candidates:
                return None
            victim = min(candidates, key=lambda t: t.lru_key())
            victim.state = EVICTING
            return victim

    def evict(self, name: str, reason: str = "admin") -> None:
        """Explicit eviction. Refused for a tenant mid-stage/mid-promote
        or with a staged shadow — evicting the swap target would strand
        the rollout half-rolled."""
        with self._lock:
            bt = self._background.get(name)
        if bt is not None:
            self._evict_background(bt, reason=reason)
            return
        tenant = self._get(name)
        with self._lock:
            if tenant.state != RESIDENT:
                raise ValueError(
                    f"tenant {name} is {tenant.state}, not resident")
            if tenant.busy is not None:
                raise ValueError(
                    f"tenant {name} is mid-{tenant.busy} — eviction "
                    "refused until the rollout operation completes")
            if tenant.shadow_staged:
                raise ValueError(
                    f"tenant {name} has a staged shadow — unstage or "
                    "promote before evicting")
            tenant.state = EVICTING
        self._evict(tenant, reason=reason)

    def _evict(self, tenant: ZooTenant, reason: str) -> None:
        """Tear down a claimed (state=EVICTING) tenant: drain its fleet,
        drop compiled programs + device weights together, release the
        ledger charge, remember what re-admission needs."""
        from shifu_tpu.obs import registry as obs_registry

        with self._lock:
            fleet, tenant.fleet = tenant.fleet, None
            tenant.scorer = None
        if fleet is not None:
            # remember BEFORE teardown: re-admission rebuilds the
            # promoted dir and re-warms the buckets live traffic used
            tenant.active_dir = fleet.active_models_dir
            try:
                tenant.warm_buckets = list(
                    fleet.snapshot().get("warmBuckets", [])) or \
                    tenant.warm_buckets
            except Exception as se:  # snapshot trouble must not block
                log.warning("zoo: cannot read %s warm buckets at "
                            "evict: %s", tenant.name, se)
            fleet.close(timeout=EVICT_DRAIN_TIMEOUT_S)
            dropped = fleet.release()
        else:
            dropped = 0
        freed = (self.ledger.release(tenant.name, "active")
                 + self.ledger.release(tenant.name, "shadow"))
        with self._lock:
            tenant.state = COLD
            tenant.evictions += 1
            tenant.last_drift_verdict = None
        obs_registry().counter("serve.zoo.evictions",
                               tenant=tenant.name, reason=reason).inc()
        self._publish_resident()
        log.info("zoo: evicted tenant %s (%s): freed %d ledgered bytes, "
                 "dropped %d compiled program signature(s)",
                 tenant.name, reason, freed, dropped)

    def _publish_resident(self) -> None:
        from shifu_tpu.obs import registry

        with self._lock:
            n = sum(1 for t in self._tenants.values()
                    if t.state == RESIDENT)
        registry().gauge("serve.zoo.resident_tenants").set(n)

    # ---- background tenants (the co-resident trainer plane) ----
    def admit_background(self, name: str,
                         meta: Optional[dict] = None) -> dict:
        """Admit (or re-admit) `name` as a `priority=background` ledger
        tenant. Idempotent: a re-admit clears a pending eviction flag —
        that is how an evicted trainer comes back once pressure
        subsides. Returns the grant info the trainer sizes its stage
        plan from."""
        import jax

        if not _NAME_RE.match(name or ""):
            raise ShifuError(
                ErrorCode.ILLEGAL_ARGUMENT,
                f"background tenant name {name!r} must match "
                f"{_NAME_RE.pattern}")
        with self._lock:
            if self._closed:
                raise ValueError("zoo is closed")
            if name in self._tenants:
                raise ValueError(
                    f"{name!r} is a registered serving tenant — pick a "
                    "different -Dshifu.coresident.tenant name")
            bt = self._background.get(name)
            if bt is None:
                bt = BackgroundTenant(name, self._reg_seq, meta)
                self._reg_seq += 1
                self._background[name] = bt
                log.info("zoo: admitted background tenant %s", name)
            else:
                bt.evict_requested = False
                if meta:
                    bt.meta.update(meta)
        free = (max(0, self.ledger.budget_bytes - self.ledger.used)
                if self.ledger.budget_bytes else None)
        return {"freeBytes": free, "devices": len(jax.devices())}

    def _get_background(self, name: str) -> BackgroundTenant:
        with self._lock:
            bt = self._background.get(name)
        if bt is None:
            raise KeyError(
                f"unknown background tenant {name!r} "
                f"(admitted: {sorted(self._background)})")
        return bt

    def background_acquire(self, name: str, nbytes: int) -> None:
        """Fit-or-fail: a background tenant NEVER triggers eviction —
        the trainer waits out serving pressure instead of creating
        it."""
        bt = self._get_background(name)
        if bt.evict_requested:
            raise LedgerFullError(
                f"background tenant {name} is flagged for eviction — "
                "heartbeat, checkpoint, and re-admit", int(nbytes))
        self.ledger.acquire(name, "background", int(nbytes))

    def background_reduce(self, name: str, nbytes: int) -> None:
        self._get_background(name)
        self.ledger.reduce(name, "background", int(nbytes))

    def background_heartbeat(self, name: str, epoch: int) -> bool:
        """Record training progress; returns True when the zoo wants
        the devices back (the trainer then checkpoints + releases)."""
        bt = self._get_background(name)
        with self._lock:
            bt.epoch = max(bt.epoch, int(epoch))
            return bt.evict_requested

    def background_release(self, name: str, final: bool = False) -> None:
        """Drop the tenant's whole charge. `final=True` (training
        completed) forgets the tenant; an eviction release keeps the
        record so `/healthz` still lists the checkpointed epoch."""
        bt = self._get_background(name)
        self.ledger.release(name, "background")
        if final:
            with self._lock:
                self._background.pop(name, None)
            log.info("zoo: background tenant %s completed and released",
                     name)

    def _claim_background_victim(self) -> Optional[BackgroundTenant]:
        with self._lock:
            candidates = [bt for bt in self._background.values()
                          if not bt.evict_requested]
        candidates = [bt for bt in candidates
                      if self.ledger.charge_of(bt.name, "background") > 0]
        if not candidates:
            return None
        return min(candidates, key=lambda bt: bt.reg_seq)

    def _evict_background(self, bt: BackgroundTenant,
                          reason: str = "pressure_background") -> int:
        """Flag + immediate charge drop. The trainer sees the flag at
        its next epoch-boundary heartbeat and frees its device buffers
        then — the byte-accounting grace window is bounded by one
        training epoch."""
        from shifu_tpu.obs import registry as obs_registry

        with self._lock:
            bt.evict_requested = True
            bt.evictions += 1
        freed = self.ledger.release(bt.name, "background")
        obs_registry().counter("serve.zoo.evictions",
                               tenant=bt.name, reason=reason).inc()
        log.warning("zoo: evicted background tenant %s (%s): freed %d "
                    "ledgered bytes (trainer checkpoints at its next "
                    "heartbeat, epoch %d last seen)",
                    bt.name, reason, freed, bt.epoch)
        return freed

    # ---- scoring ----
    def _cold_retry_after(self, tenant: ZooTenant) -> float:
        """Retry-After for a cold/admitting tenant, from OBSERVED warm-up
        time: this tenant's last admission, else the zoo-wide EMA, else
        the -Dshifu.serve.zoo.warmupMs fallback — minus what an in-
        flight admission has already spent, clamped."""
        with self._lock:
            est = tenant.warm_seconds
            if est is None:
                est = self._warm_ema
            if est is None:
                est = zoo_warmup_ms_setting() / 1000.0
            if tenant.state == ADMITTING and tenant.admit_started:
                est -= time.monotonic() - tenant.admit_started
        return min(max(est, COLD_RETRY_MIN_S), COLD_RETRY_MAX_S)

    def score_batch(self, name: str, records: Sequence[dict],
                    timeout: Optional[float] = None, trace=None):
        """Score on tenant `name`. Resident: the ordinary routed path
        (LRU touched). Cold: kick a background admission and raise
        ColdStartError — the caller answers 429 + Retry-After; the
        admission queue never blocks behind a compile.

        `records` is a list of dicts (JSON) or an already-columnar
        batch (a decoded binary wire payload) — both flow through the
        tenant fleet unchanged. The per-bucket staging buffers a
        tenant's registries allocate for the one-device_put handoff are
        charged to this ledger exactly once, via memory_analysis()'s
        stagingBytes inside residentBytes (the same true-up that prices
        weights and compiled programs)."""
        from shifu_tpu.obs import registry

        from shifu_tpu.serve.queue import RejectedError

        tenant = self._get(name)
        for _attempt in (0, 1):
            with self._lock:
                resident = tenant.state == RESIDENT
                if resident:
                    tenant.last_used = time.monotonic()
                    tenant.requests += 1
                    scorer = tenant.scorer
            if resident:
                if trace is not None:
                    trace.annotate(tenant=name)
                kw = {} if timeout is None else {"timeout": timeout}
                return scorer.score_batch(records, trace=trace, **kw)
            try:
                self.ensure_resident(name, wait=False)
            except ColdStartError:
                registry().counter("serve.zoo.cold_shed",
                                   tenant=name).inc()
                raise
            except RuntimeError as e:
                # zoo closed mid-request: the standard shutdown
                # rejection, not a 500
                raise RejectedError("closed") from e
            # no ColdStartError: the admission RACED IN between the
            # resident check and here — loop once and score instead of
            # telling a served tenant's client to come back later
        registry().counter("serve.zoo.cold_shed", tenant=name).inc()
        raise ColdStartError(name, self._cold_retry_after(tenant))

    def fleet_of(self, name: str) -> ReplicaFleet:
        """The tenant's resident fleet (raises if not resident)."""
        tenant = self._get(name)
        with self._lock:
            if tenant.state != RESIDENT or tenant.fleet is None:
                raise ValueError(f"tenant {name} is {tenant.state}")
            return tenant.fleet

    # ---- per-tenant continuous-loop seams ----
    def _wire_loop(self, tenant: ZooTenant, fleet: ReplicaFleet) -> None:
        """Per-tenant traffic-log stream + label columns, created on
        first admission (needs the registry's input columns) and kept
        ACROSS evictions — a tenant's logged traffic and drift history
        belong to the tenant, not to one residency."""
        from shifu_tpu.loop import log_sample_setting
        from shifu_tpu.loop.traffic import TrafficLog, traffic_columns

        if tenant.traffic is not None or log_sample_setting() <= 0.0:
            return
        input_columns = list(fleet.input_columns)
        label_cols = []
        mc = tenant.model_config
        if mc is not None:
            for extra_col in (mc.data_set.target_column_name,
                              mc.data_set.weight_column_name):
                if (extra_col and extra_col not in label_cols
                        and extra_col not in input_columns):
                    label_cols.append(extra_col)
        tenant.label_cols = label_cols
        tenant.traffic = TrafficLog(
            self.root, traffic_columns(input_columns + label_cols),
            stream=tenant.name, writer=self.writer)

    def _observer(self, tenant: ZooTenant) -> Callable:
        """The per-replica post-resolution hook for ONE tenant: its own
        traffic stream, its own shadow observer, its own drift cadence
        against its own fleet's health — the single-tenant server's
        _observe, owned per set."""

        def observe(replica, data, result):
            if tenant.traffic is not None:
                tenant.traffic.record(
                    data, result,
                    getattr(replica.registry, "scored_sha",
                            replica.registry.sha))
            replica.registry.observe(data, result)
            fleet = tenant.fleet
            drift = tenant.drift
            if fleet is None or drift is None:
                return
            with tenant._obs_lock:
                tenant.observed_batches += 1
                check = (tenant.observed_batches
                         % self._drift_check_every == 0)
            if check:
                # outside the cadence lock (forces a d2h flush, SH203)
                tenant.last_drift_verdict = drift.check_degrade(
                    fleet.health, self.root, model_sha=fleet.sha,
                    reporter=self.writer)

        return observe

    def _busy_guard(self, tenant: ZooTenant, op: str):
        with self._lock:
            if tenant.busy is not None:
                raise ValueError(
                    f"tenant {tenant.name} {tenant.busy} in progress — "
                    "retry when it completes")
            tenant.busy = op

    def _busy_clear(self, tenant: ZooTenant) -> None:
        with self._lock:
            tenant.busy = None

    def stage(self, name: str, models_dir: str) -> Optional[dict]:
        """STREAMED shadow stage for one tenant: the candidate's weights
        land layer-group by layer-group, each group ledger-acquired
        (evicting cold tenants as needed) before its device_put — a
        stage on a near-full budget cannot OOM, and the ledger's peak
        proves residency never left the budget."""
        tenant = self._get(name)
        # busy FIRST, residency second: the busy flag is what shields
        # this tenant from a concurrent admission's LRU eviction — the
        # other order leaves a gap where ensure_resident's fleet is
        # torn down before the stage touches it
        self._busy_guard(tenant, "stage")
        try:
            self.ensure_resident(name)
            fleet = tenant.fleet
            snap = fleet.stage(
                models_dir,
                column_configs=tenant.column_configs,
                model_config=tenant.model_config,
                drift=tenant.drift,
                put_hook=lambda n: self._acquire(tenant, "shadow", n))
            # true-up the staged programs' compiled footprint
            ma = fleet.memory_analysis()
            shadow_bytes = sum(
                int(r.get("shadow", {}).get("residentBytes", 0))
                for r in ma["replicas"])
            charged = self.ledger.charge_of(tenant.name, "shadow")
            if shadow_bytes > charged:
                self._acquire(tenant, "shadow", shadow_bytes - charged)
            elif shadow_bytes < charged:
                self.ledger.reduce(tenant.name, "shadow",
                                   charged - shadow_bytes)
            with self._lock:
                tenant.shadow_staged = True
            return snap
        except BaseException:
            # roll the partial stage back everywhere so the ledger's
            # shadow charge and the device agree again
            try:
                fleet = tenant.fleet
                if fleet is not None:
                    fleet.unstage()
            except Exception as ue:  # rollback is best-effort
                log.warning("zoo: unstage after failed stage on %s: %s",
                            name, ue)
            self.ledger.release(tenant.name, "shadow")
            with self._lock:
                tenant.shadow_staged = False
            raise
        finally:
            self._busy_clear(tenant)

    def unstage(self, name: str) -> None:
        tenant = self._get(name)
        self._busy_guard(tenant, "unstage")
        try:
            fleet = tenant.fleet
            if fleet is not None:
                fleet.unstage()
            self.ledger.release(tenant.name, "shadow")
            # re-price from measurement: buckets the SHADOW compiled
            # while staged were charged to "active" by _reprice and
            # just freed with the unstage — without this the charge
            # overstates residency until the next promote/evict
            if fleet is not None:
                measured = fleet.memory_analysis()["residentBytes"]
                charged = self.ledger.charge_of(tenant.name)
                if measured < charged:
                    self.ledger.reduce(tenant.name, "active",
                                       charged - measured)
            with self._lock:
                tenant.shadow_staged = False
        finally:
            self._busy_clear(tenant)

    def shadow_snapshot(self, name: str) -> Optional[dict]:
        tenant = self._get(name)
        fleet = tenant.fleet
        return None if fleet is None else fleet.shadow_snapshot()

    def promote(self, name: str, expected_sha: Optional[str] = None,
                step_cb: Optional[Callable] = None) -> dict:
        """Rolling promote for one tenant; afterwards the OLD active
        version's ledger charge is released and the shadow's charge
        becomes the active one — residency shrinks back to one version
        per replica, with the whole sequence inside the budget."""
        tenant = self._get(name)
        # busy first (shields against LRU eviction), then the resident
        # check is race-free
        self._busy_guard(tenant, "promote")
        try:
            with self._lock:
                fleet = tenant.fleet
                if tenant.state != RESIDENT or fleet is None:
                    raise ValueError(
                        f"tenant {name} is {tenant.state} — nothing to "
                        "promote")
            swap = fleet.promote(expected_sha, step_cb=step_cb)
            # re-price from MEASUREMENT, not bookkeeping: the promoted
            # fleet's residency replaces both old charges, and the
            # blind release+transfer would drop bytes _reprice charged
            # to "active" for buckets the SHADOW compiled while staged
            # (those programs are the new active and still resident)
            self.ledger.release(tenant.name, "active")
            self.ledger.transfer(tenant.name, "shadow", "active")
            measured = fleet.memory_analysis()["residentBytes"]
            charged = self.ledger.charge_of(tenant.name)
            if measured > charged:
                self._acquire(tenant, "active", measured - charged)
            elif measured < charged:
                self.ledger.reduce(tenant.name, "active",
                                   charged - measured)
            with self._lock:
                tenant.shadow_staged = False
                tenant.active_dir = tenant.fleet.active_models_dir
            tenant.fleet.health.clear_degraded()
            if tenant.drift is not None:
                tenant.drift.reset()
            tenant.last_drift_verdict = None
            return swap
        finally:
            self._busy_clear(tenant)

    # ---- surfaces ----
    def admitting_tenants(self) -> List[str]:
        with self._lock:
            return sorted(t.name for t in self._tenants.values()
                          if t.state == ADMITTING)

    def fleet_health_snapshot(self) -> dict:
        """Process-level health for a zoo server: aggregated over the
        RESIDENT tenants' fleet health only. An evicted tenant's torn-
        down fleet must not make /healthz report the process as
        draining — eviction is budget management, not shutdown; a zoo
        with zero resident tenants still admits cold starts and is
        `ok`."""
        with self._lock:
            resident = [(t.name, t.fleet)
                        for t in self._tenants.values()
                        if t.state == RESIDENT and t.fleet is not None]
        per = {}
        crashes = 0
        reasons = []
        draining = bool(resident)
        for name, fleet in resident:
            s = fleet.health_snapshot()
            per[name] = s
            crashes += int(s.get("workerCrashes", 0))
            if s["status"] == "degraded":
                reasons.append(
                    f"tenant {name}"
                    + (f": {s['reason']}" if s.get("reason") else ""))
            if s["status"] != "draining":
                draining = False
        if draining:
            status, reason = "draining", "all tenants draining"
        elif reasons:
            status, reason = "degraded", "; ".join(reasons)
        else:
            status, reason = "ok", ""
        return {"status": status, "reason": reason,
                "workerCrashes": crashes, "tenantsHealth": per}

    def health_snapshot(self) -> dict:
        """The /healthz `zoo` section: budget occupancy + per-tenant
        state. `residentTenants`/`hbmBudgetUsedMB` are the headline
        numbers; a non-sticky cold_start degrade reason is computed by
        the server from `admitting`."""
        ledger = self.ledger.snapshot()
        with self._lock:
            tenants = {name: t.snapshot()
                       for name, t in sorted(self._tenants.items())}
            resident = sum(1 for t in self._tenants.values()
                           if t.state == RESIDENT)
            admitting = sorted(t.name for t in self._tenants.values()
                               if t.state == ADMITTING)
            background = {name: bt.snapshot()
                          for name, bt in
                          sorted(self._background.items())}
        for name, snap in background.items():
            snap["hbmMB"] = round(
                self.ledger.charge_of(name, "background") / MB, 3)
        return {
            "tenants": tenants,
            "residentTenants": resident,
            "admitting": admitting,
            "background": background,
            "hbmBudgetMB": ledger["budgetMB"],
            "hbmBudgetUsedMB": ledger["usedMB"],
            "hbmPeakUsedMB": ledger["peakMB"],
        }

    def snapshot(self) -> dict:
        """Manifest view: ledger + per-tenant detail incl. resident
        fleet snapshots. After close(), the snapshot taken at the START
        of the drain is returned — the shutdown manifest must describe
        what was serving, not the post-teardown rubble."""
        closed = getattr(self, "_closed_snapshot", None)
        if closed is not None:
            return closed
        out = {
            "ledger": self.ledger.snapshot(),
            "tenants": {},
        }
        with self._lock:
            items = list(self._tenants.items())
            out["background"] = {name: bt.snapshot()
                                 for name, bt in
                                 sorted(self._background.items())}
        for name, tenant in sorted(items):
            snap = tenant.snapshot()
            fleet = tenant.fleet
            if fleet is not None and tenant.state == RESIDENT:
                try:
                    snap["fleet"] = fleet.snapshot()
                    snap["memory"] = fleet.memory_analysis()
                except Exception as se:  # manifest must not fail on a
                    # mid-transition tenant
                    snap["fleetError"] = f"{type(se).__name__}: {se}"
            if tenant.traffic is not None:
                snap["traffic"] = tenant.traffic.snapshot()
            out["tenants"][name] = snap
        return out

    def close(self, timeout: Optional[float] = 30.0) -> None:
        """Drain every resident tenant and flush its traffic stream.
        The zoo is FENCED first (no new admissions accepted), then
        in-flight background admissions are waited out; one that
        outlasts the bounded wait finds the fence at its final flip and
        tears its fleet down instead of resurrecting a closed zoo."""
        with self._lock:
            self._closed = True
            pending = [t.admit_event for t in self._tenants.values()
                       if t.state == ADMITTING
                       and t.admit_event is not None]
        for event in pending:
            event.wait(timeout if timeout is not None else 60.0)
        self._closed_snapshot = self.snapshot()
        with self._lock:
            tenants = list(self._tenants.values())
        for tenant in tenants:
            fleet = tenant.fleet
            if fleet is not None:
                fleet.close(timeout)
                fleet.release()
            if tenant.traffic is not None:
                tenant.traffic.close()
            with self._lock:
                tenant.state = COLD
                tenant.fleet = None
                tenant.scorer = None
        with self._lock:
            backgrounds = list(self._background.values())
        for bt in backgrounds:
            # the trainer's own process frees its buffers; the closing
            # zoo just zeroes the accounting
            self.ledger.release(bt.name, "background")
        self._publish_resident()
