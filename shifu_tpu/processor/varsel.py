"""`shifu varsel` — variable selection.

Parity: core/processor/VarSelectModelProcessor.java:121 — auto-filter, force
select/remove files, filter by KS/IV/MIX/PARETO (:181-187), FI for tree
models (:188), SE/ST sensitivity wrapper (train a model then rank columns by
knockout error delta, distributedSEWrapper :633), -list/-reset/-recover.
"""

from __future__ import annotations

import json
import os
import shutil
from typing import List, Optional

import numpy as np

from shifu_tpu.config.column_config import ColumnFlag
from shifu_tpu.config.model_config import Algorithm
from shifu_tpu.processor.basic import BasicProcessor
from shifu_tpu.utils.errors import ErrorCode, ShifuError
from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)


class VarSelProcessor(BasicProcessor):
    step = "varsel"

    def __init__(
        self,
        root: str = ".",
        list_vars: bool = False,
        reset: bool = False,
        recover: bool = False,
    ):
        super().__init__(root)
        self.list_vars = list_vars
        self.reset = reset
        self.recover = recover

    def _backup_path(self) -> str:
        return os.path.join(self.paths.varsel_dir(), "ColumnConfig.json.prevarsel")

    def run_step(self) -> None:
        self.setup()
        mc = self.model_config
        assert mc is not None

        if self.list_vars:
            for c in self.column_configs:
                if c.final_select:
                    log.info("selected: %s (ks=%.4f iv=%.4f)", c.column_name,
                             c.column_stats.ks or 0, c.column_stats.iv or 0)
            log.info("%d variables selected.",
                     sum(1 for c in self.column_configs if c.final_select))
            return
        if self.reset:
            for c in self.column_configs:
                c.final_select = False
            self.save_column_configs()
            log.info("finalSelect reset for all columns.")
            return
        if self.recover:
            bak = self._backup_path()
            if not os.path.isfile(bak):
                raise ShifuError(ErrorCode.COLUMN_CONFIG_NOT_FOUND,
                                 f"no varsel backup at {bak}")
            shutil.copy(bak, self.paths.column_config_path())
            log.info("ColumnConfig recovered from %s", bak)
            return

        # backup before changing anything (-recover support)
        self.paths.ensure(self.paths.varsel_dir())
        shutil.copy(self.paths.column_config_path(), self._backup_path())

        vs = mc.var_select
        self._apply_force_files(vs)

        if vs.force_enable:
            from shifu_tpu.varsel.selector import auto_filter

            corr, names = self._load_correlation()
            res = auto_filter(
                self.column_configs,
                missing_rate_threshold=vs.missing_rate_threshold,
                min_ks=vs.min_ks_threshold or 0.0,
                min_iv=vs.min_iv_threshold or 0.0,
                correlation=corr,
                correlation_names=names,
                correlation_threshold=vs.correlation_threshold,
            )
            for name, why in res.removed.items():
                log.info("auto-filter removed %s: %s", name, why)

        filter_by = (vs.filter_by or "KS").upper()
        if filter_by in ("SE", "ST"):
            scores = self._sensitivity(filter_by)
            self._select_by_scores(scores, vs.filter_num)
        elif filter_by == "FI":
            scores = self._feature_importance()
            self._select_by_scores(scores, vs.filter_num)
        elif filter_by in ("VOTED", "V"):
            scores = self._voted(vs)
            self._select_by_scores(scores, vs.wrapper_num or vs.filter_num)
        else:
            from shifu_tpu.varsel.selector import select_by_filter

            selected = select_by_filter(
                self.column_configs, filter_by, vs.filter_num, vs.filter_enable
            )
            log.info("selected %d variables by %s.", len(selected), filter_by)

        self.save_column_configs()
        n = sum(1 for c in self.column_configs if c.final_select)
        log.info("varsel done: %d variables final-selected.", n)

    # ---- helpers ----
    def _apply_force_files(self, vs) -> None:
        """force_select/force_remove column-name files
        (VarSelectModelProcessor force list loading)."""

        def load_names(path: Optional[str]) -> List[str]:
            if not path:
                return []
            p = self.resolve(path)
            if not os.path.isfile(p):
                return []
            with open(p) as fh:
                return [ln.strip() for ln in fh if ln.strip()]

        force_sel = set(load_names(vs.force_select_column_name_file))
        force_rem = set(load_names(vs.force_remove_column_name_file))
        for c in self.column_configs:
            if c.column_name in force_sel and c.is_feature():
                c.column_flag = ColumnFlag.FORCE_SELECT
            elif c.column_name in force_rem and c.is_feature():
                c.column_flag = ColumnFlag.FORCE_REMOVE
                c.final_select = False

    def _load_correlation(self):
        path = self.paths.correlation_path()
        if not os.path.isfile(path):
            return None, None
        import pandas as pd

        df = pd.read_csv(path, index_col=0)
        return df.to_numpy(), list(df.columns)

    def _select_by_scores(self, scores_by_name: dict, filter_num: int) -> None:
        for c in self.column_configs:
            if not c.is_force_select():
                c.final_select = False
        n_force = 0
        for c in self.column_configs:
            if c.is_force_select():
                c.final_select = True
                n_force += 1
        ranked = sorted(scores_by_name.items(), key=lambda kv: -kv[1])
        by_name = {c.column_name: c for c in self.column_configs}
        budget = max(0, filter_num - n_force)
        for name, score in ranked[:budget]:
            cc = by_name.get(name)
            if cc is not None and cc.is_feature() and not cc.is_force_remove():
                cc.final_select = True

    def _sensitivity(self, se_type: str) -> dict:
        """SE/ST wrapper: quick NN train on all candidates, then knockout
        scan. Writes se.csv (column, score) like the reference's SE report."""
        from shifu_tpu.norm.dataset import load_normalized
        from shifu_tpu.train.nn_trainer import NNTrainConfig, train_nn
        from shifu_tpu.varsel.selector import sensitivity_scores

        norm_dir = self.paths.normalized_data_dir()
        if not os.path.isdir(norm_dir):
            raise ShifuError(ErrorCode.DATA_NOT_FOUND,
                             f"{norm_dir} — run `shifu norm` first")
        meta, feats, tags, weights = load_normalized(norm_dir)
        feats = np.asarray(feats, np.float32)
        tags = np.asarray(tags, np.float32)
        cfg = NNTrainConfig.from_model_config(self.model_config)
        cfg.num_epochs = min(cfg.num_epochs, 50)  # wrapper model, not final
        res = train_nn(feats, tags, np.asarray(weights, np.float32), cfg)
        scores = sensitivity_scores(
            [{k: np.asarray(v) for k, v in layer.items()} for layer in res.params],
            cfg.activations, feats, tags, se_type,
        )
        # meta.columns are norm-plan OUTPUT names; under ONEHOT-style norms a
        # source column expands to several outputs (col_0, col_1, ...) that
        # never match ColumnConfig names. Map outputs back to their source
        # column (mapping persisted at norm time — reconstructing the plan
        # here would diverge if configs changed since norm) and keep the max
        # knockout score per source.
        src_of = (meta.extra or {}).get("sourceOf")
        if not src_of:
            log.warning(
                "normalized data predates the persisted sourceOf mapping; "
                "reconstructing from current configs — re-run `shifu norm` "
                "if configs changed since, or scores may map to no column"
            )
            from shifu_tpu.norm.normalizer import build_norm_plan

            src_of = build_norm_plan(
                self.model_config, self.column_configs
            ).source_of
        out: dict = {}
        for name, s in zip(meta.columns, scores):
            src = src_of.get(name, name)
            out[src] = max(out.get(src, float("-inf")), float(s))
        with open(os.path.join(self.paths.varsel_dir(), "se.csv"), "w") as fh:
            fh.write("column,score\n")
            for name, s in sorted(out.items(), key=lambda kv: -kv[1]):
                fh.write(f"{name},{s:.8g}\n")
        log.info("%s sensitivity computed for %d columns -> se.csv",
                 se_type, len(out))
        return out

    def _voted(self, vs) -> dict:
        """Voted selection (dvarsel): the GA wrapper proposes candidate
        variable subsets, every generation trains/validates the WHOLE
        population as one vmapped program, and the best seed wins
        (core/dvarsel/VarSelMaster.java:39, wrapper/CandidateGenerator).
        Scores: best-seed members rank first (1 + vote share), the rest by
        final-population vote share — so _select_by_scores keeps the seed."""
        from shifu_tpu.norm.dataset import load_normalized
        from shifu_tpu.varsel.voted import VotedConfig, voted_selection

        norm_dir = self.paths.normalized_data_dir()
        if not os.path.isdir(norm_dir):
            raise ShifuError(ErrorCode.DATA_NOT_FOUND,
                             f"{norm_dir} — run `shifu norm` first")
        meta, feats, tags, weights = load_normalized(norm_dir)
        feats = np.asarray(feats, np.float32)
        tags = np.asarray(tags, np.float32)
        weights = np.asarray(weights, np.float32)
        params = vs.params or {}
        # candidates train the model's CONFIGURED network, not a fixed
        # surrogate (ValidationConductor.java trains the configured net)
        cfg = VotedConfig.from_model_config(
            self.model_config,
            expect_var_count=int(params.get(
                "expect_variable_cnt", vs.wrapper_num or 20)),
            population_size=int(params.get("population_live_size", 30)),
            generations=int(params.get("population_multiply_cnt", 5)),
            cross_percent=int(params.get("hybrid_percent", 60)),
            mutation_percent=int(params.get("mutation_percent", 20)),
        )
        best, votes = voted_selection(feats, tags, weights, cfg)

        # map normalized output columns back to source columns (one-hot
        # expansion etc.), same as the SE path
        src_of = (meta.extra or {}).get("sourceOf") or {}
        best_set = set(best)
        out: dict = {}
        for j, name in enumerate(meta.columns):
            src = src_of.get(name, name)
            score = (1.0 + float(votes[j])) if j in best_set else float(votes[j])
            out[src] = max(out.get(src, float("-inf")), score)
        with open(os.path.join(self.paths.varsel_dir(), "voted.csv"), "w") as fh:
            fh.write("column,score\n")
            for name, s in sorted(out.items(), key=lambda kv: -kv[1]):
                fh.write(f"{name},{s:.6g}\n")
        log.info("voted selection: best seed has %d columns", len(best))
        return out

    def _feature_importance(self) -> dict:
        """FI filter: requires a trained tree model
        (VarSelectModelProcessor.java:188 selectByFeatureImportance)."""
        from shifu_tpu.eval.scorer import find_model_paths
        from shifu_tpu.models.tree import TreeModelSpec
        from shifu_tpu.varsel.importance import tree_feature_importance

        paths = [p for p in find_model_paths(self.paths.models_dir())
                 if p.endswith((".gbt", ".rf"))]
        if not paths:
            raise ShifuError(
                ErrorCode.MODEL_NOT_FOUND,
                "FI filter needs a trained GBT/RF model; run `shifu train`",
            )
        spec = TreeModelSpec.load(paths[0])
        return tree_feature_importance(spec)
