"""Columnar binary scoring wire format (zero-copy request path).

The JSON request path pays three per-row Python taxes before a batch
ever reaches the fused program: json parse into dicts, a per-cell
``str()`` in ``records_to_columnar``, and a per-cell pandas re-parse
back into numbers. On a fleet whose device time is sub-millisecond that
host work IS the p99 (the PR-13 stage breakdown measures featurize at
~0.45-0.64 of it) — the reference kept data off the coordinator's
interpreter entirely (Pig mappers moved bytes, not objects); this is
that discipline on the wire.

``POST /score`` (and ``/score/<set>``) accepts this format next to JSON,
negotiated by Content-Type (``application/x-shifu-columnar``); JSON
stays the default. A binary batch decodes into TYPED numpy column
views via ``np.frombuffer`` — no per-value Python objects on the
numeric path — and the typed columns short-circuit the featurize parse
(data/reader.py), so both formats converge on bit-identical
``(values, codes)`` arrays (parity pinned in tests/test_serve.py).

Layout, all little-endian, one header then ``n_cols`` column blocks::

    offset  size  field
    0       4     magic  b"SHWB"
    4       2     version (u16) = 1
    6       4     n_rows  (u32)
    10      4     n_cols  (u32)

    per column, sequentially:
    +0      2     name_len (u16)
    +2      var   column name (UTF-8, name_len bytes)
    ..      1     type code (u8)
    ..      var   payload (by type, below)

    type  code  payload
    f64   1     n_rows x 8 bytes (IEEE doubles)
    i64   2     n_rows x 8 bytes (two's-complement)
    f32   3     n_rows x 4 bytes
    i32   4     n_rows x 4 bytes
    str   5     (n_rows+1) x 4 byte u32 offsets, then offsets[-1]
                bytes of concatenated UTF-8; row i is
                bytes[offsets[i]:offsets[i+1]]

Parity discipline (why the encoder defaults to f64/i64, never f32/i32):
the JSON path stringifies every value and re-parses, so a numeric wire
column must decode to the SAME doubles that round-trip produces —
``str(float)`` round-trips IEEE doubles exactly (f64 safe) and
``str(int)`` has no ``.0`` suffix (so integers need i64, not f64, or
their categorical string form would diverge). f32/i32 are accepted on
decode for clients that know their columns are pure-numeric and can
tolerate the narrower type. Missing values are NaN in float columns
(the JSON ``null`` analog); integer and string columns carry no NaN —
encode a column with missing integers as f64 or str.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Sequence

import numpy as np

from shifu_tpu.data.reader import ColumnarData
from shifu_tpu.utils import environment

MAGIC = b"SHWB"
VERSION = 1
CONTENT_TYPE = "application/x-shifu-columnar"

TYPE_F64 = 1
TYPE_I64 = 2
TYPE_F32 = 3
TYPE_I32 = 4
TYPE_STR = 5

_DTYPES = {
    TYPE_F64: np.dtype("<f8"),
    TYPE_I64: np.dtype("<i8"),
    TYPE_F32: np.dtype("<f4"),
    TYPE_I32: np.dtype("<i4"),
}
_TYPE_OF_KIND = {"<f8": TYPE_F64, "<i8": TYPE_I64,
                 "<f4": TYPE_F32, "<i4": TYPE_I32}

_HEADER = struct.Struct("<4sHII")

DEFAULT_MAX_BODY_MB = 64.0


def max_body_bytes() -> int:
    """shifu.serve.wire.maxBodyMB — largest binary request body the
    server will decode (a bounds check before any allocation sized
    from untrusted header fields)."""
    return int(environment.get_float("shifu.serve.wire.maxBodyMB",
                                     DEFAULT_MAX_BODY_MB)
               * 1024.0 * 1024.0)


class WireFormatError(ValueError):
    """Malformed binary batch — the server answers 400, never a 500."""


# ---- shared column typing (the JSON path converges here) ----
def column_from_values(values: Sequence) -> np.ndarray:
    """One request column from raw JSON values -> the typed (or string)
    array BOTH wire formats produce, so parity between them is
    structural, not coincidental:

      all float/None  -> f64 (None = NaN; str(float) round-trips, so
                         the stringified-JSON path parses back to the
                         identical double)
      all int         -> i64 (kept integral: str(1.0) is "1.0" but a
                         categorical column must see "1")
      anything else   -> object strings, the pre-wire representation
                         (None -> "" missing token, str(v) otherwise;
                         bools and mixed int/float land here — their
                         string forms are not float-reconstructible)
    """
    kinds = set(map(type, values))
    if kinds and kinds <= {float, type(None)}:
        return np.asarray([np.nan if v is None else v for v in values],
                          dtype=np.float64)
    if kinds == {int}:
        try:
            return np.asarray(values, dtype=np.int64)
        except OverflowError:  # > 64-bit ints: stringify like JSON did
            pass
    return np.asarray(["" if v is None else str(v) for v in values],
                      dtype=object)


def conform_columns(data: ColumnarData,
                    columns: Sequence[str]) -> ColumnarData:
    """Reshape a decoded batch to the serving schema: keep the typed
    arrays of columns the client sent, synthesize absent columns as the
    empty missing token (exactly what an absent JSON field becomes).
    Extra client columns are dropped."""
    raw: Dict[str, np.ndarray] = {}
    for c in columns:
        if isinstance(data.raw, dict) and c in data.raw:
            raw[c] = data.raw[c]
        elif c in data.names:
            raw[c] = np.asarray(data.column(c), dtype=object)
        else:
            raw[c] = np.full(data.n_rows, "", dtype=object)
    out = ColumnarData(names=list(columns), raw=raw, n_rows=data.n_rows,
                       missing_values=data.missing_values)
    out.wire_format = getattr(data, "wire_format", "json")
    return out


# ---- encode ----
def encode(data: ColumnarData) -> bytes:
    """Reference encoder: a ColumnarData (typed or string columns) ->
    one wire payload. Typed numeric columns serialize as raw
    little-endian buffers; everything else as offset-indexed UTF-8."""
    parts = [_HEADER.pack(MAGIC, VERSION, data.n_rows, len(data.names))]
    for name in data.names:
        col = (data.raw[name] if isinstance(data.raw, dict)
               else data.column(name))
        nb = name.encode("utf-8")
        parts.append(struct.pack("<H", len(nb)))
        parts.append(nb)
        arr = np.asarray(col)
        code = _TYPE_OF_KIND.get(arr.dtype.newbyteorder("<").str)
        if code is not None:
            parts.append(struct.pack("<B", code))
            parts.append(np.ascontiguousarray(
                arr.astype(arr.dtype.newbyteorder("<"),
                           copy=False)).tobytes())
            continue
        encoded = [("" if v is None else str(v)).encode("utf-8")
                   for v in col]
        offsets = np.zeros(len(encoded) + 1, dtype=np.uint32)
        np.cumsum([len(b) for b in encoded], out=offsets[1:])
        parts.append(struct.pack("<B", TYPE_STR))
        parts.append(offsets.tobytes())
        parts.append(b"".join(encoded))
    return b"".join(parts)


def encode_records(records: Sequence[dict],
                   columns: Optional[Sequence[str]] = None) -> bytes:
    """JSON-style records -> one wire payload (the bench/CI client
    side). Columns default to first-seen key order across records."""
    if columns is None:
        columns = []
        for r in records:
            for k in r:
                if k not in columns:
                    columns.append(k)
    raw = {c: column_from_values([r.get(c) for r in records])
           for c in columns}
    return encode(ColumnarData(names=list(columns), raw=raw,
                               n_rows=len(records)))


# ---- decode ----
def _need(payload: bytes, offset: int, size: int, what: str) -> None:
    if size < 0 or offset + size > len(payload):
        raise WireFormatError(
            f"truncated payload: {what} needs {size} bytes at offset "
            f"{offset}, body is {len(payload)} bytes")


def _decode_strings(payload: bytes, offset: int,
                    n_rows: int, name: str) -> tuple:
    """(object array of row strings, next offset) — u32 offsets then
    concatenated UTF-8."""
    osize = (n_rows + 1) * 4
    _need(payload, offset, osize, f"column {name!r} string offsets")
    offs = np.frombuffer(payload, dtype="<u4", count=n_rows + 1,
                         offset=offset)
    offset += osize
    if offs[0] != 0 or (np.diff(offs.astype(np.int64)) < 0).any():
        raise WireFormatError(
            f"column {name!r} string offsets are not monotone from 0")
    nbytes = int(offs[-1])
    _need(payload, offset, nbytes, f"column {name!r} string bytes")
    blob = payload[offset:offset + nbytes]
    offset += nbytes
    try:
        text = blob.decode("utf-8")
    except UnicodeDecodeError as e:
        raise WireFormatError(
            f"column {name!r} string bytes are not UTF-8: {e}") from None
    out = np.empty(n_rows, dtype=object)
    if len(text) == nbytes:  # pure ASCII: byte offsets == char offsets
        for i in range(n_rows):
            out[i] = text[offs[i]:offs[i + 1]]
    else:
        for i in range(n_rows):
            out[i] = blob[offs[i]:offs[i + 1]].decode("utf-8")
    return out, offset


def decode(payload: bytes) -> ColumnarData:
    """One wire payload -> a ColumnarData whose numeric columns are
    zero-copy ``np.frombuffer`` views (no per-value Python objects) and
    whose string columns are object arrays. Every malformed shape —
    short header, wrong magic, unknown version or type code, name/
    offset/buffer overruns — raises WireFormatError (a 400, by
    contract never a 500)."""
    _need(payload, 0, _HEADER.size, "header")
    magic, version, n_rows, n_cols = _HEADER.unpack_from(payload, 0)
    if magic != MAGIC:
        raise WireFormatError(f"bad magic {magic!r} (want {MAGIC!r})")
    if version != VERSION:
        raise WireFormatError(
            f"unsupported wire version {version} (speak {VERSION})")
    # a forged column count cannot force a huge allocation: every block
    # below bounds-checks against the actual body before reading, and
    # the minimum per-column cost (3 bytes) caps plausible n_cols
    if n_cols * 3 > len(payload):
        raise WireFormatError(
            f"{n_cols} columns cannot fit a {len(payload)}-byte body")
    offset = _HEADER.size
    names: List[str] = []
    raw: Dict[str, np.ndarray] = {}
    for _ in range(n_cols):
        _need(payload, offset, 2, "column name length")
        (name_len,) = struct.unpack_from("<H", payload, offset)
        offset += 2
        _need(payload, offset, name_len, "column name")
        try:
            name = payload[offset:offset + name_len].decode("utf-8")
        except UnicodeDecodeError as e:
            raise WireFormatError(f"column name is not UTF-8: {e}") \
                from None
        offset += name_len
        if not name or name in raw:
            raise WireFormatError(
                f"empty or duplicate column name {name!r}")
        _need(payload, offset, 1, f"column {name!r} type code")
        type_code = payload[offset]
        offset += 1
        dtype = _DTYPES.get(type_code)
        if dtype is not None:
            size = n_rows * dtype.itemsize
            _need(payload, offset, size, f"column {name!r} values")
            # the zero-copy core: a typed view straight into the
            # request body — the featurizer consumes it without one
            # Python object per value
            raw[name] = np.frombuffer(payload, dtype=dtype,
                                      count=n_rows, offset=offset)
            offset += size
        elif type_code == TYPE_STR:
            raw[name], offset = _decode_strings(payload, offset,
                                                n_rows, name)
        else:
            raise WireFormatError(
                f"column {name!r} has unknown type code {type_code}")
        names.append(name)
    if offset != len(payload):
        raise WireFormatError(
            f"{len(payload) - offset} trailing bytes after the last "
            "column")
    data = ColumnarData(names=names, raw=raw, n_rows=int(n_rows))
    data.wire_format = "binary"
    return data
