"""Deterministic directory listings — the one home for artifact globs.

`os.listdir`/`glob.glob` return entries in readdir order: ext4 hash
order, tmpfs insertion order, object-store lexicographic — different
per host, per filesystem, per run. Any listing that feeds an artifact
writer, a hostsync merge, a checkpoint fingerprint or a retention sweep
must therefore be SORTED before its order can reach bytes, or the
byte-identical multi-host contract (parallel/hostsync.py) silently
breaks. `shifu check` enforces this as SH301 (rules/spmd.py); these two
helpers are the sanctioned spelling, so call sites stay grep-ably
uniform and the sort is impossible to forget.
"""

from __future__ import annotations

import glob as _glob
import os
from typing import List


def sorted_glob(pattern: str, recursive: bool = False) -> List[str]:
    """glob.glob in deterministic (lexicographic) order."""
    return sorted(_glob.glob(pattern, recursive=recursive))


def sorted_listdir(path: str) -> List[str]:
    """os.listdir in deterministic (lexicographic) order."""
    return sorted(os.listdir(path))
