"""Aux processor tests: posttrain, export (PMML/columnstats/woemapping),
encode, manage (save/switch/show), test, convert, analysis, combo."""

import json
import os

import numpy as np
import pytest

from tests.helpers import make_model_set


@pytest.fixture(scope="module")
def trained_root(tmp_path_factory):
    """One fully-trained NN model set shared across this module's tests."""
    root = str(tmp_path_factory.mktemp("ms") / "set")
    make_model_set(root, n_rows=400)
    from shifu_tpu.config.model_config import ModelConfig
    from shifu_tpu.processor.init import InitProcessor
    from shifu_tpu.processor.norm import NormProcessor
    from shifu_tpu.processor.stats import StatsProcessor
    from shifu_tpu.processor.train import TrainProcessor

    mc = ModelConfig.load(os.path.join(root, "ModelConfig.json"))
    mc.train.num_train_epochs = 25
    mc.evals[0].data_set.data_path = mc.data_set.data_path
    mc.evals[0].data_set.header_path = mc.data_set.header_path
    mc.save(os.path.join(root, "ModelConfig.json"))
    assert InitProcessor(root).run() == 0
    assert StatsProcessor(root, correlation=True).run() == 0
    assert NormProcessor(root).run() == 0
    assert TrainProcessor(root).run() == 0
    return root


class TestPostTrain:
    def test_bin_avg_score_and_fi(self, trained_root):
        from shifu_tpu.config import load_column_config_list
        from shifu_tpu.processor.posttrain import PostTrainProcessor

        assert PostTrainProcessor(trained_root).run() == 0
        cols = load_column_config_list(
            os.path.join(trained_root, "ColumnConfig.json"))
        with_avg = [c for c in cols if c.column_binning.bin_avg_score]
        assert len(with_avg) >= 10
        fi_path = os.path.join(trained_root, "tmp", "posttrain",
                               "feature_importance.csv")
        assert os.path.isfile(fi_path)
        lines = open(fi_path).read().strip().splitlines()
        assert len(lines) > 10  # header + columns


class TestExport:
    def test_pmml(self, trained_root):
        from shifu_tpu.processor.export import ExportProcessor

        assert ExportProcessor(trained_root, kind="pmml").run() == 0
        pmml_path = os.path.join(trained_root, "export", "model0.pmml")
        assert os.path.isfile(pmml_path)
        content = open(pmml_path).read()
        assert "NeuralNetwork" in content
        assert "NormContinuous" in content  # z-scale transform embedded
        assert "MapValues" in content or "Discretize" in content
        import xml.etree.ElementTree as ET

        ET.fromstring(content)  # well-formed

    def test_columnstats_and_woemapping(self, trained_root):
        from shifu_tpu.processor.export import ExportProcessor

        assert ExportProcessor(trained_root, kind="columnstats").run() == 0
        assert ExportProcessor(trained_root, kind="woemapping").run() == 0
        assert ExportProcessor(trained_root, kind="correlation").run() == 0
        stats = open(os.path.join(trained_root, "export", "columnstats.csv")).read()
        assert "columnName" in stats and "ks" in stats
        woe = json.load(open(os.path.join(trained_root, "export",
                                          "woemapping.json")))
        assert len(woe) >= 10
        any_col = next(iter(woe.values()))
        assert "woe" in any_col


class TestEncodeManageTest:
    def test_encode_woe(self, trained_root):
        from shifu_tpu.processor.encode import EncodeProcessor

        assert EncodeProcessor(trained_root).run() == 0
        out = os.path.join(trained_root, "tmp", "encode", "EncodedData")
        lines = open(out).read().strip().splitlines()
        assert lines[0].startswith("tag|")
        assert len(lines) > 300

    def test_manage_save_switch_show(self, trained_root):
        from shifu_tpu.processor.manage import ManageProcessor

        assert ManageProcessor("save", "v1", root=trained_root).run() == 0
        assert os.path.isdir(os.path.join(trained_root, ".shifu", "backup",
                                          "v1", "models"))
        # mutate then switch back
        model = os.path.join(trained_root, "models", "model0.nn")
        orig = open(model, "rb").read()
        open(model, "wb").write(b"garbage")
        assert ManageProcessor("switch", "v1", root=trained_root).run() == 0
        assert open(model, "rb").read() == orig
        assert ManageProcessor("show", root=trained_root).run() == 0

    def test_testdata(self, trained_root):
        from shifu_tpu.processor.testdata import TestDataProcessor

        assert TestDataProcessor(trained_root, n=50).run() == 0


class TestConvert:
    def test_nn_roundtrip(self, trained_root, tmp_path):
        from shifu_tpu.models.nn import NNModelSpec
        from shifu_tpu.processor.convert import ConvertProcessor

        src = os.path.join(trained_root, "models", "model0.nn")
        js = str(tmp_path / "m.json")
        back = str(tmp_path / "m2.nn")
        assert ConvertProcessor(trained_root, to_json=True, input_path=src,
                                output_path=js).run() == 0
        assert ConvertProcessor(trained_root, to_json=False, input_path=js,
                                output_path=back).run() == 0
        a, b = NNModelSpec.load(src), NNModelSpec.load(back)
        from shifu_tpu.models.nn import flatten_params

        fa, _ = flatten_params(a.params)
        fb, _ = flatten_params(b.params)
        np.testing.assert_allclose(fa, fb, atol=1e-6)

    def test_tree_roundtrip(self, tmp_path):
        from shifu_tpu.models.tree import TreeModelSpec
        from shifu_tpu.processor.convert import ConvertProcessor
        from shifu_tpu.train.tree_trainer import TreeTrainConfig, train_trees

        rng = np.random.default_rng(0)
        codes = rng.integers(0, 6, size=(300, 4)).astype(np.int32)
        y = (codes[:, 0] >= 3).astype(np.float32)
        res = train_trees(codes, y, np.ones(300, np.float32), [6] * 4,
                          [False] * 4, [f"c{i}" for i in range(4)],
                          TreeTrainConfig(tree_num=3, max_depth=3, seed=1))
        src = str(tmp_path / "model0.gbt")
        res.spec.save(src)
        js = str(tmp_path / "t.json")
        back = str(tmp_path / "t2.gbt")
        assert ConvertProcessor(".", to_json=True, input_path=src,
                                output_path=js).run() == 0
        assert ConvertProcessor(".", to_json=False, input_path=js,
                                output_path=back).run() == 0
        s1 = TreeModelSpec.load(src).independent().compute(codes[:20])
        s2 = TreeModelSpec.load(back).independent().compute(codes[:20])
        np.testing.assert_allclose(s1, s2, atol=1e-6)


class TestAnalysis:
    def test_report(self, trained_root, capsys):
        from shifu_tpu.processor.analysis import AnalysisProcessor

        assert AnalysisProcessor(trained_root).run() == 0
        out = capsys.readouterr().out
        assert "Top variables by KS" in out
        assert "model0.nn" in out
        assert os.path.isfile(os.path.join(trained_root, "tmp", "analysis",
                                           "report.txt"))


class TestCombo:
    def test_combo_workflow(self, tmp_path):
        root = str(tmp_path / "combo")
        make_model_set(root, n_rows=300)
        from shifu_tpu.config.model_config import ModelConfig
        from shifu_tpu.processor.combo import ComboProcessor

        mc = ModelConfig.load(os.path.join(root, "ModelConfig.json"))
        mc.train.num_train_epochs = 15
        mc.save(os.path.join(root, "ModelConfig.json"))

        assert ComboProcessor(root, new_algs="NN,GBT,LR").run() == 0
        assert os.path.isfile(os.path.join(root, "ComboTrain.json"))
        assert ComboProcessor(root, do_init=True).run() == 0
        assert os.path.isdir(os.path.join(root, "sub_0_NN"))
        assert os.path.isdir(os.path.join(root, "sub_1_GBT"))

        # shrink sub-model workloads
        for d in ("sub_0_NN", "sub_1_GBT"):
            p = os.path.join(root, d, "ModelConfig.json")
            smc = ModelConfig.load(p)
            smc.train.num_train_epochs = 15
            if "GBT" in d:
                smc.train.params["TreeNum"] = 5
                smc.train.params["MaxDepth"] = 3
            smc.save(p)

        assert ComboProcessor(root, do_run=True).run() == 0
        assert os.path.isfile(os.path.join(root, "assembler_LR", "models",
                                           "model0.lr"))
        assert ComboProcessor(root, do_eval=True).run() == 0
        perf = json.load(open(os.path.join(root, "evals", "Combo",
                                           "EvalPerformance.json")))
        assert perf["areaUnderRoc"] > 0.85


def test_profiler_hook(tmp_path):
    """-Dshifu.profile=<dir> wraps any step in a jax.profiler trace
    (SURVEY §5 tracing obligation)."""
    from tests.helpers import make_model_set

    root = str(tmp_path / "ms")
    make_model_set(root, n_rows=120)
    from shifu_tpu.processor.init import InitProcessor
    from shifu_tpu.utils import environment

    environment.set_property("shifu.profile", "profout")
    try:
        assert InitProcessor(root).run() == 0
    finally:
        environment.set_property("shifu.profile", "")
    prof = os.path.join(root, "profout", "init")
    assert os.path.isdir(prof)
    # jax writes a plugins/profile/<ts> dir with trace artifacts
    assert any(os.scandir(prof)), "no profiler artifacts written"
