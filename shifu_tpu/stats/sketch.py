"""Streaming, mergeable per-column sketches for bounded-memory stats.

The in-RAM stats path computes exact quantiles (stats/binning.py); the
streaming path replaces them with an SPDT streaming histogram — the same
algorithm family as the reference's EqualPopulationBinning
(core/binning/EqualPopulationBinning.java:34, HIST_SCALE=100): a capped set
of (value, weight) centroids, nearest-pair merged on overflow, quantiles by
interpolating the cumulative weight. Error is bounded by the centroid count;
the default cap (100x the bin budget, like HIST_SCALE) makes boundary drift
negligible next to binning's own discretization.

Also here: streaming moments (mean/std/min/max/missing), a capped
categorical counter (AutoTypeDistinctCountMapper's CountAndFrequentItems
analog), all update()-per-chunk with O(cap) state.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

HIST_SCALE = 100  # centroids per requested bin, EqualPopulationBinning.java:45


class StreamingHistogram:
    """SPDT centroid histogram: values ascending, positive weights."""

    def __init__(self, max_centroids: int = 1024):
        self.cap = max(max_centroids, 8)
        self.v = np.empty(0, dtype=np.float64)
        self.w = np.empty(0, dtype=np.float64)

    def update(self, values: np.ndarray, weights: Optional[np.ndarray] = None):
        """Fold a chunk in. values must be finite (callers filter NaN)."""
        if values.size == 0:
            return
        uv, inv = np.unique(values, return_inverse=True)
        if weights is None:
            uw = np.bincount(inv, minlength=uv.size).astype(np.float64)
        else:
            uw = np.bincount(inv, weights=weights, minlength=uv.size)
        v = np.concatenate([self.v, uv])
        w = np.concatenate([self.w, uw])
        order = np.argsort(v, kind="stable")
        v, w = v[order], w[order]
        # collapse exact duplicates at the seam
        if v.size > 1:
            same = np.concatenate([[False], v[1:] == v[:-1]])
            if same.any():
                group = np.cumsum(~same) - 1
                nw = np.zeros(int(group[-1]) + 1)
                np.add.at(nw, group, w)
                v, w = v[~same], nw
        self.v, self.w = self._compress(v, w)

    def _compress(self, v: np.ndarray, w: np.ndarray):
        """Merge nearest centroid pairs until under the cap. Each round picks
        the smallest non-conflicting gaps (a centroid joins one merge per
        round), so a few rounds suffice."""
        while v.size > self.cap:
            need = v.size - self.cap
            gaps = v[1:] - v[:-1]
            candidates = np.argsort(gaps, kind="stable")
            used = np.zeros(v.size, dtype=bool)
            merge_left: List[int] = []
            for i in candidates:
                if used[i] or used[i + 1]:
                    continue
                used[i] = used[i + 1] = True
                merge_left.append(i)
                if len(merge_left) >= need:
                    break
            ml = np.asarray(sorted(merge_left), dtype=np.int64)
            keep = np.ones(v.size, dtype=bool)
            keep[ml + 1] = False
            wsum = w.copy()
            wsum[ml] = w[ml] + w[ml + 1]
            vmerged = v.copy()
            vmerged[ml] = (v[ml] * w[ml] + v[ml + 1] * w[ml + 1]) / np.maximum(
                wsum[ml], 1e-300
            )
            v, w = vmerged[keep], wsum[keep]
        return v, w

    def merge(self, other: "StreamingHistogram") -> None:
        self.update(other.v, other.w)

    @property
    def total_weight(self) -> float:
        return float(self.w.sum())

    def quantile(self, q: float) -> Optional[float]:
        if self.v.size == 0:
            return None
        cum = np.cumsum(self.w)
        total = cum[-1]
        if total <= 0:
            return None
        idx = int(np.searchsorted(cum, q * total, side="left"))
        idx = min(idx, self.v.size - 1)
        return float(self.v[idx])

    def boundaries(self, max_bins: int) -> List[float]:
        """Equal-mass bin boundaries, same contract as
        weighted_quantile_boundaries: starts at -inf, strictly increasing."""
        neg_inf = float("-inf")
        if self.v.size == 0:
            return [neg_inf]
        cum = np.cumsum(self.w)
        total = cum[-1]
        if total <= 0:
            return [neg_inf]
        out = [neg_inf]
        for k in range(1, max_bins):
            target = total * k / max_bins
            idx = int(np.searchsorted(cum, target, side="left"))
            idx = min(idx, self.v.size - 1)
            b = float(self.v[idx])
            if b > out[-1]:
                out.append(b)
        return out


class NumericSketch:
    """Moments + missing counts + an SPDT histogram over the binning subset."""

    def __init__(self, max_bins: int = 10):
        self.hist = StreamingHistogram(max_centroids=HIST_SCALE * max_bins)
        # full-population histogram for the median (binning may use a subset)
        self.hist_all = StreamingHistogram(max_centroids=HIST_SCALE * max_bins)
        self.count = 0.0
        self.missing = 0.0
        self.sum = 0.0
        self.sumsq = 0.0
        self.min = np.inf
        self.max = -np.inf

    def update(
        self,
        values: np.ndarray,
        bin_mask: np.ndarray,
        bin_weights: Optional[np.ndarray] = None,
    ) -> None:
        """values float64 (NaN = missing) over VALID-tag rows only; bin_mask
        selects the binning subset (pos/neg/total per binningMethod)."""
        finite = np.isfinite(values)
        self.missing += float((~finite).sum())
        fv = values[finite]
        if fv.size:
            self.count += float(fv.size)
            self.sum += float(fv.sum())
            self.sumsq += float((fv * fv).sum())
            self.min = min(self.min, float(fv.min()))
            self.max = max(self.max, float(fv.max()))
            self.hist_all.update(fv)
        sel = finite & bin_mask
        sv = values[sel]
        if sv.size:
            self.hist.update(
                sv, None if bin_weights is None else bin_weights[sel]
            )

    @property
    def mean(self) -> Optional[float]:
        return self.sum / self.count if self.count > 0 else None

    @property
    def std_dev(self) -> Optional[float]:
        if self.count <= 0:
            return None
        m = self.sum / self.count
        var = max(self.sumsq / self.count - m * m, 0.0)
        # sample std like the reference BasicStatsCalculator
        return float(np.sqrt(var * self.count / max(self.count - 1.0, 1.0)))

    @property
    def median(self) -> Optional[float]:
        return self.hist_all.quantile(0.5)

    def merge(self, other: "NumericSketch") -> None:
        """Fold another shard's sketch in (the reduce of the sharded
        pass-1 map). Moments/min/max merge exactly; the centroid
        histograms merge exactly whenever neither side compressed (few
        distinct values), else within the SPDT error bound."""
        self.count += other.count
        self.missing += other.missing
        self.sum += other.sum
        self.sumsq += other.sumsq
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)
        self.hist.merge(other.hist)
        self.hist_all.merge(other.hist_all)


class DistinctSketch:
    """Distinct-count sketch: exact hash set up to `exact_limit`, then a
    vectorized HyperLogLog (p=12, 4096 one-byte registers, ~1.6% error) —
    the reference's HLL++ autotype sketch
    (core/autotype/AutoTypeDistinctCountMapper.java:45) done in numpy."""

    P = 12

    def __init__(self, exact_limit: int = 4096):
        self.exact_limit = exact_limit
        self.exact: Optional[set] = set()
        m = 1 << self.P
        self.registers = np.zeros(m, dtype=np.uint8)

    def update_hashes(self, h: np.ndarray) -> None:
        """h: uint64 hashes of the values."""
        m = 1 << self.P
        idx = (h & np.uint64(m - 1)).astype(np.int64)
        w = h >> np.uint64(self.P)
        # rho = leading-zero count of w in (64-P) bits, + 1
        bits = np.zeros(w.shape, dtype=np.int64)
        nz = w > 0
        # exact bit length: w < 2^52 is exactly representable in float64, and
        # frexp's exponent IS bit_length for integers (w = m * 2^e, 0.5<=m<1).
        # floor(log2(w)) would round UP for w one ulp below a power of two,
        # understating rho.
        bits[nz] = np.frexp(w[nz].astype(np.float64))[1]
        rho = (64 - self.P) - bits + 1
        np.maximum.at(self.registers, idx, rho.astype(np.uint8))
        if self.exact is not None:
            self.exact.update(h.tolist())
            if len(self.exact) > self.exact_limit:
                self.exact = None  # fall back to the registers

    def update_series(self, ser) -> None:
        import pandas as pd

        if not len(ser):
            return
        h = pd.util.hash_pandas_object(ser, index=False).to_numpy(np.uint64)
        self.update_hashes(h)

    def merge(self, other: "DistinctSketch") -> None:
        """Union another shard's sketch: HLL registers max elementwise;
        the exact sets union while BOTH sides are still exact (spilling
        to the registers past the limit, like update_hashes)."""
        np.maximum(self.registers, other.registers, out=self.registers)
        if self.exact is not None and other.exact is not None:
            self.exact |= other.exact
            if len(self.exact) > self.exact_limit:
                self.exact = None
        else:
            self.exact = None

    def estimate(self) -> int:
        if self.exact is not None:
            return len(self.exact)
        m = float(1 << self.P)
        alpha = 0.7213 / (1.0 + 1.079 / m)
        s = np.power(2.0, -self.registers.astype(np.float64)).sum()
        e = alpha * m * m / s
        zeros = int((self.registers == 0).sum())
        if e <= 2.5 * m and zeros:
            e = m * np.log(m / zeros)  # linear-counting small-range fix
        return int(round(e))


class AutoTypeSketch:
    """Streaming auto-type accumulator: distinct count + numeric-parse
    ratio + missing count, all from the pandas Series (no object arrays)."""

    def __init__(self, missing_values):
        self.distinct = DistinctSketch()
        self.missing_values = list(missing_values)
        self.total = 0.0
        self.missing = 0.0
        self.numeric_ok = 0.0

    def update(self, ser) -> None:
        import pandas as pd

        ser = ser.str.strip()
        miss = ser.isin(self.missing_values)
        non_missing = ser[~miss.to_numpy()]
        self.missing += float(miss.sum())
        self.total += float(len(non_missing))
        self.numeric_ok += float(
            pd.to_numeric(non_missing, errors="coerce").notna().sum()
        )
        self.distinct.update_series(non_missing)

    def distinct_count(self) -> int:
        return self.distinct.estimate()

    def numeric_ratio(self) -> float:
        return self.numeric_ok / self.total if self.total > 0 else 0.0

    def merge(self, other: "AutoTypeSketch") -> None:
        self.distinct.merge(other.distinct)
        self.total += other.total
        self.missing += other.missing
        self.numeric_ok += other.numeric_ok


class CategoricalSketch:
    """Capped value -> count map (reference caps categories at 10k,
    shifuconfig:107-108; beyond the working cap the rare tail would be merged
    into the missing bin anyway)."""

    def __init__(self, working_cap: int = 100_000):
        self.counts: Dict[str, float] = {}
        self.working_cap = working_cap
        self.missing = 0.0
        self.total = 0.0
        self.numeric_parse_ok = 0.0
        self.saturated = False
        # space-saving error tracking (Metwally et al.): per-key admission
        # floors, the max OBSERVED count among evicted keys (error_bound, the
        # per-key overcount ceiling for later admissions), and the total
        # observed mass evicted (distinct-count undercount signal). Floors
        # are excluded when a carried key is re-evicted, so neither quantity
        # compounds across eviction rounds.
        self.error_bound = 0.0
        self.evicted_mass = 0.0
        self._floor: Dict[str, float] = {}

    def update(self, raw: np.ndarray, missing_mask: np.ndarray) -> None:
        import pandas as pd

        ser = pd.Series(raw[~missing_mask]).str.strip()
        self.missing += float(missing_mask.sum())
        self.total += float(ser.size)
        self.numeric_parse_ok += float(
            pd.to_numeric(ser, errors="coerce").notna().sum()
        )
        vc = ser.value_counts()
        for val, cnt in vc.items():
            key = str(val)
            if key in self.counts:
                self.counts[key] += float(cnt)
            else:
                # space-saving admission: a value that was evicted earlier
                # re-enters carrying the error floor instead of restarting
                # from zero (Metwally et al. SpaceSaving; vs plain
                # frequent-items which undercounts re-entrants)
                floor = self.error_bound if self.saturated else 0.0
                self.counts[key] = float(cnt) + floor
                if floor:
                    self._floor[key] = floor
        if len(self.counts) > self.working_cap:
            self.saturated = True
            kept = sorted(self.counts.items(), key=lambda kv: -kv[1])
            for k, cnt in kept[self.working_cap:]:
                observed = cnt - self._floor.pop(k, 0.0)
                self.error_bound = max(self.error_bound, observed)
                self.evicted_mass += observed
            self.counts = dict(kept[: self.working_cap])

    def distinct_count(self) -> int:
        return len(self.counts)

    def numeric_ratio(self) -> float:
        return self.numeric_parse_ok / self.total if self.total > 0 else 0.0

    def merge(self, other: "CategoricalSketch") -> None:
        """Fold another shard's counter in: shard-0-first key order keeps
        top_categories ties deterministic; counts merge exactly while
        neither side saturated, else within the space-saving bound (the
        floors travel with the keys, so re-eviction stays non-compounding
        after a merge too)."""
        for key, cnt in other.counts.items():
            if key in self.counts:
                self.counts[key] += cnt
                self._floor[key] = (self._floor.get(key, 0.0)
                                    + other._floor.get(key, 0.0))
                if not self._floor[key]:
                    self._floor.pop(key, None)
            else:
                self.counts[key] = cnt
                if key in other._floor:
                    self._floor[key] = other._floor[key]
        self.missing += other.missing
        self.total += other.total
        self.numeric_parse_ok += other.numeric_parse_ok
        self.saturated = self.saturated or other.saturated
        self.error_bound = max(self.error_bound, other.error_bound)
        self.evicted_mass += other.evicted_mass
        if len(self.counts) > self.working_cap:
            kept = sorted(self.counts.items(), key=lambda kv: -kv[1])
            self.saturated = True
            for k, cnt in kept[self.working_cap:]:
                observed = cnt - self._floor.pop(k, 0.0)
                self.error_bound = max(self.error_bound, observed)
                self.evicted_mass += observed
            self.counts = dict(kept[: self.working_cap])

    def top_categories(self, max_categories: int) -> List[str]:
        """Descending frequency, ties by first-seen order (dict order), same
        contract as stats/binning.categorical_bins."""
        if self.saturated:
            from shifu_tpu.utils.log import get_logger

            get_logger(__name__).warning(
                "categorical sketch saturated at %d values; counts carry up "
                "to +%.0f per-key overcount and %.0f total evicted mass",
                self.working_cap, self.error_bound, self.evicted_mass,
            )
        items = sorted(self.counts.items(), key=lambda kv: -kv[1])
        cats = [k for k, _ in items]
        if max_categories and len(cats) > max_categories:
            cats = cats[:max_categories]
        return cats
