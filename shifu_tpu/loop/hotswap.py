"""Zero-downtime registry hot-swap with shadow scoring.

`SwappableRegistry` fronts the micro-batcher's `score_fn` with an
indirection the swap can flip atomically:

  * **Active** — the ModelRegistry answering live traffic. Every scored
    batch counts into per-version metrics
    (`serve.version.batches{sha=}` / `serve.version.records{sha=}`), so
    the run ledger shows exactly which model-set sha answered how many
    requests across a rollout — the per-version accounting a canary
    verdict needs.
  * **Shadow** — a staged candidate (`stage(models_dir)`) that is fully
    loaded and warmed BEFORE it ever sees traffic. While staged, a
    sampled fraction of live batches (`-Dshifu.loop.shadowSample`) is
    re-scored on the shadow OFF the request path (the batcher's
    post-resolution observer — clients never wait on it), accumulating a
    score-delta histogram (`serve.shadow.score_delta`, 0..1000 scale)
    and an agreement rate: |mean-score delta| <=
    `-Dshifu.loop.shadowTolerance` counts as agreeing. Shadow failures
    count (`serve.shadow.errors`) and never touch live traffic.
  * **Promote** — one reference assignment under the swap lock: the next
    gathered batch scores on the new version while the in-flight batch
    finishes on the old. No queue flush, no listener restart, no request
    is dropped or double-answered — the answered-per-version counters
    add up to every admitted request across the swap (pinned in
    tests/test_loop.py under concurrent load).

Compiled-program hygiene rides the existing content-sha cache key: each
ModelRegistry's fused program is keyed by ITS model-set sha, so an old
version's programs can never serve new weights, and staging pre-compiles
the candidate's row buckets (`warm`) so promotion costs zero first-batch
compiles.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from shifu_tpu.analysis.racetrack import tracked_lock
from shifu_tpu.loop import (
    shadow_sample_setting,
    shadow_tolerance_setting,
)
from shifu_tpu.serve.registry import ModelRegistry
from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)

# pinned like serve.latency_seconds: exponential 0.25 * 2^k score-scale
# edges resolve sub-point deltas without drowning multi-hundred ones
SCORE_DELTA_BUCKETS = tuple(0.25 * 2 ** k for k in range(14)) + (
    float("inf"),)


class ShadowStats:
    """Agreement accounting for one staged candidate. `labels`
    (replica=, and tenant= in a zoo) ride the delta histogram and the
    error counter so per-tenant shadow evidence stays separable."""

    def __init__(self, tolerance: Optional[float] = None,
                 labels: Optional[dict] = None) -> None:
        self.tolerance = (shadow_tolerance_setting() if tolerance is None
                          else float(tolerance))
        self.labels = dict(labels or {})
        self._lock = tracked_lock("loop.hotswap.shadow_stats")
        self.batches = 0
        self.rows = 0
        self.agree_rows = 0
        self.errors = 0
        self.sum_abs_delta = 0.0
        self.max_abs_delta = 0.0

    def note(self, delta: np.ndarray) -> None:
        from shifu_tpu.obs import registry

        d = np.abs(np.asarray(delta, dtype=np.float64))
        # a NaN delta (candidate emitted NaN scores) is maximal
        # disagreement, not a crash: +inf lands in the overflow bucket,
        # fails the tolerance test, and keeps the observer pass alive
        # (searchsorted would otherwise index past the last bucket)
        d = np.where(np.isfinite(d), d, np.inf)
        hist = registry().histogram("serve.shadow.score_delta",
                                    buckets=SCORE_DELTA_BUCKETS,
                                    **self.labels)
        if d.size:
            # one vectorized binning + one locked merge — this runs per
            # sampled batch on the single batch-resolution thread, where
            # a per-row observe() loop would eat queue headroom
            binned = np.bincount(
                np.searchsorted(np.asarray(hist.buckets), d, side="left"),
                minlength=len(hist.buckets))
            hist.add_binned(binned.tolist(), float(d.sum()), int(d.size),
                            float(d.min()), float(d.max()))
        with self._lock:
            self.batches += 1
            self.rows += d.size
            self.agree_rows += int((d <= self.tolerance).sum())
            self.sum_abs_delta += float(d.sum())
            self.max_abs_delta = max(self.max_abs_delta, float(d.max()))

    def note_error(self) -> None:
        from shifu_tpu.obs import registry

        registry().counter("serve.shadow.errors", **self.labels).inc()
        with self._lock:
            self.errors += 1

    def snapshot(self) -> dict:
        with self._lock:
            rows = max(self.rows, 1)
            return {
                "batches": self.batches,
                "rows": self.rows,
                # raw count alongside the rate: the fleet's cross-replica
                # psum aggregation needs an additive quantity (rates
                # don't sum; agree-row counts do)
                "agreeRows": self.agree_rows,
                "errors": self.errors,
                "tolerance": self.tolerance,
                "agreement": (self.agree_rows / rows if self.rows else 0.0),
                "meanAbsDelta": (self.sum_abs_delta / rows
                                 if self.rows else 0.0),
                "maxAbsDelta": self.max_abs_delta,
            }


class SwappableRegistry:
    """Atomic active/shadow pair behind one `score_raw` entry point.

    In a fleet (serve/fleet.py) each replica owns one SwappableRegistry,
    so a rolling promote flips replicas one at a time; `labels`
    (typically {"replica": "<i>"}) ride the per-version serve.version.*
    counters so every answered request stays attributable to (replica,
    sha) across the roll."""

    def __init__(self, registry: ModelRegistry,
                 labels: Optional[dict] = None) -> None:
        self._lock = tracked_lock("loop.hotswap.swap")
        self.labels = dict(labels or {})
        self._active = registry
        self._shadow: Optional[ModelRegistry] = None
        self._shadow_stats: Optional[ShadowStats] = None
        self._shadow_sample = shadow_sample_setting()
        self._shadow_tick = 0
        self._last_scored_sha: Optional[str] = None
        self.swaps = 0

    # ---- live path (batcher score_fn) ----
    def score_raw(self, data):
        from shifu_tpu.obs import registry as obs_registry
        from shifu_tpu.obs import reqtrace

        active = self._active  # one atomic read: the swap point
        result = active.score_raw(data)
        # remembered for the post-resolution observer (same worker
        # thread): a promote landing between this score and the observe
        # must not re-attribute the batch to the NEW version
        self._last_scored_sha = active.sha
        # request traces carry the sha read at the SAME swap point, so
        # a trace stays attributed to the version that actually scored
        # it across a mid-roll promote (the traffic log's scored_sha
        # discipline, per request)
        reqtrace.note_attr(scoredSha=active.sha)
        reg = obs_registry()
        reg.counter("serve.version.batches", sha=active.sha,
                    **self.labels).inc()
        reg.counter("serve.version.records", sha=active.sha,
                    **self.labels).inc(data.n_rows)
        return result

    # ---- registry façade (what the server/front end reads) ----
    @property
    def active(self) -> ModelRegistry:
        return self._active

    @property
    def sha(self) -> str:
        return self._active.sha

    @property
    def scored_sha(self) -> str:
        """Sha of the version that scored the most recently resolved
        batch — what the traffic log must stamp. Falls back to the
        active sha before any batch has scored."""
        return self._last_scored_sha or self._active.sha

    @property
    def model_names(self) -> List[str]:
        return self._active.model_names

    @property
    def active_models_dir(self) -> str:
        """Dir of the version currently serving — what an evicting zoo
        must remember so re-admission rebuilds the PROMOTED version, not
        the originally registered one."""
        return self._active.models_dir

    @property
    def fused(self) -> bool:
        return self._active.fused

    def memory_analysis(self) -> dict:
        """Active + staged-shadow resident cost (registry
        memory_analysis, the zoo ledger's per-replica read)."""
        with self._lock:  # paired read, like observe()
            active, shadow = self._active, self._shadow
        out = {"active": active.memory_analysis()}
        total = out["active"]["residentBytes"]
        if shadow is not None:
            out["shadow"] = shadow.memory_analysis()
            total += out["shadow"]["residentBytes"]
        out["residentBytes"] = total
        return out

    def release(self) -> int:
        """Eviction: release active AND any staged shadow (profiler
        cache refs dropped, further scores refused). The owning fleet is
        already drained when the zoo calls this."""
        with self._lock:
            active, shadow = self._active, self._shadow
            self._shadow = None
            self._shadow_stats = None
        n = active.release()
        if shadow is not None:
            n += shadow.release()
        return n

    @property
    def input_columns(self) -> List[str]:
        return self._active.input_columns

    def warm(self, batch_sizes):
        return self._active.warm(batch_sizes)

    def score_records(self, records):
        from shifu_tpu.serve.registry import records_to_columnar

        return self.score_raw(
            records_to_columnar(records, self.input_columns))

    # ---- shadow lifecycle ----
    def stage(self, models_dir: str, column_configs=None,
              model_config=None, drift=None, put_hook=None) -> dict:
        """Load + warm a candidate as the shadow; replaces any previously
        staged candidate. Returns the shadow summary.

        `put_hook(nbytes)` (serve/zoo.py) makes the stage STREAMED: the
        candidate's weights land layer-group by layer-group, each group
        ledger-acquired before its device_put — so staging on a
        near-full HBM budget evicts cold tenants per group instead of
        OOMing on a full second registry."""
        from shifu_tpu.obs import registry as obs_registry

        cand = ModelRegistry(models_dir, column_configs=column_configs,
                             model_config=model_config, drift=drift,
                             device=getattr(self._active, "device", None),
                             labels=getattr(self._active, "labels", None),
                             put_hook=put_hook)
        # the candidate inherits the active's residency-repricing seam:
        # a bucket first compiled by shadow traffic must be accounted
        # exactly like one compiled by live traffic
        cand.cost_hook = getattr(self._active, "cost_hook", None)
        # staged: shadow scoring must not double-count drift rows the
        # active fold already saw; promotion flips the fold live
        cand.drift_live = False
        if list(cand.input_columns) != list(self._active.input_columns):
            raise ValueError(
                "candidate input columns differ from the active set "
                f"({len(cand.input_columns)} vs "
                f"{len(self._active.input_columns)}) — a hot-swap must "
                "not change the request schema")
        # pre-compile the buckets live traffic already exercised so the
        # first post-promote batch pays zero compiles
        warmed = sorted(b for (_s, b)
                        in getattr(self._active, "_warm_buckets", set()))
        if cand.fused and warmed:
            cand.warm(warmed)
        with self._lock:
            prev, self._shadow = self._shadow, cand
            self._shadow_stats = ShadowStats(labels=self.labels)
            self._shadow_tick = 0
        if prev is not None:
            # a REPLACED candidate must free like an unstaged one: its
            # profiler cost-cache refs would otherwise pin its compiled
            # programs + device weights while every ledger sees only
            # the new candidate's bytes
            prev.release(refuse=False)
        obs_registry().counter("serve.swap.staged", sha=cand.sha,
                               **self.labels).inc()
        log.info("staged shadow model set %s from %s (warmed buckets %s)",
                 cand.sha, models_dir, warmed)
        return self.shadow_snapshot()

    def unstage(self) -> None:
        """Drop the staged candidate (rollback to active-only). Counted
        per sha — an aborted fleet-promotion round's rollback must be as
        visible in the ledger as the stage that preceded it."""
        with self._lock:
            shadow, self._shadow = self._shadow, None
            self._shadow_stats = None
        if shadow is not None:
            from shifu_tpu.obs import registry as obs_registry

            # drop the profiler's strong refs so the unstaged weights
            # and compiled programs actually free (refuse=False: a
            # shadow score racing the unstage just errors into the
            # observer's containment, or pays one fresh compile)
            shadow.release(refuse=False)
            obs_registry().counter("serve.swap.unstaged",
                                   sha=shadow.sha, **self.labels).inc()
            log.info("unstaged shadow model set %s (rolled back to "
                     "active %s)", shadow.sha, self._active.sha)

    def observe(self, data, result) -> None:
        """Post-resolution hook (batcher observer): sample live batches
        onto the shadow and accumulate score deltas. Never raises.

        The (shadow, stats) pair is read under the lock as a UNIT: a
        stage()/promote() landing between two bare reads could pair the
        old candidate with the new candidate's stats and attribute
        agreement evidence to the wrong sha (regression-pinned in
        tests/test_racetrack.py). Scoring itself happens after release —
        device work under the swap lock would block a concurrent
        promote for a whole shadow dispatch (SH203)."""
        with self._lock:
            shadow, stats = self._shadow, self._shadow_stats
            if shadow is None or stats is None:
                return
            if self._shadow_sample <= 0.0:
                return  # off, like TrafficLog's sample<=0
            self._shadow_tick += 1
            if self._shadow_sample < 1.0:
                # deterministic stride sampling: every k-th batch
                stride = max(1, int(round(1.0 / max(self._shadow_sample,
                                                    1e-6))))
                if self._shadow_tick % stride:
                    return
        try:
            shadow_res = shadow.score_raw(data)
        except Exception as e:  # candidate bugs must not hurt live traffic
            log.warning("shadow scoring failed on %s: %s", shadow.sha, e)
            stats.note_error()
            return
        from shifu_tpu.obs import registry as obs_registry

        reg = obs_registry()
        reg.counter("serve.shadow.batches", **self.labels).inc()
        reg.counter("serve.shadow.records", **self.labels).inc(data.n_rows)
        stats.note(np.asarray(shadow_res.mean)
                   - np.asarray(result.mean))

    def shadow_snapshot(self) -> Optional[dict]:
        with self._lock:  # paired read, like observe()
            shadow, stats = self._shadow, self._shadow_stats
        if shadow is None or stats is None:
            return None
        return {"sha": shadow.sha,
                "models": list(shadow.model_names),
                "fused": shadow.fused,
                **stats.snapshot()}

    def promote(self, expected_sha: Optional[str] = None) -> dict:
        """Atomically swap shadow -> active. The in-flight batch finishes
        on the old version; the next gathered batch scores on the new.
        `expected_sha` binds the swap to the candidate the caller's gate
        evidence described — if a different set was staged in between,
        the promote is refused rather than rolling out sight-unseen."""
        from shifu_tpu.obs import registry as obs_registry

        with self._lock:
            if self._shadow is None:
                raise ValueError("no staged candidate to promote")
            if expected_sha and self._shadow.sha != expected_sha:
                raise ValueError(
                    f"staged shadow is {self._shadow.sha}, not the gated "
                    f"candidate {expected_sha} — it was re-staged since "
                    "the gates evaluated; re-run the gate on the current "
                    "shadow")
            old, new = self._active, self._shadow
            stats = (self._shadow_stats.snapshot()
                     if self._shadow_stats else None)
            self._active = new
            self._shadow = None
            self._shadow_stats = None
            self.swaps += 1
            new.drift_live = True
            old.drift_live = False
        # the OLD version's compiled programs + device weights must not
        # outlive the swap in the profiler's cost cache (the PR-9 residue:
        # a promote used to leave residency doubled until cache churn).
        # refuse=False: an in-flight batch that read the old active at
        # the swap point finishes on it legally.
        old.release(refuse=False)
        obs_registry().counter("serve.swap.promotions",
                               from_sha=old.sha, to_sha=new.sha,
                               **self.labels).inc()
        log.info("promoted model set %s -> %s (swap #%d)", old.sha,
                 new.sha, self.swaps)
        return {"from": old.sha, "to": new.sha, "swaps": self.swaps,
                "shadow": stats}

    def snapshot(self) -> dict:
        snap = self._active.snapshot()
        snap["swaps"] = self.swaps
        shadow = self.shadow_snapshot()
        if shadow is not None:
            snap["shadow"] = shadow
        return snap
