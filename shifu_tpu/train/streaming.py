"""Larger-than-memory training: stream mmap'd .npy shards through the chip.

The reference trains on datasets that exceed worker memory by spilling rows
to disk (core/dtrain/dataset/MemoryDiskFloatMLDataSet.java — memory portion
first, BufferedFloatMLDataSet overflow on disk, re-read every epoch). The
TPU analog keeps the SAME on-disk artifact `shifu norm` already writes —
row-sharded .npy files — and feeds them through the overlapped prefetch
pipeline (data/pipeline.py):

    shard s is computing on device  |  shard s+1 loads + pads on the
    (dispatch is async)             |  prefetch thread, then device_put
                                    |  rides under shard s's compute

Every shard is padded to the max shard row count so ONE compiled per-shard
gradient program serves the whole stream (padding rows carry zero
significance). Peak host memory = 2 shards (current + prefetch), whatever
the dataset size; full-batch BSP semantics are preserved exactly — the
epoch gradient is the sum of shard gradients, the same sum NNMaster computes
over worker results (NNMaster.java:240-249).
"""

from __future__ import annotations

import math
import os
from typing import List, Optional, Tuple

import numpy as np

from shifu_tpu.analysis import sanitize
from shifu_tpu.norm.dataset import NormMeta, read_meta
from shifu_tpu.obs import profile
from shifu_tpu.train.nn_trainer import NNTrainConfig, TrainResult, _loss_and_errors
from shifu_tpu.train.updaters import make_updater
from shifu_tpu.models.nn import flatten_params, init_params, unflatten_params
from shifu_tpu.utils import environment
from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)

DEFAULT_TRAIN_BUDGET_MB = 1024


def train_memory_budget_bytes() -> int:
    """shifu.train.memoryBudgetMB — datasets whose normalized matrix exceeds
    it stream from shards instead of concatenating into one host array
    (the reference's trainOnDisk / MemoryDiskFloatMLDataSet envelope,
    shifuconfig:46-50)."""
    mb = environment.get_int("shifu.train.memoryBudgetMB",
                             DEFAULT_TRAIN_BUDGET_MB)
    return int(mb) * 1024 * 1024


def should_stream_training(data_dir: str, force_attr: bool = False) -> bool:
    if environment.get_property("shifu.train.forceStreaming", "") in (
        "true", "1",
    ):
        return True
    if force_attr:
        return True
    try:
        meta = read_meta(data_dir)
    except Exception:  # no shard meta yet: nothing on disk to stream
        return False
    n_cols = len(meta.columns)
    return meta.n_rows * n_cols * 4 > train_memory_budget_bytes()


class ShardFeed:
    """Double-buffered device feed over the shard files of one data dir.

    Each epoch iterates (x_dev, t_dev, sig_train_dev, sig_valid_dev) with
    shard s+1's host->device transfer overlapping shard s's compute. Shards
    are padded to the max shard length; sampling masks are drawn per shard
    from a deterministic stream so every epoch sees the identical split
    (AbstractNNWorker samples once at load time, not per epoch)."""

    def __init__(self, data_dir: str, cfg: NNTrainConfig,
                 prefix: str = "features", mesh=None, sig_override=None):
        """`sig_override(s, rows, global_offset, weights) -> (sig_t,
        sig_v)` replaces the per-shard bagging/validation draw — the
        k-fold case, where fold membership is a function of the GLOBAL
        row index (TrainModelProcessor.java:947-969)."""
        import jax

        self.data_dir = data_dir
        self.meta: NormMeta = read_meta(data_dir)
        self.prefix = prefix
        self.n_shards = len(self.meta.shard_rows)
        self.pad_rows = max(self.meta.shard_rows) if self.meta.shard_rows else 0
        self.mesh = mesh
        if mesh is not None and self.pad_rows:
            # rows shard over the mesh's data axis: pad every shard to a
            # multiple of the axis size (padding carries zero significance)
            from shifu_tpu.parallel.mesh import round_up_rows

            self.pad_rows = round_up_rows(self.pad_rows, mesh)
        self.cfg = cfg
        self._jax = jax
        # per-shard sampling masks (train significance / valid mask), drawn
        # ONCE — identical across epochs, like the reference's load-time split
        self._sig: List[Tuple[np.ndarray, np.ndarray]] = []
        from shifu_tpu.train.nn_trainer import split_and_sample

        offset = 0
        for s, rows in enumerate(self.meta.shard_rows):
            w = np.asarray(np.load(self._path("weights", s), mmap_mode="r"))
            if sig_override is not None:
                sig_t, sig_v = sig_override(s, rows, offset, w)
                sig_t = np.asarray(sig_t, np.float32)
                sig_v = np.asarray(sig_v, np.float32)
            else:
                cfg_s = NNTrainConfig(
                    **{**cfg.__dict__, "seed": cfg.seed * 100_003 + s}
                )
                sig, valid = split_and_sample(rows, cfg_s)
                sig_t = (sig * w).astype(np.float32)
                sig_v = (valid.astype(np.float32) * w).astype(np.float32)
            self._sig.append((sig_t, sig_v))
            offset += rows
        self.n_train_size = float(
            max(sum(float((st > 0).sum()) for st, _ in self._sig), 1.0)
        )

    def _path(self, prefix: str, s: int) -> str:
        return os.path.join(self.data_dir, f"{prefix}-{s:05d}.npy")

    def _load_host(self, s: int):
        """One shard, padded to pad_rows, as host arrays — the prefetch
        thread's half of the feed (disk read + pad off the compute thread)."""
        rows = self.meta.shard_rows[s]
        pad = self.pad_rows - rows
        x = np.load(self._path(self.prefix, s), mmap_mode="r")
        t = np.load(self._path("tags", s), mmap_mode="r")
        sig_t, sig_v = self._sig[s]
        x = np.asarray(x, np.float32)
        t = np.asarray(t, np.float32)
        if pad:
            x = np.pad(x, ((0, pad), (0, 0)))
            t = np.pad(t, (0, pad))
            sig_t = np.pad(sig_t, (0, pad))
            sig_v = np.pad(sig_v, (0, pad))
        return x, t, sig_t, sig_v

    def _put_device(self, arrs):
        jax = self._jax
        if self.mesh is not None:
            from shifu_tpu.parallel.mesh import shard_rows as put

            return tuple(put(a, self.mesh) for a in arrs)
        return tuple(jax.device_put(a) for a in arrs)

    def __iter__(self):
        # shard s+1's disk read + pad runs on the prefetch thread while
        # shard s computes; device_put dispatches async on consume, so the
        # host->device copy still rides under the caller's compute
        from shifu_tpu.data.pipeline import prefetch_iter

        for arrs in prefetch_iter(range(self.n_shards),
                                  transform=self._load_host):
            yield self._put_device(arrs)


# One compiled shard-gradient program per (arch, hyperparam) signature.
_SHARD_PROGRAMS: dict = {}


def _get_shard_program(cfg: NNTrainConfig, shapes):
    import jax

    key = (
        tuple(shapes), tuple(cfg.activations), cfg.loss, cfg.dropout_rate,
        cfg.mixed_precision,
    )
    prog = _SHARD_PROGRAMS.get(key)
    if prog is None:
        step_metrics = _loss_and_errors(cfg, shapes)

        @jax.jit
        def shard_grad(flat, x, t, sig_t, sig_v, key0, tclass):
            import jax.numpy as jnp

            # tclass >= 0: ONEVSALL member — binary target is (tag == class)
            t2 = jnp.where(tclass >= 0,
                           (t == tclass.astype(t.dtype)).astype(jnp.float32),
                           t)
            g, tr, va = step_metrics(flat, x, t2, sig_t, sig_v, key0)
            # weighted squared-error SUMS so shard partials add exactly
            tr_w = jnp.sum(sig_t)
            va_w = jnp.sum(sig_v)
            return g, tr * tr_w, va * va_w, tr_w, va_w

        _SHARD_PROGRAMS[key] = shard_grad
        prog = shard_grad
    return prog


def _stream_train_sha(cfg: NNTrainConfig, feed: "ShardFeed",
                      target_class: Optional[int],
                      ident_extra: Optional[dict] = None):
    """(sha, per-section shas): the full hyperparameter set in the
    `train` section, the shard layout in the `data` section, and the
    caller's extra identity (retrain's warm-start parent) in the `loop`
    section — resuming onto a different config, dataset, or parent model
    would silently train the wrong weights, and a rejection names which
    side moved."""
    from shifu_tpu.resilience.checkpoint import sectioned_sha

    sections = {
        "train": {k: v for k, v in cfg.__dict__.items()
                  if not callable(v) and k != "progress_cb"},
        "data": {"shardRows": list(feed.meta.shard_rows),
                 "columns": list(feed.meta.columns),
                 "targetClass": target_class},
    }
    if ident_extra:
        sections["loop"] = dict(ident_extra)
    return sectioned_sha(sections)


def train_nn_streamed(
    data_dir: str,
    cfg: NNTrainConfig,
    init_flat: Optional[np.ndarray] = None,
    target_class: Optional[int] = None,
    mesh=None,
    sig_override=None,
    resume: bool = False,
    ident_extra: Optional[dict] = None,
) -> TrainResult:
    """Full-batch BSP training streamed from shards: per epoch, sum shard
    gradients (the NNMaster worker-sum), then ONE weight update. Matches
    train_nn's semantics for full-batch runs; mini_batchs is ignored (each
    shard already bounds device memory).

    With a `mesh`, each streamed shard is placed row-sharded over the
    `data` axis and XLA all-reduces the shard gradient across devices —
    spill and distribution COMPOSE, like the reference running
    MemoryDiskFloatMLDataSet inside every one of its 100 workers
    (AbstractNNWorker.java:485-494): the host stream bounds memory, the
    mesh divides the compute."""
    import jax
    import jax.numpy as jnp

    if cfg.mini_batchs > 1:
        log.warning("MiniBatchs=%d is ignored on the streamed path — each "
                    "epoch is one full-batch pass over the shards",
                    cfg.mini_batchs)
    feed = ShardFeed(data_dir, cfg, mesh=mesh, sig_override=sig_override)
    d = len(feed.meta.columns)
    out_dim = cfg.n_classes if cfg.n_classes > 2 else 1
    layer_sizes = [d] + list(cfg.hidden_nodes) + [out_dim]
    params0 = init_params(layer_sizes, seed=cfg.seed, init=cfg.weight_init)
    flat0, shapes = flatten_params(params0)
    if init_flat is not None and init_flat.size == flat0.size:
        flat0 = init_flat.astype(np.float32)

    shard_grad = _get_shard_program(cfg, shapes)
    init_state, apply_update = make_updater(
        cfg.propagation,
        momentum=cfg.momentum,
        reg=cfg.regularized_constant,
        reg_level=cfg.reg_level,
        adam_beta1=cfg.adam_beta1,
        adam_beta2=cfg.adam_beta2,
    )

    flat = jnp.asarray(flat0)
    opt = init_state(flat0.size)
    lr = cfg.learning_rate
    nts = jnp.float32(feed.n_train_size)
    key0 = jax.random.PRNGKey(cfg.seed)
    tclass = jnp.int32(-1 if target_class is None else target_class)

    best_val = math.inf
    best_flat = np.asarray(flat)
    bad = 0
    tr_e = va_e = 0.0
    it_done = 0
    start_epoch = 0

    # ---- preemption safety: the epoch checkpoint captures the FULL
    # training state (weights, optimizer leaves, lr, best-weights
    # bookkeeping), so a killed run resumes mid-stream and — every
    # per-epoch input being a pure function of (seed, epoch) — finishes
    # bit-identical to an uninterrupted one ----
    from jax import tree_util as jtu

    from shifu_tpu.resilience import checkpoint as ckpt_mod
    from shifu_tpu.resilience import faults

    ck = None
    if cfg.checkpoint_path and cfg.checkpoint_every:
        sha, sha_sections = _stream_train_sha(cfg, feed, target_class,
                                              ident_extra)
        ck = ckpt_mod.StreamCheckpoint(
            cfg.checkpoint_path + ".state" + ckpt_mod.CKPT_SUFFIX,
            sha, every=0, sections=sha_sections)
        if resume:
            loaded = ck.load()
            if loaded is not None:
                _ci, arrays, meta, _blob = loaded
                start_epoch = it_done = int(meta["epoch"])
                flat = jnp.asarray(arrays["flat"])
                leaves, treedef = jtu.tree_flatten(opt)
                opt = jtu.tree_unflatten(
                    treedef, [jnp.asarray(arrays[f"opt{i}"])
                              for i in range(len(leaves))])
                best_flat = np.asarray(arrays["bestFlat"])
                lr = float(meta["lr"])
                best_val = float(meta["bestVal"])
                bad = int(meta["bad"])
                tr_e, va_e = float(meta["trE"]), float(meta["vaE"])
                faults.survived("preempt")
                log.info("resuming streamed train at epoch %d", start_epoch)

    if mesh is not None:
        from shifu_tpu.parallel.mesh import replicate

        flat = replicate(flat, mesh)
        opt = replicate(opt, mesh)

    for it in range(start_epoch, cfg.num_epochs):
        # SIGTERM-analog seam: -Dshifu.faults=preempt@epoch=N kills the
        # run between epochs, after the epoch's checkpoint landed
        faults.fault_point("epoch")
        key = jax.random.fold_in(key0, it)
        g_sum = None
        tr_sum = va_sum = tr_w = va_w = None
        for s, (x, t, sig_t, sig_v) in enumerate(feed):
            # fold the shard index in so dropout masks differ per shard
            key_s = jax.random.fold_in(key, s)
            # sanitizer seam: the shard feed device_put its arrays
            # explicitly, so the gradient dispatch must be transfer-free
            # (-Dshifu.sanitize=transfer, analysis/sanitize.py). Profiled
            # async: shard s+1's host load overlaps shard s's gradient,
            # so a per-shard wait here would serialize the feed.
            with sanitize.transfer_free("nn.shard_grad"):
                g, trs, vas, trw, vaw = profile.dispatch(
                    "nn.shard_grad", shard_grad, flat, x, t, sig_t,
                    sig_v, key_s, tclass, sync=False)
            if g_sum is None:
                g_sum, tr_sum, va_sum, tr_w, va_w = g, trs, vas, trw, vaw
            else:
                g_sum = g_sum + g
                tr_sum, va_sum = tr_sum + trs, va_sum + vas
                tr_w, va_w = tr_w + trw, va_w + vaw
        tr_e = float(tr_sum / jnp.maximum(tr_w, 1.0))
        va_e = float(va_sum / jnp.maximum(va_w, 1.0))
        # best-weights bookkeeping BEFORE the update (va measured pre-update)
        if va_e < best_val:
            best_val = va_e
            best_flat = np.asarray(flat)
            bad = 0
        else:
            bad += 1
        flat, opt = apply_update(opt, flat, g_sum, jnp.float32(lr),
                                 jnp.int32(it + 1), nts)
        lr *= 1.0 - cfg.learning_decay
        it_done = it + 1
        if cfg.progress_cb and cfg.checkpoint_every and (
            it_done % cfg.checkpoint_every == 0
        ):
            cfg.progress_cb(it_done, tr_e, va_e)
        if ck is not None and it_done % cfg.checkpoint_every == 0:
            leaves, _ = jtu.tree_flatten(opt)
            arrays = {"flat": np.asarray(flat),
                      "bestFlat": np.asarray(best_flat)}
            arrays.update({f"opt{i}": np.asarray(leaf)
                           for i, leaf in enumerate(leaves)})
            ck.save(it_done, arrays=arrays, meta={
                "epoch": it_done, "lr": lr, "bestVal": best_val,
                "bad": bad, "trE": tr_e, "vaE": va_e})
            ckpt_mod.atomic_save_npy(cfg.checkpoint_path, np.asarray(flat))
        if cfg.early_stop_window and bad >= cfg.early_stop_window:
            log.info("streamed early stop at epoch %d", it_done)
            break
        if cfg.convergence_threshold and (
            (tr_e + va_e) / 2.0 <= cfg.convergence_threshold
        ):
            break

    if ck is not None:
        ck.clear()  # completed: nothing left to resume
    use_best = cfg.valid_set_rate > 0 and math.isfinite(best_val)
    chosen = best_flat if use_best else np.asarray(flat)
    log.info("streamed train done: %d epochs over %d shards, train %.6f "
             "valid %.6f", it_done, feed.n_shards, tr_e,
             best_val if use_best else va_e)
    return TrainResult(
        params=unflatten_params(chosen, shapes),
        train_error=tr_e,
        valid_error=best_val if use_best else va_e,
        iterations=it_done,
    )
