"""Model registry: load a model set once, fuse raw→score into one program.

The offline scorer (eval/scorer.py ModelRunner) dispatches per model —
normalize (two jit kernels), forward (one jit per model), then aggregates
on the host. Fine for a batch job; for online serving every extra
dispatch is tail latency. The registry builds, per model SET, a single
jit program that takes the host-featurized inputs (filled numeric values
+ bin codes, one pair per UNIQUE norm plan — bagged models usually share
one) and computes normalization, every model's forward, the 0..1000
scaling and the ModelRunner mean/max/min/median aggregation in one fused
dispatch. TensorFlow's train/serve-shared-graph argument (Abadi et al.,
2016) and the DrJAX jit map/reduce idiom both apply directly: the same
compiled substrate that trains the models serves them.

Shape discipline: batches pad to power-of-two row buckets (the PR-1
`bucket_rows` idiom, floor 8), so steady-state serving compiles
O(log max_batch_rows) programs total — the compiled-program cache is
keyed by (model-set sha, row bucket) and `warm()` pre-compiles the
buckets a deployment expects. The PR-4 recompile watchdog sees the same
`jax.compiles` counters every other subsystem reports.

Transfer discipline: `score_raw` stages the featurized inputs into device
memory with ONE explicit `jax.device_put` per batch and dispatches the
fused program inside a `transfer_free("serve.score")` sanitizer seam —
under `-Dshifu.sanitize=transfer` any implicit host↔device copy on the
hot path raises. Results come back via one explicit `jax.device_get`.

Model sets that mix in tree/WDL/reference-format specs fall back to the
ModelRunner path (still batched, still served) — `fused` reports which
mode a registry runs in.

Replica discipline (serve/fleet.py): `device=` pins EVERYTHING this
registry owns — weights, norm constants, drift constants, the per-batch
device_put and therefore the fused dispatch itself — to one device, so
N registries over N devices are N independent scoring replicas whose
dispatches overlap. `labels` (typically {"replica": "<i>"}) ride the
registry's serve.* metrics. Both default off: a bare ModelRegistry
behaves exactly as before (default-device placement, unlabeled metrics).
"""

from __future__ import annotations

import hashlib
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from shifu_tpu.data.pipeline import bucket_rows
from shifu_tpu.data.reader import ColumnarData
from shifu_tpu.eval.scorer import (
    DEFAULT_SCORE_SCALE,
    ModelRunner,
    ScoreResult,
    find_model_paths,
    load_model,
)
from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)

# smallest serving bucket: single-record requests pad to 8 rows, keeping
# the compiled-shape set tiny without inflating tiny batches 256x like the
# ingest-side MIN_ROW_BUCKET would
SERVE_MIN_ROW_BUCKET = 8


def model_set_sha(paths: Sequence[str]) -> str:
    """Content hash of the whole model set — the registry cache key's
    stable half (a redeployed models/ dir yields a new sha, so stale
    compiled programs can never serve new weights)."""
    h = hashlib.sha256()
    for p in sorted(paths):
        h.update(os.path.basename(p).encode())
        with open(p, "rb") as fh:
            h.update(fh.read())
    return h.hexdigest()[:16]


def records_to_columnar(
    records: Sequence[dict], columns: Sequence[str],
) -> ColumnarData:
    """JSON records -> the raw columnar batch the scorers consume,
    through the SAME per-column typing rule the binary wire format uses
    (serve/wire.py:column_from_values): all-float/null columns become
    typed f64 arrays (null = NaN = the missing token) and all-int
    columns i64, so the featurizer never re-parses a value JSON already
    parsed; anything else stringifies exactly as raw CSV fields would.
    One typing rule for both wire formats is what makes JSON and binary
    batches score bit-identically."""
    from shifu_tpu.serve import wire

    n = len(records)
    raw: Dict[str, np.ndarray] = {
        c: wire.column_from_values([r.get(c) for r in records])
        for c in columns
    }
    return ColumnarData(names=list(columns), raw=raw, n_rows=n)


class _PlanFeaturizer:
    """Host half of one norm plan: raw batch -> (filled values, bin codes).

    Mirrors apply_norm_plan's host prep exactly (float64 missing-fill
    BEFORE the float32 cast, shared per-column code cache) but stops at
    the device boundary — the fused program owns every FLOP after it."""

    def __init__(self, plan) -> None:
        self.plan = plan
        self.value_specs = [s for s in plan.specs if s.kind == "value"]
        self.coded_specs = [s for s in plan.specs
                            if s.kind in ("table", "onehot")]
        self._fill64 = np.asarray([s.fill for s in self.value_specs],
                                  dtype=np.float64)

    def __call__(self, data: ColumnarData,
                 code_cache: Optional[dict] = None,
                 numeric_cache: Optional[dict] = None
                 ) -> Tuple[np.ndarray, np.ndarray]:
        from shifu_tpu.norm.normalizer import _bin_codes_for

        n = data.n_rows
        if self.value_specs:
            vals64 = self._numeric_matrix(data, numeric_cache)
            vals = np.where(np.isfinite(vals64), vals64,
                            self._fill64[None, :]).astype(np.float32)
        else:
            vals = np.zeros((n, 0), dtype=np.float32)
        if self.coded_specs:
            codes = np.stack(
                [_bin_codes_for(s.cc, data, code_cache)
                 for s in self.coded_specs],
                axis=1).astype(np.int32)
        else:
            codes = np.zeros((n, 0), dtype=np.int32)
        return vals, codes

    def _numeric_matrix(self, data: ColumnarData,
                        cache: Optional[dict] = None) -> np.ndarray:
        """[n, Cv] float64 with NaN for missing/invalid — ONE flattened
        pandas parse (data.reader.flat_numeric_matrix) instead of one per
        column: online batches are a handful of rows, and per-column
        pandas dispatch was ~25x the fused program's own latency. `cache`
        is the per-call column-name -> parsed-values dict shared with the
        other per-model featurizers and the drift monitor, so each raw
        column is parsed once per request no matter how many consumers."""
        from shifu_tpu.data.reader import flat_numeric_matrix

        names = [s.cc.column_name for s in self.value_specs]
        if cache is not None and all(c in cache for c in names):
            return np.stack([cache[c] for c in names], axis=1)
        out = flat_numeric_matrix(data, names)
        if cache is not None:
            for k, c in enumerate(names):
                cache[c] = out[:, k]
        return out


def _ndarray_nbytes(obj) -> int:
    """Total numpy-array bytes reachable under `obj` (lists/tuples/dicts
    walked; everything else ignored) — the host-side weight-size count
    the zoo's budget ledger charges before anything touches the device."""
    if isinstance(obj, np.ndarray):
        return int(obj.nbytes)
    if isinstance(obj, dict):
        return sum(_ndarray_nbytes(v) for v in obj.values())
    if isinstance(obj, (list, tuple)):
        return sum(_ndarray_nbytes(v) for v in obj)
    return 0


def _spec_weight_bytes(spec) -> int:
    """One spec's host weight bytes: its params tree, or — for non-NN
    specs (trees, adapters) without one — every array reachable on the
    spec. ONE definition for the registration-time estimate AND the
    resident charge, so the ledger's admission math can't diverge."""
    n = _ndarray_nbytes(getattr(spec, "params", None))
    if not n and hasattr(spec, "__dict__"):
        n = _ndarray_nbytes(vars(spec))
    return n


def estimate_weights_bytes(models_dir: str, column_configs=None,
                           model_config=None) -> int:
    """Host-only weight-byte estimate of a model set (specs loaded, no
    device work): what `serve/zoo.py` prices a tenant at registration
    time, before admission decides whether it can ever be resident."""
    paths = find_model_paths(models_dir)
    if not paths:
        raise ValueError(f"no models under {models_dir}")
    return sum(_spec_weight_bytes(load_model(p, column_configs,
                                             model_config))
               for p in paths)


def _build_plan_device_consts(plan, device=None, put_hook=None):
    """Static per-plan tensors the fused program closes over, pre-staged
    as jnp arrays so no constant crosses the host->device boundary at
    call time. `device` pins them to one replica's device (None keeps
    default placement). `put_hook(nbytes)` fires before each device_put
    — the zoo's budget ledger acquires each group's bytes there, so
    staging can never overshoot the budget between two puts."""
    import jax
    import jax.numpy as jnp

    def put(a, dtype):
        arr = np.asarray(a, dtype)
        if put_hook is not None:
            put_hook(int(arr.nbytes))
        return jax.device_put(arr, device)

    value_specs = [s for s in plan.specs if s.kind == "value"]
    table_specs = [s for s in plan.specs if s.kind == "table"]
    coded_specs = [s for s in plan.specs if s.kind in ("table", "onehot")]
    consts = {
        "mean": put([s.mean for s in value_specs], np.float32),
        "std": put([s.std for s in value_specs], np.float32),
        "zs": put([1.0 if s.zscore else 0.0 for s in value_specs],
                  np.float32),
        "cutoff": jnp.float32(plan.cutoff),
    }
    if table_specs:
        max_s = max(s.table.size for s in table_specs)
        tables = np.zeros((len(table_specs), max_s), dtype=np.float32)
        for k, s in enumerate(table_specs):
            tables[k, : s.table.size] = s.table
        consts["tables"] = put(tables, np.float32)
        # static columns of the shared codes matrix that feed the table
        # gather (the rest feed one-hot expansion)
        consts["tab_positions"] = np.asarray(
            [i for i, s in enumerate(coded_specs) if s.kind == "table"],
            np.int32)
    return consts


def _plan_norm_device(plan, consts, vals, codes):
    """Traced: one plan's normalized matrix [n, plan.n_out], assembled in
    spec order (value / table / onehot interleave exactly like
    apply_norm_plan's host concatenate). The value and table math is the
    normalizer's OWN traced bodies (value_norm_traced/table_norm_traced)
    — one semantics for offline norm, eval and serving."""
    import jax
    import jax.numpy as jnp

    from shifu_tpu.norm.normalizer import (
        table_norm_traced,
        value_norm_traced,
    )

    out_vals = None
    if vals.shape[1]:
        out_vals = value_norm_traced(vals, consts["mean"], consts["std"],
                                     consts["zs"], consts["cutoff"])
    out_tab = None
    if "tables" in consts:
        out_tab = table_norm_traced(codes[:, consts["tab_positions"]],
                                    consts["tables"])

    pieces = []
    vi = 0   # next value column in out_vals
    ti = 0   # next table column in out_tab
    ci = 0   # next coded column (table + onehot share the codes matrix)
    for s in plan.specs:
        if s.kind == "value":
            pieces.append(out_vals[:, vi:vi + 1])
            vi += 1
        elif s.kind == "table":
            pieces.append(out_tab[:, ti:ti + 1])
            ti += 1
            ci += 1
        else:  # onehot
            width = s.n_out
            pieces.append(jax.nn.one_hot(
                jnp.clip(codes[:, ci], 0, width - 1), width,
                dtype=jnp.float32))
            ci += 1
    return jnp.concatenate(pieces, axis=1)


class ModelRegistry:
    """Loaded model set + fused raw->score program + warm-program cache."""

    def __init__(self, models_dir: str,
                 scale: float = DEFAULT_SCORE_SCALE,
                 column_configs=None, model_config=None,
                 drift=None, device=None,
                 labels: Optional[dict] = None,
                 put_hook=None) -> None:
        self.models_dir = models_dir
        self.paths = find_model_paths(models_dir)
        if not self.paths:
            raise ValueError(f"no models under {models_dir}")
        self.sha = model_set_sha(self.paths)
        self.scale = float(scale)
        # replica pinning: every array this registry owns (and every
        # per-batch device_put) targets this device; None = default
        self.device = device
        self.labels = dict(labels or {})
        # streamed staging seam (serve/zoo.py): fires with each weight
        # group's byte count BEFORE that group is device_put, so an HBM
        # budget ledger admits the set layer-group by layer-group instead
        # of discovering a full second registry after the fact
        self._put_hook = put_hook
        # residency-repricing seam: fires (no args) after a score that
        # compiled a NEW row bucket — the zoo re-reads memory_analysis()
        # and trues the tenant's ledger charge up, so buckets first seen
        # by live traffic (not warm()) still end up accounted
        self.cost_hook = None
        self.weights_bytes = 0
        self._released = False
        self.model_names = [os.path.basename(p) for p in self.paths]
        self.specs = [load_model(p, column_configs, model_config)
                      for p in self.paths]
        self.fused = self._fusable()
        # online PSI drift (loop/drift.py): when a DriftMonitor rides
        # along, the fused program also bin-codes every batch against the
        # training ColumnConfig bins and folds the counts into the
        # monitor's device window — zero extra dispatches on the hot
        # path. `drift_live` gates the fold: a staged shadow registry
        # shares the monitor but must not double-count the sampled
        # batches it re-scores; promotion flips it live.
        self.drift = drift if (drift is not None and drift.enabled) else None
        self.drift_live = True
        self._runner: Optional[ModelRunner] = None
        self._warm_buckets: set = set()
        if self.fused:
            self._build_fused()
        else:
            # mixed/tree/WDL/reference sets: still served, via the offline
            # scorer's per-model dispatch (one ModelRunner, loaded once)
            self.weights_bytes = sum(_spec_weight_bytes(s)
                                     for s in self.specs)
            if self._put_hook is not None:
                # fallback sets load in one piece (host-resident runner):
                # the ledger still sees the whole cost, just not streamed
                self._put_hook(self.weights_bytes)
            self._runner = ModelRunner(
                self.paths, scale=scale, column_configs=column_configs,
                model_config=model_config)
            self.input_columns = self._input_columns()
            log.info("registry %s: %d models, ModelRunner fallback "
                     "(non-NN spec present; %d input columns)", self.sha,
                     len(self.paths), len(self.input_columns))

    # ---- construction ----
    def _fusable(self) -> bool:
        from shifu_tpu.compat.adapters import RefModelAdapter
        from shifu_tpu.models.nn import NNModelSpec

        return all(
            isinstance(s, NNModelSpec) and not isinstance(s, RefModelAdapter)
            for s in self.specs
        )

    def _build_fused(self) -> None:
        import jax

        from shifu_tpu.norm.normalizer import plan_from_json

        # dedupe norm plans by full signature — bagged models nearly always
        # share one plan, so the fused program normalizes once, not once
        # per bag
        import json

        plan_keys: List[str] = []
        self._plans = []
        self._featurizers: List[_PlanFeaturizer] = []
        self._model_plan_idx: List[int] = []
        for spec in self.specs:
            plan_json = {
                "normType": spec.norm_type,
                "cutoff": getattr(spec, "norm_cutoff", 4.0),
                "columns": spec.norm_specs,
            }
            key = json.dumps(plan_json, sort_keys=True)
            if key not in plan_keys:
                plan_keys.append(key)
                plan = plan_from_json(plan_json)
                self._plans.append(plan)
                self._featurizers.append(_PlanFeaturizer(plan))
            self._model_plan_idx.append(plan_keys.index(key))

        def put_group(arr):
            """One weight group's device_put, ledger-visible: the hook
            (zoo budget acquire) runs BEFORE the bytes land on device,
            so at no instant does device residency exceed what the
            ledger already accounts for."""
            arr = np.asarray(arr)
            self.weights_bytes += int(arr.nbytes)
            if self._put_hook is not None:
                self._put_hook(int(arr.nbytes))
            return jax.device_put(arr, self.device)

        def count_const(nbytes):
            self.weights_bytes += int(nbytes)
            if self._put_hook is not None:
                self._put_hook(int(nbytes))

        consts = [_build_plan_device_consts(p, self.device,
                                            put_hook=count_const)
                  for p in self._plans]
        params = [
            [{"W": put_group(layer["W"]), "b": put_group(layer["b"])}
             for layer in spec.params]
            for spec in self.specs
        ]
        self.model_widths = [
            spec.out_dim if spec.out_dim > 1 else 1 for spec in self.specs
        ]
        plans = self._plans
        model_plan_idx = self._model_plan_idx
        specs = self.specs
        scale = self.scale

        drift = self.drift
        drift_consts = None
        if drift is not None:
            # the monitor is fleet-shared; ITS constants must live on
            # THIS replica's device or the fused dispatch would mix
            # committed devices
            host_consts = drift.device_consts()
            count_const(sum(_ndarray_nbytes(np.asarray(v))
                            for v in jax.tree_util.tree_leaves(host_consts)))
            drift_consts = jax.device_put(host_consts, self.device)

        # staging layout: EVERY fused input — each plan's values and
        # codes, then the drift featurize and its valid column — rides
        # one [bucket, C] float32 host buffer, preallocated per row
        # bucket and reused, so a coalesced batch crosses host->device
        # as a SINGLE contiguous device_put instead of one transfer per
        # leaf of an input pytree. Codes travel as f32 (bin
        # cardinalities sit far below 2**24, where f32 holds every
        # integer exactly) and cast back to i32 on device.
        off = 0
        self._val_slices: List[Tuple[int, int]] = []
        self._code_slices: List[Tuple[int, int]] = []
        for feat in self._featurizers:
            nv = len(feat.value_specs)
            nc = len(feat.coded_specs)
            self._val_slices.append((off, off + nv))
            off += nv
            self._code_slices.append((off, off + nc))
            off += nc
        self._drift_slices = None
        if drift is not None:
            nv = len(drift.numeric_cols)
            nc = len(drift.coded_cols)
            dv = (off, off + nv)
            off += nv
            dc = (off, off + nc)
            off += nc
            self._drift_slices = (dv, dc, off)  # last col: valid mask
            off += 1
        self._staging_cols = off
        self._staging: Dict[int, np.ndarray] = {}
        self._drift_dead_window = None
        val_slices = self._val_slices
        code_slices = self._code_slices
        drift_slices = self._drift_slices

        def fused(staging, drift_window=None):
            import jax.numpy as jnp

            from shifu_tpu.models.nn import forward

            normed = []
            for plan, c, vs, cs in zip(plans, consts, val_slices,
                                       code_slices):
                vals = staging[:, vs[0]:vs[1]]
                codes = staging[:, cs[0]:cs[1]].astype(jnp.int32)
                normed.append(_plan_norm_device(plan, c, vals, codes))
            cols = []
            for mi, spec in enumerate(specs):
                x = normed[model_plan_idx[mi]]
                out = forward(params[mi], x, spec.activations,
                              spec.out_activation)
                if spec.out_dim <= 1:
                    out = out[:, :1]
                cols.append(out * scale)
            m = jnp.concatenate(cols, axis=1)
            outs = (m, m.mean(axis=1), m.max(axis=1), m.min(axis=1),
                    jnp.median(m, axis=1))
            # the branch is on the ARGUMENT'S PYTREE STRUCTURE (None vs
            # array), which jit treats as static — a registry without a
            # drift monitor traces the no-fold program, one with it
            # traces the fused fold; no traced value is branched on
            if drift_window is not None:  # shifu: noqa[JX002]
                # the drift fold, fused: live bin counts vs the training
                # bins accumulate into the resident window with no extra
                # dispatch and no per-batch transfer
                (dv0, dv1), (dc0, dc1), vcol = drift_slices
                outs = outs + (drift.traced_fold(
                    drift_consts, drift_window,
                    staging[:, dv0:dv1],
                    staging[:, dc0:dc1].astype(jnp.int32),
                    staging[:, vcol]),)
            return outs

        # ONE jit for the whole registry, constructed once (never inside
        # the request loop); per-bucket executables cache underneath it
        self._program = jax.jit(fused)
        self.input_columns = self._input_columns()
        log.info("registry %s: %d models fused (%d unique norm plans, "
                 "%d input columns)", self.sha, len(self.specs),
                 len(self._plans), len(self.input_columns))

    def _input_columns(self) -> List[str]:
        """Union of raw source columns across plans, first-seen order —
        the record schema the HTTP front end accepts."""
        seen: List[str] = []
        if self.fused:
            for plan in self._plans:
                for s in plan.specs:
                    if s.cc.column_name not in seen:
                        seen.append(s.cc.column_name)
            return seen
        for spec in self.specs:
            for cd in getattr(spec, "norm_specs", None) or []:
                if cd["name"] not in seen:
                    seen.append(cd["name"])
            for name in getattr(spec, "input_columns", None) or []:
                if name not in seen:
                    seen.append(name)
        return seen

    # ---- serving ----
    def bucket(self, n_rows: int) -> int:
        return bucket_rows(n_rows, minimum=SERVE_MIN_ROW_BUCKET)

    def warm(self, batch_sizes: Sequence[int]) -> List[int]:
        """Pre-compile the buckets covering `batch_sizes`; returns the
        bucket list actually warmed. Call at startup so the first real
        request never pays a compile."""
        warmed = []
        # the synthetic all-"0" rows must not fold into the live drift
        # window: they are not traffic, and with the default driftMinRows
        # they would both burn the warm-up budget and skew the PSI counts
        # toward whatever bin the literal 0 lands in
        drift_live, self.drift_live = self.drift_live, False
        try:
            for b in sorted({self.bucket(max(1, int(s)))
                             for s in batch_sizes}):
                rec = {c: "0" for c in self.input_columns}
                self.score_records([rec] * b)
                warmed.append(b)
        finally:
            self.drift_live = drift_live
        return warmed

    def score_records(self, records: Sequence[dict]) -> ScoreResult:
        data = records_to_columnar(records, self.input_columns)
        return self.score_raw(data)

    def score_raw(self, data: ColumnarData) -> ScoreResult:
        """Raw batch -> ScoreResult, padded to the row bucket and sliced
        back; one explicit device_put in, one explicit device_get out."""
        import time

        if self._released:
            raise ValueError(
                f"registry {self.sha} was released (evicted) — re-admit "
                "the tenant before scoring")

        from shifu_tpu.obs import registry as obs_registry
        from shifu_tpu.obs import reqtrace

        reg = obs_registry()
        # version lineage for request traces: bare-registry embeddings
        # get the same scoredSha attribute the SwappableRegistry stamps
        # (which overwrites this with the sha read at its swap point)
        reqtrace.note_attr(scoredSha=self.sha)
        if not self.fused:
            reg.counter("serve.score.rows", **self.labels).inc(data.n_rows)
            t_dev = time.perf_counter()
            result = self._runner.score_raw(data)
            # fallback path: the runner owns featurize+dispatch+fetch in
            # one opaque call, so the whole of it attributes as device
            reqtrace.note_stage("device", time.perf_counter() - t_dev,
                                t0=t_dev)
            if self.drift is not None and self.drift_live:
                # ModelRunner fallback: host-side fold, same binning
                self.drift.fold_host(data)
            return result
        import jax

        from shifu_tpu.analysis import sanitize

        # featurize = host parse + per-plan prep + the h2d device_put
        # (the ROADMAP's "parse+device_put" host term, now measured per
        # request instead of inferred from aggregate counters)
        t_feat = time.perf_counter()
        n = data.n_rows
        bucket = self.bucket(n)
        code_cache: dict = {}
        numeric_cache: dict = {}
        # fill the bucket's preallocated staging buffer in place — one
        # vectorized pass per coalesced batch, no per-plan pad copies.
        # Reuse is safe: the sync dispatch below returns only after the
        # device has consumed the previous contents.
        buf = self._staging.get(bucket)
        if buf is None:
            buf = np.zeros((bucket, self._staging_cols), dtype=np.float32)
            self._staging[bucket] = buf
        elif n < bucket:
            # pad rows may hold the previous batch; the valid column and
            # value/code columns beyond row n must read as zeros
            buf[n:, :] = 0.0
        for feat, vs, cs in zip(self._featurizers, self._val_slices,
                                self._code_slices):
            vals, codes = feat(data, code_cache, numeric_cache)
            buf[:n, vs[0]:vs[1]] = vals
            buf[:n, cs[0]:cs[1]] = codes
        if self.drift is not None:
            d_vals, d_codes = self.drift.featurize(data, code_cache,
                                                   numeric_cache)
            (dv0, dv1), (dc0, dc1), vcol = self._drift_slices
            buf[:n, dv0:dv1] = d_vals
            buf[:n, dc0:dc1] = d_codes
            buf[:n, vcol] = 1.0
        key = (self.sha, bucket)
        new_bucket = key not in self._warm_buckets
        if new_bucket:
            self._warm_buckets.add(key)
            reg.counter("serve.program_compiles", **self.labels).inc()
            reg.gauge("serve.registry.buckets", **self.labels).set(
                len(self._warm_buckets))
        # the hot seam: the whole batch — every plan's inputs AND the
        # drift featurize — crosses in ONE contiguous device_put, then
        # the fused dispatch must move no other bytes
        # (-Dshifu.sanitize=transfer). Profiled sync: the device_get
        # below blocks on the result anyway, so the wait costs nothing
        # and serve manifests get real per-batch device seconds.
        from shifu_tpu.obs import profile

        if self.drift is not None:
            # the window is already device-resident. A non-live registry
            # (staged shadow) folds into a throwaway window so the
            # shared monitor never double-counts sampled batches — ONE
            # dead window cached per registry, not a put per call.
            if self.drift_live:
                # per-(replica, device) window: the fleet-shared monitor
                # keeps one resident window PER folding replica (merged
                # at flush), so this replica's fold never drags another
                # device's array into its dispatch and never interleaves
                # with another replica's adoption of the same window
                window, drift_gen = self.drift.window(
                    self.device, owner=self.labels.get("replica"))
            else:
                if self._drift_dead_window is None:
                    self._drift_dead_window = jax.device_put(
                        np.zeros(self.drift.total_slots, np.float32),
                        self.device)
                window = self._drift_dead_window
                drift_gen = None
            dev_staging = jax.device_put(buf, self.device)
            reqtrace.note_stage("featurize", time.perf_counter() - t_feat,
                                t0=t_feat)
            t_dev = time.perf_counter()
            with sanitize.transfer_free("serve.score"):
                out = profile.dispatch("serve.fused_score", self._program,
                                       dev_staging, window, sync=True)
            t_d2h = time.perf_counter()
            reqtrace.note_stage("device", t_d2h - t_dev, t0=t_dev)
            m, mean, mx, mn, med = jax.device_get(out[:5])
            reqtrace.note_stage("d2h", time.perf_counter() - t_d2h,
                                t0=t_d2h)
            if self.drift_live:
                self.drift.note_window(out[5], n, gen=drift_gen,
                                       device=self.device,
                                       owner=self.labels.get("replica"))
                reg.counter("loop.drift.rows").inc(n)
        else:
            dev_staging = jax.device_put(buf, self.device)
            reqtrace.note_stage("featurize", time.perf_counter() - t_feat,
                                t0=t_feat)
            t_dev = time.perf_counter()
            with sanitize.transfer_free("serve.score"):
                out = profile.dispatch("serve.fused_score", self._program,
                                       dev_staging, sync=True)
            t_d2h = time.perf_counter()
            reqtrace.note_stage("device", t_d2h - t_dev, t0=t_dev)
            m, mean, mx, mn, med = jax.device_get(out)
            reqtrace.note_stage("d2h", time.perf_counter() - t_d2h,
                                t0=t_d2h)
        reg.counter("serve.score.rows", **self.labels).inc(n)
        if new_bucket and self.cost_hook is not None:
            # the compiled entry for this bucket exists now: let the
            # owner (zoo ledger) re-price this registry's residency
            try:
                self.cost_hook()
            except Exception as he:  # accounting must not fail scoring
                log.warning("registry cost hook failed: %s", he)
        return ScoreResult(
            model_scores=np.asarray(m)[:n],
            mean=np.asarray(mean)[:n],
            max=np.asarray(mx)[:n],
            min=np.asarray(mn)[:n],
            median=np.asarray(med)[:n],
            model_names=list(self.model_names),
            model_widths=list(self.model_widths),
        )

    def memory_analysis(self) -> dict:
        """Resident-cost accounting for the zoo's HBM budget ledger
        (serve/zoo.py): `weightsBytes` is the exact host-side count of
        every array this registry device_put at build (params + norm
        plan constants + drift constants), `programs` are the compiled
        fused program's PR-6 `memory_analysis()` numbers per cached
        signature (= per warm row bucket), and `residentBytes` is the
        high-water cost of keeping the registry warm AND scoring its
        largest compiled bucket: weights + max(args+temps+out) +
        `stagingBytes` (the per-bucket pinned host handoff buffers)."""
        programs: List[dict] = []
        if self.fused and getattr(self, "_program", None) is not None:
            from shifu_tpu.obs import profile

            programs = profile.fn_memory("serve.fused_score",
                                         self._program)
        peak = max((p["peakBytes"] for p in programs), default=0.0)
        # pinned host staging buffers, one per warm bucket: each batch's
        # single device_put mirrors exactly one of them on device, so
        # the zoo ledger charges the handoff once, here, not per request
        staging = sum(b.nbytes
                      for b in getattr(self, "_staging", {}).values())
        return {
            "weightsBytes": int(self.weights_bytes),
            "programs": programs,
            "programPeakBytes": int(peak),
            "stagingBytes": int(staging),
            "residentBytes": int(self.weights_bytes + peak + staging),
        }

    def release(self, refuse: bool = True) -> int:
        """Eviction seam: drop the profiler cost cache's strong
        references to this registry's fused program, so the compiled
        executables AND the closure'd device weights free as soon as
        in-flight dispatches finish and the caller drops the registry
        object. Compiled-program cache entries and device weights go
        together. With `refuse` (the eviction path, fleet already
        drained) new scores raise; `refuse=False` (a promoted-away or
        unstaged version that may have one in-flight batch racing the
        swap) keeps scoring legal — a straggler just pays one fresh
        AOT compile. Returns how many cached signatures were dropped."""
        from shifu_tpu.obs import profile

        n = 0
        if self.fused and getattr(self, "_program", None) is not None:
            n = profile.release_fn(self._program)
        if refuse:
            self._released = True
        return n

    def snapshot(self) -> dict:
        """Registry state for manifests/bench output: compiled buckets
        prove the steady-state compile bound."""
        snap = {
            "sha": self.sha,
            "models": list(self.model_names),
            "fused": self.fused,
            "inputColumns": len(self.input_columns),
            "warmBuckets": sorted(b for (_s, b) in self._warm_buckets),
            "weightsBytes": int(self.weights_bytes),
            "stagingBytes": int(sum(
                b.nbytes for b in getattr(self, "_staging", {}).values())),
            "driftMonitored": (len(self.drift.cols)
                               if self.drift is not None else 0),
        }
        if self.device is not None:
            snap["device"] = str(self.device)
        return snap
