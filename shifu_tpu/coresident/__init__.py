"""Co-resident preemptible trainer: retrain as an HBM-ledger tenant.

The continuous loop (PR 9) still needed 2x hardware at steady state —
`shifu retrain` shared the host with the serving fleet but never the
chips. The reference got co-residency for free: Guagua BSP training ran
*inside* the shared Hadoop cluster and MapReduce's scheduler preempted
it under serving pressure (PAPER.md). This package is the TPU rebuild's
equivalent, with the PR-15 `HbmLedger` as the admission authority:

  plan.py      stage partitioning — split the NN/WDL step program into
               K contiguous layer groups (MPMD pipeline parallelism),
               each a separately compiled program pinned to one device.
  pipeline.py  the per-stage jitted forward/backward programs; stage
               boundaries carry f32 activations (bf16 lives only inside
               matmuls, the PR-11 precision policy) and backward
               rematerializes the forward inside one jit (GPipe).
  tenant.py    the grant protocol — the trainer is a `background`
               ledger tenant: bytes acquired BEFORE every device_put,
               evictable strictly-first under serving pressure, never
               the other way around.
  trainer.py   the epoch loops. `stages=1, microbatches=1` is
               bit-identical to train_nn_streamed / train_wdl_streamed
               (the PR-8/PR-11 parity discipline); eviction checkpoints
               through a ShardedStreamCheckpoint family (per-stage
               parts) and resume is bit-identical to an uninterrupted
               run (the PR-7 contract).
"""

from shifu_tpu.coresident.config import CoresidentConfig
from shifu_tpu.coresident.tenant import (
    EvictedError,
    GrantFullError,
    HttpGrant,
    LocalGrant,
    ZooGrant,
)
from shifu_tpu.coresident.trainer import (
    train_nn_coresident,
    train_wdl_coresident,
)

__all__ = [
    "CoresidentConfig",
    "EvictedError",
    "GrantFullError",
    "HttpGrant",
    "LocalGrant",
    "ZooGrant",
    "train_nn_coresident",
    "train_wdl_coresident",
]
