"""`shifu posttrain` — bin-average scores + feature importance.

Parity: core/processor/PostTrainModelProcessor.java — per selected column,
the average model score of the records falling in each bin (binAvgScore
written back into ColumnConfig, :187-192), plus a feature-importance report
(FeatureImportanceMapper/Reducer). FI here: tree models use split-based
importance; NN/LR use SE knockout sensitivity.
"""

from __future__ import annotations

import os

import numpy as np

from shifu_tpu.norm.dataset import load_codes, load_normalized
from shifu_tpu.processor.basic import BasicProcessor
from shifu_tpu.utils.errors import ErrorCode, ShifuError
from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)


class PostTrainProcessor(BasicProcessor):
    step = "posttrain"

    def run_step(self) -> None:
        self.setup()
        from shifu_tpu.eval.scorer import ModelRunner, find_model_paths

        model_paths = find_model_paths(self.paths.models_dir())
        if not model_paths:
            raise ShifuError(ErrorCode.MODEL_NOT_FOUND,
                             "run `shifu train` before posttrain")
        codes_dir = self.paths.cleaned_data_dir()
        norm_dir = self.paths.normalized_data_dir()
        if not (os.path.isdir(codes_dir) and os.path.isdir(norm_dir)):
            raise ShifuError(ErrorCode.DATA_NOT_FOUND,
                             "run `shifu norm` before posttrain")

        cmeta, codes, tags, weights = load_codes(codes_dir)
        _, feats, _, _ = load_normalized(norm_dir)
        codes = np.asarray(codes)
        runner = ModelRunner(model_paths, column_configs=self.column_configs,
                              model_config=self.model_config)
        from shifu_tpu.models.tree import TreeModelSpec

        if all(isinstance(s, TreeModelSpec) for s in runner.specs):
            scores = np.stack(
                [m.compute(codes) * runner.scale for m in runner.models], axis=1
            ).mean(axis=1)
        else:
            scores = runner.score_normalized(np.asarray(feats, np.float32)).mean

        # ---- bin average score per column (PostTrainMapper/Reducer) ----
        by_name = {c.column_name: c for c in self.column_configs}
        slots = cmeta.extra["slots"]
        for j, name in enumerate(cmeta.columns):
            cc = by_name.get(name)
            if cc is None:
                continue
            s = int(slots[j])
            sums = np.zeros(s)
            cnts = np.zeros(s)
            np.add.at(sums, codes[:, j], scores)
            np.add.at(cnts, codes[:, j], 1.0)
            avg = np.where(cnts > 0, sums / np.maximum(cnts, 1), 0.0)
            cc.column_binning.bin_avg_score = [float(round(v, 2)) for v in avg]
        self.save_column_configs()

        # ---- feature importance report ----
        fi = self._feature_importance(runner, feats, tags)
        self.paths.ensure(self.paths.tmp_dir("posttrain"))
        with open(self.paths.feature_importance_path(), "w") as fh:
            fh.write("column,importance\n")
            for name, v in sorted(fi.items(), key=lambda kv: -kv[1]):
                fh.write(f"{name},{v:.8g}\n")
        log.info("posttrain done: binAvgScore for %d columns, FI -> %s",
                 len(cmeta.columns), self.paths.feature_importance_path())

    def _feature_importance(self, runner, feats, tags) -> dict:
        from shifu_tpu.models.nn import NNModelSpec
        from shifu_tpu.models.tree import TreeModelSpec

        spec = runner.specs[0]
        if isinstance(spec, TreeModelSpec):
            from shifu_tpu.varsel.importance import tree_feature_importance

            return tree_feature_importance(spec)
        if isinstance(spec, NNModelSpec):
            from shifu_tpu.varsel.selector import sensitivity_scores

            scores = sensitivity_scores(
                spec.params, spec.activations, np.asarray(feats, np.float32),
                np.asarray(tags, np.float32), "SE",
            )
            cols = spec.input_columns or [
                f"col_{i}" for i in range(len(scores))
            ]
            return {n: float(s) for n, s in zip(cols, scores)}
        return {}
