"""Preemption as a ledger policy (shifu_tpu/coresident/): the grant's
heartbeat can evict the trainer at ANY epoch boundary; the per-stage
checkpoint family makes that loss-free — resume is bit-identical to an
uninterrupted run (the PR-7 chaos contract), re-admission self-heals
in-process, and resuming under a CHANGED stage count is refused with
`ckpt.rejected{reason="stages"}` instead of silently mixing slices.
"""

import numpy as np
import pytest

from shifu_tpu.coresident import (
    CoresidentConfig,
    EvictedError,
    train_nn_coresident,
)
from shifu_tpu.coresident.tenant import GrantFullError, LocalGrant
from shifu_tpu.norm.dataset import write_normalized
from shifu_tpu.train.nn_trainer import NNTrainConfig


def _write_shards(tmp_path, n=500, d=6, seed=7):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    t = ((x[:, 0] - x[:, 1]) > 0).astype(np.int8)
    w = np.ones(n, np.float32)
    out = str(tmp_path / "NormalizedData")
    write_normalized(out, x, t, w, [f"c{i}" for i in range(d)],
                     n_shards=2)
    return out


def _cfg(**kw):
    base = dict(hidden_nodes=[6, 5], activations=["tanh"],
                propagation="R", num_epochs=8, valid_set_rate=0.2,
                seed=11)
    base.update(kw)
    return NNTrainConfig(**base)


def _flat(params):
    from shifu_tpu.models.nn import flatten_params

    flat, _ = flatten_params(params)
    return np.asarray(flat)


class _EvictingGrant(LocalGrant):
    """Trips the eviction flag at one epoch. `readmit=False` also
    refuses the re-admission acquire (sustained pressure), which is
    what surfaces EvictedError; `readmit=True` models pressure that
    clears immediately — the trainer must self-heal in-process."""

    def __init__(self, evict_at, readmit=False):
        super().__init__("t")
        self.evict_at = int(evict_at)
        self.readmit = readmit
        self.tripped = False

    def heartbeat(self, epoch):
        if epoch == self.evict_at:
            self.tripped = True
            return True
        return False

    def acquire(self, nbytes):
        if self.tripped and not self.readmit:
            raise GrantFullError("pressure holds", int(nbytes))
        super().acquire(nbytes)


def _run(data_dir, cfg, fam, stages=2, microbatches=2, grant=None,
         resume=False, wait_ms=-1.0):
    ccfg = CoresidentConfig(stages=stages, microbatches=microbatches,
                            family_dir=str(fam), wait_ms=wait_ms)
    return train_nn_coresident(data_dir, cfg, ccfg,
                               grant=grant or LocalGrant(),
                               resume=resume)


def test_evict_resume_bit_identical(tmp_path):
    data_dir = _write_shards(tmp_path)
    cfg = _cfg()
    ref = _run(data_dir, cfg, tmp_path / "a")

    with pytest.raises(EvictedError) as ei:
        _run(data_dir, cfg, tmp_path / "b",
             grant=_EvictingGrant(4), wait_ms=0.0)
    assert ei.value.epoch == 4
    assert "resume" in str(ei.value)

    res = _run(data_dir, cfg, tmp_path / "b", resume=True)
    assert res.iterations == ref.iterations
    np.testing.assert_array_equal(_flat(ref.params), _flat(res.params))


def test_readmission_self_heals_in_process(tmp_path):
    """When the wait window finds room again, the trainer re-places its
    stages and finishes — same bits as never-evicted, no operator in
    the loop."""
    data_dir = _write_shards(tmp_path)
    cfg = _cfg()
    ref = _run(data_dir, cfg, tmp_path / "a")
    healed = _run(data_dir, cfg, tmp_path / "b",
                  grant=_EvictingGrant(3, readmit=True), wait_ms=50.0)
    assert healed.iterations == cfg.num_epochs
    np.testing.assert_array_equal(_flat(ref.params),
                                  _flat(healed.params))


def test_resume_across_changed_stages_rejected(tmp_path):
    """K is a placement choice, never training state: each stored part
    covers a different flat slice under a different K, so the family is
    refused (counted) and training starts fresh — still correct."""
    from shifu_tpu import obs

    data_dir = _write_shards(tmp_path)
    cfg = _cfg()
    with pytest.raises(EvictedError):
        _run(data_dir, cfg, tmp_path / "fam",
             grant=_EvictingGrant(3), wait_ms=0.0)

    obs.reset()
    res = _run(data_dir, cfg, tmp_path / "fam", stages=1,
               microbatches=2, resume=True)
    reg = obs.registry()
    assert reg.counter("ckpt.rejected", reason="stages").value >= 1
    # fresh start, full run — and the fresh K=1 result is the ordinary
    # streamed trajectory
    assert res.iterations == cfg.num_epochs
    ref = _run(data_dir, cfg, tmp_path / "ref", stages=1,
               microbatches=2)
    np.testing.assert_array_equal(_flat(ref.params), _flat(res.params))


def test_evicted_snapshot_listed_resumable(tmp_path):
    """`shifu runs --resumable` material: an evicted co-resident family
    surfaces one aggregated row (family name, epoch, stage count), not
    K raw slot files."""
    from shifu_tpu.resilience.checkpoint import list_resumable

    data_dir = _write_shards(tmp_path)
    cfg = _cfg()
    with pytest.raises(EvictedError):
        _run(data_dir, cfg, tmp_path / "fam",
             grant=_EvictingGrant(4), wait_ms=0.0)

    entries = [e for e in list_resumable(str(tmp_path / "fam"))
               if e.get("family") == "coresident"]
    assert len(entries) == 1, entries
    e = entries[0]
    assert e["epoch"] == 4
    assert e["stages"] == 2
    assert e["configSha"]
    assert e["bytes"] > 0
    # completion clears the family: nothing left to resume
    _run(data_dir, cfg, tmp_path / "fam", resume=True)
    assert not [e for e in list_resumable(str(tmp_path / "fam"))
                if e.get("family") == "coresident"]
