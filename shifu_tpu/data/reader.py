"""Columnar dataset reader.

Replaces the reference's Pig/HDFS ingest (fs/ShifuFileUtils scanners,
udf/AddColumnNumAndFilterUDF row->column scatter): data is read column-wise
into numpy arrays once, then every stage (stats, norm, train, eval) operates
on dense vectors — the layout the TPU actually wants.

A data path may be a single delimited file, a gzip file, or a directory of
part files (part-*, ignoring dot-files), matching the reference's layout.
"""

from __future__ import annotations

import gzip
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from shifu_tpu.config.model_config import DEFAULT_MISSING_VALUES
from shifu_tpu.fs.listing import sorted_glob
from shifu_tpu.utils.errors import ErrorCode, ShifuError

# Default tokens treated as missing (ModelSourceDataConf.missingOrInvalidValues).
DEFAULT_MISSING = tuple(DEFAULT_MISSING_VALUES)


def strip_namespace(name: str) -> str:
    """Reference supports namespaced columns "ns::col" (column/NSColumn.java);
    simple names compare on the last segment."""
    return name.rsplit("::", 1)[-1].strip()


def read_header(header_path: str, delimiter: str = "|") -> List[str]:
    from shifu_tpu.fs.source import is_remote, open_source

    if is_remote(header_path):
        import io

        try:
            raw = open_source(header_path, "rb")
        except (OSError, FileNotFoundError) as e:
            raise ShifuError(ErrorCode.HEADER_NOT_FOUND,
                             f"{header_path} ({e})")
        try:
            fh = (gzip.open(raw, "rt") if header_path.endswith(".gz")
                  else io.TextIOWrapper(raw))
            with fh:
                line = fh.readline().rstrip("\n\r")
        finally:
            raw.close()  # gzip.open(fileobj) does not close the wrapped obj
        names = [strip_namespace(c) for c in line.split(delimiter)]
        return _dedupe_names(names)
    if not os.path.isfile(header_path):
        raise ShifuError(ErrorCode.HEADER_NOT_FOUND, header_path)
    opener = gzip.open if header_path.endswith(".gz") else open
    with opener(header_path, "rt") as fh:
        line = fh.readline().rstrip("\n\r")
    names = [strip_namespace(c) for c in line.split(delimiter)]
    return _dedupe_names(names)


def _dedupe_names(names: List[str]) -> List[str]:
    if len(names) == len(set(names)):
        return names
    # de-duplicate with positional suffixes, as the reference warns+renames
    seen: Dict[str, int] = {}
    out = []
    for n in names:
        if n in seen:
            seen[n] += 1
            out.append(f"{n}_{seen[n]}")
        else:
            seen[n] = 0
            out.append(n)
    return out


def _is_data_file(path: str) -> bool:
    """Skip Hadoop markers (_SUCCESS, _temporary), dot-files, empty files."""
    base = os.path.basename(path)
    if base.startswith(".") or base.startswith("_"):
        return False
    return os.path.isfile(path) and os.path.getsize(path) > 0


def _expand_paths(data_path: str) -> List[str]:
    from shifu_tpu.fs.source import expand_remote, is_remote

    if is_remote(data_path):
        # scheme-ful sources (hdfs://, s3://, gs://, memory://) route
        # through the SourceType seam (fs/source.py); pandas consumes the
        # returned URLs directly
        return expand_remote(data_path)
    if os.path.isdir(data_path):
        parts = [p for p in sorted_glob(os.path.join(data_path, "*"))
                 if _is_data_file(p)]
        if not parts:
            raise ShifuError(ErrorCode.DATA_NOT_FOUND, f"empty directory {data_path}")
        return parts
    if os.path.isfile(data_path):
        return [data_path]
    parts = [p for p in sorted_glob(data_path) if _is_data_file(p)]
    if parts:
        return parts
    raise ShifuError(ErrorCode.DATA_NOT_FOUND, data_path)


def drop_stray_header_rows(df, names: List[str]):
    """Drop stray header lines inside data (part files re-concatenated):
    only rows where EVERY field equals its column name are headers — a
    legitimate row whose first field happens to equal the first column's
    name must survive. Shared by the whole-file and chunked readers so
    both apply the identical rule."""
    if not (len(df) and names):
        return df
    cand = (df[names[0]] == names[0]).to_numpy()
    if not cand.any():
        return df
    sub = df[cand]
    header_row = np.ones(len(sub), dtype=bool)
    for c in names[1:]:
        header_row &= (sub[c] == c).to_numpy()
    if not header_row.any():
        return df
    drop = np.zeros(len(df), dtype=bool)
    drop[np.nonzero(cand)[0][header_row]] = True
    return df[~drop]


class LazyColumns:
    """Mapping facade over a pandas DataFrame that materializes object
    arrays per column ON ACCESS. With pandas' arrow-backed string storage
    this keeps unread columns (fat meta/padding fields) in compact arrow
    buffers — the chunked ingest path's memory depends only on the columns
    a stage actually touches."""

    def __init__(self, frame):
        self._frame = frame
        self._cache: Dict[str, np.ndarray] = {}

    def __getitem__(self, name: str) -> np.ndarray:
        arr = self._cache.get(name)
        if arr is None:
            arr = self._frame[name].to_numpy(dtype=object)
            self._cache[name] = arr
        return arr

    def __contains__(self, name: str) -> bool:
        return name in self._frame.columns

    def __iter__(self):
        return iter(self._frame.columns)

    def __len__(self) -> int:
        return len(self._frame.columns)

    def items(self):
        return ((name, self[name]) for name in self._frame.columns)


def _strings_of_typed(arr: np.ndarray) -> np.ndarray:
    """The canonical string form of a typed numeric column — EXACTLY what
    the JSON path would have carried for the same values (str() of the
    Python scalar; NaN is the "" missing token, JSON null's spelling), so
    a typed column falling back to any string-consuming code path is
    bit-identical to its stringly-typed twin."""
    out = np.empty(len(arr), dtype=object)
    if arr.dtype.kind == "f":
        out[:] = ["" if v != v else str(v) for v in arr.tolist()]
    else:
        out[:] = [str(v) for v in arr.tolist()]
    return out


@dataclass
class ColumnarData:
    """All columns as parallel numpy arrays of raw strings (or a lazy
    frame-backed mapping, or — from the binary wire path — typed numeric
    arrays), plus lazily-parsed numeric views cached per column."""

    names: List[str]
    raw: Dict[str, np.ndarray]
    n_rows: int
    missing_values: Sequence[str] = DEFAULT_MISSING
    _numeric_cache: Dict[str, np.ndarray] = field(default_factory=dict, repr=False)
    _missing_cache: Dict[str, np.ndarray] = field(default_factory=dict, repr=False)
    _string_cache: Dict[str, np.ndarray] = field(default_factory=dict, repr=False)

    @classmethod
    def from_frame(
        cls, frame, names: List[str], missing_values: Sequence[str] = DEFAULT_MISSING
    ) -> "ColumnarData":
        return cls(
            names=list(names),
            raw=LazyColumns(frame),
            n_rows=len(frame),
            missing_values=missing_values,
        )

    def _series(self, name: str):
        """pandas Series view of a column WITHOUT materializing an object
        array (arrow-backed when frame-backed). Typed wire columns enter
        as their canonical strings so every .str consumer keeps working."""
        import pandas as pd

        if isinstance(self.raw, LazyColumns):
            return self.raw._frame[name]
        return pd.Series(self.column(name))

    def typed_column(self, name: str) -> Optional[np.ndarray]:
        """The column's typed numeric array (binary wire batches), else
        None. Consumers that can stay vectorized branch on this; all
        other paths transparently see the canonical strings."""
        if isinstance(self.raw, dict):
            arr = self.raw.get(name)
            if isinstance(arr, np.ndarray) and arr.dtype.kind in "fiu":
                return arr
        return None

    def _typed_fast_ok(self) -> bool:
        """Typed shortcuts (isnan instead of token isin, astype instead
        of to_numeric) are only bit-identical to the string path while no
        missing token itself parses as a number — the same guard
        flat_numeric_matrix applies. "" is exempt: str() of a typed value
        is never empty."""
        return not any(
            _parses_as_number(m) for m in self.missing_values if m != ""
        )

    def column(self, name: str) -> np.ndarray:
        typed = self.typed_column(name)
        if typed is not None:
            cached = self._string_cache.get(name)
            if cached is None:
                cached = _strings_of_typed(typed)
                self._string_cache[name] = cached
            return cached
        return self.raw[name]

    def numeric(self, name: str) -> np.ndarray:
        """float64 view of a column; missing/invalid tokens and non-numeric
        values become NaN."""
        cached = self._numeric_cache.get(name)
        if cached is not None:
            return cached
        typed = self.typed_column(name)
        if typed is not None and self._typed_fast_ok():
            # zero-parse path: the wire already delivered numbers.
            # str(float) round-trips and str(int) parses exactly, so this
            # equals to_numeric over the canonical strings bit-for-bit
            vals = typed.astype(np.float64)
            vals[~np.isfinite(vals)] = np.nan
            self._numeric_cache[name] = vals
            return vals
        import pandas as pd

        ser = self._series(name)
        vals = pd.to_numeric(ser, errors="coerce").to_numpy(dtype=np.float64)
        if len(self.missing_values):
            # strip before the missing-set check, exactly like missing_mask —
            # " NA " must count as missing in BOTH views ("" is excluded
            # because to_numeric already coerces blank tokens to NaN)
            miss = ser.str.strip().isin(
                [m for m in self.missing_values if m != ""]
            ).to_numpy()
            vals = np.where(miss, np.nan, vals)
        vals[~np.isfinite(vals)] = np.nan
        self._numeric_cache[name] = vals
        return vals

    def missing_mask(self, name: str) -> np.ndarray:
        """True where the raw token is in the configured missing set.
        Cached — stats touches the same column's mask in several stages
        per chunk, and the prefetch thread warms it for the consumer."""
        cached = self._missing_cache.get(name)
        if cached is not None:
            return cached
        typed = self.typed_column(name)
        if typed is not None and self._typed_fast_ok():
            if typed.dtype.kind == "f" and "" in self.missing_values:
                # NaN's canonical string is "", the missing token; every
                # finite/inf value strings to something numeric, which
                # the guard says is in no missing set
                mask = np.isnan(typed)
                self._missing_cache[name] = mask
                return mask
            if typed.dtype.kind != "f":
                mask = np.zeros(len(typed), dtype=bool)
                self._missing_cache[name] = mask
                return mask
        ser = self._series(name).str.strip()
        mask = ser.isin(list(self.missing_values)).to_numpy()
        self._missing_cache[name] = mask
        return mask

    def select_rows(self, mask: np.ndarray) -> "ColumnarData":
        """Row subset (boolean mask) or reorder (integer index array)."""
        if isinstance(self.raw, LazyColumns):
            mask = np.asarray(mask)
            df = self.raw._frame
            sub = df[mask] if mask.dtype == bool else df.iloc[mask]
            return ColumnarData.from_frame(
                sub.reset_index(drop=True), self.names, self.missing_values
            )
        raw = {k: v[mask] for k, v in self.raw.items()}
        n = len(next(iter(raw.values()))) if raw else 0
        return ColumnarData(
            names=self.names,
            raw=raw,
            n_rows=n,
            missing_values=self.missing_values,
        )

    def sample_rows(self, rate: float, seed: int = 0) -> "ColumnarData":
        if rate >= 1.0:
            return self
        rng = np.random.default_rng(seed)
        mask = rng.random(self.n_rows) < rate
        return self.select_rows(mask)


def read_columnar(
    data_path: str,
    names: List[str],
    delimiter: str = "|",
    missing_values: Sequence[str] = DEFAULT_MISSING,
    max_rows: Optional[int] = None,
) -> ColumnarData:
    """Read a file/dir of delimited rows into string columns via pandas'
    C parser (chunked concat across part files)."""
    import pandas as pd

    frames = []
    remaining = max_rows
    for path in _expand_paths(data_path):
        opener = "gzip" if path.endswith(".gz") else None
        df = pd.read_csv(
            path,
            sep=delimiter,
            header=None,
            names=names,
            dtype=str,
            keep_default_na=False,
            compression=opener,
            engine="c",
            nrows=remaining,
            skip_blank_lines=True,
            on_bad_lines="skip",
        )
        frames.append(df)
        if remaining is not None:
            remaining -= len(df)
            if remaining <= 0:
                break
    df = frames[0] if len(frames) == 1 else pd.concat(frames, ignore_index=True)
    df = drop_stray_header_rows(df, names)
    raw = {name: df[name].to_numpy(dtype=object) for name in names}
    return ColumnarData(
        names=list(names), raw=raw, n_rows=len(df), missing_values=missing_values
    )


def flat_numeric_matrix(data: "ColumnarData",
                        names: Sequence[str]) -> np.ndarray:
    """[n, C] float64 with NaN for missing/invalid — `numeric()`'s exact
    semantics (strip + missing-token set, non-finite -> NaN) over many
    columns in ONE flattened pandas parse. The serve featurizer and the
    drift monitor both bin against this parse; they MUST stay
    bit-identical, which is why there is exactly one implementation.

    Typed columns (binary wire batches) skip the parse entirely — their
    doubles ARE the parse result (same guard as the typed numeric()
    path) — and only the string-backed remainder pays for tokenizing."""
    if data._typed_fast_ok():
        is_typed = [data.typed_column(c) is not None for c in names]
        if any(is_typed):
            out = np.empty((data.n_rows, len(names)), dtype=np.float64)
            rest = [c for j, c in enumerate(names) if not is_typed[j]]
            if rest:
                sub = _flat_parse(data, rest)
                k = 0
                for j, c in enumerate(names):
                    if not is_typed[j]:
                        out[:, j] = sub[:, k]
                        k += 1
            for j, c in enumerate(names):
                if is_typed[j]:
                    out[:, j] = data.numeric(c)
            return out
    return _flat_parse(data, names)


def _flat_parse(data: "ColumnarData", names: Sequence[str]) -> np.ndarray:
    import pandas as pd

    n = data.n_rows
    flat = np.concatenate([
        np.asarray(data.column(c), dtype=object) for c in names
    ])
    tokens = [m for m in data.missing_values if m != ""]
    numeric_tokens = any(_parses_as_number(t) for t in tokens)
    if not numeric_tokens:
        # fast path: a fully numeric batch casts at C speed (~10x the
        # pandas parser — this is the serve hot path, where the parse
        # competes with every replica worker for the GIL). Any
        # missing/invalid value raises and falls back to the coercing
        # parser. Python-float grammar is wider than to_numeric's in
        # exactly two reachable spots — underscore separators ("1_234")
        # and non-ASCII digits ("１２３") parse here but coerce to NaN
        # there — so the vectorized codepoint guard below routes any
        # batch containing either to the slow path; everywhere else
        # the two parsers produce the identical IEEE double (pinned in
        # tests/test_serve.py). Taken only when no missing token itself
        # parses as a number (then the token pass below must see the
        # raw strings).
        try:
            u = flat.astype("U")
            cp = u.view(np.uint32).reshape(len(u), -1)
            if not ((cp == ord("_")).any() or (cp > 127).any()):
                vals = u.astype(np.float64)
                vals[~np.isfinite(vals)] = np.nan
                return vals.reshape(len(names), n).T
        except (TypeError, ValueError):
            pass
    ser = pd.Series(flat)
    vals = pd.to_numeric(ser, errors="coerce").to_numpy(np.float64)
    if numeric_tokens:
        # the per-element strip+isin pass is a dominant host cost on an
        # online batch, and it can only CHANGE anything when a missing
        # token itself parses as a number (to_numeric already coerced
        # "?"-style tokens to NaN) — so pay it only then; skipping it
        # otherwise is bit-identical
        miss = ser.str.strip().isin(tokens).to_numpy()
        vals[miss] = np.nan
    vals[~np.isfinite(vals)] = np.nan
    return vals.reshape(len(names), n).T


def _parses_as_number(token: str) -> bool:
    """Would pd.to_numeric accept this missing token as a value? (If
    not, the coerce pass already NaN'd every occurrence.)"""
    try:
        float(str(token).strip())
        return True
    except (TypeError, ValueError):
        return False


def make_tags(
    target_col: np.ndarray, pos_tags: Sequence[str], neg_tags: Sequence[str]
) -> np.ndarray:
    """Map raw target values to {1 pos, 0 neg, -1 invalid} (reference filters
    invalid-tag rows out of stats/train)."""
    import pandas as pd

    ser = pd.Series(target_col).str.strip()
    out = np.full(len(target_col), -1, dtype=np.int32)
    out[ser.isin(list(pos_tags)).to_numpy()] = 1
    if neg_tags:
        out[ser.isin(list(neg_tags)).to_numpy()] = 0
    else:
        out[(~ser.isin(list(pos_tags))).to_numpy()] = 0
    return out


def make_class_tags(target_col: np.ndarray, tags: Sequence[str]) -> np.ndarray:
    """Multi-class: map raw target values to their index in the flattened tag
    list (posTags + negTags, one of which is empty in classification mode —
    ModelConfig.getFlattenTags / getSetTags). -1 = invalid, filtered out."""
    import pandas as pd

    ser = pd.Series(target_col).str.strip()
    out = np.full(len(target_col), -1, dtype=np.int32)
    for i, tag in enumerate(tags):
        out[(ser == str(tag).strip()).to_numpy()] = i
    return out


def make_tags_for(mc, target_col: np.ndarray,
                  pos: Optional[Sequence[str]] = None,
                  neg: Optional[Sequence[str]] = None) -> np.ndarray:
    """Dispatch on the ModelConfig's classification mode: regression (binary
    pos+neg) -> {1,0,-1}; multi-class classification -> class index 0..K-1."""
    pos = mc.data_set.pos_tags if pos is None else pos
    neg = mc.data_set.neg_tags if neg is None else neg
    all_tags = list(pos or []) + list(neg or [])
    # classification mode (XOR) uses class indices even for K == 2 — the
    # binary make_tags else-branch would map BOTH listed classes to 1 and
    # junk values to 0
    if bool(pos) != bool(neg) and len(all_tags) >= 2:
        return make_class_tags(target_col, all_tags)
    return make_tags(target_col, pos or [], neg or [])


def make_weights(
    data: ColumnarData, weight_column: Optional[str]
) -> np.ndarray:
    if not weight_column or weight_column not in data.raw:
        return np.ones(data.n_rows, dtype=np.float64)
    w = data.numeric(weight_column)
    w = np.where(np.isfinite(w) & (w >= 0), w, 1.0)
    return w
