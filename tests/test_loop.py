"""Continuous-loop subsystem (shifu_tpu/loop/): traffic log, online PSI
drift, zero-downtime hot-swap with shadow scoring, promote gating, and
`shifu retrain` warm-start provenance + chaos parity.

The acceptance pins live here: unshifted replay stays under PSI 0.05
while covariate-shifted replay crosses 0.2 and degrades /healthz with a
ledger recommendation; a hot-swap under concurrent load answers every
request (counted per version, zero lost); a retrain killed mid-stream
resumes bit-identical to an uninterrupted one.
"""

import glob
import json
import os
import shutil
import threading

import numpy as np
import pytest

from shifu_tpu.utils import environment
from tests.helpers import make_binary_dataset, make_model_set


class _Props:
    """Env-property overrides for one test, restored on exit."""

    def __init__(self, **props):
        self.props = {k.replace("_", "."): v for k, v in props.items()}

    def __enter__(self):
        for k, v in self.props.items():
            environment.set_property(k, v)
        return self

    def __exit__(self, *exc):
        for k in self.props:
            environment.set_property(k, "")


def _counter_delta(before, after, prefix):
    """Per-key counter deltas for keys starting with `prefix`."""
    out = {}
    for k, v in after.items():
        if k.startswith(prefix):
            d = v - before.get(k, 0.0)
            if d:
                out[k] = d
    return out


def _snapshot_counters():
    from shifu_tpu import obs

    return dict(obs.registry().snapshot().get("counters", {}))


@pytest.fixture(scope="module")
def model_set(tmp_path_factory):
    """One trained NN model set for the whole module (stats bins + counts
    feed the drift baseline; models feed serve/hot-swap/retrain)."""
    from shifu_tpu.processor.init import InitProcessor
    from shifu_tpu.processor.norm import NormProcessor
    from shifu_tpu.processor.stats import StatsProcessor
    from shifu_tpu.processor.train import TrainProcessor

    root = str(tmp_path_factory.mktemp("loop_ms"))
    make_model_set(root, n_rows=400)
    mcp = os.path.join(root, "ModelConfig.json")
    mc = json.load(open(mcp))
    mc["train"]["numTrainEpochs"] = 12
    json.dump(mc, open(mcp, "w"), indent=2)
    assert InitProcessor(root).run() == 0
    assert StatsProcessor(root).run() == 0
    assert NormProcessor(root).run() == 0
    assert TrainProcessor(root).run() == 0
    return root


@pytest.fixture()
def column_configs(model_set):
    from shifu_tpu.config import load_column_config_list

    return load_column_config_list(
        os.path.join(model_set, "ColumnConfig.json"))


def _raw_batch(names, rows):
    from shifu_tpu.serve.registry import records_to_columnar

    return records_to_columnar([dict(zip(names, r)) for r in rows], names)


def _training_raw(model_set):
    from shifu_tpu.data.reader import read_columnar, read_header

    names = read_header(os.path.join(model_set, "data", "header.txt"))
    return read_columnar(os.path.join(model_set, "data", "data.txt"),
                         names)


# ---------------------------------------------------------------------------
# traffic log
# ---------------------------------------------------------------------------


class _FakeResult:
    def __init__(self, n):
        self.mean = np.linspace(100.0, 900.0, n)


def _fake_data(names, n, fill="1.5"):
    from shifu_tpu.serve.registry import records_to_columnar

    return records_to_columnar([{c: fill for c in names}] * n, names)


class TestTrafficLog:
    NAMES = ["a", "b"]

    def test_rotation_flush_and_meta(self, tmp_path):
        from shifu_tpu.loop.traffic import TrafficLog, traffic_columns

        log = TrafficLog(str(tmp_path), traffic_columns(self.NAMES),
                         sample=1.0, chunk_rows=10)
        for _ in range(3):
            log.record(_fake_data(self.NAMES, 7), _FakeResult(7), "sha0")
        # the buffer rotates into a whole chunk file when it reaches
        # chunk_rows (14 >= 10 after batch 2); batch 3 stays buffered
        chunks = sorted(glob.glob(
            str(tmp_path / ".shifu/runs/traffic/traffic-*.psv")))
        assert len(chunks) == 1
        log.flush()
        chunks = sorted(glob.glob(
            str(tmp_path / ".shifu/runs/traffic/traffic-*.psv")))
        assert len(chunks) == 2
        rows = sum(1 for p in chunks for _ in open(p))
        assert rows == 21
        meta = json.load(open(
            tmp_path / ".shifu/runs/traffic/_meta.json"))
        assert meta["schema"] == "shifu.traffic/1"
        assert meta["columns"][-4:] == ["shifu_score_mean",
                                        "shifu_model_sha", "shifu_trace",
                                        "shifu_ts"]

    def test_seq_grows_across_restart(self, tmp_path):
        from shifu_tpu.loop.traffic import TrafficLog, traffic_columns

        a = TrafficLog(str(tmp_path), traffic_columns(self.NAMES),
                       sample=1.0, chunk_rows=4)
        a.record(_fake_data(self.NAMES, 4), _FakeResult(4), "s")
        a.close()
        b = TrafficLog(str(tmp_path), traffic_columns(self.NAMES),
                       sample=1.0, chunk_rows=4)
        b.record(_fake_data(self.NAMES, 4), _FakeResult(4), "s")
        b.close()
        names = sorted(os.path.basename(p) for p in glob.glob(
            str(tmp_path / ".shifu/runs/traffic/traffic-*.psv")))
        assert names == ["traffic-00001.psv", "traffic-00002.psv"]

    def test_sampling_is_deterministic(self, tmp_path):
        from shifu_tpu.loop.traffic import TrafficLog, traffic_columns

        kept = []
        for sub in ("x", "y"):
            log = TrafficLog(str(tmp_path / sub),
                             traffic_columns(self.NAMES),
                             sample=0.5, chunk_rows=1000, seed=3)
            n = sum(log.record(_fake_data(self.NAMES, 50),
                               _FakeResult(50), "s") for _ in range(4))
            log.flush()
            kept.append(n)
        assert kept[0] == kept[1]
        files = [sorted(glob.glob(str(tmp_path / sub /
                                      ".shifu/runs/traffic/*.psv")))
                 for sub in ("x", "y")]

        def rows_sans_ts(paths):
            # the trailing field is wall-clock: strip it before comparing
            return [line.rsplit("|", 1)[0]
                    for p in paths for line in open(p)]

        assert rows_sans_ts(files[0]) == rows_sans_ts(files[1])

    def test_delimiter_and_newline_sanitized(self, tmp_path):
        from shifu_tpu.loop.traffic import TrafficLog, traffic_columns

        log = TrafficLog(str(tmp_path), traffic_columns(self.NAMES),
                         sample=1.0, chunk_rows=1)
        log.record(_fake_data(self.NAMES, 1, fill="bad|val\nue"),
                   _FakeResult(1), "s")
        (path,) = glob.glob(str(tmp_path / ".shifu/runs/traffic/*.psv"))
        line = open(path).read().rstrip("\n")
        # 2 feature fields + score + sha + trace + ts = exactly 6 fields
        assert len(line.split("|")) == 6
        assert "bad;val ue" in line

    def test_readback_is_an_ordinary_chunk_stream(self, tmp_path):
        from shifu_tpu.loop.traffic import (
            TrafficLog,
            traffic_columns,
            traffic_source,
        )

        log = TrafficLog(str(tmp_path), traffic_columns(self.NAMES),
                         sample=1.0, chunk_rows=8)
        for _ in range(3):
            log.record(_fake_data(self.NAMES, 5), _FakeResult(5), "sha9")
        log.close()
        factory, names = traffic_source(str(tmp_path))
        assert names[:2] == self.NAMES
        chunks = list(factory())
        total = sum(c.n_rows for c in chunks)
        assert total == 15
        first = chunks[0]
        assert list(first.column("shifu_model_sha"))[0] == "sha9"
        # scores parse back numerically
        assert np.isfinite(first.numeric("shifu_score_mean")).all()

    def test_snapshot_counts_only_this_runs_chunks(self, tmp_path):
        """The manifest's chunk count is per-replica accounting: a
        restarted server must not claim the chunks a previous run left
        on disk (the seq counter DOES continue across restarts)."""
        from shifu_tpu.loop.traffic import TrafficLog, traffic_columns

        a = TrafficLog(str(tmp_path), traffic_columns(self.NAMES),
                       sample=1.0, chunk_rows=4)
        a.record(_fake_data(self.NAMES, 4), _FakeResult(4), "s")
        a.close()
        assert a.snapshot()["chunks"] == 1
        b = TrafficLog(str(tmp_path), traffic_columns(self.NAMES),
                       sample=1.0, chunk_rows=4)
        assert b.snapshot()["chunks"] == 0
        b.record(_fake_data(self.NAMES, 4), _FakeResult(4), "s")
        b.close()
        assert b.snapshot()["chunks"] == 1

    def test_schema_change_retires_old_chunks(self, tmp_path):
        """A restart with a different column schema must not rewrite
        _meta.json over chunks framed with the old one — old rows would
        parse misaligned into the new columns and retrain on garbage.
        The old log retires wholesale to a superseded subdir."""
        from shifu_tpu.loop.traffic import (
            TrafficLog,
            list_chunks,
            traffic_columns,
            traffic_dir,
            traffic_source,
        )

        a = TrafficLog(str(tmp_path), traffic_columns(self.NAMES),
                       sample=1.0, chunk_rows=4)
        a.record(_fake_data(self.NAMES, 4), _FakeResult(4), "s")
        a.close()
        assert len(list_chunks(str(tmp_path))) == 1
        new_cols = traffic_columns(self.NAMES + ["extra_col"])
        b = TrafficLog(str(tmp_path), new_cols, sample=1.0, chunk_rows=4)
        # active dir holds ONLY the new schema; old files retired intact
        assert list_chunks(str(tmp_path)) == []
        retired = os.path.join(traffic_dir(str(tmp_path)), "superseded-1")
        assert len(glob.glob(os.path.join(retired, "traffic-*.psv"))) == 1
        assert os.path.isfile(os.path.join(retired, "_meta.json"))
        b.record(_fake_data(self.NAMES + ["extra_col"], 4),
                 _FakeResult(4), "s")
        b.close()
        _factory, names = traffic_source(str(tmp_path))
        assert names == new_cols  # readback sees one coherent schema
        # matching-schema restart still keeps everything (no retirement)
        c = TrafficLog(str(tmp_path), new_cols, sample=1.0, chunk_rows=4)
        c.record(_fake_data(self.NAMES + ["extra_col"], 4),
                 _FakeResult(4), "s")
        c.close()
        assert len(list_chunks(str(tmp_path))) == 2

    def test_readback_without_log_raises(self, tmp_path):
        from shifu_tpu.loop.traffic import traffic_source

        with pytest.raises(FileNotFoundError):
            traffic_source(str(tmp_path))


class TestFleetTrafficLog:
    """ISSUE-18 fleet sharing: N serve processes append to ONE log under
    their (sanitized) lease ids; consumers read the union."""

    NAMES = ["a", "b"]

    def _log(self, root, writer, n=4):
        from shifu_tpu.loop.traffic import TrafficLog, traffic_columns

        log = TrafficLog(str(root), traffic_columns(self.NAMES),
                         sample=1.0, chunk_rows=4, writer=writer)
        log.record(_fake_data(self.NAMES, n), _FakeResult(n), "sha0")
        log.close()
        return log

    def test_writer_id_sanitizes_and_never_parses_as_seq(self):
        from shifu_tpu.loop.traffic import _CHUNK_RE, writer_id

        # lease ids are host-pid-token (resilience/lease.py)
        wid = writer_id("box.example-4242-deadbeef")
        assert wid == "box_example_4242_deadbeef"
        for raw in ("12345", "", "007-x"):
            wid = writer_id(raw)
            m = _CHUNK_RE.match(f"traffic-{wid}-00001.psv")
            assert m and m.group(1) == wid, (raw, wid)

    def test_union_in_seq_then_writer_order_and_scope_filter(
            self, tmp_path):
        from shifu_tpu.loop.traffic import (
            chunk_writer,
            list_chunks,
            list_writers,
        )

        self._log(tmp_path, "hostB_1_aa")
        self._log(tmp_path, "hostA_2_bb")
        self._log(tmp_path, "hostA_2_bb", n=4)  # second chunk, seq 2
        union = [os.path.basename(p) for p in list_chunks(str(tmp_path))]
        assert union == ["traffic-hostA_2_bb-00001.psv",
                         "traffic-hostB_1_aa-00001.psv",
                         "traffic-hostA_2_bb-00002.psv"]
        assert list_writers(str(tmp_path)) == ["hostA_2_bb",
                                               "hostB_1_aa"]
        only_a = list_chunks(str(tmp_path), scope="hostA_2_bb")
        assert [chunk_writer(p) for p in only_a] == ["hostA_2_bb"] * 2

    def test_per_writer_sequences_are_independent(self, tmp_path):
        """Two processes appending concurrently never race on a shared
        sequence: each writer numbers its OWN chunks, and a restart
        resumes after its own highest seq, ignoring the peer's."""
        self._log(tmp_path, "w1")
        self._log(tmp_path, "w2")
        self._log(tmp_path, "w1")  # restart of writer 1
        names = sorted(os.path.basename(p) for p in glob.glob(
            str(tmp_path / ".shifu/runs/traffic/traffic-*.psv")))
        assert names == ["traffic-w1-00001.psv", "traffic-w1-00002.psv",
                         "traffic-w2-00001.psv"]

    def test_set_writer_rebases_sequence_post_lease(self, tmp_path):
        """The server names its writer only after the lease grant;
        set_writer on a live log must re-derive the next seq from the
        new writer's own chunks."""
        from shifu_tpu.loop.traffic import TrafficLog, traffic_columns

        self._log(tmp_path, "lease1")  # pre-existing chunk of lease1
        log = TrafficLog(str(tmp_path), traffic_columns(self.NAMES),
                         sample=1.0, chunk_rows=4)
        log.set_writer("lease1")
        log.record(_fake_data(self.NAMES, 4), _FakeResult(4), "s")
        log.close()
        assert log.snapshot()["writer"] == "lease1"
        names = sorted(os.path.basename(p) for p in glob.glob(
            str(tmp_path / ".shifu/runs/traffic/traffic-*.psv")))
        assert names == ["traffic-lease1-00001.psv",
                         "traffic-lease1-00002.psv"]

    def test_readback_unions_all_writers(self, tmp_path):
        from shifu_tpu.loop.traffic import traffic_source

        self._log(tmp_path, "w1")
        self._log(tmp_path, "w2")
        factory, _names = traffic_source(str(tmp_path))
        rows = sum(c.n_rows for c in factory())
        assert rows == 8
        solo, _ = traffic_source(str(tmp_path), scope="w2")
        assert sum(c.n_rows for c in solo()) == 4


# ---------------------------------------------------------------------------
# drift monitor
# ---------------------------------------------------------------------------


class TestDriftMonitor:
    def test_unshifted_replay_stays_quiet(self, model_set, column_configs):
        from shifu_tpu.loop.drift import DriftMonitor

        mon = DriftMonitor(column_configs, threshold=0.2, min_rows=64)
        assert mon.enabled
        mon.fold_host(_training_raw(model_set))
        v = mon.verdict()
        assert v["status"] == "ok"
        # replaying the training distribution itself: everything quiet
        assert v["maxPsi"] < 0.05, v["psi"]

    def test_shifted_replay_crosses_threshold_and_degrades(
            self, model_set, column_configs, tmp_path):
        from shifu_tpu.loop.drift import DriftMonitor
        from shifu_tpu.serve.health import HealthMonitor

        names, rows, _ = make_binary_dataset(n_rows=400, seed=21)
        shifted = []
        for r in rows:
            r = list(r)
            # covariate shift: num_0 (field 1) scaled + offset far out of
            # its training bins
            try:
                r[1] = f"{float(r[1]) * 4.0 + 25.0:.6g}"
            except ValueError:
                pass
            shifted.append(r)
        mon = DriftMonitor(column_configs, threshold=0.2, min_rows=64)
        mon.fold_host(_raw_batch(names, shifted))
        health = HealthMonitor()
        ledger_root = str(tmp_path)
        v = mon.check_degrade(health, ledger_root, model_sha="abc123")
        assert v is not None and v["status"] == "drift"
        assert "num_0" in v["driftedColumns"]
        assert v["psi"]["num_0"] > 0.2
        assert health.snapshot()["status"] == "degraded"
        # exactly ONE machine-readable recommendation manifest
        recs = glob.glob(os.path.join(ledger_root,
                                      ".shifu/runs/recommend-*.json"))
        assert len(recs) == 1
        rec = json.load(open(recs[0]))["recommendation"]
        assert rec["action"] == "retrain"
        assert rec["modelSetSha"] == "abc123"
        assert "num_0" in rec["drift"]["driftedColumns"]
        # a second breach on the same columns stamps no second manifest
        mon.check_degrade(health, ledger_root, model_sha="abc123")
        assert len(glob.glob(os.path.join(
            ledger_root, ".shifu/runs/recommend-*.json"))) == 1

    def test_reset_mid_flush_drops_old_window_counts(
            self, model_set, column_configs, monkeypatch):
        """A promotion reset() landing while a window flush is between
        its swap (under the lock) and its merge-back must DROP the old
        version's counts instead of resurrecting them into the zeroed
        host fold — the new version's PSI must start from a clean
        slate."""
        import jax
        import jax.numpy as jnp

        from shifu_tpu.loop.drift import DriftMonitor

        mon = DriftMonitor(column_configs, threshold=0.2, min_rows=64)
        assert mon.enabled
        mon.note_window(jnp.ones(mon.total_slots, jnp.float32), 8)
        real_get = jax.device_get
        fired = []

        def reset_then_get(x):
            if not fired:
                fired.append(1)
                mon.reset()  # the promotion, exactly mid-flush
            return real_get(x)

        monkeypatch.setattr(jax, "device_get", reset_then_get)
        mon._flush()
        assert fired
        assert float(mon._host.sum()) == 0.0  # old counts dropped
        # and post-reset traffic still folds normally
        monkeypatch.setattr(jax, "device_get", real_get)
        mon.note_window(jnp.ones(mon.total_slots, jnp.float32), 8)
        mon._flush()
        assert float(mon._host.sum()) == float(mon.total_slots)
        # the fold-ADOPTION path is guarded the same way: a window read
        # before the reset must not be adopted after it (the registry
        # passes window()'s generation back through note_window)
        _w, gen = mon.window()
        mon.reset()
        mon.note_window(jnp.full(mon.total_slots, 7.0, jnp.float32), 8,
                        gen=gen)
        assert mon._rows == 0 and not mon._windows  # stale: dropped
        w, gen = mon.window()
        mon.note_window(w + 1.0, 8, gen=gen)  # current gen: adopted
        mon._flush()
        assert float(mon._host.sum()) == float(mon.total_slots)
        # fleet-PR regression: a fold whose BASE window a concurrent
        # flush already merged must be DROPPED (its token's flush epoch
        # is stale) — adopting base+delta would double-count the base
        # into the next flush (the N-replica worker interleave)
        w, tok = mon.window()
        mon.note_window(w + 1.0, 8, gen=tok)
        mon._flush()          # merges w+1.0; bumps the key's epoch
        before = float(mon._host.sum())
        mon.note_window(w + 1.0, 8, gen=tok)  # stale epoch: dropped
        mon._flush()
        assert float(mon._host.sum()) == before
        # and a fresh token folds normally again
        w, tok = mon.window()
        mon.note_window(w + 1.0, 8, gen=tok)
        mon._flush()
        assert float(mon._host.sum()) == before + float(mon.total_slots)

    def test_reset_reopens_the_degrade_loop(self, model_set,
                                            column_configs, tmp_path):
        """After a promote acts on the recommendation, reset() clears the
        monitor so drift on the NEW version's traffic degrades and
        recommends AGAIN — the closed loop closes more than once."""
        from shifu_tpu.loop.drift import DriftMonitor
        from shifu_tpu.serve.health import HealthMonitor

        names, rows, _ = make_binary_dataset(n_rows=400, seed=22)
        shifted = []
        for r in rows:
            r = list(r)
            try:
                r[1] = f"{float(r[1]) * 4.0 + 25.0:.6g}"
            except ValueError:
                pass
            shifted.append(r)
        mon = DriftMonitor(column_configs, threshold=0.2, min_rows=64)
        health = HealthMonitor()
        ledger_root = str(tmp_path)
        mon.fold_host(_raw_batch(names, shifted))
        assert mon.check_degrade(health, ledger_root,
                                 model_sha="v1")["status"] == "drift"
        # promote path: recommendation acted on — health clears, monitor
        # resets (what ScoringServer.promote_candidate does)
        health.clear_degraded()
        mon.reset()
        assert health.snapshot()["status"] == "ok"
        assert mon.verdict()["rows"] == 0
        # the new version drifts too: re-degrades + SECOND recommendation
        mon.fold_host(_raw_batch(names, shifted))
        v = mon.check_degrade(health, ledger_root, model_sha="v2")
        assert v["status"] == "drift"
        assert health.snapshot()["status"] == "degraded"
        recs = sorted(glob.glob(os.path.join(
            ledger_root, ".shifu/runs/recommend-*.json")))
        assert len(recs) == 2
        assert json.load(open(recs[1]))["recommendation"][
            "modelSetSha"] == "v2"

    def test_clear_degraded_spares_crash_degrades(self):
        """A promote clears the STICKY (drift) degrade only: scoring
        crashes degrade through their own hysteresis, and routing full
        traffic back onto a still-crashing replica because an unrelated
        promote landed would be wrong."""
        from shifu_tpu.serve.health import HealthMonitor

        h = HealthMonitor()
        h.note_crash("worker died")
        assert h.snapshot()["status"] == "degraded"
        h.clear_degraded()  # promote acts on drift, not on crashes
        assert h.snapshot()["status"] == "degraded"
        # a PURE drift degrade (no crash underneath) DOES clear
        h2 = HealthMonitor()
        h2.note_degraded("psi over threshold")
        h2.clear_degraded()
        assert h2.snapshot()["status"] == "ok"

    def test_clear_degraded_keeps_layered_crash_degrade(self):
        """Crash degrade + drift degrade can LAYER; promoting away the
        drift must leave the crash degrade (and its clean-batch
        hysteresis) underneath."""
        from shifu_tpu.serve.health import HealthMonitor

        h = HealthMonitor(ok_after=2)
        h.note_crash("worker died")
        h.note_degraded("psi over threshold")
        h.clear_degraded()  # promote acted on the drift only
        snap = h.snapshot()
        assert snap["status"] == "degraded"
        assert snap["reason"] == "worker died"  # crash cause restored
        h.note_ok()
        h.note_ok()  # hysteresis resumes and heals the crash degrade
        assert h.snapshot()["status"] == "ok"

    def test_check_degrade_returns_verdict_when_quiet(
            self, model_set, column_configs):
        """One verdict computation per cadence: the quiet path hands the
        verdict back instead of None, so callers never call verdict()
        a second time."""
        from shifu_tpu.loop.drift import DriftMonitor

        mon = DriftMonitor(column_configs, threshold=0.2, min_rows=64)
        mon.fold_host(_training_raw(model_set))
        v = mon.check_degrade()
        assert v is not None and v["status"] == "ok"

    def test_warming_below_min_rows_never_degrades(self, column_configs):
        from shifu_tpu.loop.drift import DriftMonitor

        mon = DriftMonitor(column_configs, threshold=0.0, min_rows=10_000)
        names, rows, _ = make_binary_dataset(n_rows=50, seed=33)
        mon.fold_host(_raw_batch(names, rows))
        v = mon.verdict()
        assert v["status"] == "warming"
        assert v["driftedColumns"] == []
        assert mon.check_degrade() is None or v["status"] != "drift"

    def test_fused_fold_matches_host_fold(self, model_set, column_configs):
        """The traced in-program fold and the host fallback fold must
        agree bin-for-bin — one drift definition, two execution paths."""
        from shifu_tpu.loop.drift import DriftMonitor
        from shifu_tpu.serve.registry import ModelRegistry

        fused_mon = DriftMonitor(column_configs, threshold=0.2,
                                 min_rows=64)
        reg = ModelRegistry(os.path.join(model_set, "models"),
                            drift=fused_mon)
        assert reg.fused
        raw = _training_raw(model_set)
        reg.score_raw(raw)
        host_mon = DriftMonitor(column_configs, threshold=0.2, min_rows=64)
        host_mon.fold_host(raw)
        a = fused_mon.psi_by_column()
        b = host_mon.psi_by_column()
        assert set(a) == set(b)
        for k in a:
            assert a[k] == pytest.approx(b[k], abs=1e-9), k
        # and the raw counts themselves are identical
        assert np.array_equal(fused_mon._host, host_mon._host)

    def test_warm_does_not_pollute_drift_window(self, model_set,
                                                column_configs):
        """Startup warm-up scores synthetic all-"0" rows; they are not
        traffic and must fold NOTHING into the drift monitor — else they
        burn the min-rows warm-up and skew the PSI baseline."""
        from shifu_tpu.loop.drift import DriftMonitor
        from shifu_tpu.serve.registry import ModelRegistry

        mon = DriftMonitor(column_configs, threshold=0.2, min_rows=64)
        reg = ModelRegistry(os.path.join(model_set, "models"), drift=mon)
        reg.warm([1, 16])
        assert mon.verdict()["rows"] == 0
        assert reg.drift_live  # restored for real traffic
        reg.score_raw(_training_raw(model_set))
        assert mon.verdict()["rows"] > 0

    def test_column_with_mismatched_counts_not_monitored(
            self, column_configs):
        import copy

        from shifu_tpu.loop.drift import DriftMonitor

        ccs = copy.deepcopy(column_configs)
        victim = next(c for c in ccs
                      if c.column_binning.bin_boundary
                      and c.column_binning.bin_count_pos)
        victim.column_binning.bin_count_pos = [1, 2]  # wrong arity
        victim.column_binning.bin_count_neg = [1, 2]
        mon = DriftMonitor(ccs, threshold=0.2, min_rows=1)
        assert victim.column_name not in [c.name for c in mon.cols]


# ---------------------------------------------------------------------------
# hot-swap + shadow scoring
# ---------------------------------------------------------------------------


def _perturbed_candidate(model_set, tmp_path, delta=1e-3):
    """A candidate dir whose single NN model differs slightly (new sha,
    near-identical scores)."""
    from shifu_tpu.models.nn import NNModelSpec

    cand = str(tmp_path / "candidate")
    os.makedirs(cand, exist_ok=True)
    spec = NNModelSpec.load(os.path.join(model_set, "models", "model0.nn"))
    spec.params[-1]["b"] = np.asarray(spec.params[-1]["b"]) + delta
    spec.save(os.path.join(cand, "model0.nn"))
    return cand


class TestHotSwap:
    def test_stage_shadow_agree_promote(self, model_set, tmp_path):
        from shifu_tpu.loop.hotswap import SwappableRegistry
        from shifu_tpu.serve.registry import ModelRegistry

        with _Props(shifu_loop_shadowSample="1.0"):
            sw = SwappableRegistry(
                ModelRegistry(os.path.join(model_set, "models")))
            old_sha = sw.sha
            cand = _perturbed_candidate(model_set, tmp_path)
            staged = sw.stage(cand)
            assert staged["sha"] != old_sha
            raw = _training_raw(model_set)
            res = sw.score_raw(raw)
            sw.observe(raw, res)
            snap = sw.shadow_snapshot()
            assert snap["rows"] == raw.n_rows
            assert snap["errors"] == 0
            # +1e-3 on the output bias: full agreement at tolerance 5.0
            assert snap["agreement"] == 1.0
            assert snap["maxAbsDelta"] < 5.0
            out = sw.promote()
            assert out["from"] == old_sha and out["to"] == staged["sha"]
            assert sw.sha == staged["sha"]
            assert sw.shadow_snapshot() is None

    def test_promote_without_stage_raises(self, model_set):
        from shifu_tpu.loop.hotswap import SwappableRegistry
        from shifu_tpu.serve.registry import ModelRegistry

        sw = SwappableRegistry(
            ModelRegistry(os.path.join(model_set, "models")))
        with pytest.raises(ValueError):
            sw.promote()

    def test_stage_rejects_schema_change(self, model_set, tmp_path):
        from shifu_tpu.loop.hotswap import SwappableRegistry
        from shifu_tpu.models.nn import NNModelSpec
        from shifu_tpu.serve.registry import ModelRegistry

        spec = NNModelSpec.load(
            os.path.join(model_set, "models", "model0.nn"))
        spec.norm_specs = spec.norm_specs[:-1]  # drop an input column
        spec.layer_sizes = list(spec.layer_sizes)
        cand = str(tmp_path / "bad_candidate")
        os.makedirs(cand)
        spec.save(os.path.join(cand, "model0.nn"))
        sw = SwappableRegistry(
            ModelRegistry(os.path.join(model_set, "models")))
        with pytest.raises(ValueError, match="schema"):
            sw.stage(cand)

    def test_swap_under_load_loses_nothing(self, model_set, tmp_path):
        """The acceptance pin: concurrent scoring across a hot-swap —
        every request answered exactly once, per-version counters account
        for every row, both versions served."""
        from shifu_tpu.loop.hotswap import SwappableRegistry
        from shifu_tpu.serve.batcher import AdmissionQueue
        from shifu_tpu.serve.registry import ModelRegistry
        from shifu_tpu.serve.server import Scorer

        before = _snapshot_counters()
        with _Props(shifu_loop_shadowSample="1.0"):
            sw = SwappableRegistry(
                ModelRegistry(os.path.join(model_set, "models")))
            old_sha = sw.sha
            cand = _perturbed_candidate(model_set, tmp_path)
            scorer = Scorer(sw, AdmissionQueue(256), max_wait_ms=1.0)
            names = list(sw.input_columns)
            rec = {c: "0.5" for c in names}
            n_threads, per_thread, rows_per = 4, 30, 3
            errors = []
            answered = [0] * n_threads
            swapped = threading.Event()

            def client(ti):
                for _ in range(per_thread):
                    try:
                        res = scorer.score_batch([rec] * rows_per,
                                                 timeout=30.0)
                        assert len(res.mean) == rows_per
                        answered[ti] += rows_per
                    except Exception as e:  # noqa: BLE001
                        errors.append(e)

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(n_threads)]
            for t in threads:
                t.start()
            # stage + promote mid-flight
            sw.stage(cand)
            swapped.set()
            out = sw.promote()
            for t in threads:
                t.join()
            scorer.close()
            assert not errors, errors[:3]
            total = n_threads * per_thread * rows_per
            assert sum(answered) == total
            after = _snapshot_counters()
            per_version = _counter_delta(before, after,
                                         "serve.version.records")
            assert sum(per_version.values()) == total, per_version
            # the swap happened mid-load: the new version answered the
            # tail (the old may have answered everything before the swap
            # on a fast promote, so only the new sha is REQUIRED)
            assert any(out["to"] in k for k in per_version), per_version

    def test_scored_sha_survives_a_promote(self, model_set, tmp_path):
        """The observer attributes a batch to the version that SCORED it:
        a promote landing between the score and the observe must not
        re-stamp the batch with the new sha."""
        from shifu_tpu.loop.hotswap import SwappableRegistry
        from shifu_tpu.serve.registry import ModelRegistry

        sw = SwappableRegistry(
            ModelRegistry(os.path.join(model_set, "models")))
        old_sha = sw.sha
        sw.score_raw(_training_raw(model_set))
        sw.stage(_perturbed_candidate(model_set, tmp_path))
        sw.promote()
        assert sw.sha != old_sha          # the NEXT batch is the new set
        assert sw.scored_sha == old_sha   # the last batch stays the old

    def test_shadow_delta_binning_matches_observe(self):
        """The vectorized add_binned path (ShadowStats.note) lands every
        observation in the same bucket a per-value observe() would —
        including exact bucket edges and the +inf overflow."""
        from shifu_tpu.loop.hotswap import SCORE_DELTA_BUCKETS
        from shifu_tpu.obs.metrics import Histogram

        d = np.abs(np.asarray([0.0, 0.4, 0.5, 0.7, 3.0, -2.0, 1e6],
                              dtype=np.float64))
        bulk = Histogram(buckets=SCORE_DELTA_BUCKETS)
        binned = np.bincount(
            np.searchsorted(np.asarray(bulk.buckets), d, side="left"),
            minlength=len(bulk.buckets))
        bulk.add_binned(binned.tolist(), float(d.sum()), int(d.size),
                        float(d.min()), float(d.max()))
        ref = Histogram(buckets=SCORE_DELTA_BUCKETS)
        for v in d:
            ref.observe(float(v))
        got, want = bulk.as_dict(), ref.as_dict()
        assert got["counts"] == want["counts"]
        assert got["count"] == want["count"]
        assert got["sum"] == pytest.approx(want["sum"])
        assert (got["min"], got["max"]) == (want["min"], want["max"])

    def test_nan_shadow_delta_is_disagreement_not_crash(self):
        """A candidate emitting NaN scores must show up as disagreement
        in the gate evidence — not kill the observer pass."""
        from shifu_tpu.loop.hotswap import ShadowStats

        stats = ShadowStats(tolerance=0.5)
        stats.note(np.asarray([0.1, np.nan, 0.2, np.inf]))
        snap = stats.snapshot()
        assert snap["rows"] == 4
        assert snap["agreement"] == pytest.approx(0.5)  # NaN/inf disagree
        assert snap["maxAbsDelta"] == np.inf

    def test_shadow_sample_zero_disables_shadow_scoring(
            self, model_set, tmp_path):
        """shadowSample=0 means OFF (like the traffic log's sample<=0),
        not one-batch-in-a-million."""
        from shifu_tpu.loop.hotswap import SwappableRegistry
        from shifu_tpu.serve.registry import ModelRegistry

        with _Props(shifu_loop_shadowSample="0"):
            sw = SwappableRegistry(
                ModelRegistry(os.path.join(model_set, "models")))
            sw.stage(_perturbed_candidate(model_set, tmp_path))
            raw = _training_raw(model_set)
            res = sw.score_raw(raw)
            sw.observe(raw, res)
            assert sw.shadow_snapshot()["rows"] == 0

    def test_promote_bound_to_expected_sha(self, model_set, tmp_path):
        """promote(expected_sha) refuses a shadow that is not the
        candidate the gate evidence described."""
        from shifu_tpu.loop.hotswap import SwappableRegistry
        from shifu_tpu.serve.registry import ModelRegistry

        sw = SwappableRegistry(
            ModelRegistry(os.path.join(model_set, "models")))
        cand = _perturbed_candidate(model_set, tmp_path)
        staged = sw.stage(cand)
        with pytest.raises(ValueError, match="re-staged"):
            sw.promote(expected_sha="0" * 16)
        assert sw.shadow_snapshot() is not None  # still staged
        out = sw.promote(expected_sha=staged["sha"])
        assert out["to"] == staged["sha"]

    def test_shadow_error_contained(self, model_set, tmp_path):
        from shifu_tpu.loop.hotswap import SwappableRegistry
        from shifu_tpu.serve.registry import ModelRegistry

        with _Props(shifu_loop_shadowSample="1.0"):
            sw = SwappableRegistry(
                ModelRegistry(os.path.join(model_set, "models")))
            cand = _perturbed_candidate(model_set, tmp_path)
            sw.stage(cand)
            sw._shadow.score_raw = None  # simulate a candidate crash
            raw = _training_raw(model_set)
            res = sw.score_raw(raw)  # live path unaffected
            sw.observe(raw, res)     # shadow failure contained
            snap = sw.shadow_snapshot()
            assert snap["errors"] == 1
            assert len(res.mean) == raw.n_rows


# ---------------------------------------------------------------------------
# promote gates
# ---------------------------------------------------------------------------


class TestPromoteGates:
    def _shadow(self, **kw):
        base = {"sha": "c" * 16, "rows": 500, "errors": 0,
                "agreement": 0.99, "tolerance": 5.0}
        base.update(kw)
        return base

    def _rec(self):
        return {"recommendation": {
            "action": "retrain", "modelSetSha": "a" * 16,
            "drift": {"driftedColumns": ["num_0"], "maxPsi": 0.31}}}

    def test_all_gates_pass(self):
        from shifu_tpu.loop.promote import evaluate_gates

        d = evaluate_gates(self._shadow(), self._rec(),
                           agree_min=0.95, min_rows=64)
        assert d["promote"] is True
        assert d["gates"]["shadow"]["ok"] and d["gates"]["drift"]["ok"]
        assert d["gates"]["drift"]["recommendation"]["maxPsi"] == 0.31

    @pytest.mark.parametrize("shadow,why", [
        (None, "no shadow stats"),
        ({"rows": 10, "errors": 0, "agreement": 1.0}, "10 shadow rows"),
        ({"rows": 500, "errors": 2, "agreement": 1.0}, "errored"),
        ({"rows": 500, "errors": 0, "agreement": 0.5}, "agreement"),
    ])
    def test_shadow_gate_failures(self, shadow, why):
        from shifu_tpu.loop.promote import evaluate_gates

        d = evaluate_gates(shadow, self._rec(),
                           agree_min=0.95, min_rows=64)
        assert d["promote"] is False
        assert why in d["gates"]["shadow"]["reason"]

    def test_shadow_gate_rejects_foreign_evidence(self):
        """Agreement earned by a previously staged candidate must not
        green-light a different one."""
        from shifu_tpu.loop.promote import evaluate_gates

        d = evaluate_gates(self._shadow(), self._rec(),
                           agree_min=0.95, min_rows=64,
                           candidate_sha="d" * 16)
        assert d["promote"] is False
        assert "not the candidate" in d["gates"]["shadow"]["reason"]
        # matching sha (or unknown candidate sha): evidence accepted
        ok = evaluate_gates(self._shadow(), self._rec(),
                            agree_min=0.95, min_rows=64,
                            candidate_sha="c" * 16)
        assert ok["promote"] is True

    def test_drift_gate_rejects_stale_recommendation(self):
        """A recommendation stamped against an older active sha was
        already acted on — it must not justify rollouts forever."""
        from shifu_tpu.loop.promote import evaluate_gates

        d = evaluate_gates(self._shadow(), self._rec(),
                           agree_min=0.95, min_rows=64,
                           active_sha="b" * 16)  # rec targets "a"*16
        assert d["promote"] is False
        assert "already acted on" in d["gates"]["drift"]["reason"]
        ok = evaluate_gates(self._shadow(), self._rec(),
                            agree_min=0.95, min_rows=64,
                            active_sha="a" * 16)
        assert ok["promote"] is True

    def test_drift_gate_blocks_without_recommendation(self):
        from shifu_tpu.loop.promote import evaluate_gates

        d = evaluate_gates(self._shadow(), None,
                           agree_min=0.95, min_rows=64)
        assert d["promote"] is False
        assert "no retrain recommendation" in d["gates"]["drift"]["reason"]
        d2 = evaluate_gates(self._shadow(), None, agree_min=0.95,
                            min_rows=64, require_drift=False)
        assert d2["promote"] is True

    def test_offline_swap_is_recoverable(self, tmp_path):
        from shifu_tpu.loop.promote import offline_swap

        root = str(tmp_path)
        os.makedirs(os.path.join(root, "models"))
        open(os.path.join(root, "models", "model0.nn"), "w").write("old")
        cand = os.path.join(root, "models.candidate")
        os.makedirs(cand)
        open(os.path.join(cand, "model0.nn"), "w").write("new")
        out = offline_swap(root, cand)
        assert open(os.path.join(root, "models", "model0.nn")).read() \
            == "new"
        assert open(os.path.join(
            root, "models.previous", "model0.nn")).read() == "old"
        assert out["models"].endswith("models")

    def test_run_promote_writes_manifest_and_exit_codes(self, tmp_path):
        from shifu_tpu.loop.promote import run_promote

        root = str(tmp_path)
        # no shadow stats, no recommendation -> held (exit 1) + manifest
        assert run_promote(root, None) == 1
        (p,) = glob.glob(os.path.join(root, ".shifu/runs/promote-*.json"))
        m = json.load(open(p))["promote"]
        assert m["decision"]["promote"] is False
        assert not m["decision"]["gates"]["shadow"]["ok"]


# ---------------------------------------------------------------------------
# PSI merge/fold edge cases (satellite)
# ---------------------------------------------------------------------------


class TestPsiEdgeCases:
    def test_zero_sides_defined(self):
        from shifu_tpu.stats.psi import psi_from_counts

        assert psi_from_counts(np.zeros(4), np.ones(4)) == 0.0
        assert psi_from_counts(np.ones(4), np.zeros(4)) == 0.0
        assert psi_from_counts(np.zeros(0), np.zeros(0)) == 0.0

    def test_zero_expected_frequency_is_smoothed_finite(self):
        from shifu_tpu.stats.psi import psi_from_counts

        # a live category training never saw (expected 0, actual > 0)
        # and a training bin live traffic never hits (actual 0)
        e = np.array([100.0, 50.0, 0.0])
        a = np.array([0.0, 80.0, 70.0])
        p = psi_from_counts(e, a)
        assert np.isfinite(p) and p > 0.0

    def test_identical_distributions_are_zero(self):
        from shifu_tpu.stats.psi import psi_from_counts

        c = np.array([10.0, 20.0, 30.0])
        assert psi_from_counts(c, c * 7) == pytest.approx(0.0, abs=1e-12)

    def _accs(self, column_configs, k):
        import copy

        from shifu_tpu.stats.psi import PsiAccumulator

        return [PsiAccumulator(copy.deepcopy(column_configs), "cat_0")
                for _ in range(k)]

    def test_merge_additivity_matches_single_fold(self, column_configs):
        """PSI is computed from pure counts: S accumulators over chunk
        slices, merged, must equal the single accumulator — including
        units only one shard saw."""
        import copy

        from shifu_tpu.data.reader import read_columnar, read_header
        from shifu_tpu.stats.psi import PsiAccumulator

        names, rows, _ = make_binary_dataset(n_rows=300, seed=5)
        import tempfile

        with tempfile.TemporaryDirectory() as d:
            from tests.helpers import write_dataset

            data_path, _h = write_dataset(d, names, rows)
            data = read_columnar(data_path,
                                 read_header(os.path.join(d, "header.txt")))
        ccs_a = copy.deepcopy(column_configs)
        ccs_b = copy.deepcopy(column_configs)
        single = PsiAccumulator(ccs_a, "cat_0")
        single.update(data)
        shards = [PsiAccumulator(copy.deepcopy(column_configs), "cat_0")
                  for _ in range(3)]
        n = data.n_rows
        for s in range(3):
            mask = np.zeros(n, dtype=bool)
            mask[s::3] = True
            shards[s].update(data.select_rows(mask))
        merged = shards[0]
        merged.merge(shards[1])
        merged.merge(shards[2])
        for j in range(len(single.cols)):
            assert np.array_equal(single.overall[j], merged.overall[j])
        assert sorted(single.unit_counts) == sorted(merged.unit_counts)
        for u in single.unit_counts:
            for j in range(len(single.cols)):
                assert np.array_equal(single.unit_counts[u][j],
                                      merged.unit_counts[u][j])
        single.finalize()
        merged_ccs = [copy.deepcopy(c) for c in column_configs]
        merged2 = PsiAccumulator(merged_ccs, "cat_0")
        merged2.merge(merged)
        merged2.finalize()
        for ca, cb in zip(ccs_a, merged_ccs):
            assert ca.column_stats.psi == cb.column_stats.psi
            assert ca.column_stats.unit_stats == cb.column_stats.unit_stats

    def test_merge_rejects_mismatched_layout(self, column_configs):
        import copy

        from shifu_tpu.stats.psi import PsiAccumulator

        a = PsiAccumulator(copy.deepcopy(column_configs), "cat_0")
        b = PsiAccumulator(copy.deepcopy(column_configs), "cat_1")
        with pytest.raises(ValueError, match="cannot merge"):
            a.merge(b)

    def test_unseen_category_lands_in_missing_slot(self, column_configs):
        from shifu_tpu.serve.registry import records_to_columnar
        from shifu_tpu.stats.psi import PsiAccumulator

        cat = next(c for c in column_configs if c.is_categorical()
                   and c.column_binning.bin_category)
        acc = PsiAccumulator([cat], "unit")
        recs = [{cat.column_name: "NEVER_SEEN_IN_TRAINING", "unit": "u1"}]
        data = records_to_columnar(recs * 5, [cat.column_name, "unit"])
        acc.update(data)
        # all 5 rows in the trailing missing/unseen slot
        assert acc.overall[0][-1] == 5.0
        assert acc.overall[0][:-1].sum() == 0.0


# ---------------------------------------------------------------------------
# sharded correlation/PSI parity (satellite: ROADMAP item-2 residue)
# ---------------------------------------------------------------------------


class TestShardedCorrPsiParity:
    def test_s8_vs_s1_byte_parity(self, tmp_path):
        """The corr/PSI chunk pass divided over the ShardPlan (S=8) must
        reproduce the S=1 artifacts byte-for-byte: PSI state is integer
        counts in f64 (exact), and every correlation shard folds with the
        SAME first-chunk shift so the merged f64 moments are the same
        sums."""
        from shifu_tpu.processor.init import InitProcessor
        from shifu_tpu.processor.stats import StatsProcessor

        base = str(tmp_path / "base")
        make_model_set(base, n_rows=420, seed=9)
        mcp = os.path.join(base, "ModelConfig.json")
        mc = json.load(open(mcp))
        mc["stats"]["psiColumnName"] = "cat_0"
        json.dump(mc, open(mcp, "w"), indent=2)
        assert InitProcessor(base).run() == 0
        roots = {}
        for s in (1, 8):
            root = str(tmp_path / f"s{s}")
            shutil.copytree(base, root)
            with _Props(shifu_ingest_forceStreaming="true",
                        shifu_ingest_chunkRows="48",
                        shifu_lifecycle_shards=str(s)):
                assert StatsProcessor(root, correlation=True,
                                      psi=True).run() == 0
            roots[s] = root
        corr1 = open(os.path.join(
            roots[1], "tmp", "stats", "correlation.csv")).read()
        corr8 = open(os.path.join(
            roots[8], "tmp", "stats", "correlation.csv")).read()
        assert corr1 == corr8
        cc1 = json.load(open(os.path.join(roots[1], "ColumnConfig.json")))
        cc8 = json.load(open(os.path.join(roots[8], "ColumnConfig.json")))
        psi1 = [(c["columnName"], c["columnStats"].get("psi"),
                 c["columnStats"].get("unitStats")) for c in cc1]
        psi8 = [(c["columnName"], c["columnStats"].get("psi"),
                 c["columnStats"].get("unitStats")) for c in cc8]
        assert psi1 == psi8
        assert any(p is not None and p != 0.0 for _n, p, _u in psi1)

    def test_correlation_merge_requires_shared_shift(self):
        """Per-shard shifts would change the f64 summands, not just their
        order — the driver derives ONE shift from the globally first
        chunk; merging accumulators built over different column sets
        rejects."""
        from shifu_tpu.stats.correlation import StreamingCorrelation

        a = StreamingCorrelation()
        b = StreamingCorrelation()
        a.names = ["x", "y"]
        b.names = ["x", "z"]
        a._acc = [np.ones((2, 2))] * 4
        b._acc = [np.ones((2, 2))] * 4
        with pytest.raises(ValueError, match="different column sets"):
            a.merge(b)
        # same columns, different shifts: the f64 moment sums are
        # residuals around the shift — folding them would be silently
        # wrong, so merge rejects instead
        b.names = ["x", "y"]
        a._shift = np.asarray([0.0, 1.0], dtype=np.float32)
        b._shift = np.asarray([5.0, 1.0], dtype=np.float32)
        with pytest.raises(ValueError, match="different shifts"):
            a.merge(b)
        b._shift = a._shift.copy()
        a.merge(b)  # shared shift folds fine


# ---------------------------------------------------------------------------
# retrain: warm start, provenance, chaos parity
# ---------------------------------------------------------------------------


def _prep_trained(root, n_rows=300, epochs=12, algorithm="NN",
                  extra_mc=None):
    from shifu_tpu.processor.init import InitProcessor
    from shifu_tpu.processor.norm import NormProcessor
    from shifu_tpu.processor.stats import StatsProcessor
    from shifu_tpu.processor.train import TrainProcessor

    make_model_set(root, n_rows=n_rows, seed=7, algorithm=algorithm)
    mcp = os.path.join(root, "ModelConfig.json")
    mc = json.load(open(mcp))
    mc["train"]["numTrainEpochs"] = epochs
    mc["train"]["epochsPerIteration"] = 2
    for k, v in (extra_mc or {}).items():
        mc["train"][k] = v
    json.dump(mc, open(mcp, "w"), indent=2)
    assert InitProcessor(root).run() == 0
    assert StatsProcessor(root).run() == 0
    assert NormProcessor(root).run() == 0
    assert TrainProcessor(root).run() == 0
    return root


class TestRetrain:
    def test_requires_parent_models(self, tmp_path):
        from shifu_tpu.processor.init import InitProcessor
        from shifu_tpu.processor.retrain import RetrainProcessor
        from shifu_tpu.utils.errors import ShifuError

        root = str(tmp_path / "ms")
        make_model_set(root, n_rows=120)
        assert InitProcessor(root).run() == 0
        with pytest.raises(ShifuError, match="shifu train"):
            RetrainProcessor(root).run()

    def test_from_traffic_and_data_are_mutually_exclusive(self, tmp_path):
        """Both flags name a source; silently preferring one would train
        on data the operator did not ask for — reject up front."""
        from shifu_tpu.processor.retrain import RetrainProcessor
        from shifu_tpu.utils.errors import ShifuError

        root = str(tmp_path / "ms")
        make_model_set(root, n_rows=120)
        with pytest.raises(ShifuError, match="mutually exclusive"):
            RetrainProcessor(root, from_traffic=True,
                             data_path="new.csv")

    def test_nn_warm_start_provenance_and_candidate(self, model_set):
        from shifu_tpu.processor.retrain import RetrainProcessor
        from shifu_tpu.serve.registry import model_set_sha

        assert RetrainProcessor(model_set).run() == 0
        cand = os.path.join(model_set, "models.candidate")
        assert os.path.isfile(os.path.join(cand, "model0.nn"))
        manifests = sorted(
            p for p in glob.glob(
                os.path.join(model_set, ".shifu/runs/retrain-*.json"))
            if not p.endswith(".trace.json"))
        m = json.load(open(manifests[-1]))
        rt = m["retrain"]
        assert rt["parent"]["modelSetSha"] == model_set_sha(
            [os.path.join(model_set, "models", "model0.nn")])
        assert rt["candidate"]["modelSetSha"] != rt["parent"]["modelSetSha"]
        assert set(rt["configShas"]) == {"data", "train", "loop"}
        assert rt["source"]["kind"] == "data"
        assert rt["source"]["rows"] > 0
        # originals untouched: retrain normalizes into tmp/retrain
        assert os.path.isdir(os.path.join(model_set, "tmp", "retrain",
                                          "norm", "NormalizedData"))
        assert os.path.isfile(os.path.join(model_set, "models",
                                           "model0.nn"))

    def test_gbt_appends_parent_trees_bitwise(self, tmp_path):
        from shifu_tpu.models.tree import TreeModelSpec
        from shifu_tpu.processor.retrain import RetrainProcessor

        root = _prep_trained(str(tmp_path / "gbt"), n_rows=260,
                             algorithm="GBT",
                             extra_mc={"params": {"TreeNum": 8}})
        parent = TreeModelSpec.load(
            os.path.join(root, "models", "model0.gbt"))
        assert RetrainProcessor(root, append_trees=4).run() == 0
        cand = TreeModelSpec.load(
            os.path.join(root, "models.candidate", "model0.gbt"))
        assert len(cand.trees) == len(parent.trees) + 4
        assert json.dumps(cand.trees[:len(parent.trees)], sort_keys=True,
                          default=str) \
            == json.dumps(parent.trees, sort_keys=True, default=str)
        m = json.load(open(sorted(
            p for p in glob.glob(os.path.join(
                root, ".shifu/runs/retrain-*.json"))
            if not p.endswith(".trace.json"))[-1]))
        assert m["retrain"]["warmStart"]["appendedTrees"] == 4
        assert m["retrain"]["parent"]["trees"] == len(parent.trees)

    def test_traffic_log_roundtrip_retrains(self, tmp_path):
        """Serve -> traffic log -> retrain: the log is label-joined (the
        target rides the request conversion as an extra raw column) and
        `shifu retrain --from-traffic` consumes exactly the logged
        chunks."""
        from shifu_tpu.processor.retrain import RetrainProcessor
        from shifu_tpu.serve.server import ScoringServer

        root = _prep_trained(str(tmp_path / "ms"), n_rows=260, epochs=6)
        names, rows, _ = make_binary_dataset(n_rows=120, seed=13)
        writers = set()
        with _Props(shifu_loop_logSample="1.0",
                    shifu_loop_logChunkRows="64"):
            # TWO serve processes in sequence (fresh lease each): the
            # fleet-shared log keeps one chunk family per writer and the
            # retrain below consumes the union
            for start_at in (0, 60):
                server = ScoringServer(root=root, port=0)
                server.start()
                try:
                    writers.add(server.traffic.writer)
                    for start in range(start_at, start_at + 60, 30):
                        recs = [dict(zip(names, r))
                                for r in rows[start:start + 30]]
                        server.scorer.score_batch(recs)
                finally:
                    manifest = server.shutdown()
        assert len(writers) == 2 and all(writers)
        m = json.load(open(manifest))
        assert m["traffic"]["chunks"] >= 1
        assert RetrainProcessor(root, from_traffic=True).run() == 0
        rm = json.load(open(sorted(
            p for p in glob.glob(os.path.join(
                root, ".shifu/runs/retrain-*.json"))
            if not p.endswith(".trace.json"))[-1]))
        src = rm["retrain"]["source"]
        assert src["kind"] == "traffic"
        assert src["trafficChunks"]
        assert src["rows"] > 0
        # the lineage manifest records the whole fleet's writers
        assert sorted(src["trafficWriters"]) == sorted(writers)
        assert os.path.isfile(os.path.join(root, "models.candidate",
                                           "model0.nn"))

    def test_chaos_parity_resume_bit_identical(self, tmp_path):
        """Acceptance: kill `shifu retrain` mid-stream, `--resume`
        produces weights bit-identical to an uninterrupted retrain."""
        from shifu_tpu.models.nn import NNModelSpec, flatten_params
        from shifu_tpu.processor.retrain import RetrainProcessor
        from shifu_tpu.resilience.faults import PreemptionError

        clean = _prep_trained(str(tmp_path / "clean"), n_rows=260,
                              epochs=10)
        chaos = str(tmp_path / "chaos")
        shutil.copytree(clean, chaos)
        with _Props(shifu_train_forceStreaming="true"):
            assert RetrainProcessor(clean).run() == 0
            with _Props(shifu_faults="preempt@epoch=4"):
                with pytest.raises(PreemptionError):
                    RetrainProcessor(chaos).run()
            m = json.load(open(os.path.join(
                chaos, ".shifu/runs/retrain-1.json")))
            assert m["status"] == "failed"
            c = m["metrics"]["counters"]
            assert c.get('fault.injected{seam="preempt"}') == 1.0
            # the retrain trainer checkpoint is listed as resumable
            from shifu_tpu.resilience.checkpoint import list_resumable

            names = [e["name"] for e in list_resumable(chaos)]
            assert any(n.startswith("retrain-") for n in names), names
            with _Props(shifu_resume="true"):
                assert RetrainProcessor(chaos).run() == 0
        a = flatten_params(NNModelSpec.load(os.path.join(
            clean, "models.candidate", "model0.nn")).params)[0]
        b = flatten_params(NNModelSpec.load(os.path.join(
            chaos, "models.candidate", "model0.nn")).params)[0]
        assert np.array_equal(a, b)

    def test_checkpoint_rejection_names_diverged_section(self, tmp_path):
        """A streamed-train snapshot whose `loop` section (warm-start
        parent) diverged is rejected naming exactly that section."""
        from shifu_tpu.resilience.checkpoint import (
            StreamCheckpoint,
            sectioned_sha,
        )

        path = str(tmp_path / "t.ckpt.npz")
        sha_a, sec_a = sectioned_sha({
            "train": {"lr": 0.1}, "data": {"rows": 10},
            "loop": {"parentModelSetSha": "aaaa"}})
        StreamCheckpoint(path, sha_a, every=0, sections=sec_a).save(
            3, arrays={"w": np.zeros(2)}, meta={"epoch": 3})
        sha_b, sec_b = sectioned_sha({
            "train": {"lr": 0.1}, "data": {"rows": 10},
            "loop": {"parentModelSetSha": "bbbb"}})
        before = _snapshot_counters()
        ck = StreamCheckpoint(path, sha_b, every=0, sections=sec_b)
        assert ck.load() is None
        after = _snapshot_counters()
        d = _counter_delta(before, after, "ckpt.rejected")
        assert d.get('ckpt.rejected{reason="config",section="loop"}') \
            == 1.0, d


# ---------------------------------------------------------------------------
# CLI wiring
# ---------------------------------------------------------------------------


class TestLoopCli:
    def test_parsers_exist(self):
        from shifu_tpu.cli import build_parser

        p = build_parser()
        args = p.parse_args(["retrain", "--from-traffic",
                             "--append-trees", "7"])
        assert args.command == "retrain"
        assert args.from_traffic and args.append_trees == 7
        args = p.parse_args(["promote", "--no-drift-gate", "--force",
                             "--serve-url", "http://x:1", "--stage"])
        assert args.command == "promote"
        assert args.no_drift_gate and args.force and args.stage
        args = p.parse_args(["serve", "--traffic-log"])
        assert args.traffic_log == "1.0"
        args = p.parse_args(["serve", "--traffic-log", "0.25"])
        assert args.traffic_log == "0.25"

    def test_bad_traffic_log_fraction_fails_startup(self, tmp_path,
                                                    monkeypatch):
        """A malformed --traffic-log value must fail the serve startup,
        not silently disable logging (get_float would swallow it into
        the 0.0 default and the server would log nothing for days)."""
        from shifu_tpu.cli import main

        monkeypatch.chdir(tmp_path)  # no model set needed: fails before
        assert main(["serve", "--traffic-log", "0,5"]) == 1
        assert main(["serve", "--traffic-log", "1.5"]) == 1
        assert main(["serve", "--traffic-log", "0"]) == 1
