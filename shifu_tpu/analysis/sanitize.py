"""Runtime sanitizer harness: ``-Dshifu.sanitize=transfer,nan,recompile,race``.

The static pass (engine.py) catches what the AST can see; this harness
catches what only the runtime can — the ASan/TSan analog for a jit
pipeline. Four opt-in modes, combined freely:

  transfer   arms ``jax.transfer_guard("disallow")`` around *declared
             traced stages* (the ``transfer_free(...)`` seams in
             nn_trainer / streaming / data.pipeline). Explicit
             ``jax.device_put``/``device_get`` stay legal; any IMPLICIT
             host↔device transfer inside a seam raises, the trip is
             recorded, and the step fails like a sanitizer trap. The
             guard is scoped to seams, not whole steps, because host→
             device staging (chunk feeds, scalar operand creation) is
             legitimate *between* traced stages.
  nan        arms ``jax.debug_nans`` for the step (the checkify-style
             trap): the first NaN/Inf produced under jit raises
             FloatingPointError at the producing primitive.
  recompile  a watchdog on the obs/jaxprobe compile counters: each armed
             stage gets a compile budget (``shifu.sanitize.recompileBudget``,
             default 64); a breach is recorded and logged as a ledger
             warning — recompile storms are a perf bug, not a
             correctness trap, so the step still completes.
  divergence multi-host lockstep witness (parallel/hostsync.py): every
             barrier part published while armed carries a stamp — a
             monotone per-(step, host) sequence id plus a digest of
             (config sha, barrier step, call-site, merge-key order).
             An awaiting peer that observes a mismatched digest or an
             out-of-order sequence raises DivergenceError LOUDLY
             instead of silently merging divergent state; the static
             counterpart is JX301/SH301/SH302 (rules/spmd.py).
             Single-process runs record per-window fold digests
             (data/pipeline.py flush), so a re-run can diff exactly
             which window broke determinism.
  race       lock instrumentation (analysis/racetrack.py): every
             ``tracked_lock(...)`` site constructed while armed records
             per-thread acquisition stacks; lock-order inversions and
             ``@guarded_by`` violations make the verdict unclean,
             long holds past ``shifu.sanitize.race.holdMs`` are
             reported (perf hazard, not gated). Arming is read at lock
             CONSTRUCTION time, so set ``-Dshifu.sanitize=race`` before
             building the serve/loop objects to be watched.

Verdicts: ``Sanitizer.verdict()`` returns a ``shifu.sanitize/1`` dict —
BasicProcessor.run() embeds it in the run-ledger manifest (success AND
failure), bench.py embeds it per scenario. Trip/breach counts also land
in the metrics registry (``sanitizer.*``), so `shifu runs` output and
Prometheus exports see them too.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import traceback
from typing import Dict, Iterable, List, Optional, Sequence

from shifu_tpu.analysis.racetrack import tracked_lock
from shifu_tpu.utils import environment
from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)

SCHEMA = "shifu.sanitize/1"
MODES = ("transfer", "nan", "recompile", "race", "divergence")
DEFAULT_RECOMPILE_BUDGET = 64
DEFAULT_MAX_FOLD_DIGESTS = 512


class DivergenceError(RuntimeError):
    """A hostsync barrier observed divergent peer state while
    -Dshifu.sanitize=divergence was armed: a peer's stamp digest did not
    match this host's (different config/call-site/merge-key order) or
    its barrier sequence was out of order. Raised INSTEAD of merging —
    a divergent merge would poison every downstream artifact silently;
    the refusal names the step, both hosts, and both digests."""

_lock = tracked_lock("analysis.sanitize")
_current: Optional["Sanitizer"] = None


def modes_from_environment() -> List[str]:
    """Parse -Dshifu.sanitize=transfer,nan,recompile (also accepts
    'all'); unknown mode names raise so a typo cannot silently disarm
    the run."""
    raw = (environment.get_property("shifu.sanitize", "") or "").strip()
    if not raw:
        return []
    if raw.lower() == "all":
        return list(MODES)
    modes = [m.strip().lower() for m in raw.split(",") if m.strip()]
    unknown = [m for m in modes if m not in MODES]
    if unknown:
        raise ValueError(
            f"shifu.sanitize: unknown mode(s) {', '.join(unknown)} "
            f"(known: {', '.join(MODES)})")
    return modes


def recompile_budget() -> int:
    return environment.get_int("shifu.sanitize.recompileBudget",
                               DEFAULT_RECOMPILE_BUDGET)


def max_fold_digests() -> int:
    """shifu.sanitize.divergence.maxFolds — cap on per-window fold
    digests kept for the verdict (a long stream would otherwise grow
    the manifest unboundedly; the digests past the cap still count)."""
    return environment.get_int("shifu.sanitize.divergence.maxFolds",
                               DEFAULT_MAX_FOLD_DIGESTS)


def _is_transfer_error(e: BaseException) -> bool:
    return "transfer" in str(e).lower() and "isallowed" in str(e)


def _barrier_call_site() -> str:
    """module:function of the nearest stack frame OUTSIDE the sanitizer/
    hostsync plumbing — the publish site whose identity the divergence
    digest pins. Deliberately not the line number: peers must agree on
    WHICH barrier they are at, while a trailing-whitespace edit between
    restarts must not read as divergence."""
    skip = ("sanitize.py", "hostsync.py")
    for frame in reversed(traceback.extract_stack()[:-1]):
        base = frame.filename.rsplit("/", 1)[-1]
        if base not in skip:
            return f"{base}:{frame.name}"
    return "?"


class Sanitizer:
    """One armed sanitizer scope (a lifecycle step or a bench scenario)."""

    def __init__(self, modes: Iterable[str],
                 budget: Optional[int] = None) -> None:
        self.modes = frozenset(modes)
        unknown = self.modes - set(MODES)
        if unknown:
            raise ValueError(f"unknown sanitizer mode(s): {sorted(unknown)}")
        self.budget = recompile_budget() if budget is None else budget
        self.transfer_trips = 0
        self.nan_trips = 0
        self.recompile_breaches = 0
        self.recompile_seconds = 0.0  # wall-clock of breached stages' compiles
        self.stages_armed = 0
        self.events: List[dict] = []
        # divergence-mode state: per-(step, host) barrier sequence
        # counters, published stamps, peer checks, and the single-host
        # fold-digest trail (all under _lock — thread-hosts share one
        # process-global sanitizer)
        self.divergence_trips = 0
        self.divergence_stamps = 0
        self.divergence_checks = 0
        self.fold_digests: List[dict] = []
        self.folds_recorded = 0
        self._barrier_seq: Dict[tuple, int] = {}
        self._max_folds = max_fold_digests()
        # race-mode scope: the verdict reports the tracker's DELTA from
        # this sanitizer's construction (the tracker itself is
        # process-global, like the fault-injection counters)
        from shifu_tpu.analysis import racetrack

        self._race_mark = racetrack.tracker().mark()

    @property
    def active(self) -> bool:
        return bool(self.modes)

    # ---- recording (also mirrored into the metrics registry so ledger
    # tables/Prometheus see sanitizer activity without parsing verdicts)
    def _record(self, kind: str, stage: str, detail: str) -> None:
        self.events.append({"kind": kind, "stage": stage,
                            "detail": detail})
        from shifu_tpu.obs import registry

        registry().counter(f"sanitizer.{kind}").inc()

    def record_transfer_trip(self, stage: str, detail: str) -> None:
        self.transfer_trips += 1
        self._record("transfer.trips", stage, detail)
        log.warning("sanitizer[transfer] trip in %s: %s", stage,
                    detail[:200])

    def record_nan_trip(self, stage: str, detail: str) -> None:
        self.nan_trips += 1
        self._record("nan.trips", stage, detail)
        log.warning("sanitizer[nan] trap in %s: %s", stage, detail[:200])

    def record_recompile_breach(self, stage: str, compiles: float,
                                seconds: float = 0.0) -> None:
        self.recompile_breaches += 1
        self.recompile_seconds += seconds
        self._record("recompile.breaches", stage,
                     f"{compiles:.0f} compiles ({seconds:.2f}s wall-clock)"
                     f" > budget {self.budget}")
        log.warning(
            "sanitizer[recompile] budget breach in %s: %.0f compiles "
            "costing %.2fs wall-clock > budget %d "
            "(shifu.sanitize.recompileBudget)", stage, compiles, seconds,
            self.budget)

    def record_divergence_trip(self, stage: str, detail: str) -> None:
        with _lock:
            self.divergence_trips += 1
        self._record("divergence.trips", stage, detail)
        log.warning("sanitizer[divergence] trip in %s: %s", stage,
                    detail[:300])

    # ---- divergence stamps (the hostsync barrier contract)
    def barrier_stamp(self, step: str, host_index: int, sha: str,
                      merge_keys: Sequence[str]) -> dict:
        """The stamp publish_part embeds while armed: a monotone
        per-(step, host) sequence id plus a digest of (config sha, step,
        publishing call-site, merge-key ORDER). Peers at the same
        barrier must compute the identical digest — anything else means
        the fleet is not running the same merge."""
        with _lock:
            key = (step, int(host_index))
            seq = self._barrier_seq.get(key, 0) + 1
            self._barrier_seq[key] = seq
            self.divergence_stamps += 1
        digest = hashlib.sha256(json.dumps({
            "configSha": sha,
            "step": step,
            "site": _barrier_call_site(),
            "mergeKeys": list(merge_keys),
        }, sort_keys=True).encode("utf-8")).hexdigest()[:16]
        from shifu_tpu.obs import registry

        registry().counter("sanitizer.divergence.stamps",
                           step=step).inc()
        return {"seq": seq, "digest": digest}

    def check_barrier_stamps(self, step: str, own_host: int,
                             own_stamp: Optional[dict],
                             peer_stamps: Dict[int, Optional[dict]]
                             ) -> None:
        """Validate every peer's stamp against this host's at an
        await_parts barrier. Raises DivergenceError on the first
        mismatch — the named refusal that replaces a silent merge of
        divergent state."""
        from shifu_tpu.obs import registry

        registry().counter("sanitizer.divergence.checks",
                           step=step).inc()
        with _lock:
            self.divergence_checks += 1
        if own_stamp is None:
            return  # this host published unarmed (stamp-free stream)
        for host, stamp in sorted(peer_stamps.items()):
            if host == own_host:
                continue
            problem = None
            if stamp is None:
                problem = ("peer published NO divergence stamp — fleet "
                           "is not uniformly armed")
            elif stamp.get("digest") != own_stamp.get("digest"):
                problem = (f"digest mismatch: peer {stamp.get('digest')}"
                           f" != own {own_stamp.get('digest')} (config "
                           f"sha, call-site or merge-key order differs)")
            elif stamp.get("seq") != own_stamp.get("seq"):
                problem = (f"out-of-order barrier sequence: peer "
                           f"{stamp.get('seq')} != own "
                           f"{own_stamp.get('seq')}")
            if problem:
                detail = (f"barrier '{step}': host {host} diverged from "
                          f"host {own_host} — {problem}")
                self.record_divergence_trip(step, detail)
                raise DivergenceError(
                    f"sanitizer[divergence] {detail}; refusing to merge"
                    f" (the verdict rides the run manifest)")

    def record_fold(self, stage: str, arrays) -> None:
        """Single-process determinism trail: digest one window fold so a
        re-run can diff exactly where the fold stream diverged."""
        h = hashlib.sha256()
        for a in arrays:
            import numpy as np

            h.update(np.ascontiguousarray(a).tobytes())
        with _lock:
            self.folds_recorded += 1
            seq = self.folds_recorded
            if len(self.fold_digests) < self._max_folds:
                self.fold_digests.append(
                    {"stage": stage, "seq": seq,
                     "digest": h.hexdigest()[:16]})
        from shifu_tpu.obs import registry

        registry().counter("sanitizer.divergence.folds",
                           stage=stage).inc()

    # ---- arming
    @contextlib.contextmanager
    def armed(self, stage: str):
        """Arm the step-scoped modes around `stage`: debug_nans for the
        whole region, the recompile watchdog over its compile-counter
        delta. Transfer guarding happens at the finer transfer_free()
        seams inside. Exceptions propagate (sanitizer-trap semantics) —
        trips are recorded first, and the caller's ledger write still
        sees the verdict because it runs in its own finally."""
        if not self.active:
            yield
            return
        self.stages_armed += 1
        compiles0 = self._compile_count()
        seconds0 = self._compile_seconds()
        nan_cm = contextlib.nullcontext()
        if "nan" in self.modes:
            import jax

            nan_cm = jax.debug_nans(True)
        try:
            with nan_cm:
                yield
        except FloatingPointError as e:
            if "nan" in self.modes:
                self.record_nan_trip(stage, f"{type(e).__name__}: {e}")
            raise
        finally:
            if "recompile" in self.modes:
                delta = self._compile_count() - compiles0
                if delta > self.budget:
                    # the jaxprobe duration events make the breach
                    # actionable: N compiles AND the wall-clock they cost
                    self.record_recompile_breach(
                        stage, delta,
                        self._compile_seconds() - seconds0)

    @contextlib.contextmanager
    def transfer_free(self, stage: str):
        """Declare a region transfer-free. Under the `transfer` mode any
        implicit host↔device transfer inside raises (explicit
        device_put/device_get remain legal); the trip is recorded and
        the error propagates."""
        if "transfer" not in self.modes:
            yield
            return
        import jax

        try:
            with jax.transfer_guard("disallow"):
                yield
        except Exception as e:
            if _is_transfer_error(e):
                self.record_transfer_trip(stage, str(e))
            raise

    # ---- verdict
    def verdict(self) -> dict:
        from shifu_tpu.analysis import racetrack

        race_armed = "race" in self.modes
        race = {"armed": race_armed}
        race_dirty = 0
        if race_armed:
            race.update(racetrack.tracker().verdict(self._race_mark))
            # inversions + guard violations are correctness findings;
            # long holds are a perf hazard — reported, never gating
            # `clean` (the recompile-watchdog contract)
            race_dirty = race["inversions"] + race["guardViolations"]
        return {
            "schema": SCHEMA,
            "modes": sorted(self.modes),
            "stagesArmed": self.stages_armed,
            "transfer": {
                "armed": "transfer" in self.modes,
                "trips": self.transfer_trips,
            },
            "nan": {
                "armed": "nan" in self.modes,
                "trips": self.nan_trips,
            },
            "recompile": {
                "armed": "recompile" in self.modes,
                "budgetPerStage": self.budget,
                "breaches": self.recompile_breaches,
                "breachedCompileSeconds": round(self.recompile_seconds, 3),
            },
            "race": race,
            "divergence": {
                "armed": "divergence" in self.modes,
                "trips": self.divergence_trips,
                "stampsPublished": self.divergence_stamps,
                "barriersChecked": self.divergence_checks,
                "foldsRecorded": self.folds_recorded,
                "foldDigests": list(self.fold_digests),
            },
            "events": self.events,
            "clean": not (self.transfer_trips or self.nan_trips
                          or self.recompile_breaches or race_dirty
                          or self.divergence_trips),
        }

    @staticmethod
    def _compile_count() -> float:
        from shifu_tpu import obs

        obs.install_jax_probes()
        return obs.registry().counter("jax.compiles").value

    @staticmethod
    def _compile_seconds() -> float:
        from shifu_tpu import obs

        obs.install_jax_probes()
        return obs.registry().timer("jax.compile").seconds


def from_environment() -> Sanitizer:
    return Sanitizer(modes_from_environment())


def current() -> Optional[Sanitizer]:
    return _current


@contextlib.contextmanager
def activate(san: Sanitizer):
    """Make `san` the process-current sanitizer so library seams
    (transfer_free below) find it without plumbing. Nested activation
    restores the previous one on exit."""
    global _current
    with _lock:
        prev, _current = _current, san
    try:
        yield san
    finally:
        with _lock:
            _current = prev


@contextlib.contextmanager
def transfer_free(stage: str):
    """Library-side seam: no-op unless a sanitizer with the `transfer`
    mode is active. Cheap enough for per-dispatch call sites (one global
    read when disarmed)."""
    san = _current
    if san is None or "transfer" not in san.modes:
        yield
        return
    with san.transfer_free(stage):
        yield


def _divergence_active() -> Optional[Sanitizer]:
    san = _current
    if san is not None and "divergence" in san.modes:
        return san
    return None


def barrier_stamp(step: str, host_index: int, sha: str,
                  merge_keys: Sequence[str]) -> Optional[dict]:
    """hostsync.publish_part seam: the stamp to embed in the part
    header, or None when divergence is disarmed (one global read)."""
    san = _divergence_active()
    if san is None:
        return None
    return san.barrier_stamp(step, host_index, sha, merge_keys)


def check_barrier_stamps(step: str, own_host: int,
                         own_stamp: Optional[dict],
                         peer_stamps: Dict[int, Optional[dict]]) -> None:
    """hostsync.await_parts seam: validate peers before the merge;
    raises DivergenceError on mismatch, no-op when disarmed."""
    san = _divergence_active()
    if san is None:
        return
    san.check_barrier_stamps(step, own_host, own_stamp, peer_stamps)


def record_fold(stage: str, arrays) -> None:
    """data-pipeline seam: digest one window fold while armed (no-op
    otherwise) — the single-process determinism trail."""
    san = _divergence_active()
    if san is not None:
        san.record_fold(stage, arrays)
