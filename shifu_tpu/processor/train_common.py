"""Shared trainer-orchestration helpers for the NN and WDL processors.

The progress-line format is a CONTRACT (the reference's NNOutput progress
files are tailed by TailThread and parsed by downstream tooling,
TrainModelProcessor.java:1862) — it must exist in exactly one place.
"""

from __future__ import annotations

from typing import Callable, List


def progress_line(trainer_id: int, epoch: int, train_err: float,
                  valid_err: float) -> str:
    return (f"Trainer {trainer_id} Epoch #{epoch} "
            f"Train Error:{train_err:.8f} Validation Error:{valid_err:.8f}\n")


def progress_writer(path: str, trainer_id: int = 0,
                    echo: bool = True) -> Callable:
    """Single-trainer progress callback: (epoch, train_err, valid_err).
    `echo` mirrors the line to the console (the reference TailThread tails
    progress files to the console for interactive runs)."""
    from shifu_tpu.utils.log import get_logger

    log = get_logger(__name__)

    def cb(it, tr, va):
        with open(path, "a") as fh:
            fh.write(progress_line(trainer_id, it, tr, va))
        if echo:
            log.info("trainer %d epoch %d train %.6f valid %.6f",
                     trainer_id, it, tr, va)

    return cb


def member_progress_writer(paths: List[str]) -> Callable:
    """Vmapped-member progress callback: ((member, epoch), tr, va)."""

    def cb(member_it, tr, va):
        i, it = member_it
        with open(paths[i], "a") as fh:
            fh.write(progress_line(i, it, tr, va))

    return cb
