"""Tiny camelCase-JSON dataclass bridge.

The reference serializes configs with Jackson using camelCase field names
(container/obj/*.java). We keep Python snake_case attributes and map them to
camelCase on the wire, tolerating unknown keys (forward/backward compat, like
Jackson's FAIL_ON_UNKNOWN_PROPERTIES=false used by the reference's JSONUtils).
"""

from __future__ import annotations

import dataclasses
import enum
import json
import math
import typing
from typing import Any, Optional, Type, TypeVar, get_args, get_origin

T = TypeVar("T")


def snake_to_camel(name: str) -> str:
    parts = name.split("_")
    return parts[0] + "".join(p.title() for p in parts[1:])


class JsonEnum(enum.Enum):
    """Enum that serializes to its value and parses case-insensitively.

    The reference parses most enums case-insensitively (e.g. runMode "local"
    vs "LOCAL", norm type "WOE_ZSCALE" vs "woe_zscale").
    """

    @classmethod
    def parse(cls, raw: Any, default=None):
        """Parse a wire value. None/empty -> default; an unrecognized value
        raises (fail fast, like Jackson's unknown-enum-constant error in the
        reference) rather than silently degrading to None."""
        if raw is None or (isinstance(raw, str) and not raw.strip()):
            return default
        if isinstance(raw, cls):
            return raw
        text = str(raw).strip()
        for member in cls:
            if str(member.value).lower() == text.lower() or member.name.lower() == text.lower():
                return member
        # Aliases hook: subclasses may define _ALIASES {lower-name: member-name}
        aliases = getattr(cls, "_ALIASES", None)
        if aliases:
            target = dict(aliases).get(text.lower())
            if target is not None:
                return cls[target]
        raise ValueError(
            f"invalid {cls.__name__} value {raw!r}; expected one of "
            f"{[m.value for m in cls]}"
        )

    def to_json(self):
        return self.value


def _encode(value: Any) -> Any:
    if isinstance(value, JsonEnum):
        return value.to_json()
    if isinstance(value, enum.Enum):
        return value.value
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        return encode_dataclass(value)
    if isinstance(value, dict):
        return {k: _encode(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_encode(v) for v in value]
    if isinstance(value, float):
        # Jackson writes Infinity/-Infinity/NaN tokens; json.dump does the same
        # with allow_nan=True, so floats pass through.
        return value
    return value


def encode_dataclass(obj: Any) -> dict:
    out = {}
    for f in dataclasses.fields(obj):
        if f.metadata.get("skip_json"):
            continue
        wire = f.metadata.get("json", snake_to_camel(f.name))
        out[wire] = _encode(getattr(obj, f.name))
    return out


def _decode(ftype: Any, raw: Any) -> Any:
    if raw is None:
        return None
    origin = get_origin(ftype)
    if origin is typing.Union:  # Optional[X]
        args = [a for a in get_args(ftype) if a is not type(None)]
        if len(args) == 1:
            return _decode(args[0], raw)
        return raw
    if origin in (list, tuple):
        (inner,) = get_args(ftype) or (Any,)
        return [_decode(inner, v) for v in raw]
    if origin is dict:
        return dict(raw)
    if isinstance(ftype, type):
        if issubclass(ftype, JsonEnum):
            return ftype.parse(raw)
        if dataclasses.is_dataclass(ftype):
            return decode_dataclass(ftype, raw)
        if ftype is float:
            if isinstance(raw, str):
                low = raw.strip().lower()
                if low in ("infinity", "+infinity", "inf"):
                    return math.inf
                if low in ("-infinity", "-inf"):
                    return -math.inf
                if low == "nan":
                    return math.nan
            return float(raw)
        if ftype is int and not isinstance(raw, bool):
            return int(raw)
        if ftype is bool:
            if isinstance(raw, bool):
                return raw
            # Jackson-style coercion: "true"/"false"/0/1 are valid booleans
            if isinstance(raw, str):
                return raw.strip().lower() in ("true", "1", "yes", "on")
            if isinstance(raw, (int, float)):
                return bool(raw)
            return raw
        if ftype is str:
            return str(raw)
    return raw


def decode_dataclass(cls: Type[T], data: Optional[dict]) -> T:
    if data is None:
        data = {}
    hints = typing.get_type_hints(cls)
    kwargs = {}
    for f in dataclasses.fields(cls):
        wire = f.metadata.get("json", snake_to_camel(f.name))
        if wire in data:
            kwargs[f.name] = _decode(hints[f.name], data[wire])
        # else: dataclass default applies
    return cls(**kwargs)


def dump_json(obj: Any, path: str) -> None:
    with open(path, "w") as fh:
        json.dump(_encode(obj), fh, indent=2, default=str)
        fh.write("\n")


def dumps_json(obj: Any) -> str:
    return json.dumps(_encode(obj), indent=2, default=str)
