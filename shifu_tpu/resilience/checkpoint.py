"""Atomic artifact writes + mid-stream checkpoint/resume.

Two layers:

`atomic_write` / `atomic_write_json` / `atomic_save_npy`
    Every checkpoint-like artifact (trainer weights, stream snapshots,
    manifests) must be torn-file-proof: a kill mid-write must leave
    either the previous complete file or the new complete file, never a
    half-written one. The pattern is the only portable one — write to a
    temp file IN THE SAME DIRECTORY, then `os.replace` (atomic on POSIX
    within a filesystem). `shifu check` rule SH104 flags direct
    `np.save`/`open(.., "w")` writes to checkpoint-like paths that
    bypass these helpers.

`StreamCheckpoint`
    The mid-stream snapshot for chunked folds: every
    `shifu.ckpt.everyChunks` folded chunks (default 16) the owning loop
    persists `(chunk_index, fold arrays, meta)` plus a config sha; a
    resumed run (`shifu <step> --resume`) loads it, skips the already-
    folded chunks, and — because the snapshot captures the exact f32
    device window + host f64 fold rather than forcing an early flush —
    produces BIT-IDENTICAL results to an uninterrupted run. A sha
    mismatch (config changed between runs) rejects the checkpoint and
    starts fresh; corrupt files are rejected the same way, never
    crashed on.

Format: one `.ckpt.npz` file — named numpy arrays plus a `__meta__`
JSON payload (chunk index, config sha, caller meta) and an optional
`__blob__` (pickled host-side state, e.g. pass-1 sketches). Writes go
through `atomic_write` with the `ckpt` fault seam inside, so the chaos
harness can prove a kill during checkpointing is survivable.

Metrics: `ckpt.writes`, `ckpt.bytes`, `ckpt.resumes`, `ckpt.rejected`.
"""

from __future__ import annotations

import io
import json
import os
import tempfile
from typing import Callable, Dict, List, Optional, Tuple, Union

import numpy as np

from shifu_tpu.utils import environment
from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)

DEFAULT_EVERY_CHUNKS = 16
CKPT_SUBDIR = os.path.join(".shifu", "runs", "ckpt")
CKPT_SUFFIX = ".ckpt.npz"

META_KEY = "__meta__"
BLOB_KEY = "__blob__"


def every_chunks_setting() -> int:
    """shifu.ckpt.everyChunks — stream-checkpoint cadence (chunks between
    snapshots; <= 0 disables mid-stream checkpointing)."""
    return environment.get_int("shifu.ckpt.everyChunks",
                               DEFAULT_EVERY_CHUNKS)


def ckpt_stream_enabled() -> bool:
    """shifu.ckpt.stream — master switch for mid-stream checkpoints
    (default on; the bench measures the on/off wall-clock ratio)."""
    return environment.get_bool("shifu.ckpt.stream", True) \
        and every_chunks_setting() > 0


def resume_requested() -> bool:
    """shifu.resume — set by the CLI `--resume` flags."""
    return environment.get_bool("shifu.resume", False)


# ---------------------------------------------------------------------------
# atomic writes
# ---------------------------------------------------------------------------


def atomic_write(path: str,
                 data: Union[bytes, Callable[[io.BufferedWriter], None]],
                 ) -> str:
    """Write `data` (bytes, or a writer callable) to `path` atomically:
    temp file in the same directory, fsync, `os.replace`. A kill at any
    point leaves the previous file intact."""
    from shifu_tpu.resilience import faults

    path = os.path.abspath(path)
    d = os.path.dirname(path)
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix="." + os.path.basename(path),
                               suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as fh:
            if callable(data):
                data(fh)
            else:
                fh.write(data)
            fh.flush()
            os.fsync(fh.fileno())
        # the injectable failure window: after the bytes are down but
        # before the rename — exactly where a torn write would happen
        # without the temp+replace discipline
        faults.fault_point("ckpt")
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:  # already replaced or never created
            pass
        raise
    return path


def atomic_write_json(path: str, obj, indent: int = 2,
                      sort_keys: bool = True) -> str:
    return atomic_write(
        path, json.dumps(obj, indent=indent, sort_keys=sort_keys,
                         default=str).encode("utf-8"))


def atomic_save_npy(path: str, array: np.ndarray) -> str:
    """Atomic `np.save` — the drop-in for every trainer checkpoint write
    (a torn weights.npy used to be possible on any mid-save kill)."""
    buf = io.BytesIO()
    np.save(buf, np.asarray(array))
    return atomic_write(path, buf.getvalue())


# ---------------------------------------------------------------------------
# stream checkpoints
# ---------------------------------------------------------------------------


def config_sha(ident: dict) -> str:
    """Checkpoint-compatibility identity: sha1 over the canonical JSON of
    the caller's identity dict (hyperparameters, layouts, seeds),
    truncated to 16 hex chars. One definition so every resumable stream
    agrees on what 'same config' means."""
    import hashlib

    return hashlib.sha1(
        json.dumps(ident, sort_keys=True, default=str).encode()
    ).hexdigest()[:16]


def sectioned_sha(sections: Dict[str, dict]) -> Tuple[str, Dict[str, str]]:
    """(overall sha, per-section shas) for a SECTIONED identity — e.g.
    {"data": {...}, "train": {...}, "loop": {...}}. The overall sha keys
    checkpoint compatibility exactly like `config_sha`; the per-section
    shas ride in the snapshot meta so a rejection can say WHICH section
    (data vs train vs loop) diverged instead of just "config changed"."""
    per = {name: config_sha(ident) for name, ident in sections.items()}
    return config_sha(per), per


def resume_slice(numbered, after: int):
    """Skip the already-folded prefix of an enumerate()-style stream:
    yields the (index, item) pairs with index > `after` (the chunk index
    a StreamCheckpoint recorded). Indices ride with the items, so
    index-keyed draws ([seed, chunk_index] sampling) are preserved."""
    for pair in numbered:
        if pair[0] > after:
            yield pair


def ckpt_dir(root: str) -> str:
    return os.path.join(os.path.abspath(root), CKPT_SUBDIR)


def ckpt_path(root: str, step: str, name: str) -> str:
    return os.path.join(ckpt_dir(root), f"{step}-{name}{CKPT_SUFFIX}")


def ckpt_base(root: str, step: str, name: str) -> str:
    """Suffix-less base path for a sharded checkpoint family
    (`<base>-shardNNNNN.ckpt.npz` + `<base>-shared.ckpt.npz`)."""
    return os.path.join(ckpt_dir(root), f"{step}-{name}")


class StreamCheckpoint:
    """One resumable stream's snapshot file.

    `save` persists (chunk_index, arrays, meta [, blob]) atomically;
    `load` returns them only when the stored config sha matches —
    resuming a fold onto changed config/binning would be silently wrong,
    so mismatch means start fresh. `maybe_save` applies the cadence so
    callers write one line, and `state_fn` is only invoked when a write
    is actually due (snapshotting can cost a device sync)."""

    def __init__(self, path: str, config_sha: str,
                 every: Optional[int] = None,
                 sections: Optional[Dict[str, str]] = None) -> None:
        self.path = path
        self.config_sha = config_sha
        # per-section shas (sectioned_sha): stored in the snapshot meta so
        # a config-mismatch rejection names the diverged section(s)
        self.sections = dict(sections) if sections else None
        self.every = every_chunks_setting() if every is None else int(every)
        self._since = 0

    # ---- write side ----
    def save(self, chunk_index: int,
             arrays: Optional[Dict[str, np.ndarray]] = None,
             meta: Optional[dict] = None,
             blob: Optional[bytes] = None) -> str:
        from shifu_tpu.obs import registry
        from shifu_tpu.resilience import retry

        payload: Dict[str, np.ndarray] = {}
        for k, v in (arrays or {}).items():
            assert not k.startswith("__"), k
            payload[k] = np.asarray(v)
        header = {
            "chunkIndex": int(chunk_index),
            "configSha": self.config_sha,
            "meta": meta or {},
        }
        if self.sections:
            header["sections"] = self.sections
        payload[META_KEY] = np.frombuffer(
            json.dumps(header, sort_keys=True).encode("utf-8"),
            dtype=np.uint8)
        if blob is not None:
            payload[BLOB_KEY] = np.frombuffer(blob, dtype=np.uint8)
        buf = io.BytesIO()
        np.savez(buf, **payload)
        data = buf.getvalue()
        # retried: an injected (or real, transient) failure during the
        # checkpoint write must not kill the stream it protects
        retry.retry_call(lambda: atomic_write(self.path, data), seam="ckpt")
        reg = registry()
        reg.counter("ckpt.writes").inc()
        reg.counter("ckpt.bytes").inc(len(data))
        return self.path

    def maybe_save(self, chunk_index: int,
                   state_fn: Callable[[], Tuple[Optional[Dict[str, np.ndarray]],
                                                Optional[dict],
                                                Optional[bytes]]],
                   ) -> bool:
        """Cadence-gated save after folding chunk `chunk_index`; returns
        True when a snapshot was written."""
        if self.every <= 0:
            return False
        self._since += 1
        if self._since < self.every:
            return False
        self._since = 0
        arrays, meta, blob = state_fn()
        self.save(chunk_index, arrays=arrays, meta=meta, blob=blob)
        return True

    # ---- read side ----
    def load(self) -> Optional[Tuple[int, Dict[str, np.ndarray],
                                     dict, Optional[bytes]]]:
        """(chunk_index, arrays, meta, blob) or None (absent / corrupt /
        config mismatch — all mean start fresh, never crash)."""
        from shifu_tpu.obs import registry

        if not os.path.isfile(self.path):
            return None
        try:
            with np.load(self.path) as z:
                header = json.loads(bytes(z[META_KEY].tobytes()).decode())
                arrays = {k: z[k] for k in z.files
                          if k not in (META_KEY, BLOB_KEY)}
                blob = (z[BLOB_KEY].tobytes()
                        if BLOB_KEY in z.files else None)
        except Exception as e:  # corrupt/truncated checkpoint: start fresh
            log.warning("checkpoint %s unreadable (%s); starting fresh",
                        self.path, e)
            registry().counter("ckpt.rejected", reason="corrupt").inc()
            return None
        if header.get("configSha") != self.config_sha:
            # name the diverged section(s) when both sides recorded them:
            # "config changed" is useless at 3am; "the data section
            # changed but train didn't" tells the operator to re-run the
            # upstream step rather than question their hyperparameters
            stored = header.get("sections") or {}
            diverged = "unknown"
            if stored and self.sections:
                names = sorted(
                    k for k in set(stored) | set(self.sections)
                    if stored.get(k) != self.sections.get(k))
                diverged = ",".join(names) or "unknown"
            log.warning("checkpoint %s was built under a different config "
                        "(%s != %s; diverged section(s): %s); starting "
                        "fresh", self.path, header.get("configSha"),
                        self.config_sha, diverged)
            registry().counter("ckpt.rejected", reason="config",
                               section=diverged).inc()
            return None
        registry().counter("ckpt.resumes").inc()
        return int(header["chunkIndex"]), arrays, header.get("meta", {}), blob

    def clear(self) -> None:
        """Remove the snapshot (the stream completed; nothing to resume)."""
        try:
            os.unlink(self.path)
        except OSError:  # never written / already cleared
            pass


class ShardedStreamCheckpoint:
    """Per-shard snapshot family for a sharded streaming fold.

    One snapshot file PER ROW SHARD — shard s's file carries (its own
    chunk cursor, its own local fold state, its own counters) — plus one
    `-shared` file for the state no single shard owns (the post-psum
    host float64 fold, writer bookkeeping). All files share the caller's
    config sha.

    Kill-atomicity is two-phase: shard files ALTERNATE between two slots
    (`-shard00000-a` / `-b`) per save epoch, and the shared file —
    written LAST, itself atomic — is the commit pointer: its meta names
    the epoch and the slot that form the current complete family. A kill
    anywhere during the S shard-file writes touches only the NEW slot;
    the shared pointer still names the previous slot, whose files this
    save never opened — so the previous complete snapshot is never lost,
    exactly the guarantee the single-file `atomic_write` gave the
    unsharded folds. `load` verifies every pointed-at shard file carries
    the committed epoch and shard count and otherwise rejects the WHOLE
    family (`ckpt.rejected{reason=partial|epoch|shards}`) — shards must
    never resume from different cadence points than the shared reduce
    state they fold into.

    Under a multi-process HostPlan (`n_hosts` > 1) the family is
    PER-HOST: host h's files live under `<base>-h00h-...`, carry only
    h's own cursor slice and local fold state, and h resumes from them
    alone — no host ever reads another host's cursors. The committed
    stamp records the host count, and a host-count change between runs
    rejects the family (`ckpt.rejected{reason=hosts}`) exactly like a
    shard-count change does: the chunk -> host assignment moved, so
    every stored cursor names a different slice. `n_hosts=1` keeps the
    legacy un-prefixed file names byte-for-byte.

    The layout is identical on a real pod, so the resume contract
    carries over unchanged. `clear` globs the whole family — including
    stale slot or extra-shard files a previous run with a different
    shard count left — so nothing phantom ever shows in `shifu runs
    --resumable` (a 1-host clear also sweeps leftover per-host families;
    a multi-host clear touches only its OWN host's files — other hosts'
    live families are theirs to clear).
    """

    _SLOTS = ("a", "b")

    def __init__(self, base: str, config_sha: str, n_shards: int,
                 every: Optional[int] = None,
                 sections: Optional[Dict[str, str]] = None,
                 n_hosts: int = 1, host_index: int = 0,
                 part_kind: str = "shards") -> None:
        self.base = base
        self.n_shards = max(1, int(n_shards))
        self.n_hosts = max(1, int(n_hosts))
        self.host_index = int(host_index)
        self.config_sha = config_sha
        # what a part IS: "shards" for the row-sharded folds (legacy
        # byte-identical), "stages" for the co-resident trainer's
        # per-pipeline-stage family. The kind names the stamp key, the
        # per-part file infix, and the rejection reason when the count
        # moved between runs.
        self.part_kind = part_kind
        self._part_infix = ("shard" if part_kind == "shards"
                            else (part_kind[:-1] if part_kind.endswith("s")
                                  else part_kind) or "part")
        self.every = every_chunks_setting() if every is None else int(every)
        self._since = 0
        self._epoch = 0
        family = (base if self.n_hosts == 1
                  else f"{base}-h{self.host_index:03d}")
        self._family = family
        self._shards = [
            {slot: StreamCheckpoint(
                f"{family}-{self._part_infix}{s:05d}-{slot}{CKPT_SUFFIX}",
                config_sha, every=0, sections=sections)
             for slot in self._SLOTS}
            for s in range(self.n_shards)]
        self._shared = StreamCheckpoint(f"{family}-shared{CKPT_SUFFIX}",
                                        config_sha, every=0,
                                        sections=sections)

    def _slot(self, epoch: int) -> str:
        return self._SLOTS[epoch % len(self._SLOTS)]

    # ---- write side ----
    def save(self, per_shard: List[Tuple[int, Optional[Dict[str, np.ndarray]],
                                         Optional[dict], Optional[bytes]]],
             shared: Tuple[Optional[Dict[str, np.ndarray]], Optional[dict],
                           Optional[bytes]]) -> None:
        """Persist every shard's (cursor, arrays, meta, blob) into the
        next slot, then commit by writing the shared pointer last."""
        assert len(per_shard) == self.n_shards, \
            (len(per_shard), self.n_shards)
        epoch = self._epoch + 1
        slot = self._slot(epoch)
        stamp = {"epoch": epoch, self.part_kind: self.n_shards}
        if self.n_hosts > 1:
            stamp["hosts"] = self.n_hosts
            stamp["host"] = self.host_index
        for cks, (ci, arrays, meta, blob) in zip(self._shards, per_shard):
            cks[slot].save(ci, arrays=arrays,
                           meta={**(meta or {}), **stamp}, blob=blob)
        arrays, meta, blob = shared
        self._shared.save(-1, arrays=arrays,
                          meta={**(meta or {}), **stamp, "slot": slot},
                          blob=blob)
        self._epoch = epoch  # committed

    def maybe_save(self, state_fn: Callable[[], tuple]) -> bool:
        """Cadence-gated save (one call per folded chunk); `state_fn`
        returns (per_shard, shared) and is only invoked when a write is
        due."""
        if self.every <= 0:
            return False
        self._since += 1
        if self._since < self.every:
            return False
        self._since = 0
        per_shard, shared = state_fn()
        self.save(per_shard, shared)
        return True

    # ---- read side ----
    def load(self) -> Optional[Tuple[
            List[int], List[Tuple[Dict[str, np.ndarray], dict,
                                  Optional[bytes]]],
            Tuple[Dict[str, np.ndarray], dict, Optional[bytes]]]]:
        """(cursors, per_shard [(arrays, meta, blob)], shared) or None.
        The shared pointer names the committed (epoch, slot); any shard
        file of that slot missing/corrupt/sha-mismatched, a shard-count
        change, or an epoch disagreeing with the pointer rejects the
        WHOLE family — partial resumes would silently double- or
        drop-fold chunks."""
        from shifu_tpu.obs import registry

        shared = self._shared.load()
        if shared is None:
            return None
        epoch = shared[2].get("epoch")
        slot = shared[2].get("slot")
        if epoch is None or slot not in self._SLOTS:
            registry().counter("ckpt.rejected", reason="partial").inc()
            return None
        if shared[2].get(self.part_kind) != self.n_shards:
            # e.g. `ckpt.rejected{reason="stages"}` when a co-resident
            # resume asks for a different pipeline partitioning than the
            # family was written under — every stored part covers a
            # different flat slice, so resuming would be silently wrong
            log.warning("sharded checkpoint %s was written with %s %s "
                        "(now %d); starting fresh", self.base,
                        shared[2].get(self.part_kind), self.part_kind,
                        self.n_shards)
            registry().counter("ckpt.rejected",
                               reason=self.part_kind).inc()
            return None
        if shared[2].get("hosts", 1) != self.n_hosts:
            # the chunk -> host assignment moved: every stored cursor
            # names a slice this run will never be handed, so resuming
            # would double- and drop-fold chunks at once
            log.warning("sharded checkpoint %s was written with %s hosts "
                        "(now %d); starting fresh", self._family,
                        shared[2].get("hosts", 1), self.n_hosts)
            registry().counter("ckpt.rejected", reason="hosts").inc()
            return None
        loads = [cks[slot].load() for cks in self._shards]
        if any(ld is None for ld in loads):
            registry().counter("ckpt.rejected", reason="partial").inc()
            return None
        epochs = {ld[2].get("epoch") for ld in loads}
        if epochs != {epoch}:
            log.warning("sharded checkpoint %s slot %s has epochs %s but "
                        "the pointer committed %s; starting fresh",
                        self.base, slot,
                        sorted(str(e) for e in epochs), epoch)
            registry().counter("ckpt.rejected", reason="epoch").inc()
            return None
        self._epoch = int(epoch)
        cursors = [ld[0] for ld in loads]
        per_shard = [(ld[1], ld[2], ld[3]) for ld in loads]
        return cursors, per_shard, (shared[1], shared[2], shared[3])

    def clear(self) -> None:
        """Remove the WHOLE family — both slots, the pointer, and any
        stale `-shardNNNNN*` files a run with a different shard count
        left behind (they would otherwise show as phantom resumables).
        A 1-host clear also sweeps per-host (`-hNNN-*`) families from an
        earlier multi-host run; a multi-host clear stays inside its own
        host's family — the other hosts' files are live state owned by
        running peers."""
        from shifu_tpu.fs.listing import sorted_glob

        patterns = [self._family + "-" + self._part_infix + "*"
                    + CKPT_SUFFIX]
        if self.n_hosts == 1:
            patterns.append(self.base + "-h*" + CKPT_SUFFIX)
        for pattern in patterns:
            for path in sorted_glob(pattern):
                try:
                    os.unlink(path)
                except OSError:  # already gone
                    pass
        self._shared.clear()


def list_resumable(root: str) -> List[dict]:
    """Stream checkpoints a preempted step left behind — the data for
    `shifu runs --resumable`. Scans <root>/.shifu/runs/ckpt (the chunked
    fold snapshots) AND the trainer checkpoint dirs (streamed NN/WDL
    state lives beside cfg.checkpoint_path — under tmp/train/ for
    `shifu train`, under tmp/retrain/train/ for `shifu retrain`)."""
    from shifu_tpu.fs.listing import sorted_glob

    root = os.path.abspath(root)
    paths: List[str] = []
    d = ckpt_dir(root)
    if os.path.isdir(d):
        paths.extend(os.path.join(d, name) for name in sorted(os.listdir(d))
                     if name.endswith(CKPT_SUFFIX))
    trainer_globs = [
        ("train", os.path.join(root, "tmp", "train")),
        ("retrain", os.path.join(root, "tmp", "retrain", "train")),
    ]
    step_of = {}
    for step, base in trainer_globs:
        for path in sorted_glob(
                os.path.join(base, "**", "*" + CKPT_SUFFIX),
                recursive=True):
            paths.append(path)
            step_of[path] = step
    import re

    # a co-resident family is MANY files (per-stage slots + the shared
    # commit pointer) but ONE resumable run: list the pointer as one
    # aggregated entry and hide the per-stage slot files behind it
    part_re = re.compile(r"^coresident-.+-stage\d{5}-[ab]$")
    out: List[dict] = []
    for path in paths:
        name = os.path.basename(path)[: -len(CKPT_SUFFIX)]
        if os.path.dirname(path) == d and part_re.match(name):
            continue
        if os.path.dirname(path) != d:
            # trainer snapshot: qualify with its checkpoint dir so bagged
            # members (checkpoint_0, checkpoint_1, ...) stay distinct,
            # and with the step so `shifu retrain --resume` state is
            # distinguishable from `shifu train --resume` state
            name = (f"{step_of.get(path, 'train')}-"
                    f"{os.path.basename(os.path.dirname(path))}")
        entry = {
            "name": name,
            "path": path,
            "bytes": os.path.getsize(path),
            "mtime": os.path.getmtime(path),
        }
        try:
            with np.load(path) as z:
                header = json.loads(bytes(z[META_KEY].tobytes()).decode())
            entry["chunkIndex"] = header.get("chunkIndex")
            entry["configSha"] = header.get("configSha")
            entry["meta"] = header.get("meta", {})
            if (os.path.dirname(path) == d
                    and name.startswith("coresident-")
                    and name.endswith("-shared")):
                # the family's commit pointer: surface the run identity
                # (trainer epoch + stage count) for `shifu runs
                # --resumable`
                entry["name"] = name[: -len("-shared")]
                entry["family"] = "coresident"
                entry["epoch"] = entry["meta"].get("it")
                entry["stages"] = entry["meta"].get("stages")
        except Exception:  # unreadable: still listed, marked corrupt
            entry["corrupt"] = True
        out.append(entry)
    return out
