"""Meshed vs single-device tree-build scaling on a virtual CPU mesh.

Usage: python scripts/scaling_cpu_mesh.py [n_devices] [out.json]

Measures the fused GBT tree program (train/tree_trainer.py) at 1 device
and at N virtual CPU devices (the same shard_map + per-level psum path
that runs on a real TPU pod over ICI), and writes one JSON with the
wall-clock ratio. On a single host the N "devices" share the same cores,
so the interesting quantity is that the meshed program SCALES AT ALL
(collective overhead stays sub-linear), not the absolute speedup — real
speedup needs real chips. The driver-facing line for round 5 lives in
SCALING_r05.json."""

from __future__ import annotations

import json
import os
import subprocess
import sys

_CHILD = r"""
import json, sys, time
import numpy as np
from shifu_tpu.utils.platform import force_platform

n_dev = int(sys.argv[1])
force_platform("cpu", n_devices=n_dev)
import jax

from shifu_tpu.parallel.mesh import data_mesh
from shifu_tpu.train.tree_trainer import TreeTrainConfig, train_trees

rng = np.random.default_rng(0)
n, F, bins, depth, trees = 200_000, 30, 32, 6, 3
codes = rng.integers(0, bins, size=(n, F)).astype(np.int32)
y = (codes[:, 0] + codes[:, 1] > bins).astype(np.float32)
w = np.ones(n, np.float32)
cfg = TreeTrainConfig(algorithm="GBT", tree_num=trees, max_depth=depth,
                      learning_rate=0.1, valid_set_rate=0.1, seed=3)
cols = [f"f{i}" for i in range(F)]
mesh = data_mesh(n_dev) if n_dev > 1 else None

def run():
    train_trees(codes, y, w, [bins + 1] * F, [False] * F, cols, cfg,
                mesh=mesh)

run()  # compile
ts = []
for _ in range(3):
    t0 = time.perf_counter(); run(); ts.append(time.perf_counter() - t0)
print(json.dumps({"n_devices": n_dev, "seconds": sorted(ts)[1],
                  "row_trees_per_s": n * trees / sorted(ts)[1]}))
"""


def measure(n_dev: int) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    out = subprocess.run(
        [sys.executable, "-c", _CHILD, str(n_dev)],
        capture_output=True, text=True, timeout=900, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    if out.returncode != 0:
        raise SystemExit(f"{n_dev}-device run failed:\n{out.stderr[-2000:]}")
    return json.loads(out.stdout.strip().splitlines()[-1])


def main() -> None:
    n_dev = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    out_path = sys.argv[2] if len(sys.argv) > 2 else "SCALING_r05.json"
    single = measure(1)
    meshed = measure(n_dev)
    result = {
        "bench": "gbt_tree_build 200k x 30, 3 trees, depth 6",
        "single_device": single,
        "meshed": meshed,
        "meshed_over_single": round(
            meshed["row_trees_per_s"] / single["row_trees_per_s"], 3),
        "note": ("virtual CPU devices share one host's cores: the line "
                 "proves the shard_map+psum path runs and keeps collective "
                 "overhead bounded, not real-chip speedup"),
    }
    with open(out_path, "w") as fh:
        json.dump(result, fh, indent=2)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
