"""SH rules: pipeline hygiene. Smaller-bore than the JX pack but the
same motivation — the failure modes that creep into a long-lived
pipeline (swallowed exceptions, shared mutable defaults, streaming entry
points that silently ignore the chunk/prefetch plumbing).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterator

from shifu_tpu.analysis.engine import (
    Module,
    PackageContext,
    Rule,
    dotted_name,
    register,
)
from shifu_tpu.analysis.rules.jaxrules import _mutable_default

_BLANKET = {"Exception", "BaseException"}

# tool pragmas are not justifications: strip them and require that some
# actual prose remains on the line
_PRAGMA_RE = re.compile(
    r"noqa(?::\s*[A-Za-z]+\d+(?:\s*,\s*[A-Za-z]+\d+)*)?"
    r"|type:\s*ignore\S*|pragma:?\s*no\s*cover",
    re.IGNORECASE)


def _justified(line: str) -> bool:
    """True when the line carries a human justification comment — a '#'
    comment with prose beyond recognized tool pragmas (so a bare
    `# type: ignore` or `# noqa: E722` does not silence SH101, but
    `# pragma: no cover - jax absent in CI` does)."""
    if "#" not in line:
        return False
    comment = line.split("#", 1)[1]
    remainder = _PRAGMA_RE.sub("", comment)
    return bool(re.search(r"[A-Za-z]{3,}", remainder))


@register
class BlanketExcept(Rule):
    """SH101 — bare/blanket except.

    bad:  except: pass                      # error: swallows everything
    bad:  except Exception: return None     # warning unless justified
    good: except ValueError: ...            # or a blanket except with a
          re-raise, or a same-line justification comment / noqa.
    """

    id = "SH101"
    severity = "error"
    summary = ("bare `except:` (error) / blanket `except Exception` "
               "without re-raise or justification comment (warning)")

    def check(self, module: Module,
              ctx: PackageContext) -> Iterator["Finding"]:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                yield self.finding(
                    module, node,
                    "bare `except:` catches SystemExit/KeyboardInterrupt "
                    "too — name the exception (or BaseException + raise)")
                continue
            names = {dotted_name(t).split(".")[-1]
                     for t in (node.type.elts
                               if isinstance(node.type, ast.Tuple)
                               else [node.type])}
            if not names & _BLANKET:
                continue
            swallows = all(isinstance(s, ast.Pass) for s in node.body)
            reraises = any(isinstance(n, ast.Raise)
                           for n in ast.walk(node))
            justified = _justified(module.line_text(node.lineno))
            if swallows:
                yield self.finding(
                    module, node,
                    "blanket except with a bare `pass` silently swallows "
                    "every failure — narrow it or justify with a comment")
            elif not reraises and not justified:
                yield self.finding(
                    module, node,
                    "blanket `except " + "/".join(sorted(names & _BLANKET))
                    + "` without re-raise — narrow it, or add a same-line "
                    "justification comment", severity="warning")


@register
class MutableDefaultArg(Rule):
    """SH102 — mutable default argument.

    bad:  def f(x, acc=[]): acc.append(x)   # shared across calls
    good: def f(x, acc=None): acc = [] if acc is None else acc
    """

    id = "SH102"
    severity = "error"
    summary = "mutable default argument (list/dict/set shared across calls)"

    def check(self, module: Module,
              ctx: PackageContext) -> Iterator["Finding"]:
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Lambda)):
                continue
            a = node.args
            pos = a.posonlyargs + a.args
            pairs = list(zip(reversed(pos), reversed(a.defaults)))
            pairs += [(p, d) for p, d in zip(a.kwonlyargs, a.kw_defaults)
                      if d is not None]
            for param, default in pairs:
                if _mutable_default(default):
                    name = getattr(node, "name", "<lambda>")
                    yield self.finding(
                        module, default,
                        f"mutable default for `{param.arg}` of `{name}` "
                        f"is shared across calls — default to None and "
                        f"construct inside")


_CKPT_PATH_RE = re.compile(r"ckpt|checkpoint|manifest", re.IGNORECASE)
_WRITE_MODES = {"w", "wb", "w+", "wb+"}
# the one module allowed to open checkpoint paths directly: it IS the
# atomic-write helper
_ATOMIC_HELPER = os.path.join("resilience", "checkpoint.py")


@register
class NonAtomicCheckpointWrite(Rule):
    """SH104 — torn-file-prone checkpoint write / jitterless retry sleep.

    bad:  np.save(cfg.checkpoint_path, w)      # kill mid-write = torn file
    bad:  open(manifest_path, "w")             # same failure mode
    good: resilience.checkpoint.atomic_save_npy / atomic_write_json /
          atomic_write (temp file + os.replace in the same directory).

    bad:  while True:
              try: fetch()
              except OSError: time.sleep(1)    # fixed sleep: herd + no cap
    good: resilience.retry.retry_call (exponential backoff, full jitter,
          bounded budget) — or any computed, non-constant delay.
    """

    id = "SH104"
    severity = "error"
    summary = ("non-atomic write to a checkpoint/manifest-like path "
               "(error) / constant time.sleep in a retry loop (warning)")

    def check(self, module: Module,
              ctx: PackageContext) -> Iterator["Finding"]:
        if module.path.endswith(_ATOMIC_HELPER):
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name in ("np.save", "numpy.save") and node.args:
                target = module.segment(node.args[0])
                if _CKPT_PATH_RE.search(target):
                    yield self.finding(
                        module, node,
                        f"np.save to checkpoint-like path `{target}` can "
                        f"leave a torn file on kill — use "
                        f"resilience.checkpoint.atomic_save_npy")
            elif name == "open" and len(node.args) >= 2:
                mode = node.args[1]
                if not (isinstance(mode, ast.Constant)
                        and isinstance(mode.value, str)
                        and mode.value in _WRITE_MODES):
                    continue
                target = module.segment(node.args[0])
                if _CKPT_PATH_RE.search(target):
                    yield self.finding(
                        module, node,
                        f"direct open(..., \"{mode.value}\") write to "
                        f"checkpoint/manifest-like path `{target}` — use "
                        f"resilience.checkpoint.atomic_write/"
                        f"atomic_write_json")
            elif name == "time.sleep" and node.args:
                arg = node.args[0]
                if not (isinstance(arg, ast.Constant)
                        and isinstance(arg.value, (int, float))):
                    continue  # computed delay: assume backoff/jitter
                in_retry_loop = False
                for anc in module.ancestors(node):
                    if isinstance(anc, (ast.For, ast.While)):
                        in_retry_loop = any(
                            isinstance(n, ast.ExceptHandler)
                            for n in ast.walk(anc))
                        break
                if in_retry_loop:
                    yield self.finding(
                        module, node,
                        "constant time.sleep in a retry loop — no "
                        "backoff, no jitter (thundering herd on shared "
                        "backends); use resilience.retry.retry_call",
                        severity="warning")


# ---------------------------------------------------------------------------
# SH105 — knob catalog discipline
# ---------------------------------------------------------------------------

_GETTER_TYPES = {"get_property": "str", "get_int": "int",
                 "get_float": "float", "get_bool": "bool"}
_KNOBS_MODULE = os.path.join("analysis", "knobs.py")


def _literal_key(module: Module, node: ast.AST):
    """Resolve a knob-key argument to (key_or_glob, dynamic) — a
    Constant string, an f-string with dynamic parts collapsed to `*`,
    or a module-level UPPER_CASE string constant; None when the key is
    not statically resolvable (a plain variable)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value, False
    if isinstance(node, ast.JoinedStr):
        parts: list = []
        for v in node.values:
            if isinstance(v, ast.Constant):
                parts.append(str(v.value))
            else:
                if not parts or parts[-1] != "*":
                    parts.append("*")
        return "".join(parts), True
    if isinstance(node, ast.Name):
        # MODULE-LEVEL constants only (tree.body, not ast.walk): a
        # same-named local inside some unrelated function must not
        # mis-resolve a runtime-bound key and fabricate a type-mismatch
        for n in module.tree.body:
            if (isinstance(n, ast.Assign) and len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Name)
                    and n.targets[0].id == node.id
                    and isinstance(n.value, ast.Constant)
                    and isinstance(n.value.value, str)):
                return n.value.value, False
    return None, False


@register
class KnobCatalog(Rule):
    """SH105 — every -Dshifu.* read must match the knob catalog
    (analysis/knobs.py), and every declared knob must have a reader.

    bad:  environment.get_int("shifu.serve.maxBatchRow", 1024)
          # typo'd key: silently always the default
    bad:  environment.get_int("shifu.loop.logSample", 0)
          # declared float, read as int: "0.5" truncates to the default
    good: environment.get_float("shifu.loop.logSample", 0.0)
    Dynamic keys read via f-strings must literalize (dynamic part -> *)
    to a declared glob: f"shifu.retry.{seam}.max" -> shifu.retry.*.max.
    """

    id = "SH105"
    severity = "error"
    summary = ("environment.get_* of an undeclared/mistyped shifu.* "
               "knob, or a declared knob nothing reads")

    def _reads(self, ctx: PackageContext):
        """Package-wide {key_or_glob} actually read (cached per ctx)."""
        cached = getattr(ctx, "_sh105_reads", None)
        if cached is not None:
            return cached
        reads = set()
        for m in ctx.modules:
            if m.path.endswith(os.path.join("utils", "environment.py")):
                continue  # the getter implementation itself
            for node in ast.walk(m.tree):
                if not (isinstance(node, ast.Call) and node.args):
                    continue
                if dotted_name(node.func).split(".")[-1] not in _GETTER_TYPES:
                    continue
                key, _dyn = _literal_key(m, node.args[0])
                if key and key.startswith("shifu."):
                    reads.add(key)
        ctx._sh105_reads = reads
        return reads

    def check(self, module: Module,
              ctx: PackageContext) -> Iterator["Finding"]:
        from shifu_tpu.analysis.knobs import by_name

        declared = by_name()
        if module.path.endswith(os.path.join("utils", "environment.py")):
            return
        # the catalog side: declared knobs nothing in the analyzed tree
        # reads, reported at their declaration lines (only when the
        # catalog itself is part of the sweep, so fixture trees in tests
        # don't spray unread-knob noise)
        if module.path.endswith(_KNOBS_MODULE):
            reads = self._reads(ctx)
            for node in ast.walk(module.tree):
                if (isinstance(node, ast.Constant)
                        and isinstance(node.value, str)
                        and node.value in declared
                        and node.value not in reads):
                    # only the name field (first string of a _K(...) call)
                    parent = module.parent.get(node)
                    if (isinstance(parent, ast.Call)
                            and parent.args and parent.args[0] is node):
                        yield self.finding(
                            module, node,
                            f"knob `{node.value}` is declared in the "
                            f"catalog but nothing reads it — remove the "
                            f"entry or wire the read site")
            return
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            getter = dotted_name(node.func).split(".")[-1]
            if getter not in _GETTER_TYPES:
                continue
            key, _dyn = _literal_key(module, node.args[0])
            if not key or not key.startswith("shifu."):
                continue
            knob = declared.get(key)
            if knob is None:
                yield self.finding(
                    module, node,
                    f"`{getter}(\"{key}\", ...)` reads a knob the "
                    f"catalog (analysis/knobs.py) does not declare — "
                    f"declare it (or fix the key; a typo silently "
                    f"returns the default forever)")
            elif getter != "get_property" and _GETTER_TYPES[getter] != \
                    knob.type:
                yield self.finding(
                    module, node,
                    f"`{getter}(\"{key}\", ...)` reads a knob declared "
                    f"as {knob.type} — a mistyped read silently falls "
                    f"back to the default (use get_{knob.type} or fix "
                    f"the catalog)")


_STREAM_ENTRY_RE = re.compile(r"(_streamed|_streaming)$|^stream_")
_PLUMBING_PARAM_RE = re.compile(r"chunk|prefetch|feed|source|factory")
# names that mean "this entry point iterates RAW ingest chunks" — the
# loops that must divide work over the lifecycle shard planner
_CHUNK_LOOP_NAMES = {"chunk_source", "iter_columnar_chunks",
                     "chunk_factory", "chunk_rows_setting"}
# ... and the planner vocabulary that proves it does
_SHARD_PLAN_NAMES = {"ShardPlan", "shard_of", "shard_slice",
                     "lifecycle_shards", "fold_group"}
_SINGLE_SHARD_RE = re.compile(r"single[- ]shard", re.IGNORECASE)


@register
class StreamingPlumbing(Rule):
    """SH103 — streaming entry point without chunk/prefetch plumbing, or
    chunk loop without the shard planner.

    Every streamed path must honor shifu.ingest.prefetchChunks and the
    chunk sizing knobs — an entry point that hand-rolls its own loop
    silently loses the overlapped-pipeline behavior (and its tests).
    And every entry point that loops RAW ingest chunks must divide them
    over the lifecycle shard planner (data/pipeline.ShardPlan) — a
    hand-rolled chunk loop is O(rows) no matter how many chips are
    attached — or declare single-shard intent ("single-shard" in its
    docstring) when the loop is genuinely host-local.

    bad:  def train_foo_streamed(dir, cfg):
              for shard in read_all(dir): ...   # no prefetch, no knobs
    bad:  def score_streaming(path):
              for chunk in chunk_source(path)(): ...  # O(rows), no plan
    good: drive shifu_tpu.data.pipeline.prefetch_iter (directly or via a
          feed/chunk_factory parameter), or accept chunk_rows/prefetch;
          divide chunks with ShardPlan.shard_of / declare single-shard.
    """

    id = "SH103"
    severity = "warning"
    summary = ("streaming entry point without chunk/prefetch plumbing, "
               "or raw-chunk loop bypassing the shard planner")

    def check(self, module: Module,
              ctx: PackageContext) -> Iterator["Finding"]:
        for node in module.tree.body:
            yield from self._check_def(module, ctx, node)

    def _check_def(self, module: Module, ctx: PackageContext,
                   node) -> Iterator["Finding"]:
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if isinstance(node, ast.ClassDef):  # methods are entry points
                for sub in node.body:
                    yield from self._check_def(module, ctx, sub)
            return
        if not _STREAM_ENTRY_RE.search(node.name):
            return
        closure = ctx.reference_closure(module, node)
        delegates = any(_STREAM_ENTRY_RE.search(n)
                        for n in closure - {node.name})
        params = [p.arg for p in (node.args.posonlyargs + node.args.args
                                  + node.args.kwonlyargs)]
        has_plumbing = (
            any(_PLUMBING_PARAM_RE.search(p) for p in params)
            or bool({"prefetch_iter", "chunk_source", "stream_columnar"}
                    & closure)
            # delegating to another streaming entry point (processor
            # wrappers around train/*_streamed) inherits its plumbing
            or delegates)
        if not has_plumbing:
            yield self.finding(
                module, node,
                f"streaming entry point `{node.name}` neither drives "
                f"prefetch_iter/chunk_source nor accepts chunk/prefetch "
                f"plumbing (chunk_rows=, prefetch=, feed=, *_factory=) — "
                f"the overlapped-pipeline knobs will be silently ignored")
            return
        # sharded-lifecycle check: a raw-chunk loop that bypasses the
        # shard planner reintroduces an O(rows) single-host path
        if not (_CHUNK_LOOP_NAMES & closure) or delegates:
            return
        if _SHARD_PLAN_NAMES & closure:
            return
        doc = ast.get_docstring(node) or ""
        if _SINGLE_SHARD_RE.search(doc):
            return
        yield self.finding(
            module, node,
            f"streaming entry point `{node.name}` loops raw ingest "
            f"chunks without the shard planner — divide chunks with "
            f"data/pipeline.ShardPlan (shard_of/shard_slice) so the "
            f"fold stays O(rows/shards), or declare \"single-shard\" "
            f"intent in its docstring")
