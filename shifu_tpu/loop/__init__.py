"""Closed-loop continuous training: serve feeds train feeds serve.

The reference Shifu is a one-shot pipeline (`new -> ... -> eval ->
export`); production traffic is a stream. This package closes the loop
over the existing subsystems instead of duplicating them:

  traffic.py   append-only serve-side traffic log — rotating chunk files
               under the `.shifu/runs` ledger layout, written atomically
               (resilience.checkpoint.atomic_write) and readable back
               through the ordinary `data/stream.chunk_source`, so logged
               traffic is just another chunk stream every lifecycle step
               already consumes.
  drift.py     online PSI drift — each served micro-batch is bin-coded
               against the training ColumnConfig bins inside the fused
               serve program and folded into a per-column device window
               (the PR-1/PR-8 windowed-fold idiom), exported via /metrics
               and the serve shutdown manifest; past the degrade
               threshold /healthz flips to `degraded` and a retrain
               recommendation manifest lands in the run ledger.
  hotswap.py   zero-downtime registry hot-swap — an atomic
               swap-by-content-sha with shadow scoring (the candidate
               scores a sampled fraction of live batches alongside the
               active set; per-version serve.* metrics + score-delta
               stats), so a canary rollout is decidable from the ledger.
  promote.py   the promotion gate: shadow agreement + drift verdict ->
               promote/hold decision, written as a `promote-<seq>.json`
               ledger manifest (`shifu promote`).

`shifu retrain` (processor/retrain.py) consumes the traffic log and/or
new data through the existing ShardPlan streaming feeds, warm-starts
NN/LR from the previous model and extends GBT by appending trees.

Knobs (all -D properties):

  shifu.loop.logSample        fraction of served rows logged (default 0 =
                              off; `shifu serve --traffic-log` sets 1.0)
  shifu.loop.logChunkRows     rows per traffic chunk file (default 4096)
  shifu.loop.psiDegrade       per-column PSI that flips /healthz to
                              degraded + recommends retrain (default 0.2)
  shifu.loop.driftMinRows     live rows before drift verdicts bind
                              (default 256 — PSI over a handful of rows
                              is sampling noise, not a shift; below it
                              the verdict reports `warming`)
  shifu.loop.driftCheckBatches  batches between drift verdict checks
                              (default 32; a check flushes the window)
  shifu.loop.shadowSample     fraction of live batches the staged shadow
                              version also scores (default 0.25)
  shifu.loop.shadowTolerance  |mean-score delta| (0..1000 scale) counted
                              as agreement (default 5.0)
  shifu.loop.promoteAgree     min shadow agreement rate to promote
                              (default 0.95)
  shifu.loop.promoteMinRows   min shadow-scored rows before a promote
                              decision is meaningful (default 64)
  shifu.loop.appendTrees      GBT retrain: trees appended on new chunks
                              (default 10)
"""

from __future__ import annotations

from shifu_tpu.utils import environment

DEFAULT_LOG_CHUNK_ROWS = 4096
DEFAULT_PSI_DEGRADE = 0.2
DEFAULT_DRIFT_MIN_ROWS = 256
DEFAULT_DRIFT_CHECK_BATCHES = 32
DEFAULT_SHADOW_SAMPLE = 0.25
DEFAULT_SHADOW_TOLERANCE = 5.0
DEFAULT_PROMOTE_AGREE = 0.95
DEFAULT_PROMOTE_MIN_ROWS = 64
DEFAULT_APPEND_TREES = 10


def log_sample_setting() -> float:
    return environment.get_float("shifu.loop.logSample", 0.0)


def log_chunk_rows_setting() -> int:
    return environment.get_int("shifu.loop.logChunkRows",
                               DEFAULT_LOG_CHUNK_ROWS)


def psi_degrade_setting() -> float:
    return environment.get_float("shifu.loop.psiDegrade",
                                 DEFAULT_PSI_DEGRADE)


def drift_min_rows_setting() -> int:
    return environment.get_int("shifu.loop.driftMinRows",
                               DEFAULT_DRIFT_MIN_ROWS)


def drift_check_batches_setting() -> int:
    return environment.get_int("shifu.loop.driftCheckBatches",
                               DEFAULT_DRIFT_CHECK_BATCHES)


def shadow_sample_setting() -> float:
    return environment.get_float("shifu.loop.shadowSample",
                                 DEFAULT_SHADOW_SAMPLE)


def shadow_tolerance_setting() -> float:
    return environment.get_float("shifu.loop.shadowTolerance",
                                 DEFAULT_SHADOW_TOLERANCE)


def promote_agree_setting() -> float:
    return environment.get_float("shifu.loop.promoteAgree",
                                 DEFAULT_PROMOTE_AGREE)


def promote_min_rows_setting() -> int:
    return environment.get_int("shifu.loop.promoteMinRows",
                               DEFAULT_PROMOTE_MIN_ROWS)


def append_trees_setting() -> int:
    return environment.get_int("shifu.loop.appendTrees",
                               DEFAULT_APPEND_TREES)
