"""Self-contained HTML gain chart (core/eval/GainChart.java:35 +
GainChartTemplate.java parity: one file, no external assets, operation-point
table + curves). Rendered as inline SVG so it opens anywhere."""

from __future__ import annotations

from typing import Dict, List

from shifu_tpu.eval.metrics import PerformanceResult


def _polyline(points, width, height, color) -> str:
    if not points:
        return ""
    pts = " ".join(
        f"{x * width:.1f},{height - y * height:.1f}" for x, y in points
    )
    return (
        f'<polyline fill="none" stroke="{color}" stroke-width="2" '
        f'points="{pts}"/>'
    )


def _chart(title: str, series: Dict[str, List], x_key: str, y_key: str) -> str:
    width, height = 420, 300
    colors = ["#4878CF", "#D65F5F", "#6ACC65", "#956CB4"]
    lines, legends = [], []
    for i, (name, rows) in enumerate(series.items()):
        pts = [(r[x_key], r[y_key]) for r in rows]
        lines.append(_polyline(pts, width, height, colors[i % len(colors)]))
        legends.append(
            f'<tspan x="10" dy="14" fill="{colors[i % len(colors)]}">{name}</tspan>'
        )
    axis = (
        f'<rect x="0" y="0" width="{width}" height="{height}" fill="none" '
        f'stroke="#999"/>'
    )
    grid = "".join(
        f'<line x1="{width*k/10:.0f}" y1="0" x2="{width*k/10:.0f}" '
        f'y2="{height}" stroke="#eee"/>' for k in range(1, 10)
    )
    return f"""
<div class="chart">
  <h3>{title}</h3>
  <svg width="{width + 140}" height="{height + 20}">
    <g transform="translate(4,10)">{axis}{grid}{''.join(lines)}</g>
    <text x="{width + 14}" y="20" font-size="12">{''.join(legends)}</text>
  </svg>
</div>"""


def _table(rows: List[Dict]) -> str:
    cols = [
        ("actionRate", "Action rate"),
        ("binLowestScore", "Score"),
        ("recall", "Recall"),
        ("precision", "Precision"),
        ("fpr", "FPR"),
        ("liftUnit", "Lift"),
    ]
    head = "".join(f"<th>{label}</th>" for _, label in cols)
    body = "".join(
        "<tr>" + "".join(f"<td>{r[k]:.4f}</td>" for k, _ in cols) + "</tr>"
        for r in rows
    )
    return f"<table><thead><tr>{head}</tr></thead><tbody>{body}</tbody></table>"


def render_gain_chart(
    eval_name: str, model_name: str, perf: PerformanceResult
) -> str:
    roc = _chart(
        "ROC", {"unweighted": perf.roc, "weighted": perf.weighted_roc},
        "fpr", "recall",
    )
    gains = _chart(
        "Gains (recall vs action rate)",
        {"unweighted": perf.gains, "weighted": perf.weighted_gains},
        "actionRate", "recall",
    )
    pr = _chart(
        "Precision-Recall",
        {"unweighted": perf.pr, "weighted": perf.weighted_pr},
        "recall", "precision",
    )
    return f"""<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>{eval_name} gain chart</title>
<style>
 body {{ font-family: sans-serif; margin: 24px; color: #222; }}
 .chart {{ display: inline-block; margin-right: 24px; vertical-align: top; }}
 table {{ border-collapse: collapse; margin-top: 16px; }}
 th, td {{ border: 1px solid #ccc; padding: 4px 10px; font-size: 13px; }}
 th {{ background: #f4f4f4; }}
</style></head>
<body>
<h2>Eval “{eval_name}” — {model_name}</h2>
<p>AUC = {perf.area_under_roc:.6f} (weighted {perf.weighted_area_under_roc:.6f})</p>
{roc}{gains}{pr}
<h3>Operating points</h3>
{_table(perf.gains)}
</body></html>
"""
