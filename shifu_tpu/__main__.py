"""`python -m shifu_tpu ...` — same entry as the `shifu` CLI (cli.py),
so environments without the console script (CI lint jobs, bare
checkouts) can still run e.g. `python -m shifu_tpu check shifu_tpu/`."""

import sys

from shifu_tpu.cli import main

if __name__ == "__main__":
    sys.exit(main())
