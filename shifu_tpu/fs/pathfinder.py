"""PathFinder: single source of truth for the on-disk artifact layout.

Mirrors the contract of the reference's fs/PathFinder.java:38 — every pipeline
artifact (configs, stats outputs, normalized data, models, eval results, tmp
state) has exactly one canonical location under the model-set directory, so
steps communicate only through the filesystem and any step can be re-run.

Layout (relative to the model-set root):

    ModelConfig.json
    ColumnConfig.json
    models/                     final model specs (model0.nn, model1.gbt, ...)
    tmp/                        per-step intermediate state
      autotype/                 distinct-count sketches
      stats/                    per-column histogram shards
      norm/                     normalized dense matrix shards (.npy + meta)
      varsel/                   sensitivity outputs
      train/                    checkpoints, progress files, grid-search state
    evals/<EvalName>/           eval artifacts (scores, confusion, charts)
"""

from __future__ import annotations

import os
from typing import Optional


class PathFinder:
    MODEL_CONFIG = "ModelConfig.json"
    COLUMN_CONFIG = "ColumnConfig.json"

    def __init__(self, root: str = "."):
        self.root = os.path.abspath(root)

    # ---- config files ----
    def model_config_path(self) -> str:
        return os.path.join(self.root, self.MODEL_CONFIG)

    def column_config_path(self) -> str:
        return os.path.join(self.root, self.COLUMN_CONFIG)

    # ---- models ----
    def models_dir(self) -> str:
        return os.path.join(self.root, "models")

    def model_path(self, index: int, suffix: str) -> str:
        return os.path.join(self.models_dir(), f"model{index}.{suffix}")

    # ---- tmp per-step state ----
    def tmp_dir(self, step: Optional[str] = None) -> str:
        base = os.path.join(self.root, "tmp")
        return os.path.join(base, step) if step else base

    def autotype_path(self) -> str:
        return os.path.join(self.tmp_dir("autotype"), "count_info.json")

    def pre_train_stats_path(self) -> str:
        return os.path.join(self.tmp_dir("stats"), "pre_train_stats.json")

    def correlation_path(self) -> str:
        return os.path.join(self.tmp_dir("stats"), "correlation.csv")

    def psi_path(self) -> str:
        return os.path.join(self.tmp_dir("stats"), "psi.json")

    def normalized_data_dir(self) -> str:
        return os.path.join(self.tmp_dir("norm"), "NormalizedData")

    def normalized_validation_dir(self) -> str:
        return os.path.join(self.tmp_dir("norm"), "NormalizedValidationData")

    def cleaned_data_dir(self) -> str:
        # GBT/RF trains on "cleaned" (selected raw) columns, not z-scored ones
        # (reference TrainModelProcessor.java:1366-1372).
        return os.path.join(self.tmp_dir("norm"), "CleanedData")

    def shuffle_dir(self) -> str:
        return os.path.join(self.tmp_dir("norm"), "ShuffledData")

    def varsel_dir(self) -> str:
        return self.tmp_dir("varsel")

    def se_report_path(self) -> str:
        return os.path.join(self.varsel_dir(), "se.csv")

    def train_dir(self) -> str:
        return self.tmp_dir("train")

    def checkpoint_dir(self, trainer_id: int) -> str:
        return os.path.join(self.train_dir(), f"checkpoint_{trainer_id}")

    def tmp_model_path(self, trainer_id: int, suffix: str) -> str:
        return os.path.join(self.train_dir(), f"tmp_model{trainer_id}.{suffix}")

    def progress_path(self, trainer_id: int) -> str:
        return os.path.join(self.train_dir(), f"progress_{trainer_id}.log")

    def val_error_path(self, trainer_id: int) -> str:
        return os.path.join(self.train_dir(), f"val_error_{trainer_id}.txt")

    def feature_importance_path(self) -> str:
        return os.path.join(self.tmp_dir("posttrain"), "feature_importance.csv")

    def bin_avg_score_path(self) -> str:
        return os.path.join(self.tmp_dir("posttrain"), "bin_avg_score.json")

    # ---- evals ----
    def eval_dir(self, eval_name: str) -> str:
        return os.path.join(self.root, "evals", eval_name)

    def eval_score_path(self, eval_name: str) -> str:
        return os.path.join(self.eval_dir(eval_name), "EvalScore.csv")

    def eval_norm_path(self, eval_name: str) -> str:
        return os.path.join(self.eval_dir(eval_name), "EvalNorm.csv")

    def eval_performance_path(self, eval_name: str) -> str:
        return os.path.join(self.eval_dir(eval_name), "EvalPerformance.json")

    def eval_confusion_path(self, eval_name: str) -> str:
        return os.path.join(self.eval_dir(eval_name), "EvalConfusionMatrix.csv")

    def gain_chart_path(self, eval_name: str) -> str:
        return os.path.join(self.eval_dir(eval_name), "gainchart.html")

    # ---- export ----
    def export_dir(self) -> str:
        return os.path.join(self.root, "export")

    def pmml_path(self, index: int) -> str:
        return os.path.join(self.export_dir(), f"model{index}.pmml")

    # ---- model-set versioning (ManageModelProcessor parity) ----
    def backup_dir(self, version: str) -> str:
        return os.path.join(self.root, ".shifu", "backup", version)

    def ensure(self, path: str) -> str:
        os.makedirs(path, exist_ok=True)
        return path
