"""NN/LR trainer tests.

Mirrors the reference's cluster-free strategy (core/dtrain/DTrainTest.java:44
simulates 24 workers in-process and asserts error decreases): here the same
pure train step runs on an 8-virtual-device mesh, and sharded vs single-device
gradients must agree.
"""

import math
import os

import numpy as np
import pytest

from shifu_tpu.train.nn_trainer import NNTrainConfig, TrainResult, train_nn
from shifu_tpu.train.updaters import make_updater


def make_xor_like(n=512, d=6, seed=3):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    logits = 1.5 * x[:, 0] - 2.0 * x[:, 1] + 0.8 * x[:, 2] * x[:, 3]
    t = (logits + rng.normal(scale=0.3, size=n) > 0).astype(np.float32)
    w = np.ones(n, dtype=np.float32)
    return x, t, w


class TestUpdaters:
    def _roundtrip(self, prop, **kw):
        import jax.numpy as jnp

        init, apply = make_updater(prop, **kw)
        w = jnp.ones(5)
        g = jnp.asarray([0.5, -0.5, 0.0, 1.0, -1.0])
        state = init(5)
        w2, state2 = apply(
            state, w, g, jnp.float32(0.1), jnp.int32(1), jnp.float32(100.0)
        )
        return np.asarray(w), np.asarray(w2)

    def test_backprop_step(self):
        w, w2 = self._roundtrip("B")
        # delta = g*lr (no momentum history)
        np.testing.assert_allclose(w2 - w, [0.05, -0.05, 0.0, 0.1, -0.1], atol=1e-6)

    def test_manhattan_step(self):
        w, w2 = self._roundtrip("M")
        np.testing.assert_allclose(w2 - w, [0.1, -0.1, 0.0, 0.1, -0.1], atol=1e-6)

    def test_rprop_first_step_uses_initial_update(self):
        w, w2 = self._roundtrip("R")
        # change == 0 on first iter -> sign(g) * 0.1 initial update
        np.testing.assert_allclose(w2 - w, [0.1, -0.1, 0.0, 0.1, -0.1], atol=1e-6)

    def test_adam_first_step_is_lr_sized(self):
        w, w2 = self._roundtrip("ADAM")
        # bias-corrected first adam step = lr * sign(g)
        np.testing.assert_allclose(
            w2 - w, [0.1, -0.1, 0.0, 0.1, -0.1], atol=1e-3
        )

    def test_l2_regularization_shrinks(self):
        import jax.numpy as jnp

        init, apply = make_updater("B", reg=10.0, reg_level="L2")
        w = jnp.ones(3)
        g = jnp.zeros(3)
        w2, _ = apply(
            init(3), w, g, jnp.float32(0.1), jnp.int32(1), jnp.float32(100.0)
        )
        np.testing.assert_allclose(np.asarray(w2), [0.9, 0.9, 0.9], atol=1e-6)

    def test_l2_regularization_applies_under_optimizers(self):
        # ADVICE r1: optimizer branches silently dropped reg; with zero
        # gradient an L2-regularized step must still shrink the weights.
        import jax.numpy as jnp

        for prop in ["ADAM", "ADAGRAD", "RMSPROP", "MOMENTUM", "NESTEROV"]:
            init, apply = make_updater(prop, reg=10.0, reg_level="L2")
            w = jnp.ones(3)
            g = jnp.zeros(3)
            w2, _ = apply(
                init(3), w, g, jnp.float32(0.1), jnp.int32(1),
                jnp.float32(100.0),
            )
            assert float(np.asarray(w2)[0]) < 1.0, prop

    def test_all_rules_run(self):
        for prop in ["B", "Q", "M", "R", "ADAM", "ADAGRAD", "RMSPROP",
                     "MOMENTUM", "NESTEROV"]:
            w, w2 = self._roundtrip(prop)
            assert np.isfinite(w2).all(), prop


class TestTrainNN:
    def test_error_decreases_and_converges(self):
        x, t, w = make_xor_like()
        cfg = NNTrainConfig(
            hidden_nodes=[16], activations=["tanh"], learning_rate=0.1,
            propagation="R", num_epochs=60, valid_set_rate=0.2, seed=1,
        )
        res = train_nn(x, t, w, cfg)
        assert res.iterations == 60
        assert res.valid_error < 0.15  # vs ~0.24 baseline variance of labels

    def test_lr_zero_hidden_layers(self):
        x, t, w = make_xor_like()
        cfg = NNTrainConfig(
            hidden_nodes=[], activations=[], learning_rate=0.5,
            propagation="ADAM", loss="log", num_epochs=80, valid_set_rate=0.2,
        )
        res = train_nn(x, t, w, cfg)
        assert len(res.params) == 1  # single linear layer
        assert res.valid_error < 0.2

    def test_early_stop_window_halts(self):
        x, t, w = make_xor_like(n=256)
        cfg = NNTrainConfig(
            hidden_nodes=[8], num_epochs=500, valid_set_rate=0.3,
            early_stop_window=5, propagation="R", seed=2,
        )
        res = train_nn(x, t, w, cfg)
        assert res.iterations < 500

    def test_mesh_sharded_matches_single_device(self):
        """DP sharding must not change the math: same seed, same result."""
        from shifu_tpu.parallel.mesh import data_mesh

        x, t, w = make_xor_like(n=264)  # not divisible by 8 -> exercises padding
        cfg = NNTrainConfig(hidden_nodes=[8], num_epochs=10, propagation="B",
                            valid_set_rate=0.25, seed=5)
        res_single = train_nn(x, t, w, cfg)
        mesh = data_mesh()
        assert mesh.devices.size == 8
        res_mesh = train_nn(x, t, w, cfg, mesh=mesh)
        f1, _ = _flat(res_single)
        f2, _ = _flat(res_mesh)
        np.testing.assert_allclose(f1, f2, rtol=2e-3, atol=2e-4)

    def test_bagging_sampling_with_replacement(self):
        from shifu_tpu.train.nn_trainer import split_and_sample

        cfg = NNTrainConfig(valid_set_rate=0.2, bagging_sample_rate=1.0,
                            bagging_with_replacement=True, seed=11)
        sig, valid = split_and_sample(10_000, cfg)
        assert (sig[valid] == 0).all()
        nonval = sig[~valid]
        assert nonval.max() > 1  # poisson produces counts > 1
        assert abs(nonval.mean() - 1.0) < 0.05

    def test_minibatch_runs(self):
        x, t, w = make_xor_like(n=512)
        cfg = NNTrainConfig(hidden_nodes=[8], num_epochs=30, mini_batchs=4,
                            propagation="ADAM", learning_rate=0.05,
                            valid_set_rate=0.2)
        res = train_nn(x, t, w, cfg)
        assert res.valid_error < 0.25

    def test_continuous_init_resumes(self):
        x, t, w = make_xor_like(n=256)
        cfg = NNTrainConfig(hidden_nodes=[8], num_epochs=20, propagation="R",
                            valid_set_rate=0.2, seed=9)
        res1 = train_nn(x, t, w, cfg)
        flat1, shapes = _flat(res1)
        res2 = train_nn(x, t, w, cfg, init_flat=flat1)
        assert res2.valid_error <= res1.valid_error + 0.02


def _flat(res: TrainResult):
    from shifu_tpu.models.nn import flatten_params

    return flatten_params(res.params)


class TestModelSpec:
    def test_save_load_roundtrip(self, tmp_path):
        from shifu_tpu.models.nn import IndependentNNModel, NNModelSpec

        x, t, w = make_xor_like(n=128)
        cfg = NNTrainConfig(hidden_nodes=[8], num_epochs=15, valid_set_rate=0.2)
        res = train_nn(x, t, w, cfg)
        spec = NNModelSpec(
            layer_sizes=[x.shape[1], 8, 1],
            activations=["tanh"],
            input_columns=[f"f{i}" for i in range(x.shape[1])],
            params=res.params,
            train_error=res.train_error,
            valid_error=res.valid_error,
        )
        path = str(tmp_path / "model0.nn")
        spec.save(path)
        loaded = NNModelSpec.load(path)
        assert loaded.layer_sizes == spec.layer_sizes
        s1 = IndependentNNModel(spec).compute(x[:10])
        s2 = IndependentNNModel(loaded).compute(x[:10])
        np.testing.assert_allclose(s1, s2, atol=1e-6)
        assert ((s1 >= 0) & (s1 <= 1)).all()


class TestGridSearch:
    def test_flatten_cartesian(self):
        from shifu_tpu.train.grid_search import flatten_params

        out = flatten_params(
            {"LearningRate": [0.1, 0.2], "NumHiddenNodes": [[10], [20]],
             "Propagation": "R"}
        )
        assert len(out) == 4
        assert all(o["Propagation"] == "R" for o in out)
        assert {o["LearningRate"] for o in out} == {0.1, 0.2}

    def test_plain_params_single(self):
        from shifu_tpu.train.grid_search import flatten_params

        out = flatten_params({"LearningRate": 0.1, "NumHiddenNodes": [10]})
        assert len(out) == 1

    def test_threshold_caps(self):
        from shifu_tpu.train.grid_search import flatten_params

        out = flatten_params({"A": list(range(10)), "B": list(range(10))})
        assert len(out) == 30  # default shifu.gridsearch.threshold


class TestBaggedTraining:
    """Parallel bagging contract (TrainModelProcessor.java:768-945, 5 Guagua
    jobs in parallel): every member trains in ONE vmapped program and matches
    the serially-trained member for the same seed."""

    def test_bagged_members_match_serial(self):
        import jax.numpy as jnp

        from shifu_tpu.train.nn_trainer import (
            NNTrainConfig,
            train_nn,
            train_nn_bagged,
        )

        x, t, w = make_xor_like(n=800, d=8)
        base = NNTrainConfig(hidden_nodes=[8], activations=["tanh"],
                             propagation="R", num_epochs=15,
                             valid_set_rate=0.2, bagging_sample_rate=0.8,
                             bagging_with_replacement=True)
        M = 4
        bagged = train_nn_bagged(x, t, w, base, M)
        assert len(bagged) == M
        for i in range(M):
            cfg_i = NNTrainConfig(**{**base.__dict__, "seed": i * 1000 + 7})
            serial = train_nn(x, t, w, cfg_i)
            assert bagged[i].iterations == serial.iterations
            assert bagged[i].valid_error == pytest.approx(
                serial.valid_error, rel=1e-4, abs=1e-5)
            for lb, ls in zip(bagged[i].params, serial.params):
                np.testing.assert_allclose(lb["W"], ls["W"], rtol=2e-3,
                                           atol=2e-4)
        # members must differ (independent bagging draws)
        assert bagged[0].valid_error != bagged[1].valid_error

    def test_bagged_is_one_program_dispatch(self):
        """Op-count assertion: M members = ONE batched XLA execution, not M."""
        import jax

        from shifu_tpu.train.nn_trainer import NNTrainConfig, train_nn_bagged

        x, t, w = make_xor_like(n=400, d=6)
        base = NNTrainConfig(hidden_nodes=[4], activations=["tanh"],
                             num_epochs=5, valid_set_rate=0.2)
        calls = []
        orig = jax.vmap

        def counting_vmap(fn, **kw):
            batched = orig(fn, **kw)

            def wrapper(*a, **k):
                calls.append(1)
                return batched(*a, **k)

            return wrapper

        jax.vmap = counting_vmap
        try:
            res = train_nn_bagged(x, t, w, base, 5)
        finally:
            jax.vmap = orig
        assert len(res) == 5
        assert sum(calls) == 1  # one batched dispatch for all 5 members


class TestSVM:
    """Linear SVM = liblinear parity path (core/alg/SVMTrainer.java:38):
    L2-regularized hinge on the raw decision value, Const -> C."""

    def _separable(self, n=2000, d=6, margin=1.0, seed=5):
        rng = np.random.default_rng(seed)
        w_true = np.zeros(d)
        w_true[0], w_true[1] = 2.0, -1.5
        x = rng.normal(size=(n, d)).astype(np.float32)
        raw = x @ w_true
        keep = np.abs(raw) > margin  # carve a hard margin
        x, raw = x[keep], raw[keep]
        t = (raw > 0).astype(np.float32)
        return x, t, w_true

    def test_hinge_separates_and_recovers_direction(self):
        from shifu_tpu.train.nn_trainer import NNTrainConfig, train_nn

        x, t, w_true = self._separable()
        w = np.ones(len(t), np.float32)
        cfg = NNTrainConfig(hidden_nodes=[], activations=[], loss="hinge",
                            propagation="Q", learning_rate=0.05,
                            reg_level="L2", regularized_constant=0.01,
                            num_epochs=150, valid_set_rate=0.15, seed=3)
        res = train_nn(x, t, w, cfg)
        w_fit = res.params[0]["W"][:, 0]
        # decision direction parity with the generating hyperplane
        cos = float(w_fit @ w_true
                    / (np.linalg.norm(w_fit) * np.linalg.norm(w_true)))
        assert cos > 0.97, cos
        # and the margin actually separates
        dec = x @ w_fit + res.params[0]["b"][0]
        acc = float(((dec > 0) == (t > 0.5)).mean())
        assert acc > 0.99, acc

    def test_svm_matches_lr_decisions_on_margin_set(self):
        """Decision-quality parity: on a hard-margin set the hinge model
        classifies at least as well as LR (the reported valid_error metric
        is squared error of sigmoid outputs, which structurally favors
        log-loss — misclassification is the comparable quantity)."""
        from shifu_tpu.train.nn_trainer import NNTrainConfig, train_nn

        x, t, _ = self._separable(seed=11)
        w = np.ones(len(t), np.float32)
        common = dict(hidden_nodes=[], activations=[], propagation="Q",
                      learning_rate=0.05, num_epochs=120,
                      valid_set_rate=0.2, seed=4)
        svm = train_nn(x, t, w, NNTrainConfig(loss="hinge", reg_level="L2",
                                              regularized_constant=0.01,
                                              **common))
        lr = train_nn(x, t, w, NNTrainConfig(loss="log", **common))

        def miss(res):
            dec = x @ res.params[0]["W"][:, 0] + res.params[0]["b"][0]
            return float(((dec > 0) != (t > 0.5)).mean())

        assert miss(svm) <= miss(lr) + 1e-9
        assert miss(svm) < 0.005

    def test_svm_config_wiring_and_kernel_rejection(self):
        from shifu_tpu.config.model_config import Algorithm, new_model_config
        from shifu_tpu.train.nn_trainer import NNTrainConfig

        mc = new_model_config("m", Algorithm.SVM)
        cfg = NNTrainConfig.from_model_config(mc)
        assert cfg.loss == "hinge"
        assert cfg.hidden_nodes == []
        assert cfg.reg_level == "L2"
        # Const -> C: reg = 1/C
        mc.train.params["Const"] = 4.0
        assert NNTrainConfig.from_model_config(
            mc).regularized_constant == pytest.approx(0.25)
        mc.train.params["Kernel"] = "rbf"
        with pytest.raises(ValueError):
            NNTrainConfig.from_model_config(mc)
        # the inspector fails the config before training starts
        from shifu_tpu.config.inspector import ModelStep, probe

        res = probe(mc, ModelStep.TRAIN)
        assert not res.status
        assert any("Kernel" in m for m in res.causes)

    def test_svm_spec_io_and_pmml(self, tmp_path):
        """An SVM model flows through the NN spec format and PMML export
        (scores sigmoid(w.x+b) — monotone in the decision value, so
        ranking metrics are unchanged)."""
        import xml.etree.ElementTree as ET

        from shifu_tpu.export.pmml import nn_to_pmml
        from shifu_tpu.models.nn import NNModelSpec, forward
        from shifu_tpu.train.nn_trainer import NNTrainConfig, train_nn

        x, t, _ = self._separable(seed=21)
        w = np.ones(len(t), np.float32)
        cfg = NNTrainConfig(hidden_nodes=[], activations=[], loss="hinge",
                            propagation="Q", learning_rate=0.05,
                            reg_level="L2", regularized_constant=0.01,
                            num_epochs=40, valid_set_rate=0.2, seed=3)
        res = train_nn(x, t, w, cfg)
        d = x.shape[1]
        cols = [f"c{i}" for i in range(d)]
        spec = NNModelSpec(
            layer_sizes=[d, 1], activations=[],
            input_columns=cols,
            norm_type="ZSCALE", algorithm="SVM", loss="hinge",
            norm_specs=[{"name": n, "kind": "value", "outNames": [n],
                         "mean": 0.0, "std": 1.0, "fill": 0.0,
                         "zscore": True} for n in cols],
            norm_cutoff=4.0, params=res.params,
            train_error=res.train_error, valid_error=res.valid_error)
        p = str(tmp_path / "model0.nn")
        spec.save(p)
        spec2 = NNModelSpec.load(p)
        # header survives the roundtrip (not just the weights)
        assert spec2.algorithm == "SVM"
        assert spec2.loss == "hinge"
        assert spec2.activations == []
        assert spec2.layer_sizes == [d, 1]
        import jax.numpy as jnp

        s1 = np.asarray(forward(spec.params, jnp.asarray(x),
                                spec.activations))[:, 0]
        s2 = np.asarray(forward(spec2.params, jnp.asarray(x),
                                spec2.activations))[:, 0]
        np.testing.assert_array_equal(s1, s2)
        # the exported NeuralNetwork must actually carry the weights:
        # the single output neuron gets one Con per input column (+bias)
        NS = "{http://www.dmg.org/PMML-4_2}"
        root = ET.fromstring(nn_to_pmml(spec, model_name="svm0"))
        net = root.find(f"{NS}NeuralNetwork")
        assert (net.find(f"{NS}NeuralInputs").get("numberOfInputs")
                == str(d))
        layers = net.findall(f"{NS}NeuralLayer")
        neurons = layers[-1].findall(f"{NS}Neuron")
        cons = neurons[-1].findall(f"{NS}Con")
        assert len(cons) == d
        got_w = sorted(float(c.get("weight")) for c in cons)
        want_w = sorted(float(v) for v in res.params[0]["W"][:, 0])
        np.testing.assert_allclose(got_w, want_w, atol=1e-6)
