"""Normalize engine tests — NormType semantics parity with core/Normalizer.java
(zscore clamp, woe lookup w/ missing bin, onehot expansion, index variants) and
the end-to-end NormProcessor artifact layout."""

import math
import os

import numpy as np
import pytest

from shifu_tpu.config import ColumnConfig, ColumnType
from shifu_tpu.config.model_config import (
    MissingValueFillType,
    ModelConfig,
    NormType,
)
from shifu_tpu.data.reader import ColumnarData
from shifu_tpu.norm.dataset import load_codes, load_normalized
from shifu_tpu.norm.normalizer import (
    apply_norm_plan,
    build_norm_plan,
    woe_mean_std,
)

from tests.helpers import make_model_set


def _num_col(name="x", mean=10.0, std=2.0, bounds=None, woe=None):
    cc = ColumnConfig(column_num=1, column_name=name, column_type=ColumnType.N)
    cc.final_select = True
    cc.column_stats.mean = mean
    cc.column_stats.std_dev = std
    cc.column_stats.min = 4.0
    cc.column_stats.max = 16.0
    cc.column_binning.bin_boundary = bounds or [-math.inf, 8.0, 12.0]
    nbins = len(cc.column_binning.bin_boundary) + 1
    cc.column_binning.bin_count_woe = woe or [0.1 * i for i in range(nbins)]
    cc.column_binning.bin_weighted_woe = cc.column_binning.bin_count_woe
    cc.column_binning.bin_count_pos = [10] * nbins
    cc.column_binning.bin_count_neg = [10] * nbins
    cc.column_binning.bin_pos_rate = [0.5] * nbins
    return cc


def _cat_col(name="c", cats=("a", "b"), posrate=(0.8, 0.2, 0.5), woe=(1.0, -1.0, 0.0)):
    cc = ColumnConfig(column_num=2, column_name=name, column_type=ColumnType.C)
    cc.final_select = True
    cc.column_binning.bin_category = list(cats)
    cc.column_binning.bin_pos_rate = list(posrate)
    cc.column_binning.bin_count_woe = list(woe)
    cc.column_binning.bin_weighted_woe = list(woe)
    cc.column_binning.bin_count_pos = [8, 2, 5]
    cc.column_binning.bin_count_neg = [2, 8, 5]
    # posrate-encoded mean/std as the stats engine computes them
    cc.column_stats.mean = 0.5
    cc.column_stats.std_dev = 0.3
    return cc


def _data(num_vals, cat_vals):
    n = len(num_vals)
    raw = {
        "x": np.array([str(v) if v is not None else "" for v in num_vals], dtype=object),
        "c": np.array([v if v is not None else "?" for v in cat_vals], dtype=object),
    }
    return ColumnarData(names=["x", "c"], raw=raw, n_rows=n)


def _mc(norm_type, cutoff=4.0, fill=MissingValueFillType.POSRATE):
    mc = ModelConfig()
    mc.normalize.norm_type = norm_type
    mc.normalize.std_dev_cut_off = cutoff
    mc.normalize.category_missing_norm_type = fill
    return mc


class TestZScale:
    def test_numeric_zscore_and_clamp(self):
        cols = [_num_col()]
        data = _data([10.0, 12.0, 100.0, -100.0], [])
        data.names = ["x"]
        data.raw.pop("c")
        plan = build_norm_plan(_mc(NormType.ZSCALE), cols)
        out = apply_norm_plan(plan, data)
        # (v-10)/2 clamped at ±4 std
        assert out[:, 0] == pytest.approx([0.0, 1.0, 4.0, -4.0], abs=1e-5)

    def test_numeric_missing_goes_to_mean(self):
        cols = [_num_col()]
        data = _data([None, "bad"], [])
        data.names = ["x"]
        data.raw.pop("c")
        plan = build_norm_plan(_mc(NormType.ZSCALE), cols)
        out = apply_norm_plan(plan, data)
        assert out[:, 0] == pytest.approx([0.0, 0.0], abs=1e-6)

    def test_categorical_posrate_zscored(self):
        cols = [_cat_col()]
        data = _data([], ["a", "b", "zzz", None])
        data.names = ["c"]
        data.raw.pop("x")
        plan = build_norm_plan(_mc(NormType.ZSCALE), cols)
        out = apply_norm_plan(plan, data)
        # posrate a=0.8, b=0.2; unseen/missing -> missing-bin posrate 0.5
        exp = [(0.8 - 0.5) / 0.3, (0.2 - 0.5) / 0.3, 0.0, 0.0]
        assert out[:, 0] == pytest.approx(exp, abs=1e-5)

    def test_old_zscale_categorical_raw_posrate(self):
        cols = [_cat_col()]
        data = _data([], ["a", "b", None])
        data.names = ["c"]
        data.raw.pop("x")
        plan = build_norm_plan(_mc(NormType.OLD_ZSCALE), cols)
        out = apply_norm_plan(plan, data)
        assert out[:, 0] == pytest.approx([0.8, 0.2, 0.5], abs=1e-6)

    def test_zero_std_outputs_zero(self):
        cols = [_num_col(std=0.0)]
        data = _data([10.0, 99.0], [])
        data.names = ["x"]
        data.raw.pop("c")
        out = apply_norm_plan(build_norm_plan(_mc(NormType.ZSCALE), cols), data)
        assert out[:, 0] == pytest.approx([0.0, 0.0])


class TestWoe:
    def test_woe_lookup_and_missing_bin(self):
        cols = [_num_col(woe=[0.5, -0.5, 0.2, 0.9]), _cat_col()]
        data = _data([5.0, 9.0, 13.0, None], ["a", "b", "zzz", None])
        plan = build_norm_plan(_mc(NormType.WOE), cols)
        out = apply_norm_plan(plan, data)
        # numeric: bins (-inf,8),(8,12),(12,inf); missing -> slot 3
        assert out[:, 0] == pytest.approx([0.5, -0.5, 0.2, 0.9], abs=1e-6)
        # categorical: woe a=1, b=-1; unseen+missing -> missing bin 0.0
        assert out[:, 1] == pytest.approx([1.0, -1.0, 0.0, 0.0], abs=1e-6)

    def test_woe_zscale_matches_reference_formula(self):
        cc = _cat_col()
        data = _data([], ["a", "b", None])
        data.names = ["c"]
        data.raw.pop("x")
        plan = build_norm_plan(_mc(NormType.WOE_ZSCALE), [cc])
        out = apply_norm_plan(plan, data)
        m, s = woe_mean_std(cc, False)
        exp = [(1.0 - m) / s, (-1.0 - m) / s, (0.0 - m) / s]
        assert out[:, 0] == pytest.approx(exp, abs=1e-5)

    def test_woe_mean_std_formula(self):
        cc = _cat_col()
        # counts: (10, 10, 10), woe (1, -1, 0) -> mean 0
        m, s = woe_mean_std(cc, False)
        assert m == pytest.approx(0.0)
        # squaredSum=20, n=30 -> sqrt(20/29)
        assert s == pytest.approx(math.sqrt(20.0 / 29.0))

    def test_hybrid(self):
        cols = [_num_col(), _cat_col()]
        data = _data([12.0, 8.0], ["a", "b"])
        out = apply_norm_plan(build_norm_plan(_mc(NormType.HYBRID), cols), data)
        assert out[:, 0] == pytest.approx([1.0, -1.0], abs=1e-5)  # zscore
        assert out[:, 1] == pytest.approx([1.0, -1.0], abs=1e-6)  # woe


class TestOneHotIndex:
    def test_onehot_expands_all_slots(self):
        cols = [_num_col(), _cat_col()]
        data = _data([5.0, None], ["b", "zzz"])
        plan = build_norm_plan(_mc(NormType.ONEHOT), cols)
        out = apply_norm_plan(plan, data)
        # numeric 4 slots + cat 3 slots
        assert out.shape == (2, 7)
        assert out[0, :4].tolist() == [1, 0, 0, 0]
        assert out[1, :4].tolist() == [0, 0, 0, 1]  # missing -> last
        assert out[0, 4:].tolist() == [0, 1, 0]
        assert out[1, 4:].tolist() == [0, 0, 1]  # unseen -> last
        assert plan.out_names[0] == "x_0"

    def test_zscale_onehot(self):
        cols = [_num_col(), _cat_col()]
        data = _data([12.0], ["a"])
        plan = build_norm_plan(_mc(NormType.ZSCALE_ONEHOT), cols)
        out = apply_norm_plan(plan, data)
        assert out.shape == (1, 4)  # 1 zscore + 3 onehot
        assert out[0, 0] == pytest.approx(1.0, abs=1e-5)
        assert out[0, 1:].tolist() == [1, 0, 0]

    def test_index_variants(self):
        cols = [_num_col(), _cat_col()]
        data = _data([12.0], ["b"])
        plan = build_norm_plan(_mc(NormType.ZSCALE_INDEX), cols)
        out = apply_norm_plan(plan, data)
        assert out[0, 0] == pytest.approx(1.0, abs=1e-5)
        assert out[0, 1] == pytest.approx(1.0)  # index of "b"

        plan = build_norm_plan(_mc(NormType.WOE_INDEX), cols)
        out = apply_norm_plan(plan, data)
        assert out[0, 0] == pytest.approx(0.2, abs=1e-6)  # numeric woe bin 2
        assert out[0, 1] == pytest.approx(1.0)

    def test_discrete_zscale_snaps_to_boundary(self):
        cols = [_num_col()]
        data = _data([5.0, 9.0, 13.0, None], [])
        data.names = ["x"]
        data.raw.pop("c")
        plan = build_norm_plan(_mc(NormType.DISCRETE_ZSCALE), cols)
        out = apply_norm_plan(plan, data)
        # bin0 -> min 4.0, bin1 -> 8.0, bin2 -> 12.0, missing -> mean 10
        exp = [(4 - 10) / 2, (8 - 10) / 2, (12 - 10) / 2, 0.0]
        assert out[:, 0] == pytest.approx(exp, abs=1e-5)

    def test_asis(self):
        cols = [_num_col(), _cat_col()]
        data = _data([7.5, "bad"], ["a", "b"])
        out = apply_norm_plan(build_norm_plan(_mc(NormType.ASIS_PR), cols), data)
        assert out[0, 0] == pytest.approx(7.5)
        assert out[1, 0] == pytest.approx(10.0)  # invalid -> mean
        assert out[:, 1] == pytest.approx([0.8, 0.2])


class TestNormProcessor:
    def test_end_to_end_artifacts(self, tmp_path):
        root = str(tmp_path / "ms")
        make_model_set(root, n_rows=300)
        cwd = os.getcwd()
        os.chdir(root)
        try:
            from shifu_tpu.processor.init import InitProcessor
            from shifu_tpu.processor.norm import NormProcessor
            from shifu_tpu.processor.stats import StatsProcessor

            assert InitProcessor(root).run() == 0
            assert StatsProcessor(root).run() == 0
            assert NormProcessor(root, shuffle=True).run() == 0
        finally:
            os.chdir(cwd)

        from shifu_tpu.fs.pathfinder import PathFinder

        paths = PathFinder(root)
        meta, feats, tags, weights = load_normalized(paths.normalized_data_dir())
        assert meta.n_rows == feats.shape[0] > 0
        assert feats.shape[1] == len(meta.columns) == 12  # 10 num + 2 cat
        assert feats.dtype == np.float32
        assert set(np.unique(tags)).issubset({0, 1})
        assert np.isfinite(feats).all()
        # z-scaled numerics should be roughly centered
        assert abs(float(feats[:, 0].mean())) < 1.0

        cmeta, codes, ctags, cweights = load_codes(paths.cleaned_data_dir())
        assert codes.shape == (meta.n_rows, 12)
        assert codes.dtype == np.int16
        slots = cmeta.extra["slots"]
        assert len(slots) == 12
        assert (codes < np.asarray(slots)[None, :]).all()
        np.testing.assert_array_equal(np.asarray(ctags), np.asarray(tags))
