"""Run ledger: one machine-readable manifest per lifecycle step.

`BasicProcessor.run()` writes `<modelset>/.shifu/runs/<step>-<seq>.json`
after every step — success OR failure — carrying the step name, argv, config
hashes, the full metrics-registry snapshot (row counts, stage timers,
per-epoch training series, compile/transfer counters), the Chrome-trace path,
exit status, and JAX backend/device info. The reference's equivalent is
scattered Hadoop job counters and log lines that die with the console
(SURVEY §5); here "what did step X actually do" is a file you can diff.

`shifu runs [--last N] [--step S] [--json]` (cli.py) lists/inspects them.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
from typing import List, Optional

from shifu_tpu.fs.listing import sorted_glob

SCHEMA = "shifu.run/1"
RUNS_SUBDIR = os.path.join(".shifu", "runs")

_MANIFEST_RE = re.compile(r"^(?P<step>.+)-(?P<seq>\d+)\.json$")


def runs_dir(root: str) -> str:
    return os.path.join(os.path.abspath(root), RUNS_SUBDIR)


def _config_hash(path: str) -> Optional[str]:
    try:
        with open(path, "rb") as fh:
            return hashlib.sha256(fh.read()).hexdigest()[:16]
    except OSError:
        return None


def jax_runtime_info() -> dict:
    """Backend/device identity for the manifest. Cheap if jax is already
    initialized (every step that did device work initialized it); never
    raises — a step that failed before importing jax still gets a manifest."""
    try:
        import jax

        devices = jax.devices()
        return {
            "version": jax.__version__,
            "backend": jax.default_backend(),
            "deviceCount": len(devices),
            "deviceKind": getattr(devices[0], "device_kind", "")
            if devices else "",
        }
    except Exception:  # pragma: no cover - jax import/init failure
        return {}


class RunLedger:
    """Sequence-numbered manifest writer for one model-set root."""

    def __init__(self, root: str) -> None:
        self.root = os.path.abspath(root)
        self.dir = runs_dir(root)

    def next_seq(self, step: str) -> int:
        """1 + highest existing sequence number for this step."""
        highest = 0
        for path in sorted_glob(os.path.join(self.dir, f"{step}-*.json")):
            m = _MANIFEST_RE.match(os.path.basename(path))
            if m and m.group("step") == step:
                highest = max(highest, int(m.group("seq")))
        return highest + 1

    def manifest_path(self, step: str, seq: int) -> str:
        return os.path.join(self.dir, f"{step}-{seq}.json")

    def trace_path(self, step: str, seq: int) -> str:
        return os.path.join(self.dir, f"{step}-{seq}.trace.json")

    def write(
        self,
        step: str,
        seq: int,
        *,
        status: str,
        exit_status: int,
        started_at: float,
        elapsed_seconds: float,
        argv: List[str],
        registry,
        tracer=None,
        profile: Optional[dict] = None,
        error: Optional[str] = None,
        extra: Optional[dict] = None,
    ) -> str:
        """Write the manifest (and the step's Chrome trace beside it)."""
        import datetime

        os.makedirs(self.dir, exist_ok=True)
        trace_rel = None
        if tracer is not None:
            saved = tracer.save(self.trace_path(step, seq))
            if saved:
                trace_rel = os.path.relpath(saved, self.root)
        manifest = {
            "schema": SCHEMA,
            "step": step,
            "seq": seq,
            "status": status,
            "exitStatus": exit_status,
            "error": error,
            "argv": list(argv),
            "startedAt": datetime.datetime.fromtimestamp(
                started_at, datetime.timezone.utc
            ).isoformat(),
            "startedAtUnix": started_at,
            "elapsedSeconds": round(elapsed_seconds, 4),
            "configHashes": {
                "ModelConfig.json": _config_hash(
                    os.path.join(self.root, "ModelConfig.json")),
                "ColumnConfig.json": _config_hash(
                    os.path.join(self.root, "ColumnConfig.json")),
            },
            "jax": jax_runtime_info(),
            "metrics": registry.snapshot(),
            "profile": profile,
            "tracePath": trace_rel,
        }
        if extra:
            manifest.update(extra)
        path = self.manifest_path(step, seq)
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(manifest, fh, indent=2, sort_keys=True)
        os.replace(tmp, path)
        return path


def list_runs(root: str, last: Optional[int] = None,
              step: Optional[str] = None) -> List[dict]:
    """Manifests under <root>/.shifu/runs, newest first; each dict gains a
    `path` key. Unparseable files are skipped."""
    out: List[dict] = []
    for path in sorted_glob(os.path.join(runs_dir(root), "*.json")):
        name = os.path.basename(path)
        if name.endswith(".trace.json") or not _MANIFEST_RE.match(name):
            continue
        try:
            with open(path) as fh:
                m = json.load(fh)
        except (OSError, ValueError):
            continue
        if step and m.get("step") != step:
            continue
        m["path"] = path
        out.append(m)
    out.sort(key=lambda m: (m.get("startedAtUnix", 0.0), m.get("seq", 0)),
             reverse=True)
    if last is not None:
        out = out[:last]
    return out


def format_runs(manifests: List[dict], show_traces: bool = False) -> str:
    """Human table for `shifu runs`; `show_traces` adds a TRACES column
    (captured request-trace count + slowest ms from the manifest's
    trace summary) so serve-run rows point at their `shifu trace`
    evidence."""
    if not manifests:
        return "(no runs recorded under .shifu/runs)"
    traces_col = f"{'TRACES':<14} " if show_traces else ""
    header = f"{'STEP':<10} {'SEQ':>4} {'STATUS':<7} {'ELAPSED':>9} " \
             f"{'STARTED (UTC)':<20} {traces_col}KEY METRICS"
    lines = [header]
    for m in manifests:
        metrics = m.get("metrics", {})
        hints = []
        counters = metrics.get("counters", {})
        for key in sorted(counters):
            base = key.split("{", 1)[0]
            if base.endswith((".rows", ".rows_valid", ".records")):
                hints.append(f"{base}={int(counters[key])}")
        gauges = metrics.get("gauges", {})
        for key in sorted(gauges):
            base = key.split("{", 1)[0]
            if base in ("eval.auc", "train.valid_error"):
                hints.append(f"{base}={gauges[key]:.4f}")
        n_series = len(metrics.get("series", {}))
        if n_series:
            hints.append(f"series={n_series}")
        started = (m.get("startedAt") or "")[:19]
        tr_cell = ""
        if show_traces:
            tr = m.get("traces") or {}
            if tr.get("count"):
                slowest = tr.get("slowestMs")
                tr_cell = (f"{tr['count']}@{slowest:.1f}ms"
                           if slowest is not None else str(tr["count"]))
            else:
                tr_cell = "-"
            tr_cell = f"{tr_cell:<14} "
        lines.append(
            f"{m.get('step', '?'):<10} {m.get('seq', 0):>4} "
            f"{m.get('status', '?'):<7} "
            f"{m.get('elapsedSeconds', 0.0):>8.2f}s "
            f"{started:<20} {tr_cell}{', '.join(hints[:4])}"
        )
    return "\n".join(lines)
