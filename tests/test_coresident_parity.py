"""Numerical contracts of the co-resident pipeline trainer
(shifu_tpu/coresident/trainer.py + pipeline.py):

* `stages=1, microbatches=1` is BIT-identical to the existing streamed
  trainers (NN and WDL) — the co-resident path is the same math with a
  grant wrapped around it, never a different trainer;
* microbatch gradient accumulation order is pinned sequential, so any
  M is bit-identical to M=1 (GPipe microbatching is a memory shape,
  not a numerics choice);
* stage-boundary activations are always f32; bf16 appears only inside
  stage matmuls when `mixed_precision` is armed (the PR-11 policy).

Runs under the conftest-forced 8-virtual-device CPU mesh, so a K=2
pipeline really pins its stages to distinct devices.
"""

import numpy as np
import pytest

from shifu_tpu.coresident import CoresidentConfig, train_nn_coresident
from shifu_tpu.coresident.tenant import LocalGrant
from shifu_tpu.norm.dataset import write_codes, write_normalized
from shifu_tpu.train.nn_trainer import NNTrainConfig


def _write_shards(tmp_path, n=600, d=6, n_shards=2, seed=3):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    t = ((1.2 * x[:, 0] - x[:, 1]) > 0).astype(np.int8)
    w = np.ones(n, dtype=np.float32)
    out = str(tmp_path / "NormalizedData")
    write_normalized(out, x, t, w, [f"c{i}" for i in range(d)],
                     n_shards=n_shards)
    return out


def _cfg(**kw):
    base = dict(hidden_nodes=[6, 5], activations=["tanh"],
                propagation="R", num_epochs=8, valid_set_rate=0.2,
                seed=11)
    base.update(kw)
    return NNTrainConfig(**base)


def _flat(params):
    from shifu_tpu.models.nn import flatten_params

    flat, _shapes = flatten_params(params)
    return np.asarray(flat)


def _run(data_dir, cfg, stages, microbatches, family_dir):
    ccfg = CoresidentConfig(stages=stages, microbatches=microbatches,
                            family_dir=str(family_dir))
    return train_nn_coresident(data_dir, cfg, ccfg, grant=LocalGrant())


def test_nn_stages1_bit_identical_to_streamed(tmp_path):
    from shifu_tpu.train.streaming import train_nn_streamed

    data_dir = _write_shards(tmp_path)
    cfg = _cfg()
    streamed = train_nn_streamed(data_dir, cfg)
    co = _run(data_dir, cfg, 1, 1, tmp_path / "fam")
    assert co.iterations == streamed.iterations
    assert co.train_error == streamed.train_error
    assert co.valid_error == streamed.valid_error
    np.testing.assert_array_equal(_flat(co.params),
                                  _flat(streamed.params))


def test_nn_microbatch_accumulation_order_is_pinned(tmp_path):
    """M only reshapes the pipeline fill; the sequential fold makes the
    result bit-identical to whole-shard dispatch."""
    data_dir = _write_shards(tmp_path)
    cfg = _cfg()
    base = _run(data_dir, cfg, 1, 1, tmp_path / "a")
    m3 = _run(data_dir, cfg, 1, 3, tmp_path / "b")
    np.testing.assert_array_equal(_flat(base.params), _flat(m3.params))


def test_nn_two_stage_pipeline_bit_identical(tmp_path):
    data_dir = _write_shards(tmp_path)
    cfg = _cfg()
    base = _run(data_dir, cfg, 1, 1, tmp_path / "a")
    piped = _run(data_dir, cfg, 2, 2, tmp_path / "b")
    np.testing.assert_array_equal(_flat(base.params),
                                  _flat(piped.params))


def _wdl_fixture(tmp_path, n=600, nd=4, nc=2, vocab=6, seed=5):
    rng = np.random.default_rng(seed)
    dense = rng.normal(size=(n, nd)).astype(np.float32)
    codes = rng.integers(0, vocab, size=(n, nc)).astype(np.int16)
    t = ((dense[:, 0] + (codes[:, 0] >= 3)) > 0.5).astype(np.int8)
    w = np.ones(n, np.float32)
    norm_dir = str(tmp_path / "NormalizedData")
    codes_dir = str(tmp_path / "CleanedData")
    cols = [f"d{i}" for i in range(nd)] + [f"c{i}" for i in range(nc)]
    write_normalized(norm_dir, np.concatenate(
        [dense, codes.astype(np.float32)], 1), t, w, cols, n_shards=2)
    write_codes(codes_dir, np.concatenate(
        [np.zeros((n, nd), np.int16), codes], 1), t, w, cols,
        [1] * nd + [vocab] * nc, n_shards=2)
    return norm_dir, codes_dir, list(range(nd)), [nd, nd + 1], \
        [vocab] * nc


def test_wdl_stages1_bit_identical_to_streamed(tmp_path):
    from shifu_tpu.coresident import train_wdl_coresident
    from shifu_tpu.models.wdl import flatten_wdl
    from shifu_tpu.train.streaming_wdl import train_wdl_streamed
    from shifu_tpu.train.wdl_trainer import WDLTrainConfig

    norm_dir, codes_dir, num_idx, cat_idx, vocabs = \
        _wdl_fixture(tmp_path)
    cfg = WDLTrainConfig(hidden=[8], activations=["relu"], embed_dim=4,
                         num_epochs=6, valid_set_rate=0.2, seed=3)
    streamed = train_wdl_streamed(norm_dir, codes_dir, num_idx,
                                  cat_idx, vocabs, cfg)
    ccfg = CoresidentConfig(stages=1, microbatches=1,
                            family_dir=str(tmp_path / "fam"))
    co = train_wdl_coresident(norm_dir, codes_dir, num_idx, cat_idx,
                              vocabs, cfg, ccfg, grant=LocalGrant())
    assert co.iterations == streamed.iterations
    np.testing.assert_array_equal(flatten_wdl(co.params),
                                  flatten_wdl(streamed.params))


def test_wdl_pipeline_tracks_single_stage(tmp_path):
    """WDL K=3 reproduces K=1 bit-exactly (pure partitioning); K=2/M=2
    additionally re-times the wide-logit add — pinned to float noise,
    never drift."""
    from shifu_tpu.coresident import train_wdl_coresident
    from shifu_tpu.models.wdl import flatten_wdl
    from shifu_tpu.train.wdl_trainer import WDLTrainConfig

    norm_dir, codes_dir, num_idx, cat_idx, vocabs = \
        _wdl_fixture(tmp_path)
    cfg = WDLTrainConfig(hidden=[8, 5], activations=["relu"],
                         embed_dim=4, num_epochs=6, valid_set_rate=0.2,
                         seed=3)

    def run(k, m, fam):
        ccfg = CoresidentConfig(stages=k, microbatches=m,
                                family_dir=str(tmp_path / fam))
        return train_wdl_coresident(norm_dir, codes_dir, num_idx,
                                    cat_idx, vocabs, cfg, ccfg,
                                    grant=LocalGrant())

    base = run(1, 1, "a")
    k3 = run(3, 1, "b")
    np.testing.assert_array_equal(flatten_wdl(base.params),
                                  flatten_wdl(k3.params))
    k2m2 = run(2, 2, "c")
    np.testing.assert_allclose(flatten_wdl(base.params),
                               flatten_wdl(k2m2.params), atol=1e-6)


def test_stage_boundary_dtype_is_f32_bf16_only_inside(tmp_path):
    """PR-11 policy at the pipeline seam: the activation handed
    stage-to-stage is f32 whether or not mixed precision is armed;
    arming it puts bf16 INSIDE the stage matmuls only."""
    import jax
    import jax.numpy as jnp

    from shifu_tpu.coresident.pipeline import make_nn_stage_programs
    from shifu_tpu.coresident.plan import nn_plan
    from shifu_tpu.models.nn import flatten_params, init_params

    sizes = [6, 8, 1]
    flat, shapes = flatten_params(init_params(sizes, seed=0))
    plan = nn_plan(shapes, 2)
    h = jnp.zeros((4, 6), jnp.float32)
    for mixed in (False, True):
        cfg = _cfg(mixed_precision=mixed)
        progs = make_nn_stage_programs(cfg, plan)
        flat0 = jnp.asarray(np.asarray(flat))[plan.stages[0].lo:
                                              plan.stages[0].hi]
        out = progs["fwd"][0](flat0, h)
        assert out.dtype == jnp.float32  # the boundary contract
        jaxpr = str(jax.make_jaxpr(progs["fwd"][0])(flat0, h))
        assert ("bf16" in jaxpr) == mixed
