"""Hybrid (H) column semantics — numeric bins + category bins + missing
(Normalizer.hybridNormalize:683, bin layout Normalizer.java:622-638)."""

import os

import numpy as np


def test_hybrid_bin_index_layout():
    from shifu_tpu.stats.binning import hybrid_bin_index

    bounds = [-np.inf, 0.0, 10.0]  # 3 numeric bins
    cats = ["NA_SPECIAL", "REFUSED"]
    raw = np.array(["-5", "3", "12", "NA_SPECIAL", "REFUSED", "junk", "7"],
                   dtype=object)
    miss = np.zeros(7, bool)
    idx = hybrid_bin_index(raw, bounds, cats, miss)
    # numeric: -5 -> bin0, 3 -> bin1, 12 -> bin2, 7 -> bin1
    # cats: NA_SPECIAL -> 3+0, REFUSED -> 3+1; junk -> missing slot 5
    assert idx.tolist() == [0, 1, 2, 3, 4, 5, 1]
    miss[0] = True  # configured-missing token overrides everything
    assert hybrid_bin_index(raw, bounds, cats, miss)[0] == 5


def _hybrid_model_set(tmp_path, n=500, seed=9):
    """Dataset whose `mixed` column is numeric with special string codes."""
    from shifu_tpu.config.model_config import Algorithm, new_model_config

    rng = np.random.default_rng(seed)
    y = (rng.random(n) < 0.45).astype(int)
    x = rng.normal(loc=y * 2.0, scale=1.0, size=n)
    special = rng.random(n) < 0.25
    # special codes carry their own signal (strongly negative class)
    mixed = np.where(special, np.where(y == 1, "SP_POS", "SP_NEG"),
                     np.char.mod("%.4f", x))
    other = rng.normal(loc=y, scale=1.2, size=n)

    root = str(tmp_path / "ms")
    data_dir = os.path.join(root, "data")
    os.makedirs(data_dir, exist_ok=True)
    with open(os.path.join(data_dir, "header.txt"), "w") as fh:
        fh.write("target|mixed|other\n")
    with open(os.path.join(data_dir, "data.txt"), "w") as fh:
        for i in range(n):
            fh.write(f"{'M' if y[i] else 'B'}|{mixed[i]}|{other[i]:.5f}\n")

    mc = new_model_config("HybridTest", Algorithm.NN)
    mc.data_set.data_path = os.path.join(data_dir, "data.txt")
    mc.data_set.header_path = os.path.join(data_dir, "header.txt")
    mc.data_set.data_delimiter = "|"
    mc.data_set.header_delimiter = "|"
    mc.data_set.target_column_name = "target"
    mc.data_set.pos_tags = ["M"]
    mc.data_set.neg_tags = ["B"]
    mc.save(os.path.join(root, "ModelConfig.json"))
    return root


def test_hybrid_stats_and_norm_end_to_end(tmp_path):
    from shifu_tpu.config.column_config import (
        ColumnType,
        load_column_config_list,
    )
    from shifu_tpu.processor.init import InitProcessor
    from shifu_tpu.processor.norm import NormProcessor
    from shifu_tpu.processor.stats import StatsProcessor

    root = _hybrid_model_set(tmp_path)
    assert InitProcessor(root).run() == 0

    # mark the mixed column H (users opt in, like the reference)
    cc_path = os.path.join(root, "ColumnConfig.json")
    ccs = load_column_config_list(cc_path)
    for c in ccs:
        if c.column_name == "mixed":
            c.column_type = ColumnType.H
    from shifu_tpu.config.column_config import save_column_config_list

    save_column_config_list(cc_path, ccs)

    assert StatsProcessor(root).run() == 0
    ccs = load_column_config_list(cc_path)
    mixed = next(c for c in ccs if c.column_name == "mixed")
    assert mixed.column_type == ColumnType.H
    bn = mixed.column_binning
    assert bn.bin_boundary, "hybrid column lost its numeric bins"
    assert set(bn.bin_category or []) == {"SP_POS", "SP_NEG"}
    total_bins = len(bn.bin_boundary) + len(bn.bin_category) + 1
    assert len(bn.bin_count_pos) == total_bins
    # every valid row lands in some bin
    assert sum(bn.bin_count_pos) + sum(bn.bin_count_neg) > 0
    # special-code bins carry their class signal
    nb = len(bn.bin_boundary)
    sp_pos_idx = nb + (bn.bin_category or []).index("SP_POS")
    sp_neg_idx = nb + (bn.bin_category or []).index("SP_NEG")
    assert bn.bin_pos_rate[sp_pos_idx] > 0.9
    assert bn.bin_pos_rate[sp_neg_idx] < 0.1
    # numeric moments computed over parseable values only
    assert mixed.column_stats.mean is not None
    assert abs(mixed.column_stats.mean) < 5

    assert NormProcessor(root).run() == 0
    from shifu_tpu.norm.dataset import load_codes

    meta, codes, tags, _ = load_codes(
        os.path.join(root, "tmp", "norm", "CleanedData"))
    j = meta.columns.index("mixed")
    assert int(meta.extra["slots"][j]) == total_bins
    assert codes[:, j].max() < total_bins


def test_hybrid_woe_norm_table_covers_all_bins(tmp_path):
    from shifu_tpu.config.column_config import (
        ColumnType,
        load_column_config_list,
        save_column_config_list,
    )
    from shifu_tpu.config.model_config import ModelConfig, NormType
    from shifu_tpu.processor.init import InitProcessor
    from shifu_tpu.processor.norm import NormProcessor
    from shifu_tpu.processor.stats import StatsProcessor

    root = _hybrid_model_set(tmp_path)
    assert InitProcessor(root).run() == 0
    cc_path = os.path.join(root, "ColumnConfig.json")
    ccs = load_column_config_list(cc_path)
    for c in ccs:
        if c.column_name == "mixed":
            c.column_type = ColumnType.H
    save_column_config_list(cc_path, ccs)
    assert StatsProcessor(root).run() == 0

    mc = ModelConfig.load(os.path.join(root, "ModelConfig.json"))
    mc.normalize.norm_type = NormType.HYBRID
    mc.save(os.path.join(root, "ModelConfig.json"))
    assert NormProcessor(root).run() == 0

    from shifu_tpu.norm.normalizer import build_norm_plan, spec_to_json

    ccs = load_column_config_list(cc_path)
    plan = build_norm_plan(mc, ccs)
    spec = next(s for s in plan.specs if s.cc.column_name == "mixed")
    # hybridNormalize: H columns take the woe table (Normalizer.java:683)
    assert spec.kind == "table"
    mixed = next(c for c in ccs if c.column_name == "mixed")
    total_bins = (len(mixed.column_binning.bin_boundary)
                  + len(mixed.column_binning.bin_category) + 1)
    assert len(spec.table) == total_bins
    d = spec_to_json(spec)
    assert d.get("hybrid") and d.get("boundaries") and d.get("categories")
