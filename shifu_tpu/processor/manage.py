"""`shifu save/switch/show` — model-set versioning.

Parity: core/processor/ManageModelProcessor.java:30 — git-branch-like local
bookkeeping of (ModelConfig.json, ColumnConfig.json, models/) snapshots under
.shifu/backup/<version>.
"""

from __future__ import annotations

import datetime
import os
import shutil

from shifu_tpu.processor.basic import BasicProcessor
from shifu_tpu.utils.errors import ErrorCode, ShifuError
from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)


class ManageProcessor(BasicProcessor):
    step = "manage"

    def __init__(self, command: str, version: str = None, root: str = "."):
        super().__init__(root)
        self.command = command
        self.version = version

    def run_step(self) -> None:
        if self.command == "show":
            self._show()
            return
        self.setup(need_columns=False)
        if self.command == "save":
            self._save()
        elif self.command == "switch":
            self._switch()

    def _versions_root(self) -> str:
        return os.path.join(self.root, ".shifu", "backup")

    def _save(self) -> None:
        version = self.version or datetime.datetime.now().strftime(
            "%Y%m%d-%H%M%S"
        )
        dst = self.paths.backup_dir(version)
        if os.path.isdir(dst):
            raise ShifuError(ErrorCode.INVALID_MODEL_CONFIG,
                             f"version {version} already exists")
        os.makedirs(dst, exist_ok=True)
        for name in ("ModelConfig.json", "ColumnConfig.json"):
            src = os.path.join(self.root, name)
            if os.path.isfile(src):
                shutil.copy(src, os.path.join(dst, name))
        models = self.paths.models_dir()
        if os.path.isdir(models):
            shutil.copytree(models, os.path.join(dst, "models"))
        log.info("model set saved as version %s", version)

    def _switch(self) -> None:
        src = self.paths.backup_dir(self.version)
        if not os.path.isdir(src):
            raise ShifuError(ErrorCode.INVALID_MODEL_CONFIG,
                             f"version {self.version} not found")
        for name in ("ModelConfig.json", "ColumnConfig.json"):
            p = os.path.join(src, name)
            if os.path.isfile(p):
                shutil.copy(p, os.path.join(self.root, name))
        models_bak = os.path.join(src, "models")
        if os.path.isdir(models_bak):
            shutil.rmtree(self.paths.models_dir(), ignore_errors=True)
            shutil.copytree(models_bak, self.paths.models_dir())
        log.info("switched to version %s", self.version)

    def _show(self) -> None:
        root = self._versions_root()
        if not os.path.isdir(root):
            log.info("no saved versions.")
            return
        for v in sorted(os.listdir(root)):
            log.info("version: %s", v)
