"""Filesystem layer: canonical artifact path layout + IO helpers."""

from shifu_tpu.fs.pathfinder import PathFinder  # noqa: F401
