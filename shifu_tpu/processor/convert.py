"""`shifu convert` — model spec format conversion.

Parity: util/IndependentTreeModelUtils.java:138 (`shifu convert` zip<->binary
spec). Our binary specs convert to/from a readable JSON form:
    -tozip  binary (.nn/.lr/.gbt/.rf/.wdl) -> .json (inspectable/portable)
    -tobin  .json -> binary spec
"""

from __future__ import annotations

import json
import os

import numpy as np

from shifu_tpu.processor.basic import BasicProcessor
from shifu_tpu.utils.errors import ErrorCode, ShifuError
from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)


class ConvertProcessor(BasicProcessor):
    step = "convert"

    def __init__(self, root: str = ".", to_json: bool = True,
                 input_path: str = None, output_path: str = None,
                 mode: str = None):
        super().__init__(root)
        self.to_json = to_json
        self.input_path = input_path
        self.output_path = output_path
        self.mode = mode  # toref | toeg | tozipref | fromref | None

    @classmethod
    def from_args(cls, args) -> "ConvertProcessor":
        mode = None
        for flag in ("toref", "toeg", "tozipref", "fromref"):
            if getattr(args, flag, False):
                mode = flag
                break
        return cls(to_json=not args.tobin, input_path=args.input,
                   output_path=args.output, mode=mode)

    def run_step(self) -> None:
        if not self.input_path:
            raise ShifuError(ErrorCode.INVALID_MODEL_CONFIG,
                             "convert needs an input model path")
        if self.mode == "toref":
            self._to_reference(fmt="binary")
        elif self.mode == "toeg":
            self._to_reference(fmt="eg")
        elif self.mode == "tozipref":
            self._to_reference(fmt="zip")
        elif self.mode == "fromref":
            self._from_reference()
        elif self.to_json:
            self._to_json()
        else:
            self._to_binary()

    def _to_reference(self, fmt: str) -> None:
        """Export a native spec into the reference's model formats
        (BinaryNNSerializer.java:46 / BinaryDTSerializer.java:62 /
        IndependentTreeModelUtils.java:40 zip)."""
        from shifu_tpu.compat.adapters import (
            nn_spec_to_eg_bytes,
            nn_spec_to_egb_bytes,
            tree_spec_to_ref_bytes,
            tree_spec_to_zip_bytes,
        )
        from shifu_tpu.eval.scorer import load_model
        from shifu_tpu.models.nn import NNModelSpec
        from shifu_tpu.models.tree import TreeModelSpec

        spec = load_model(self.input_path)
        suffix = os.path.splitext(self.input_path)[1]
        if isinstance(spec, NNModelSpec):
            if fmt == "eg":
                blob = nn_spec_to_eg_bytes(spec)
            else:
                # EGB container needs the project ColumnConfig stats
                try:
                    self.setup()
                except Exception:
                    raise ShifuError(
                        ErrorCode.INVALID_COLUMN_CONFIG,
                        "-toref for NN needs ModelConfig/ColumnConfig in cwd "
                        "(use -toeg for a standalone Encog text export)",
                    )
                blob = nn_spec_to_egb_bytes(
                    spec, self.column_configs,
                    cutoff=self.model_config.normalize.std_dev_cut_off or 4.0,
                )
            out = self.output_path or self.input_path + ".ref.nn"
        elif isinstance(spec, TreeModelSpec):
            if fmt == "zip":
                blob = tree_spec_to_zip_bytes(spec)
                out = self.output_path or self.input_path + ".zip"
            else:
                blob = tree_spec_to_ref_bytes(spec)
                out = self.output_path or self.input_path + f".ref{suffix}"
        else:
            from shifu_tpu.models.wdl import WDLModelSpec

            if isinstance(spec, WDLModelSpec):
                # BinaryWDLSerializer container: needs ColumnConfig stats
                # for the embedded NNColumnStats (compat/wdl.py)
                from shifu_tpu.compat import wdl as cwdl

                try:
                    self.setup()
                except Exception:
                    raise ShifuError(
                        ErrorCode.INVALID_COLUMN_CONFIG,
                        "-toref for WDL needs ModelConfig/ColumnConfig in "
                        "cwd (the container embeds per-column stats)",
                    )
                blob = cwdl.write_wdl_model(cwdl.wdl_spec_to_ref(
                    spec, self.column_configs,
                    cutoff=self.model_config.normalize.std_dev_cut_off
                    or 4.0,
                ))
                out = self.output_path or self.input_path + ".ref.wdl"
            else:
                raise ShifuError(
                    ErrorCode.MODEL_NOT_FOUND,
                    f"cannot export {self.input_path} to reference format")
        with open(out, "wb") as fh:
            fh.write(blob)
        log.info("exported %s -> %s (reference %s format)",
                 self.input_path, out, fmt)

    def _from_reference(self) -> None:
        """Report on a reference spec; reference models score directly via
        `shifu eval` (scorer sniffs formats), so import just validates."""
        from shifu_tpu.compat.adapters import load_ref_model

        adapter = load_ref_model(self.input_path)
        if adapter is None:
            raise ShifuError(ErrorCode.MODEL_NOT_FOUND,
                             f"{self.input_path} is not a reference-format spec")
        log.info("loaded reference spec %s: kind=%s algorithm=%s",
                 self.input_path, adapter.kind, adapter.algorithm)

    def _to_json(self) -> None:
        from shifu_tpu.eval.scorer import load_model
        from shifu_tpu.models.nn import NNModelSpec, flatten_params
        from shifu_tpu.models.tree import TreeModelSpec
        from shifu_tpu.models.wdl import WDLModelSpec, flatten_wdl

        spec = load_model(self.input_path)
        out = self.output_path or self.input_path + ".json"
        if isinstance(spec, NNModelSpec):
            head = spec.header()
            flat, shapes = flatten_params(spec.params)
            head["layerShapes"] = [list(s) for s in shapes]
            head["weights"] = [float(x) for x in flat]
        elif isinstance(spec, TreeModelSpec):
            head = {
                "algorithm": spec.algorithm,
                "inputColumns": spec.input_columns,
                "slots": spec.slots,
                "boundaries": spec.boundaries,
                "categories": spec.categories,
                "loss": spec.loss,
                "learningRate": spec.learning_rate,
                "convertToProb": spec.convert_to_prob,
                "trees": [
                    {
                        "weight": t.weight,
                        "feature": t.feature.tolist(),
                        "leftMask": t.left_mask.astype(int).tolist(),
                        "leafValue": [float(v) for v in t.leaf_value],
                    }
                    for t in spec.trees
                ],
            }
        elif isinstance(spec, WDLModelSpec):
            head = {
                "algorithm": "WDL", "hidden": spec.hidden,
                "activations": spec.activations, "embedDim": spec.embed_dim,
                "denseColumns": spec.dense_columns,
                "catColumns": spec.cat_columns,
                "vocabSizes": spec.vocab_sizes,
                "normSpecs": spec.norm_specs,
                "categories": spec.categories,
                "weights": [float(x) for x in flatten_wdl(spec.params)],
            }
        else:  # pragma: no cover
            raise ShifuError(ErrorCode.MODEL_NOT_FOUND, str(self.input_path))
        head["sourceFormat"] = os.path.splitext(self.input_path)[1]
        with open(out, "w") as fh:
            json.dump(head, fh)
        log.info("converted %s -> %s", self.input_path, out)

    def _to_binary(self) -> None:
        with open(self.input_path) as fh:
            head = json.load(fh)
        alg = head.get("algorithm", "NN")
        out = self.output_path
        if alg in ("GBT", "RF"):
            from shifu_tpu.models.tree import DenseTree, TreeModelSpec

            trees = [
                DenseTree(
                    feature=np.asarray(t["feature"], np.int32),
                    left_mask=np.asarray(t["leftMask"], bool),
                    leaf_value=np.asarray(t["leafValue"], np.float32),
                    weight=float(t["weight"]),
                )
                for t in head["trees"]
            ]
            spec = TreeModelSpec(
                algorithm=alg, trees=trees,
                input_columns=head.get("inputColumns", []),
                slots=head.get("slots", []),
                boundaries=head.get("boundaries", []),
                categories=head.get("categories", []),
                loss=head.get("loss", "squared"),
                learning_rate=float(head.get("learningRate", 0.05)),
                convert_to_prob=head.get("convertToProb", "SIGMOID"),
            )
            out = out or f"model_converted.{alg.lower()}"
        elif alg == "WDL":
            from shifu_tpu.models.wdl import (
                WDLModelSpec,
                init_wdl_params,
                unflatten_wdl,
            )

            spec = WDLModelSpec(
                hidden=head["hidden"], activations=head["activations"],
                embed_dim=head["embedDim"],
                dense_columns=head["denseColumns"],
                cat_columns=head["catColumns"],
                vocab_sizes=head["vocabSizes"],
                norm_specs=head.get("normSpecs", []),
                categories=head.get("categories", []),
            )
            template = init_wdl_params(
                len(spec.dense_columns), spec.vocab_sizes, spec.embed_dim,
                spec.hidden,
            )
            spec.params = unflatten_wdl(
                np.asarray(head["weights"], np.float32), template
            )
            out = out or "model_converted.wdl"
        else:
            from shifu_tpu.models.nn import NNModelSpec, unflatten_params

            spec = NNModelSpec(
                layer_sizes=head["layerSizes"],
                activations=head["activations"],
                out_activation=head.get("outActivation", "sigmoid"),
                input_columns=head.get("inputColumns", []),
                norm_type=head.get("normType", "ZSCALE"),
                algorithm=head.get("algorithm", "NN"),
                loss=head.get("loss", "squared"),
                norm_specs=head.get("normSpecs", []),
            )
            spec.params = unflatten_params(
                np.asarray(head["weights"], np.float32),
                [tuple(s) for s in head["layerShapes"]],
            )
            out = out or f"model_converted{head.get('sourceFormat', '.nn')}"
        spec.save(out)
        log.info("converted %s -> %s", self.input_path, out)
