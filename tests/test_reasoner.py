"""Reason codes (core/Reasoner.java + CalculateReasonCodeUDF parity)."""

import json
import os

import numpy as np

from tests.helpers import make_model_set


def _posttrained_root(tmp_path):
    root = str(tmp_path / "ms")
    make_model_set(root, n_rows=400)
    from shifu_tpu.config.model_config import ModelConfig
    from shifu_tpu.processor.init import InitProcessor
    from shifu_tpu.processor.norm import NormProcessor
    from shifu_tpu.processor.posttrain import PostTrainProcessor
    from shifu_tpu.processor.stats import StatsProcessor
    from shifu_tpu.processor.train import TrainProcessor
    from shifu_tpu.processor.varsel import VarSelProcessor

    assert InitProcessor(root).run() == 0
    assert StatsProcessor(root).run() == 0
    assert VarSelProcessor(root).run() == 0  # Reasoner needs finalSelect
    assert NormProcessor(root).run() == 0
    mc = ModelConfig.load(os.path.join(root, "ModelConfig.json"))
    mc.train.num_train_epochs = 25
    mc.save(os.path.join(root, "ModelConfig.json"))
    assert TrainProcessor(root).run() == 0
    assert PostTrainProcessor(root).run() == 0
    return root


def test_reasoner_ranks_by_bin_avg_score(tmp_path):
    from shifu_tpu.config.column_config import load_column_config_list
    from shifu_tpu.config.model_config import ModelConfig
    from shifu_tpu.data.reader import read_columnar, read_header
    from shifu_tpu.eval.reasoner import Reasoner

    root = _posttrained_root(tmp_path)
    ccs = load_column_config_list(os.path.join(root, "ColumnConfig.json"))
    assert any(c.column_binning.bin_avg_score for c in ccs
               if c.final_select), "posttrain must fill binAvgScore"

    mc = ModelConfig.load(os.path.join(root, "ModelConfig.json"))
    names = read_header(mc.data_set.header_path, mc.data_set.header_delimiter)
    data = read_columnar(mc.data_set.data_path, names, delimiter="|")

    reasoner = Reasoner(ccs, {"num_0": "RC_NUM0"}, num_top_variables=3)
    codes = reasoner.reason_codes(data)
    assert len(codes) == data.n_rows
    assert all(1 <= len(r) <= 3 for r in codes)
    # mapped name appears when num_0 ranks; unmapped columns fall back to
    # their own name
    flat = {c for row in codes for c in row}
    assert flat  # nonempty reason vocabulary
    diffs = reasoner.score_diffs(data)
    # the top reason of row 0 really is its argmax column
    top_col = reasoner.columns[int(np.argmax(diffs[0]))].column_name
    expected = {"num_0": "RC_NUM0"}.get(top_col, top_col)
    assert codes[0][0] == expected


def test_eval_score_appends_reason_column(tmp_path):
    from shifu_tpu.config.model_config import ModelConfig
    from shifu_tpu.processor.evaluate import EvalProcessor

    root = _posttrained_root(tmp_path)
    rc_path = os.path.join(root, "reasoncodes.json")
    with open(rc_path, "w") as fh:
        json.dump({"num_0": "RC_NUM0", "num_3": "RC_NUM3"}, fh)
    mc = ModelConfig.load(os.path.join(root, "ModelConfig.json"))
    ev = mc.evals[0]
    ev.data_set.data_path = mc.data_set.data_path
    ev.data_set.header_path = mc.data_set.header_path
    ev.data_set.data_delimiter = "|"
    ev.custom_paths = {"reasonCodePath": rc_path}
    mc.save(os.path.join(root, "ModelConfig.json"))

    assert EvalProcessor(root, score_name="Eval1").run() == 0
    import glob

    score_file = glob.glob(os.path.join(root, "**", "EvalScore*"),
                           recursive=True)[0]
    with open(score_file) as fh:
        header = fh.readline().strip().split("|")
        first = fh.readline().strip().split("|")
    assert header[-1] == "reasons"
    assert first[-1]  # nonempty ^-joined reason list
