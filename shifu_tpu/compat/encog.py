"""Encog BasicNetwork compatibility: EG text format + flat-network forward.

The reference persists NN models as Encog EG text files (header line
``encog,BasicNetwork,java,3.0.0,...``; golden specs at
/root/reference/src/test/resources/model/model0.nn and
example/*/ModelStore/*/models/*.nn) and loads them through
EncogDirectoryPersistence (util/ModelSpecLoaderUtils.java:409).  This module
reads/writes that format and evaluates the flat network with one numpy
matmul per layer instead of Encog's per-neuron loop
(FlatNetwork.computeLayer), so a whole batch scores at once.

Flat-network layout (Encog convention, mirrored by
core/dtrain/dataset/FloatFlatNetwork.java): layers are stored OUTPUT-FIRST;
``layerCounts[t]`` includes the bias neuron, ``layerFeedCounts[t]`` excludes
it; the weight rows feeding layer ``t-1`` start at ``weightIndex[t-1]`` and
each row is [w_from_each_input..., w_bias].
"""

from __future__ import annotations

import io
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

# ---------------------------------------------------------------------------
# activation bank (names as serialized by Encog / shifu's own activations,
# math mirrored from org.encog ActivationSigmoid/TANH/Linear and
# core/dtrain/nn/Activation{ReLU,LeakyReLU,Swish,PTANH}.java)
# ---------------------------------------------------------------------------


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def apply_activation(name: str, x: np.ndarray, params: Optional[List[float]] = None):
    n = name.lower().replace("activation", "")
    if n == "sigmoid":
        return _sigmoid(x)
    if n in ("tanh", "htan"):
        return np.tanh(x)
    if n == "linear":
        return x
    if n == "relu":
        thresh = params[0] if params else 0.0
        low = params[1] if params and len(params) > 1 else 0.0
        return np.where(x <= thresh, low, x)
    if n == "leakyrelu":
        thresh = params[0] if params else 0.0
        alpha = params[1] if params and len(params) > 1 else 0.01
        return np.where(x <= thresh, x * alpha, x)
    if n == "swish":
        return x * _sigmoid(x)
    if n == "ptanh":
        return np.where(x > 0, np.tanh(x), 0.25 * np.tanh(x))
    if n == "log":
        return np.where(x >= 0, np.log(1 + x), -np.log(1 - x))
    if n == "elliott":
        s = params[0] if params else 1.0
        return ((x * s) / 2) / (1 + np.abs(x * s)) + 0.5
    if n == "elliottsymmetric":
        s = params[0] if params else 1.0
        return (x * s) / (1 + np.abs(x * s))
    raise ValueError(f"unsupported Encog activation: {name}")


# our trainer's activation names -> Encog class names
TO_ENCOG_NAME = {
    "sigmoid": "ActivationSigmoid",
    "tanh": "ActivationTANH",
    "linear": "ActivationLinear",
    "relu": "ActivationReLU",
    "leakyrelu": "ActivationLeakyReLU",
    "swish": "ActivationSwish",
    "ptanh": "ActivationPTANH",
    "log": "ActivationLOG",
}
FROM_ENCOG_NAME = {v.lower(): k for k, v in TO_ENCOG_NAME.items()}


@dataclass
class EncogNetwork:
    """Flat Encog BasicNetwork (output-first layer order)."""

    layer_counts: List[int]  # incl. bias neuron
    layer_feed_counts: List[int]  # excl. bias neuron
    weights: np.ndarray  # flat f64, output-first transitions
    activations: List[str]  # Encog class names, one per layer
    activation_params: List[List[float]] = field(default_factory=list)
    bias_activation: List[float] = field(default_factory=list)
    properties: Dict[str, str] = field(default_factory=dict)
    feature_set: List[int] = field(default_factory=list)  # BasicFloatNetwork subset

    def __post_init__(self):
        n = len(self.layer_counts)
        if not self.bias_activation:
            self.bias_activation = [0.0] + [1.0] * (n - 1)
        if not self.activation_params:
            self.activation_params = [[] for _ in self.activations]

    # -- derived Encog arrays ------------------------------------------------
    @property
    def input_count(self) -> int:
        return self.layer_feed_counts[-1]

    @property
    def output_count(self) -> int:
        return self.layer_feed_counts[0]

    @property
    def layer_index(self) -> List[int]:
        idx, acc = [], 0
        for c in self.layer_counts:
            idx.append(acc)
            acc += c
        return idx

    @property
    def weight_index(self) -> List[int]:
        idx, acc = [], 0
        for t in range(len(self.layer_counts) - 1):
            idx.append(acc)
            acc += self.layer_feed_counts[t] * self.layer_counts[t + 1]
        idx.append(acc)
        return idx

    def default_layer_output(self) -> List[float]:
        out: List[float] = []
        for t, c in enumerate(self.layer_counts):
            vals = [0.0] * c
            if c > self.layer_feed_counts[t]:  # bias neuron sits last
                vals[-1] = self.bias_activation[t]
            out.extend(vals)
        return out

    # -- compute -------------------------------------------------------------
    def compute(self, x: np.ndarray) -> np.ndarray:
        """Forward a [B, inputCount] batch -> [B, outputCount] (float64)."""
        x = np.asarray(x, dtype=np.float64)
        squeeze = x.ndim == 1
        if squeeze:
            x = x[None, :]
        widx = self.weight_index
        n_layers = len(self.layer_counts)
        for t in range(n_layers - 1, 0, -1):
            if self.layer_counts[t] > self.layer_feed_counts[t]:
                bias_col = np.full((x.shape[0], 1), self.bias_activation[t])
                aug = np.concatenate([x, bias_col], axis=1)
            else:
                aug = x
            out_feed = self.layer_feed_counts[t - 1]
            w = self.weights[widx[t - 1] : widx[t - 1] + out_feed * self.layer_counts[t]]
            w = w.reshape(out_feed, self.layer_counts[t])
            x = apply_activation(
                self.activations[t - 1], aug @ w.T, self.activation_params[t - 1]
            )
        return x[:, 0] if squeeze and x.shape[1] == 1 else (x[0] if squeeze else x)


# ---------------------------------------------------------------------------
# EG text format
# ---------------------------------------------------------------------------


def _parse_num_list(val: str, cast=float) -> list:
    return [cast(v) for v in val.split(",") if v != ""]


def read_eg(data: bytes) -> EncogNetwork:
    """Parse an Encog EG text file (BasicNetwork)."""
    text = data.decode("utf-8", errors="replace")
    lines = text.splitlines()
    if not lines or not lines[0].startswith("encog,"):
        raise ValueError("not an Encog EG file")
    section = ""
    props: Dict[str, str] = {}
    net: Dict[str, str] = {}
    acts: List[str] = []
    act_params: List[List[float]] = []
    for line in lines[1:]:
        line = line.strip()
        if not line:
            continue
        if line.startswith("["):
            section = line.strip("[]")
            continue
        if section == "BASIC:PARAMS":
            k, _, v = line.partition("=")
            props[k] = v
        elif section == "BASIC:NETWORK":
            k, _, v = line.partition("=")
            net[k] = v
        elif section == "BASIC:ACTIVATION":
            parts = line.split(",")
            name = parts[0].strip().strip('"')
            acts.append(name)
            act_params.append([float(p) for p in parts[1:] if p.strip()])
    layer_counts = _parse_num_list(net["layerCounts"], int)
    layer_feed = _parse_num_list(net["layerFeedCounts"], int)
    weights = np.array(_parse_num_list(net["weights"]), dtype=np.float64)
    bias_act = _parse_num_list(net.get("biasActivation", ""))
    return EncogNetwork(
        layer_counts=layer_counts,
        layer_feed_counts=layer_feed,
        weights=weights,
        activations=acts,
        activation_params=act_params,
        bias_activation=bias_act or None or [],
        properties=props,
    )


def _fmt(v: float) -> str:
    return repr(float(v))


def write_eg(net: EncogNetwork) -> bytes:
    """Serialize to Encog EG text loadable by EncogDirectoryPersistence."""
    out = io.StringIO()
    ts = int(time.time() * 1000)
    out.write(f"encog,BasicNetwork,java,3.0.0,1,{ts}\n")
    out.write("[BASIC]\n[BASIC:PARAMS]\n")
    for k, v in net.properties.items():
        out.write(f"{k}={v}\n")
    out.write("[BASIC:NETWORK]\n")
    n = len(net.layer_counts)
    zeros = ",".join(["0"] * n)
    out.write("beginTraining=0\n")
    out.write("connectionLimit=0\n")
    out.write(f"contextTargetOffset={zeros}\n")
    out.write(f"contextTargetSize={zeros}\n")
    out.write(f"endTraining={n - 1}\n")
    out.write("hasContext=f\n")
    out.write(f"inputCount={net.input_count}\n")
    out.write("layerCounts=" + ",".join(map(str, net.layer_counts)) + "\n")
    out.write("layerFeedCounts=" + ",".join(map(str, net.layer_feed_counts)) + "\n")
    out.write(f"layerContextCount={zeros}\n")
    out.write("layerIndex=" + ",".join(map(str, net.layer_index)) + "\n")
    out.write("output=" + ",".join(_fmt(v) for v in net.default_layer_output()) + "\n")
    out.write(f"outputCount={net.output_count}\n")
    out.write("weightIndex=" + ",".join(map(str, net.weight_index)) + "\n")
    out.write("weights=" + ",".join(_fmt(w) for w in net.weights) + "\n")
    out.write("biasActivation=" + ",".join(_fmt(b) for b in net.bias_activation) + "\n")
    out.write("[BASIC:ACTIVATION]\n")
    for name, params in zip(net.activations, net.activation_params):
        line = f'"{name}"'
        if params:
            line += "," + ",".join(_fmt(p) for p in params)
        out.write(line + "\n")
    return out.getvalue().encode("utf-8")


# ---------------------------------------------------------------------------
# conversion to/from our NNModelSpec layer list
# ---------------------------------------------------------------------------


def from_layers(
    weights: List[np.ndarray],
    biases: List[np.ndarray],
    hidden_activations: List[str],
    out_activation: str = "sigmoid",
) -> EncogNetwork:
    """Build an EncogNetwork from input-first [in,out] weight matrices."""
    n_trans = len(weights)
    feed = [weights[0].shape[0]] + [w.shape[1] for w in weights]  # input-first
    feed_rev = feed[::-1]  # output-first
    layer_counts = [feed_rev[0]] + [c + 1 for c in feed_rev[1:]]
    acts_in_first = list(hidden_activations[:n_trans - 1]) + [out_activation]
    enc_acts = [TO_ENCOG_NAME[a.lower()] for a in acts_in_first[::-1]] + ["ActivationLinear"]
    flat: List[float] = []
    for t in range(n_trans - 1, -1, -1):  # output-first transitions
        w, b = np.asarray(weights[t], np.float64), np.asarray(biases[t], np.float64)
        for j in range(w.shape[1]):
            flat.extend(w[:, j])
            flat.append(b[j])
    return EncogNetwork(
        layer_counts=layer_counts,
        layer_feed_counts=feed_rev,
        weights=np.array(flat, dtype=np.float64),
        activations=enc_acts,
    )


def to_layers(net: EncogNetwork):
    """Decompose into input-first ([in,out] weight, [out] bias) pairs +
    activation names; only valid when every non-output layer has a bias."""
    widx = net.weight_index
    weights, biases, acts = [], [], []
    n = len(net.layer_counts)
    for t in range(n - 1, 0, -1):  # input side -> output side
        out_feed = net.layer_feed_counts[t - 1]
        in_count = net.layer_counts[t]
        w = net.weights[widx[t - 1] : widx[t - 1] + out_feed * in_count]
        w = w.reshape(out_feed, in_count)
        has_bias = in_count > net.layer_feed_counts[t]
        if has_bias:
            weights.append(w[:, :-1].T.copy())
            biases.append((w[:, -1] * net.bias_activation[t]).copy())
        else:
            weights.append(w.T.copy())
            biases.append(np.zeros(out_feed))
        acts.append(FROM_ENCOG_NAME.get(net.activations[t - 1].lower(), "linear"))
    return weights, biases, acts
