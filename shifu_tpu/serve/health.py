"""Serve health state machine: ok | degraded | draining, with a reason.

/healthz used to be a liveness ping; under the self-healing serve path it
is the load balancer's routing signal, so it must distinguish three
states the supervisor actually produces:

  ok        scoring normally.
  degraded  still scoring, but a worker crash was survived recently —
            the state a router uses to de-prioritize (not eject) a
            replica. Clears back to `ok` after `ok_after` consecutive
            clean batches.
  draining  not accepting new work (shutdown in progress, or the worker
            restart budget is exhausted) — /healthz returns 503 so the
            balancer stops routing here while in-flight work finishes.

Transitions are monotone toward draining: once draining, crash/ok notes
cannot resurrect the replica (a drained server restarts, it does not
heal). Every transition lands in `serve.health.transitions{to=...}` so
the run-ledger manifest carries the replica's health history.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Optional

from shifu_tpu.analysis.racetrack import guarded_by, tracked_lock
from shifu_tpu.utils import environment

OK = "ok"
DEGRADED = "degraded"
DRAINING = "draining"

# circuit-breaker states (CircuitBreaker below): CLOSED passes traffic,
# OPEN quarantines the replica, HALF_OPEN lets single probes through
BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half_open"

DEFAULT_OK_AFTER = 3

DEFAULT_BREAKER_FAILURES = 3
DEFAULT_PROBE_BASE_MS = 500.0
DEFAULT_PROBE_CAP_MS = 30_000.0
DEFAULT_PROBE_OKS = 2
# a half-open probe that never produced an outcome (e.g. it was
# deadline-shed before dispatch) is abandoned after this long, so a
# lost probe cannot wedge the replica in half-open forever
PROBE_ABANDON_S = 60.0

DEFAULT_SLO_TARGET = 0.99
DEFAULT_SLO_WINDOW_S = 60.0
# rolling-window event bound: at 4096 requests the window estimate is
# already statistical, and the deque stays O(KB) at any uptime
SLO_WINDOW_EVENTS = 4096


def breaker_failures_setting() -> int:
    """shifu.serve.breaker.failures — consecutive dispatch failures that
    trip a replica's breaker open."""
    return environment.get_int("shifu.serve.breaker.failures",
                               DEFAULT_BREAKER_FAILURES)


def breaker_probe_base_ms_setting() -> float:
    """shifu.serve.breaker.probeBaseMs — first open->half-open probe
    backoff window."""
    return environment.get_float("shifu.serve.breaker.probeBaseMs",
                                 DEFAULT_PROBE_BASE_MS)


def breaker_probe_cap_ms_setting() -> float:
    """shifu.serve.breaker.probeCapMs — probe backoff ceiling."""
    return environment.get_float("shifu.serve.breaker.probeCapMs",
                                 DEFAULT_PROBE_CAP_MS)


def breaker_probe_oks_setting() -> int:
    """shifu.serve.breaker.probeOks — consecutive successful half-open
    probes before the breaker closes."""
    return environment.get_int("shifu.serve.breaker.probeOks",
                               DEFAULT_PROBE_OKS)


def slo_ms_setting() -> float:
    """shifu.serve.sloMs — per-request latency SLO threshold in ms
    (0 = SLO accounting off)."""
    return environment.get_float("shifu.serve.sloMs", 0.0)


def slo_target_setting() -> float:
    """shifu.serve.sloTarget — the objective: the fraction of requests
    that must meet sloMs (burn rate is measured against 1 - target)."""
    return environment.get_float("shifu.serve.sloTarget",
                                 DEFAULT_SLO_TARGET)


def tenant_slo_ms(tenant: str) -> float:
    """Per-tenant SLO threshold: shifu.serve.slo.<tenant>.ms, falling
    back to the fleet-wide shifu.serve.sloMs — a latency-sensitive zoo
    tenant gets its own objective without forking the fleet knob."""
    return environment.get_float(f"shifu.serve.slo.{tenant}.ms",
                                 slo_ms_setting())


def tenant_slo_target(tenant: str) -> float:
    """Per-tenant objective: shifu.serve.slo.<tenant>.target, falling
    back to shifu.serve.sloTarget."""
    return environment.get_float(f"shifu.serve.slo.{tenant}.target",
                                 slo_target_setting())


class SloTracker:
    """Good/bad SLO accounting + burn rate over a rolling window.

    A request is GOOD when its end-to-end latency meets
    `-Dshifu.serve.sloMs`; good/bad land in the `serve.slo.good` /
    `serve.slo.bad` counters. `burn_rate()` is the classic SRE number:
    the bad fraction over the rolling window divided by the error
    budget (1 - target) — 1.0 means the budget burns exactly at the
    sustainable rate, above it /healthz carries an SLO reason."""

    def __init__(self, slo_ms: Optional[float] = None,
                 target: Optional[float] = None,
                 window_s: float = DEFAULT_SLO_WINDOW_S,
                 labels: Optional[dict] = None) -> None:
        # a zoo tenant's tracker resolves ITS knobs first (the labels
        # carry the identity), so per-tenant objectives and the tenant=
        # label on serve.slo.* land together
        tenant = (labels or {}).get("tenant")
        if slo_ms is None:
            slo_ms = tenant_slo_ms(tenant) if tenant else slo_ms_setting()
        self.slo_ms = float(slo_ms)
        if target is None:
            target = (tenant_slo_target(tenant) if tenant
                      else slo_target_setting())
        target = float(target)
        self.target = min(max(target, 0.0), 0.9999)
        self.window_s = float(window_s)
        # fleet-identity labels ({"tenant": ...} in a zoo): per-tenant
        # SLO series stay separable on one /metrics page
        self.labels = dict(labels or {})
        self._lock = tracked_lock("serve.slo")
        self._events: deque = deque(maxlen=SLO_WINDOW_EVENTS)
        self._good = 0
        self._bad = 0

    @property
    def enabled(self) -> bool:
        return self.slo_ms > 0.0

    def observe(self, latency_s: float, ok: Optional[bool] = None) -> None:
        """Count one request. `ok=None` applies the latency test;
        `ok=False` forces a bad count — shed (429) and failed requests
        got NO score, which must burn budget rather than dilute the
        window as sub-millisecond "good" outcomes."""
        if not self.enabled:
            return
        from shifu_tpu.obs import registry

        if ok is None:
            ok = latency_s * 1e3 <= self.slo_ms
        with self._lock:
            self._events.append((time.perf_counter(), ok))
            if ok:
                self._good += 1
            else:
                self._bad += 1
        registry().counter("serve.slo.good" if ok else "serve.slo.bad",
                           **self.labels).inc()

    def burn_rate(self, now: Optional[float] = None) -> float:
        """Bad fraction over the rolling window / (1 - target); exported
        as the `serve.slo.burn_rate` gauge on every read."""
        if not self.enabled:
            return 0.0
        from shifu_tpu.obs import registry

        if now is None:
            now = time.perf_counter()
        with self._lock:
            recent = [ok for t, ok in self._events
                      if now - t <= self.window_s]
        if not recent:
            rate = 0.0
        else:
            bad = sum(1 for ok in recent if not ok)
            rate = (bad / len(recent)) / max(1e-9, 1.0 - self.target)
        registry().gauge("serve.slo.burn_rate", **self.labels).set(rate)
        return rate

    def snapshot(self) -> dict:
        rate = self.burn_rate()
        with self._lock:
            return {
                "sloMs": self.slo_ms,
                "target": self.target,
                "windowSeconds": self.window_s,
                "good": self._good,
                "bad": self._bad,
                "burnRate": round(rate, 4),
                "burning": rate > 1.0,
            }


class HealthMonitor:
    """Thread-safe tri-state health with crash-recovery hysteresis.

    `labels` (typically {"replica": "<i>"}) ride the transition counter
    so a fleet's per-replica health histories stay separable in one
    metrics page; the fleet-level aggregation over these monitors lives
    in serve/fleet.py (`ReplicaFleet.health_snapshot`)."""

    def __init__(self, ok_after: int = DEFAULT_OK_AFTER,
                 labels: Optional[dict] = None) -> None:
        self._lock = tracked_lock("serve.health")
        self.labels = dict(labels or {})
        self._state = OK
        self._reason = ""
        self._ok_after = max(1, ok_after)
        self._ok_streak = 0
        self._crashes = 0
        self._sticky = False  # degrade that clean batches must NOT clear
        # the crash-caused degrade is tracked SEPARATELY from the sticky
        # (drift) one: the two can layer, and clearing the sticky overlay
        # must leave the crash degrade (and its hysteresis) underneath
        self._crash_degraded = False
        self._crash_reason = ""

    @guarded_by("_lock")
    def _transition(self, state: str, reason: str) -> None:
        # caller holds the lock (declared + race-checked via @guarded_by)
        if self._state == state:
            self._reason = reason
            return
        self._state = state
        self._reason = reason
        from shifu_tpu.obs import registry

        registry().counter("serve.health.transitions", to=state,
                           **self.labels).inc()

    def note_crash(self, reason: str) -> None:
        with self._lock:
            self._crashes += 1
            self._ok_streak = 0
            self._crash_degraded = True
            self._crash_reason = reason
            if self._state != DRAINING:
                self._transition(DEGRADED, reason)

    def note_degraded(self, reason: str) -> None:
        """Degrade WITHOUT counting a crash and WITHOUT the clean-batch
        hysteresis clearing it (the drift path: scoring is healthy, the
        MODEL is stale — only an operator action like `shifu promote`
        resolves it, via clear_degraded)."""
        with self._lock:
            self._sticky = True
            if self._state != DRAINING:
                self._transition(DEGRADED, reason)

    def clear_degraded(self) -> None:
        """Drop a sticky (non-crash) degrade — called after a hot-swap
        promoted a fresh model set. A crash-caused degrade is NOT
        cleared: scoring itself was failing, and only the clean-batch
        hysteresis (note_ok) may lift it — a promote must not route full
        traffic back onto a still-crashing replica."""
        with self._lock:
            was_sticky, self._sticky = self._sticky, False
            self._ok_streak = 0
            if self._state != DEGRADED or not was_sticky:
                return
            if self._crash_degraded:
                # the crash degrade layered UNDER the drift one survives:
                # scoring was failing, and only clean batches heal that
                self._reason = self._crash_reason
                return
            self._transition(OK, "")

    def note_ok(self) -> None:
        with self._lock:
            if self._state != DEGRADED or self._sticky:
                return
            self._ok_streak += 1
            if self._ok_streak >= self._ok_after:
                self._crash_degraded = False
                self._crash_reason = ""
                self._transition(OK, "")

    def set_draining(self, reason: str) -> None:
        with self._lock:
            self._transition(DRAINING, reason)

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def reason(self) -> str:
        with self._lock:
            return self._reason

    @property
    def crashes(self) -> int:
        with self._lock:
            return self._crashes

    def snapshot(self) -> dict:
        with self._lock:
            return {"status": self._state, "reason": self._reason,
                    "workerCrashes": self._crashes}


class CircuitBreaker:
    """Per-replica circuit breaker over device-dispatch outcomes.

    The health monitor above answers "is the WORKER alive"; this answers
    "is the DEVICE trustworthy". A replica whose dispatches keep failing
    (dead device, poisoned compile cache, wedged runtime) must leave the
    routing set entirely — restarts alone put it straight back in line
    to eat the next batch. Classic three-state machine:

      closed     normal: failures count a consecutive streak; reaching
                 `shifu.serve.breaker.failures` TRIPS the breaker.
      open       quarantined: the router treats the replica as absent.
                 Each trip schedules a probe a jittered exponential
                 backoff away (resilience/retry.py's backoff window —
                 equal-jitter over it, so a fleet of tripped breakers
                 does not probe a recovering backend in lockstep, and a
                 probe is never scheduled at zero delay).
      half_open  the backoff elapsed: the router sends exactly ONE live
                 request as the probe. `shifu.serve.breaker.probeOks`
                 consecutive successes close the breaker; any failure
                 re-opens it with a doubled (capped) backoff.

    A failed probe request is not sacrificed: the fleet's failover path
    replays it on a healthy replica like any other failed-batch rider.
    Every transition counts `serve.breaker.transitions{to=,replica=}`
    and flips the `serve.breaker.open{replica=}` gauge."""

    def __init__(self, failures: Optional[int] = None,
                 probe_base_ms: Optional[float] = None,
                 probe_cap_ms: Optional[float] = None,
                 probe_oks: Optional[int] = None,
                 labels: Optional[dict] = None,
                 rng=None) -> None:
        import random

        self.labels = dict(labels or {})
        self.failures = (breaker_failures_setting() if failures is None
                         else int(failures))
        self.probe_base_ms = (breaker_probe_base_ms_setting()
                              if probe_base_ms is None
                              else float(probe_base_ms))
        self.probe_cap_ms = (breaker_probe_cap_ms_setting()
                             if probe_cap_ms is None
                             else float(probe_cap_ms))
        self.probe_oks = max(1, breaker_probe_oks_setting()
                             if probe_oks is None else int(probe_oks))
        self._rng = rng or random.Random()
        self._lock = tracked_lock("serve.breaker")
        self._state = BREAKER_CLOSED
        self._fail_streak = 0
        self._ok_streak = 0
        self._open_attempts = 0   # consecutive trips without a close
        self._open_until = 0.0    # monotonic deadline of the quarantine
        self._probe_inflight = False
        self._probe_started = 0.0
        self._trips = 0
        self._last_error = ""

    @guarded_by("_lock")
    def _probe_busy(self, now: float) -> bool:
        return (self._probe_inflight
                and now - self._probe_started < PROBE_ABANDON_S)

    @guarded_by("_lock")
    def _transition(self, state: str) -> None:
        # caller holds the lock (declared + race-checked via @guarded_by)
        if self._state == state:
            return
        self._state = state
        from shifu_tpu.obs import registry

        reg = registry()
        reg.counter("serve.breaker.transitions", to=state,
                    **self.labels).inc()
        reg.gauge("serve.breaker.open", **self.labels).set(
            0.0 if state == BREAKER_CLOSED else 1.0)

    @guarded_by("_lock")
    def _probe_delay_s(self) -> float:
        from shifu_tpu.resilience.retry import backoff_window_ms

        window = backoff_window_ms(self.probe_base_ms, self.probe_cap_ms,
                                   max(1, self._open_attempts))
        # equal jitter: at least half the window, never zero — a probe
        # scheduled at 0 ms would re-dispatch into the failure instantly
        return (window * (0.5 + 0.5 * self._rng.random())) / 1000.0

    def admit(self, now: Optional[float] = None) -> Optional[str]:
        """Router placement gate. Returns a grant token — "closed"
        (normal traffic) or "probe" (this request IS the half-open
        probe) — or None when the replica is quarantined. A granted
        probe that is never dispatched (the queue shed it) must be
        returned via cancel()."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            if self._state == BREAKER_CLOSED:
                return "closed"
            if self._state == BREAKER_OPEN:
                if now < self._open_until:
                    return None
                self._transition(BREAKER_HALF_OPEN)
                self._probe_inflight = True
                self._probe_started = now
                return "probe"
            # half-open: one probe at a time
            if self._probe_busy(now):
                return None
            self._probe_inflight = True
            self._probe_started = now
            return "probe"

    def cancel(self, grant: Optional[str]) -> None:
        """Give back an admit() grant whose request never dispatched."""
        if grant != "probe":
            return
        with self._lock:
            self._probe_inflight = False

    def note_ok(self) -> None:
        """One successful dispatch on this replica."""
        with self._lock:
            if self._state == BREAKER_CLOSED:
                self._fail_streak = 0
                return
            if self._state == BREAKER_OPEN:
                # a straggler batch from before the trip: not a probe,
                # proves nothing about the device NOW
                return
            self._probe_inflight = False
            self._ok_streak += 1
            if self._ok_streak < self.probe_oks:
                return
            self._fail_streak = 0
            self._open_attempts = 0
            self._last_error = ""
            self._transition(BREAKER_CLOSED)

    def note_failure(self, error: str = "") -> None:
        """One failed dispatch on this replica."""
        from shifu_tpu.obs import registry

        tripped = False
        with self._lock:
            if error:
                self._last_error = error
            if self._state == BREAKER_OPEN:
                return  # straggler from before the trip
            if self._state == BREAKER_HALF_OPEN:
                # the probe failed: back to quarantine, longer backoff
                self._probe_inflight = False
                self._ok_streak = 0
                self._open_attempts += 1
                self._open_until = time.monotonic() + self._probe_delay_s()
                self._transition(BREAKER_OPEN)
                return
            self._fail_streak += 1
            if self._fail_streak < self.failures:
                return
            self._ok_streak = 0
            self._open_attempts += 1
            self._trips += 1
            self._open_until = time.monotonic() + self._probe_delay_s()
            self._transition(BREAKER_OPEN)
            tripped = True
        if tripped:
            registry().counter("serve.breaker.trips", **self.labels).inc()

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def trips(self) -> int:
        with self._lock:
            return self._trips

    def probe_due(self, now: Optional[float] = None) -> bool:
        """True when the router should PREFER this replica for one
        request (the probe): open past its backoff, or half-open with no
        probe in flight."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            if self._state == BREAKER_OPEN:
                return now >= self._open_until
            if self._state == BREAKER_HALF_OPEN:
                return not self._probe_busy(now)
            return False

    def routable(self, now: Optional[float] = None) -> bool:
        """False when the replica must be treated as absent (open and
        inside its backoff, or half-open with the probe slot taken)."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            if self._state == BREAKER_CLOSED:
                return True
            if self._state == BREAKER_OPEN:
                return now >= self._open_until
            return not self._probe_busy(now)

    def snapshot(self) -> dict:
        now = time.monotonic()
        with self._lock:
            snap = {
                "state": self._state,
                "trips": self._trips,
                "failStreak": self._fail_streak,
                "openAttempts": self._open_attempts,
            }
            if self._state == BREAKER_OPEN:
                snap["probeInMs"] = round(
                    max(0.0, (self._open_until - now) * 1000.0), 1)
            if self._last_error:
                snap["lastError"] = self._last_error
            return snap
