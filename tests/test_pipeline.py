"""Overlapped streaming pipeline tests (data/pipeline.py).

Contract: the prefetch pipeline is a pure latency optimization — chunk
order, results, and every accumulated statistic are bit-identical to the
serial path; shape bucketing bounds the jit compile count at
O(log max_chunk_rows) for ANY chunk-size sequence; the device-resident
accumulator syncs exactly once per pass.
"""

import os
import time

import numpy as np
import pytest

from shifu_tpu.utils import environment
from tests.helpers import make_binary_dataset, make_model_set, write_dataset


def _set_props(**kv):
    for k, v in kv.items():
        environment.set_property(k, str(v))


def _clear_props(*keys):
    for k in keys:
        environment.set_property(k, "")


class TestPrefetchIter:
    def test_order_and_transform(self):
        from shifu_tpu.data.pipeline import prefetch_iter

        got = list(prefetch_iter(range(50), depth=3,
                                 transform=lambda x: x * 2))
        assert got == [2 * i for i in range(50)]

    def test_depth_zero_is_serial_inline(self):
        from shifu_tpu.data.pipeline import prefetch_iter

        import threading

        main = threading.get_ident()
        seen = []
        list(prefetch_iter(range(5), depth=0,
                           transform=lambda x: seen.append(
                               threading.get_ident()) or x))
        assert seen == [main] * 5

    def test_worker_exception_reraises_in_consumer(self):
        from shifu_tpu.data.pipeline import prefetch_iter

        def boom(x):
            if x == 3:
                raise ValueError("chunk 3 bad")
            return x

        it = prefetch_iter(range(10), depth=2, transform=boom)
        got = []
        with pytest.raises(ValueError, match="chunk 3 bad"):
            for v in it:
                got.append(v)
        assert got == [0, 1, 2]

    def test_failing_source_iter_raises_not_hangs(self):
        from shifu_tpu.data.pipeline import prefetch_iter

        class BadSource:
            def __iter__(self):
                raise OSError("no such file")

        with pytest.raises(OSError, match="no such file"):
            list(prefetch_iter(BadSource(), depth=2))

    def test_early_break_stops_worker(self):
        import threading

        from shifu_tpu.data.pipeline import prefetch_iter

        before = threading.active_count()
        it = prefetch_iter(range(10_000), depth=2)
        for v in it:
            if v == 5:
                break
        it.close()
        deadline = time.time() + 5.0
        while threading.active_count() > before and time.time() < deadline:
            time.sleep(0.01)
        assert threading.active_count() <= before

    def test_depth_from_environment_knob(self):
        from shifu_tpu.data.pipeline import prefetch_chunks_setting

        _set_props(**{"shifu.ingest.prefetchChunks": "5"})
        try:
            assert prefetch_chunks_setting() == 5
        finally:
            _clear_props("shifu.ingest.prefetchChunks")
        assert prefetch_chunks_setting() == 2

    def test_timers_accumulate_across_threads(self):
        from shifu_tpu.data.pipeline import prefetch_iter
        from shifu_tpu.utils.timing import StageTimers

        timers = StageTimers()
        n = 0
        for _ in prefetch_iter(range(8), depth=2, timers=timers,
                               stage="parse"):
            with timers.timer("consume"):
                n += 1
        assert timers.calls("parse") == 9  # 8 items + the end pull
        assert timers.calls("consume") == 8
        assert "parse" in timers.summary()
        d = timers.as_dict()
        assert d["parse"]["calls"] == 9 and d["parse"]["seconds"] >= 0


class TestBucketRows:
    def test_powers_of_two_with_floor(self):
        from shifu_tpu.data.pipeline import bucket_rows

        assert bucket_rows(1) == 256
        assert bucket_rows(256) == 256
        assert bucket_rows(257) == 512
        assert bucket_rows(65536) == 65536
        assert bucket_rows(65537) == 131072

    def test_bounded_shape_count_for_any_sequence(self):
        from shifu_tpu.data.pipeline import bucket_rows

        rng = np.random.default_rng(0)
        sizes = rng.integers(1, 100_000, size=1000)
        buckets = {bucket_rows(int(n)) for n in sizes}
        # O(log max): [256 .. 131072] is 10 distinct powers of two
        assert len(buckets) <= 10


class TestBoundedJitShapes:
    def test_aggregation_compiles_log_bounded_programs(self):
        """57 distinct chunk sizes through the bucketed bin aggregation
        must compile exactly one program per power-of-two bucket (probed
        via the jit cache), not one per chunk size."""
        import jax.numpy as jnp

        from shifu_tpu.data.pipeline import bucket_rows
        from shifu_tpu.ops.binagg import bin_aggregate_jit

        total_slots = 7  # unique static arg so earlier tests can't collide
        sizes = list(range(1, 400, 7))
        before = bin_aggregate_jit._cache_size()
        for n in sizes:
            pad = bucket_rows(n)
            codes = np.zeros((pad, 2), np.int32)
            tags = np.full(pad, -1, np.int32)
            tags[:n] = 1
            bin_aggregate_jit(
                jnp.asarray(codes),
                jnp.asarray(np.array([0, 3], np.int32)),
                total_slots,
                jnp.asarray(tags),
                jnp.asarray(np.ones(pad, np.float32)),
                jnp.asarray(np.zeros((pad, 1), np.float32)),
            )
        compiled = bin_aggregate_jit._cache_size() - before
        expect = len({bucket_rows(n) for n in sizes})
        assert compiled == expect  # == 2: buckets {256, 512}
        assert compiled <= int(np.ceil(np.log2(max(sizes)))) + 1

    def test_streaming_stats_pass2_compile_count(self):
        """End to end: a hand-built chunk factory with 12 different chunk
        sizes (incl. sub-bucket and ragged ones) must add at most one
        aggregation program per distinct row bucket."""
        from shifu_tpu.config import ColumnConfig, ColumnType
        from shifu_tpu.config.column_config import ColumnFlag
        from shifu_tpu.config.model_config import Algorithm, new_model_config
        from shifu_tpu.data.pipeline import bucket_rows
        from shifu_tpu.data.reader import ColumnarData
        from shifu_tpu.ops.binagg import bin_aggregate_jit
        from shifu_tpu.stats.engine import compute_stats_streaming

        rng = np.random.default_rng(5)
        sizes = [37, 64, 100, 129, 256, 300, 333, 400, 480, 511, 513, 700]

        def factory():
            for i, n in enumerate(sizes):
                y = (rng.random(n) < 0.4).astype(int)
                yield ColumnarData(
                    names=["target", "num_0"],
                    raw={
                        "target": np.array([str(v) for v in y], object),
                        "num_0": np.array(
                            [f"{v:.4f}" for v in
                             rng.normal(loc=y, size=n)], object),
                    },
                    n_rows=n,
                )

        mc = new_model_config("JitProbe", Algorithm.NN)
        mc.data_set.target_column_name = "target"
        mc.data_set.pos_tags = ["1"]
        mc.data_set.neg_tags = ["0"]
        cols = [
            ColumnConfig(column_num=0, column_name="target",
                         column_flag=ColumnFlag.TARGET),
            ColumnConfig(column_num=1, column_name="num_0",
                         column_type=ColumnType.N),
        ]
        before = bin_aggregate_jit._cache_size()
        compute_stats_streaming(mc, cols, factory)
        compiled = bin_aggregate_jit._cache_size() - before
        assert compiled <= len({bucket_rows(n) for n in sizes})  # <= 3
        assert cols[1].column_stats.total_count == sum(sizes)


class TestPrefetchParity:
    """The acceptance contract: prefetch on vs off is bit-identical."""

    @pytest.mark.parametrize("chunk_rows", [512, 700])
    def test_streaming_stats_prefetch_bit_identical(self, tmp_path,
                                                    chunk_rows):
        """Full StatsProcessor run, serial vs prefetched, at chunk sizes
        that leave a ragged final chunk — the written ColumnConfig.json
        must match byte for byte."""
        from shifu_tpu.processor.init import InitProcessor
        from shifu_tpu.processor.stats import StatsProcessor

        root = str(tmp_path / "ms")
        make_model_set(root, n_rows=3000)
        assert InitProcessor(root).run() == 0
        cc_path = os.path.join(root, "ColumnConfig.json")

        _set_props(**{"shifu.ingest.forceStreaming": "true",
                      "shifu.ingest.chunkRows": str(chunk_rows),
                      "shifu.ingest.prefetchChunks": "0"})
        try:
            assert StatsProcessor(root).run() == 0
            with open(cc_path, "rb") as fh:
                serial = fh.read()
            _set_props(**{"shifu.ingest.prefetchChunks": "3"})
            assert StatsProcessor(root).run() == 0
            with open(cc_path, "rb") as fh:
                prefetched = fh.read()
        finally:
            _clear_props("shifu.ingest.forceStreaming",
                         "shifu.ingest.chunkRows",
                         "shifu.ingest.prefetchChunks")
        assert prefetched == serial

    def test_streaming_matches_in_ram_compute_stats(self, tmp_path):
        """With EqualInterval binning (sketch min/max is exact, so both
        paths derive identical boundaries), streamed stats must reproduce
        the in-RAM aggregation exactly: same bins, bit-equal counts and
        count-derived metrics; moments match to float-summation order."""
        from shifu_tpu.config import load_column_config_list
        from shifu_tpu.config.model_config import (
            BinningMethod,
            ModelConfig,
        )
        from shifu_tpu.processor.init import InitProcessor
        from shifu_tpu.processor.stats import StatsProcessor

        root = str(tmp_path / "ms")
        make_model_set(root, n_rows=2500)
        mc = ModelConfig.load(os.path.join(root, "ModelConfig.json"))
        mc.stats.binning_method = BinningMethod.EQUAL_INTERVAL
        mc.save(os.path.join(root, "ModelConfig.json"))
        assert InitProcessor(root).run() == 0
        cc_path = os.path.join(root, "ColumnConfig.json")

        assert StatsProcessor(root).run() == 0
        exact = load_column_config_list(cc_path)

        _set_props(**{"shifu.ingest.forceStreaming": "true",
                      "shifu.ingest.chunkRows": "700"})
        try:
            assert StatsProcessor(root).run() == 0
        finally:
            _clear_props("shifu.ingest.forceStreaming",
                         "shifu.ingest.chunkRows")
        stream = load_column_config_list(cc_path)

        for e, s in zip(exact, stream):
            if e.is_target():
                continue
            assert s.column_binning.bin_boundary == \
                e.column_binning.bin_boundary, e.column_name
            assert s.column_binning.bin_category == \
                e.column_binning.bin_category
            assert s.column_binning.bin_count_pos == \
                e.column_binning.bin_count_pos, e.column_name
            assert s.column_binning.bin_count_neg == \
                e.column_binning.bin_count_neg
            assert s.column_stats.ks == pytest.approx(
                e.column_stats.ks, abs=1e-9)
            assert s.column_stats.iv == pytest.approx(
                e.column_stats.iv, abs=1e-9)
            assert s.column_stats.total_count == e.column_stats.total_count
            assert s.column_stats.missing_count == \
                e.column_stats.missing_count
            if s.column_stats.mean is not None:
                assert s.column_stats.mean == pytest.approx(
                    e.column_stats.mean, rel=1e-5)
                assert s.column_stats.std_dev == pytest.approx(
                    e.column_stats.std_dev, rel=1e-4)

    def test_streaming_norm_prefetch_bit_identical(self, tmp_path):
        from shifu_tpu.norm.dataset import load_codes, load_normalized
        from shifu_tpu.processor.init import InitProcessor
        from shifu_tpu.processor.norm import NormProcessor
        from shifu_tpu.processor.stats import StatsProcessor

        root = str(tmp_path / "ms")
        make_model_set(root, n_rows=1500)
        assert InitProcessor(root).run() == 0
        assert StatsProcessor(root).run() == 0

        def run_norm(prefetch):
            _set_props(**{"shifu.ingest.forceStreaming": "true",
                          "shifu.ingest.chunkRows": "256",
                          "shifu.ingest.prefetchChunks": str(prefetch)})
            try:
                assert NormProcessor(root).run() == 0
            finally:
                _clear_props("shifu.ingest.forceStreaming",
                             "shifu.ingest.chunkRows",
                             "shifu.ingest.prefetchChunks")
            _, f, t, w = load_normalized(
                os.path.join(root, "tmp", "norm", "NormalizedData"))
            _, c, _, _ = load_codes(
                os.path.join(root, "tmp", "norm", "CleanedData"))
            return (np.asarray(f).copy(), np.asarray(t).copy(),
                    np.asarray(w).copy(), np.asarray(c).copy())

        f0, t0, w0, c0 = run_norm(0)
        f2, t2, w2, c2 = run_norm(3)
        np.testing.assert_array_equal(f2, f0)
        np.testing.assert_array_equal(t2, t0)
        np.testing.assert_array_equal(w2, w0)
        np.testing.assert_array_equal(c2, c0)


class TestDeviceAccumulator:
    @pytest.mark.parametrize("flush_rows", [10**9, 100])
    def test_fold_matches_host_fold(self, flush_rows):
        """One device window (flush_rows huge) and forced multi-window
        flushing (flush_rows=100 -> a f64 host fold every ~2 chunks) must
        both reproduce the reference per-chunk host fold."""
        import jax.numpy as jnp

        from shifu_tpu.data.pipeline import DeviceAccumulator
        from shifu_tpu.ops.binagg import bin_aggregate_jit

        rng = np.random.default_rng(2)
        acc = DeviceAccumulator(flush_rows=flush_rows)
        assert acc.empty and acc.fetch() is None
        host = None
        for _ in range(4):
            n = 64
            codes = rng.integers(0, 3, size=(n, 1)).astype(np.int32)
            tags = rng.integers(0, 2, size=n).astype(np.int32)
            vals = rng.normal(size=(n, 1)).astype(np.float32)
            agg = bin_aggregate_jit(
                jnp.asarray(codes), jnp.asarray(np.zeros(1, np.int32)), 3,
                jnp.asarray(tags), jnp.asarray(np.ones(n, np.float32)),
                jnp.asarray(vals))
            acc.add(agg, rows=n)
            part = [np.asarray(x, np.float64) for x in agg]
            if host is None:
                host = part
            else:
                host = [
                    np.minimum(h, p) if k == 6 else
                    np.maximum(h, p) if k == 7 else h + p
                    for k, (h, p) in enumerate(zip(host, part))
                ]
        got = acc.fetch()
        for g, h in zip(got, host):
            np.testing.assert_allclose(g, h, rtol=1e-6)


class TestReaderRegressions:
    """Satellite fixes: stray-header filtering + missing-token parity."""

    def test_read_columnar_keeps_row_with_header_like_first_field(
            self, tmp_path):
        """read_columnar must apply the same all-fields-must-match header
        rule as the chunked reader: a data row whose FIRST field collides
        with the first column name survives, a full header row does not."""
        from shifu_tpu.data.reader import read_columnar

        p = str(tmp_path / "d.csv")
        names = ["a", "b"]
        with open(p, "w") as fh:
            fh.write("a|b\n")    # stray full header: dropped
            fh.write("a|1\n")    # legit row: first field happens to be 'a'
            fh.write("x|2\n")
        data = read_columnar(p, names)
        assert list(data.column("a")) == ["a", "x"]
        assert list(data.column("b")) == ["1", "2"]

    def test_numeric_and_missing_mask_agree_on_padded_tokens(self):
        """' NA ' must count as missing in BOTH views: missing_mask
        strips before the set check, so numeric must too."""
        from shifu_tpu.data.reader import ColumnarData

        data = ColumnarData(
            names=["v"],
            raw={"v": np.array(["1.5", " NA ", "NA", " 2.5 ", "?"],
                               object)},
            n_rows=5,
            missing_values=("", "NA", "?"),
        )
        mask = data.missing_mask("v")
        vals = data.numeric("v")
        np.testing.assert_array_equal(
            mask, [False, True, True, False, True])
        # every masked-missing token is NaN in the numeric view, and
        # whitespace-padded real numbers still parse
        np.testing.assert_array_equal(np.isnan(vals), mask)
        assert vals[3] == 2.5


class TestPipelineOverlap:
    def test_prefetch_overlaps_producer_and_consumer(self):
        """With producer and consumer each sleeping T per item, the
        pipelined wall-clock must land well under the 2T-per-item serial
        sum (the overlap the stage timers are meant to expose)."""
        from shifu_tpu.data.pipeline import prefetch_iter
        from shifu_tpu.utils.timing import StageTimers

        n, t = 8, 0.03
        timers = StageTimers()

        def slow_source():
            for i in range(n):
                time.sleep(t)
                yield i

        t0 = time.perf_counter()
        for _ in prefetch_iter(slow_source(), depth=2, timers=timers,
                               stage="parse"):
            with timers.timer("device"):
                time.sleep(t)
        wall = time.perf_counter() - t0
        serial = 2 * n * t
        assert wall < serial * 0.8
        # the timers see the full per-stage cost even though it overlapped
        assert timers.seconds("parse") >= n * t * 0.9
        assert timers.seconds("device") >= n * t * 0.9
