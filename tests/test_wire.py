"""Columnar binary wire protocol (shifu_tpu/serve/wire.py): encode/
decode roundtrips, malformed-payload fuzzing (400-never-500 contract),
JSON<->binary scoring parity (bit-identical, incl. missing tokens,
unseen categories, non-ASCII), the one-device_put-per-coalesced-batch
pin, and the HTTP negotiation surface (Content-Type routing, 415/400
JSON error bodies, format-labeled metrics, the zoo per-set route)."""

import json
import os
import struct
import urllib.error
import urllib.request

import numpy as np
import pytest

from tests.helpers import make_model_set


@pytest.fixture(scope="module")
def model_set(tmp_path_factory):
    from shifu_tpu.processor.init import InitProcessor
    from shifu_tpu.processor.norm import NormProcessor
    from shifu_tpu.processor.stats import StatsProcessor
    from shifu_tpu.processor.train import TrainProcessor

    root = str(tmp_path_factory.mktemp("wire_ms"))
    make_model_set(root, n_rows=300)
    mcp = os.path.join(root, "ModelConfig.json")
    mc = json.load(open(mcp))
    mc["normalize"]["normType"] = "HYBRID"  # value kernel + woe gather
    mc["train"]["numTrainEpochs"] = 25
    json.dump(mc, open(mcp, "w"), indent=2)
    assert InitProcessor(root).run() == 0
    assert StatsProcessor(root).run() == 0
    assert NormProcessor(root).run() == 0
    assert TrainProcessor(root).run() == 0
    return root


def _parity_records(cols, n=6, seed=3):
    """Records exercising every parity-sensitive shape: plain floats,
    ints, missing tokens, absent fields, unseen categories, non-ASCII
    categorical values."""
    rng = np.random.default_rng(seed)
    recs = []
    for i in range(n):
        r = {}
        for j, c in enumerate(cols):
            if c.startswith("cat"):
                r[c] = ["red", "green", "blüe-∅", "never-seen", "?"][
                    (i + j) % 5]
            else:
                r[c] = float(np.round(rng.normal(), 5))
        recs.append(r)
    # row with explicit nulls and a row with absent fields
    recs[0][cols[0]] = None
    recs[1] = {k: v for k, v in recs[1].items() if k != cols[1]}
    # an all-int numeric column value (i64 wire path)
    recs[2][cols[0]] = 3
    return recs


# ---------------------------------------------------------------------------
# encode/decode roundtrip
# ---------------------------------------------------------------------------


class TestRoundtrip:
    def test_typed_and_string_columns(self):
        from shifu_tpu.serve import wire

        records = [
            {"f": 1.5, "i": 3, "s": "café", "m": None},
            {"f": None, "i": -7, "s": "x", "m": 2.0},
            {"f": -0.25, "i": 9, "s": "", "m": "tok"},
        ]
        data = wire.decode(wire.encode_records(records))
        assert data.wire_format == "binary"
        assert data.names == ["f", "i", "s", "m"]
        assert data.n_rows == 3
        # numeric columns decode to typed zero-copy views
        f = data.typed_column("f")
        assert f is not None and f.dtype == np.float64
        np.testing.assert_array_equal(f, [1.5, np.nan, -0.25])
        i = data.typed_column("i")
        assert i is not None and i.dtype == np.int64
        np.testing.assert_array_equal(i, [3, -7, 9])
        # mixed column went through the string path, None -> ""
        assert data.typed_column("m") is None
        assert list(data.column("m")) == ["", "2.0", "tok"]
        # non-ASCII categorical survives byte-exact
        assert list(data.column("s")) == ["café", "x", ""]

    def test_canonical_strings_match_json_path(self):
        from shifu_tpu.serve import wire
        from shifu_tpu.serve.registry import records_to_columnar

        records = [{"a": 1.5, "b": 3, "c": "zé"},
                   {"a": None, "b": -2, "c": None}]
        cols = ["a", "b", "c"]
        via_wire = wire.decode(wire.encode_records(records, cols))
        via_json = records_to_columnar(records, cols)
        for c in cols:
            assert list(via_wire.column(c)) == list(via_json.column(c))
            np.testing.assert_array_equal(via_wire.numeric(c),
                                          via_json.numeric(c))
            np.testing.assert_array_equal(via_wire.missing_mask(c),
                                          via_json.missing_mask(c))

    def test_conform_synthesizes_absent_columns(self):
        from shifu_tpu.serve import wire

        data = wire.decode(wire.encode_records([{"a": 1.0}, {"a": 2.0}]))
        out = wire.conform_columns(data, ["a", "zzz"])
        assert out.names == ["a", "zzz"]
        assert out.wire_format == "binary"
        assert list(out.column("zzz")) == ["", ""]
        # the typed column rides through untouched
        assert out.typed_column("a") is not None

    def test_encoder_type_selection(self):
        from shifu_tpu.serve import wire

        # bools are NOT ints here: their strings aren't numeric
        assert wire.column_from_values([True, False]).dtype == object
        # mixed int/float stringifies (a "1" vs "1.0" categorical
        # identity must not depend on its neighbors' types)
        assert wire.column_from_values([1, 2.0]).dtype == object
        # > 64-bit ints stringify like the JSON path did
        assert wire.column_from_values([10 ** 30]).dtype == object
        assert wire.column_from_values([1, 2]).dtype == np.int64
        assert wire.column_from_values([1.0, None]).dtype == np.float64

    def test_f32_i32_accepted_on_decode(self):
        from shifu_tpu.data.reader import ColumnarData
        from shifu_tpu.serve import wire

        data = ColumnarData(
            names=["a", "b"],
            raw={"a": np.asarray([1.5, 2.5], np.float32),
                 "b": np.asarray([3, 4], np.int32)},
            n_rows=2)
        out = wire.decode(wire.encode(data))
        assert out.typed_column("a").dtype == np.float32
        assert out.typed_column("b").dtype == np.int32
        np.testing.assert_array_equal(out.numeric("a"), [1.5, 2.5])


# ---------------------------------------------------------------------------
# malformed payloads: WireFormatError always, anything else never
# ---------------------------------------------------------------------------


class TestMalformed:
    def _payload(self):
        from shifu_tpu.serve import wire

        return wire.encode_records([
            {"num": 1.5, "cat": "rouge"},
            {"num": None, "cat": "vért"},
        ])

    def test_truncation_sweep(self):
        """EVERY proper prefix of a valid payload must raise
        WireFormatError — no IndexError, no struct.error, no hang."""
        from shifu_tpu.serve import wire

        payload = self._payload()
        for cut in range(len(payload)):
            with pytest.raises(wire.WireFormatError):
                wire.decode(payload[:cut])

    def test_wrong_magic_and_version(self):
        from shifu_tpu.serve import wire

        payload = self._payload()
        with pytest.raises(wire.WireFormatError, match="magic"):
            wire.decode(b"NOPE" + payload[4:])
        bad_ver = payload[:4] + struct.pack("<H", 99) + payload[6:]
        with pytest.raises(wire.WireFormatError, match="version"):
            wire.decode(bad_ver)

    def test_row_count_buffer_mismatch(self):
        from shifu_tpu.serve import wire

        payload = self._payload()
        # forge n_rows upward: every numeric/offset buffer is now too
        # short for the claimed rows
        forged = payload[:6] + struct.pack("<I", 10 ** 6) + payload[10:]
        with pytest.raises(wire.WireFormatError):
            wire.decode(forged)
        # forge n_cols upward: must fail the plausibility bound, not
        # walk off the end
        forged = payload[:10] + struct.pack("<I", 2 ** 31) + payload[14:]
        with pytest.raises(wire.WireFormatError):
            wire.decode(forged)

    def test_trailing_bytes_and_unknown_type(self):
        from shifu_tpu.serve import wire

        payload = self._payload()
        with pytest.raises(wire.WireFormatError, match="trailing"):
            wire.decode(payload + b"\x00")
        # corrupt the first column's type code (offset: header + name_len
        # field + 3-byte name "num")
        off = 14 + 2 + 3
        bad = payload[:off] + b"\xee" + payload[off + 1:]
        with pytest.raises(wire.WireFormatError):
            wire.decode(bad)

    def test_non_monotone_string_offsets(self):
        from shifu_tpu.serve import wire

        # one str column, 2 rows, offsets [0, 5, 3] (decreasing)
        head = struct.pack("<4sHII", wire.MAGIC, wire.VERSION, 2, 1)
        col = (struct.pack("<H", 1) + b"c" + struct.pack("<B", wire.TYPE_STR)
               + np.asarray([0, 5, 3], np.uint32).tobytes() + b"abc")
        with pytest.raises(wire.WireFormatError, match="monotone"):
            wire.decode(head + col)

    def test_duplicate_and_empty_column_names(self):
        from shifu_tpu.serve import wire

        head = struct.pack("<4sHII", wire.MAGIC, wire.VERSION, 1, 2)
        one = (struct.pack("<H", 1) + b"a" + struct.pack("<B", wire.TYPE_I32)
               + np.asarray([7], np.int32).tobytes())
        with pytest.raises(wire.WireFormatError, match="duplicate"):
            wire.decode(head + one + one)


# ---------------------------------------------------------------------------
# scoring parity: binary and JSON paths are bit-identical
# ---------------------------------------------------------------------------


class TestScoringParity:
    def test_registry_bit_identical(self, model_set):
        from shifu_tpu.serve import wire
        from shifu_tpu.serve.registry import ModelRegistry

        reg = ModelRegistry(os.path.join(model_set, "models"))
        recs = _parity_records(reg.input_columns)
        via_json = reg.score_records(recs)
        decoded = wire.decode(wire.encode_records(recs))
        via_bin = reg.score_raw(
            wire.conform_columns(decoded, reg.input_columns))
        # BIT-identical, not allclose: both formats must converge on the
        # same (values, codes) arrays before the same fused program
        np.testing.assert_array_equal(via_bin.model_scores,
                                      via_json.model_scores)
        np.testing.assert_array_equal(via_bin.mean, via_json.mean)
        np.testing.assert_array_equal(via_bin.median, via_json.median)

    @pytest.mark.parametrize("n_replicas", [1, 2])
    def test_fleet_bit_identical(self, model_set, n_replicas):
        from shifu_tpu.serve import wire
        from shifu_tpu.serve.fleet import ReplicaFleet

        fleet = ReplicaFleet.build(os.path.join(model_set, "models"),
                                   n_replicas=n_replicas)
        try:
            cols = list(fleet.input_columns)
            recs = _parity_records(cols)
            via_json = fleet.score_batch(recs, timeout=30)
            decoded = wire.decode(wire.encode_records(recs, cols))
            via_bin = fleet.score_batch(decoded, timeout=30)
            np.testing.assert_array_equal(via_bin.model_scores,
                                          via_json.model_scores)
            np.testing.assert_array_equal(via_bin.mean, via_json.mean)
        finally:
            fleet.close(10)

    def test_one_device_put_per_coalesced_batch(self, model_set,
                                                monkeypatch):
        """The transfer seam, pinned: after warm-up, scoring one
        coalesced batch issues EXACTLY one jax.device_put — the pinned
        staging-buffer handoff. (The fused dispatch itself runs inside
        the transfer_free sanitizer, so implicit copies already raise;
        this pins the explicit side.)"""
        import jax

        from shifu_tpu.serve.registry import ModelRegistry

        reg = ModelRegistry(os.path.join(model_set, "models"))
        recs = _parity_records(reg.input_columns, n=5)
        reg.score_records(recs)  # warm: compiles + allocates staging
        real_put = jax.device_put
        calls = []

        def counting_put(x, *a, **kw):
            calls.append(x)
            return real_put(x, *a, **kw)

        monkeypatch.setattr(jax, "device_put", counting_put)
        reg.score_records(recs)
        assert len(calls) == 1
        # and the one put is the staging buffer itself: a single
        # contiguous f32 matrix, not a pytree of per-plan leaves
        assert isinstance(calls[0], np.ndarray)
        assert calls[0].dtype == np.float32
        assert calls[0].ndim == 2

    def test_staging_buffer_reuse_and_accounting(self, model_set):
        from shifu_tpu.serve.registry import ModelRegistry

        reg = ModelRegistry(os.path.join(model_set, "models"))
        recs = _parity_records(reg.input_columns, n=5)
        r1 = reg.score_records(recs)
        buf_id = id(reg._staging[reg.bucket(5)])
        r2 = reg.score_records(recs)
        # same bucket -> same preallocated buffer, same answers
        assert id(reg._staging[reg.bucket(5)]) == buf_id
        np.testing.assert_array_equal(r1.model_scores, r2.model_scores)
        # a short batch after a longer one: stale pad rows must be wiped
        r3 = reg.score_records(recs[:2])
        np.testing.assert_array_equal(r3.model_scores,
                                      r1.model_scores[:2])
        mem = reg.memory_analysis()
        assert mem["stagingBytes"] == sum(
            b.nbytes for b in reg._staging.values())
        assert mem["stagingBytes"] > 0
        assert mem["residentBytes"] >= (mem["weightsBytes"]
                                        + mem["stagingBytes"])
        assert reg.snapshot()["stagingBytes"] == mem["stagingBytes"]


# ---------------------------------------------------------------------------
# HTTP surface: negotiation, error bodies, labeled metrics, zoo route
# ---------------------------------------------------------------------------


def _post_raw(url, body, ctype):
    req = urllib.request.Request(
        url, data=body if isinstance(body, bytes) else body.encode(),
        headers={"Content-Type": ctype})
    with urllib.request.urlopen(req, timeout=30) as r:
        return r.status, json.loads(r.read())


class TestHTTPWire:
    def test_binary_post_parity_errors_and_labels(self, model_set):
        from shifu_tpu import obs
        from shifu_tpu.serve import wire
        from shifu_tpu.serve.server import ScoringServer

        obs.reset()
        srv = ScoringServer(root=model_set, max_wait_ms=1,
                            replicas=1).start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            recs = _parity_records(srv.registry.input_columns)
            status, via_json = _post_raw(
                f"{base}/score", json.dumps({"records": recs}),
                "application/json")
            assert status == 200
            payload = wire.encode_records(recs)
            status, via_bin = _post_raw(f"{base}/score", payload,
                                        wire.CONTENT_TYPE)
            assert status == 200
            assert via_bin["scores"] == via_json["scores"]

            # unknown Content-Type: 415 with a JSON error body
            with pytest.raises(urllib.error.HTTPError) as he:
                _post_raw(f"{base}/score", payload, "application/msgpack")
            assert he.value.code == 415
            err = json.loads(he.value.read())
            assert "accepts" in err and wire.CONTENT_TYPE in err["accepts"]

            # malformed binary payloads: 400 + JSON body, never a 500 —
            # truncations, garbled magic, forged row counts
            for bad in (payload[:7], payload[:-2], b"XXXX" + payload[4:],
                        payload[:6] + struct.pack("<I", 10 ** 6)
                        + payload[10:], b""):
                with pytest.raises(urllib.error.HTTPError) as he:
                    _post_raw(f"{base}/score", bad, wire.CONTENT_TYPE)
                assert he.value.code == 400
                assert "error" in json.loads(he.value.read())

            snap = obs.registry().snapshot()["counters"]
            assert snap['serve.requests{format="binary",replica="0"}'] == 1
            assert snap['serve.requests{format="json",replica="0"}'] == 1
            assert snap['serve.wire.bytes{format="binary"}'] == len(payload)
            assert snap['serve.wire.bytes{format="json"}'] > 0
            page = urllib.request.urlopen(f"{base}/metrics",
                                          timeout=10).read().decode()
            assert 'serve_wire_bytes_total{format="binary"}' in page
            assert ('serve_requests_total'
                    '{format="binary",replica="0"}') in page
        finally:
            manifest = srv.shutdown()
        # both formats' labeled counters land in the shutdown manifest
        m = json.load(open(manifest))
        counters = m["metrics"]["counters"]
        assert counters['serve.requests{format="binary",replica="0"}'] == 1
        assert counters['serve.wire.bytes{format="binary"}'] == len(payload)

    def test_oversize_binary_body_is_400(self, model_set):
        from shifu_tpu.serve import wire
        from shifu_tpu.serve.server import ScoringServer
        from shifu_tpu.utils import environment

        srv = ScoringServer(root=model_set, max_wait_ms=1,
                            replicas=1).start()
        environment.set_property("shifu.serve.wire.maxBodyMB", "0.00001")
        try:
            base = f"http://127.0.0.1:{srv.port}"
            payload = wire.encode_records(
                _parity_records(srv.registry.input_columns, n=8))
            assert len(payload) > wire.max_body_bytes()
            with pytest.raises(urllib.error.HTTPError) as he:
                _post_raw(f"{base}/score", payload, wire.CONTENT_TYPE)
            assert he.value.code == 400
            assert "maxBodyMB" in json.loads(he.value.read())["error"]
        finally:
            environment.set_property("shifu.serve.wire.maxBodyMB", "")
            srv.shutdown()

    def test_zoo_set_route_parity(self, model_set):
        from shifu_tpu import obs
        from shifu_tpu.serve import wire
        from shifu_tpu.serve.server import ScoringServer

        obs.reset()
        srv = ScoringServer(root=model_set, port=0, replicas=1,
                            zoo={"a": model_set}).start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            cols = srv.registry.input_columns
            recs = _parity_records(cols)
            status, via_json = _post_raw(
                f"{base}/score/a", json.dumps({"records": recs}),
                "application/json")
            assert status == 200
            status, via_bin = _post_raw(
                f"{base}/score/a", wire.encode_records(recs, cols),
                wire.CONTENT_TYPE)
            assert status == 200
            assert via_bin["scores"] == via_json["scores"]
        finally:
            srv.shutdown()
