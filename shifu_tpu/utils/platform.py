"""JAX platform selection shared by the CLI, tests, and driver entry points.

Some TPU plugins (axon) ignore the JAX_PLATFORMS env var; the config API
wins either way, so force the platform through jax.config BEFORE the backend
initializes. Safe to call multiple times; a no-op once a backend exists.
"""

from __future__ import annotations

import os
from typing import Optional


def force_platform(platform: Optional[str] = None, n_devices: Optional[int] = None) -> None:
    """Force `platform` (default: the JAX_PLATFORMS env var, if set) and
    optionally request n virtual host devices (CPU mesh testing)."""
    platform = platform or os.environ.get("JAX_PLATFORMS")
    if n_devices:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n_devices}"
            ).strip()
    if not platform:
        return
    os.environ.setdefault("JAX_PLATFORMS", platform)
    try:
        import jax

        jax.config.update("jax_platforms", platform)
    except (ImportError, RuntimeError, ValueError):
        # best-effort: jax absent, or already initialized with a fixed
        # platform — the env var set above still steers later imports
        pass
