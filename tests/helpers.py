"""Synthetic dataset generators for tests — WDBC-shaped tabular data."""

from __future__ import annotations

import os

import numpy as np


def make_binary_dataset(
    n_rows: int = 600,
    n_numeric: int = 10,
    n_categorical: int = 2,
    missing_rate: float = 0.02,
    seed: int = 7,
):
    """Two-gaussian binary classification data with categorical columns and
    missing tokens. Returns (header_names, rows_of_strings, y)."""
    rng = np.random.default_rng(seed)
    y = (rng.random(n_rows) < 0.4).astype(int)
    names = ["diagnosis"]
    cols = []
    for j in range(n_numeric):
        shift = 1.5 * (j % 3 == 0)
        x = rng.normal(loc=y * shift + j * 0.1, scale=1.0 + 0.05 * j)
        cols.append(x)
        names.append(f"num_{j}")
    cat_values = ["red", "green", "blue", "violet"]
    cat_cols = []
    for j in range(n_categorical):
        probs_pos = np.array([0.5, 0.25, 0.15, 0.10])
        probs_neg = np.array([0.10, 0.15, 0.25, 0.5])
        choice = np.where(
            y == 1,
            rng.choice(4, size=n_rows, p=probs_pos),
            rng.choice(4, size=n_rows, p=probs_neg),
        )
        cat_cols.append(np.array(cat_values)[choice])
        names.append(f"cat_{j}")

    rows = []
    for i in range(n_rows):
        fields = ["M" if y[i] else "B"]
        for x in cols:
            if rng.random() < missing_rate:
                fields.append("")
            else:
                fields.append(f"{x[i]:.6g}")
        for c in cat_cols:
            if rng.random() < missing_rate:
                fields.append("?")
            else:
                fields.append(str(c[i]))
        rows.append(fields)
    return names, rows, y


def make_multiclass_dataset(
    n_rows: int = 900,
    n_numeric: int = 8,
    seed: int = 11,
    classes=("low", "mid", "high"),
):
    """K-class dataset: per-class shifted gaussians (separable), plus one
    categorical column correlated with the class. Returns (names, rows, y)."""
    rng = np.random.default_rng(seed)
    k = len(classes)
    y = rng.integers(k, size=n_rows)
    names = ["grade"]
    cols = []
    for j in range(n_numeric):
        scale = 1.0 if j % 2 == 0 else 1.5
        x = rng.normal(loc=y * 2.0 * ((j % 3) + 1) / 3.0, scale=scale)
        cols.append(x)
        names.append(f"num_{j}")
    cat_values = np.array(["aa", "bb", "cc", "dd"])
    choice = (y + rng.integers(0, 2, size=n_rows)) % 4
    names.append("cat_0")

    rows = []
    for i in range(n_rows):
        fields = [str(classes[y[i]])]
        fields.extend(f"{x[i]:.6g}" for x in cols)
        fields.append(str(cat_values[choice[i]]))
        rows.append(fields)
    return names, rows, y


def make_multiclass_model_set(
    root: str,
    n_rows: int = 900,
    seed: int = 11,
    algorithm: str = "NN",
    method: str = "NATIVE",
    classes=("low", "mid", "high"),
):
    """Model set in classification mode: posTags = all classes, negTags
    empty (the reference's XOR semantics, ModelConfig.isClassification)."""
    from shifu_tpu.config.model_config import (
        Algorithm,
        MultipleClassification,
        new_model_config,
    )

    names, rows, _ = make_multiclass_dataset(
        n_rows=n_rows, seed=seed, classes=classes
    )
    data_dir = os.path.join(root, "data")
    data_path, header_path = write_dataset(data_dir, names, rows)

    mc = new_model_config("TestMulti", Algorithm.parse(algorithm))
    mc.data_set.data_path = data_path
    mc.data_set.header_path = header_path
    mc.data_set.data_delimiter = "|"
    mc.data_set.header_delimiter = "|"
    mc.data_set.target_column_name = "grade"
    mc.data_set.pos_tags = list(classes)
    mc.data_set.neg_tags = []
    mc.train.multi_classify_method = MultipleClassification.parse(method)
    mc.evals[0].data_set.data_path = data_path
    mc.evals[0].data_set.header_path = header_path
    mc.evals[0].data_set.data_delimiter = "|"
    os.makedirs(root, exist_ok=True)
    mc.save(os.path.join(root, "ModelConfig.json"))
    return root


def write_dataset(dirpath: str, names, rows, delimiter: str = "|"):
    os.makedirs(dirpath, exist_ok=True)
    header = os.path.join(dirpath, "header.txt")
    with open(header, "w") as fh:
        fh.write(delimiter.join(names) + "\n")
    data = os.path.join(dirpath, "data.txt")
    with open(data, "w") as fh:
        for r in rows:
            fh.write(delimiter.join(r) + "\n")
    return data, header


def make_model_set(root: str, n_rows: int = 600, seed: int = 7, algorithm: str = "NN"):
    """Create a ready-to-init model set dir with synthetic data. Returns root."""
    from shifu_tpu.config.model_config import Algorithm, new_model_config

    names, rows, _ = make_binary_dataset(n_rows=n_rows, seed=seed)
    data_dir = os.path.join(root, "data")
    data_path, header_path = write_dataset(data_dir, names, rows)

    mc = new_model_config("TestModel", Algorithm.parse(algorithm))
    mc.data_set.data_path = data_path
    mc.data_set.header_path = header_path
    mc.data_set.data_delimiter = "|"
    mc.data_set.header_delimiter = "|"
    mc.data_set.target_column_name = "diagnosis"
    mc.data_set.pos_tags = ["M"]
    mc.data_set.neg_tags = ["B"]
    os.makedirs(root, exist_ok=True)
    mc.save(os.path.join(root, "ModelConfig.json"))
    return root
