"""The trainer-as-tenant grant protocol.

The trainer never touches a device byte it has not been granted: every
host-counted buffer is `acquire`d BEFORE its device_put (the PR-15
serving-tenant invariant, applied to training). Three grant backends,
one protocol:

  ZooGrant    in-process against a live ModelZoo (the bench / CI serve
              process trains inside itself).
  HttpGrant   against a remote serve process's `/admin/coresident/*`
              plane (`shifu retrain --coresident --serve-url ...`).
  LocalGrant  a private HbmLedger with no serving fleet — standalone
              runs and tests keep the exact accounting discipline
              without a zoo.

`heartbeat` is the preemption channel: the zoo evicts a background
tenant by dropping its ledger charge and flagging it; the trainer
learns at its next epoch boundary, checkpoints, releases its buffers,
and polls for re-admission — or surfaces `EvictedError` so the caller
can `--resume` later. The grace window between the flag and the drop
is bounded by one epoch.
"""

from __future__ import annotations

import json
import time
import urllib.request
from typing import Optional

from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)


class GrantFullError(RuntimeError):
    """The grant cannot fit the requested bytes right now (background
    acquires are fit-or-fail: a trainer never evicts a serving
    tenant)."""

    def __init__(self, msg: str, deficit: int = 0) -> None:
        super().__init__(msg)
        self.deficit = int(deficit)


class EvictedError(RuntimeError):
    """The ledger evicted the trainer and re-admission did not land
    within the wait window. State is checkpointed; resume with
    `--resume` once serving pressure subsides."""

    def __init__(self, tenant: str, epoch: int) -> None:
        super().__init__(
            f"co-resident trainer {tenant!r} evicted at epoch {epoch}; "
            "checkpointed — resume with --resume")
        self.tenant = tenant
        self.epoch = int(epoch)


class Grant:
    """Protocol base. Subclasses implement the five verbs."""

    name = ""

    def admit(self, meta: Optional[dict] = None) -> dict:
        raise NotImplementedError

    def acquire(self, nbytes: int) -> None:
        raise NotImplementedError

    def reduce(self, nbytes: int) -> None:
        raise NotImplementedError

    def heartbeat(self, epoch: int) -> bool:
        raise NotImplementedError

    def release(self, final: bool = False) -> None:
        raise NotImplementedError

    def free_bytes(self) -> Optional[int]:
        """Unused budget headroom (None = unbounded) — what
        plan.default_stages sizes K from."""
        return None

    def wait_readmit(self, nbytes: int, wait_ms: float,
                     poll_s: float = 0.25) -> bool:
        """Poll `acquire` until the evicted trainer's bytes fit again
        or the window closes. On True the charge is HELD — the caller
        device_puts without re-acquiring."""
        deadline = time.monotonic() + max(0.0, wait_ms) / 1000.0
        while True:
            try:
                self.admit()  # clears the evicted flag server-side
                self.acquire(nbytes)
                return True
            except (GrantFullError, OSError):
                if time.monotonic() >= deadline:
                    return False
                time.sleep(poll_s)


class LocalGrant(Grant):
    """A private ledger: same acquire-before-put bookkeeping, no
    serving fleet to contend with (budget_mb=0 = unbounded)."""

    def __init__(self, name: str = "retrain",
                 budget_mb: float = 0.0) -> None:
        from shifu_tpu.serve.zoo import HbmLedger

        self.name = name
        self.ledger = HbmLedger(budget_mb)

    def admit(self, meta: Optional[dict] = None) -> dict:
        return {"freeBytes": self.free_bytes(), "devices": 0}

    def acquire(self, nbytes: int) -> None:
        from shifu_tpu.serve.zoo import LedgerFullError

        try:
            self.ledger.acquire(self.name, "background", int(nbytes))
        except LedgerFullError as e:
            raise GrantFullError(str(e), e.deficit) from e

    def reduce(self, nbytes: int) -> None:
        self.ledger.reduce(self.name, "background", int(nbytes))

    def heartbeat(self, epoch: int) -> bool:
        return False

    def release(self, final: bool = False) -> None:
        self.ledger.release(self.name, "background")

    def free_bytes(self) -> Optional[int]:
        if not self.ledger.budget_bytes:
            return None
        return max(0, self.ledger.budget_bytes - self.ledger.used)


class ZooGrant(Grant):
    """In-process grant against a live ModelZoo: the trainer is a
    first-class `priority=background` tenant of the serving ledger."""

    def __init__(self, zoo, name: str = "retrain") -> None:
        self.zoo = zoo
        self.name = name

    def admit(self, meta: Optional[dict] = None) -> dict:
        return self.zoo.admit_background(self.name, meta=meta)

    def acquire(self, nbytes: int) -> None:
        from shifu_tpu.serve.zoo import LedgerFullError

        try:
            self.zoo.background_acquire(self.name, int(nbytes))
        except LedgerFullError as e:
            raise GrantFullError(str(e), e.deficit) from e

    def reduce(self, nbytes: int) -> None:
        self.zoo.background_reduce(self.name, int(nbytes))

    def heartbeat(self, epoch: int) -> bool:
        return bool(self.zoo.background_heartbeat(self.name, epoch))

    def release(self, final: bool = False) -> None:
        self.zoo.background_release(self.name, final=final)

    def free_bytes(self) -> Optional[int]:
        ledger = self.zoo.ledger
        if not ledger.budget_bytes:
            return None
        return max(0, ledger.budget_bytes - ledger.used)


class HttpGrant(Grant):
    """Grant over the serve process's `/admin/coresident/*` plane."""

    def __init__(self, url: str, name: str = "retrain",
                 timeout_s: float = 10.0) -> None:
        self.url = url.rstrip("/")
        self.name = name
        self.timeout_s = float(timeout_s)
        self._free: Optional[int] = None

    def _post(self, action: str, payload: dict) -> dict:
        body = json.dumps({"tenant": self.name, **payload}).encode()
        req = urllib.request.Request(
            f"{self.url}/admin/coresident/{action}", data=body,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout_s) as resp:
                return json.loads(resp.read().decode() or "{}")
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")
            if e.code == 409:
                deficit = 0
                try:
                    deficit = int(json.loads(detail).get("deficit", 0))
                except (ValueError, TypeError, AttributeError):
                    deficit = 0  # detail is free-form on other 409s
                raise GrantFullError(
                    f"grant {action} refused: {detail}", deficit) from e
            raise

    def admit(self, meta: Optional[dict] = None) -> dict:
        out = self._post("admit", {"meta": meta or {}})
        self._free = out.get("freeBytes")
        return out

    def acquire(self, nbytes: int) -> None:
        self._post("charge", {"bytes": int(nbytes)})

    def reduce(self, nbytes: int) -> None:
        self._post("charge", {"bytes": -int(nbytes)})

    def heartbeat(self, epoch: int) -> bool:
        return bool(self._post("heartbeat",
                               {"epoch": int(epoch)}).get("evicted"))

    def release(self, final: bool = False) -> None:
        self._post("release", {"final": bool(final)})

    def free_bytes(self) -> Optional[int]:
        return self._free
